"""Benchmark: training-step throughput on the available accelerator.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

Metric: tokens/sec on a Llama-2-architecture training step (bf16 compute,
fp32 params/Adam), sized to the chip. vs_baseline compares achieved MFU
against the reference's published A100 number — Llama2-7B at 890 tokens/s/GPU
(ref: docs/guide/getting_started.md:200-201), i.e. 6*7e9*890/312e12 = 12.0%
MFU on A100-80GB bf16 — so the ratio is hardware-normalized.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

# --- robust backend bring-up (round-1 BENCH died with rc=1 on a transient
# 'axon' tunnel failure at jax.devices(); round-2 fell back to CPU after two
# 2-minute probes while the tunnel wedge lasted hours; round-3 spent the
# WHOLE driver window probing a dead tunnel because the 1800 s probe budget
# exceeded the driver's kill timeout — see VERDICT.md r3 "What's weak" #1).
# Probe the backend in a SUBPROCESS with exponential-backoff retries across
# a budget capped at a FRACTION of the driver window (default 400 s) so the
# remainder is reserved for an actual measurement; if the accelerator never
# comes up, fall back to cpu but emit an HONEST record (cpu_fallback: true,
# vs_baseline: null, no MFU) that cannot be mistaken for a chip number.

_PROBE_LOG: list = []  # (attempt, elapsed_s, cause) for the emitted record


def _probe_backend(budget_s: float = None) -> str:
    """Return the first platform that initializes, probing in a throwaway
    subprocess (a wedged tunnel can hang jax.devices() forever and poison
    this process's backend cache). Retries with exponential backoff until
    `budget_s` (env BENCH_PROBE_BUDGET_S, default 400 s — a FRACTION of
    the driver window, so the rest is reserved for measuring) runs out."""
    if budget_s is None:
        budget_s = float(os.environ.get("BENCH_PROBE_BUDGET_S", "400"))
    # the probe must honor an inherited JAX_PLATFORMS the same way the main
    # process will (config-level pin beats the axon sitecustomize override)
    # or it would probe the wrong platform
    code = ("import os, jax\n"
            "p = os.environ.get('JAX_PLATFORMS')\n"
            "if p:\n"
            "    jax.config.update('jax_platforms', p)\n"
            "print(jax.devices()[0].platform)")
    t0 = time.monotonic()
    attempt = 0
    sleep_s = 30.0
    while True:
        attempt += 1
        elapsed = time.monotonic() - t0
        try:
            attempt_timeout = max(min(150.0, budget_s - elapsed), 10.0)
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=attempt_timeout)
            if r.returncode == 0:
                plat = r.stdout.strip().splitlines()[-1]
                _PROBE_LOG.append((attempt, round(elapsed, 1), f"ok:{plat}"))
                return plat
            err_lines = r.stderr.strip().splitlines() if r.stderr else []
            cause = (err_lines[-1][:200] if err_lines
                     else f"rc={r.returncode}")
        except subprocess.TimeoutExpired:
            cause = f"timeout({attempt_timeout:.0f}s)"
        _PROBE_LOG.append((attempt, round(elapsed, 1), cause))
        print(f"bench: probe attempt {attempt} at t+{elapsed:.0f}s failed: "
              f"{cause}", file=sys.stderr)
        remaining = budget_s - (time.monotonic() - t0)
        if remaining <= 10.0:  # not enough left for a meaningful attempt
            return "cpu"
        # clamp the final sleep so the whole budget gets spent probing
        time.sleep(min(sleep_s, max(remaining - 10.0, 0.0)))
        sleep_s = min(sleep_s * 2, 600.0)


def _no_measurement_record(note: str, value: float = 0.0,
                           cpu_fallback: bool = True) -> dict:
    """The shared shape of every record that is NOT an accelerator
    measurement — probe-phase kill and CPU fallback both use it so the
    schema cannot diverge."""
    return {
        "metric": "train_tokens_per_sec_per_chip",
        "value": value,
        "unit": note,
        "vs_baseline": None,
        "cpu_fallback": cpu_fallback,
        "requested_platform": _REQUESTED_PLATFORM,
        "probe_attempts": [
            {"attempt": a, "t_s": t, "cause": c} for a, t, c in _PROBE_LOG
        ],
    }


_PHASE = "probe"  # probe -> measure -> emitted


def _emit_killed_record(signum, frame):
    """If the CALLER's timeout kills us before the record is out, still
    leave an honest no-measurement record on stdout instead of dying
    recordless (round-1 BENCH was rc=1 with no output). Armed for the
    WHOLE probe+measure lifetime — round 3 only covered the probe, so a
    kill during compile/measure would have died recordless too. One-shot
    and phase-aware: once the real record is printed ("emitted" phase,
    i.e. only the extras suites remain), a late SIGTERM must exit without
    printing a second JSON line into the one-line stdout contract."""
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    if _PHASE != "emitted":
        print(json.dumps(_no_measurement_record(
            f"no measurement: killed during the {_PHASE} phase — not a "
            "result")), flush=True)
    sys.exit(0)


_env_platform = os.environ.get("JAX_PLATFORMS", "")
_REQUESTED_PLATFORM = _env_platform or "auto"
_CPU_FALLBACK = False
signal.signal(signal.SIGTERM, _emit_killed_record)
if _env_platform != "cpu" and _probe_backend() == "cpu":
    # cpu_fallback means "accelerator unreachable after the full backoff
    # budget" — a probe that SUCCEEDED at cpu (no accelerator present, e.g.
    # a dev laptop) is an ordinary cpu run, not a tunnel wedge.
    probe_gave_up = not (_PROBE_LOG and _PROBE_LOG[-1][2] == "ok:cpu")
    if probe_gave_up:
        # Pin cpu so a number is still recorded rather than rc=1 or an
        # unbounded hang — but NEVER silently: the emitted record carries
        # cpu_fallback/requested_platform/probe_attempts, vs_baseline is
        # null, and no MFU is printed.
        _CPU_FALLBACK = True
        print(f"bench: FALLING BACK TO CPU after {len(_PROBE_LOG)} probe "
              f"attempts; requested platform was {_REQUESTED_PLATFORM!r}. "
              "The emitted record is NOT an accelerator number.",
              file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
# probe finished: a kill from here on is reported as the measure phase
_PHASE = "measure"

import jax
import jax.numpy as jnp

from megatron_tpu.utils.platform import ensure_env_platform
ensure_env_platform()

# bf16 peak FLOP/s per chip by device kind (public spec sheets)
PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
    "TPU v6 lite": 918e12,
    "TPU7x": 2307e12,
    # no "cpu" entry on purpose: a CPU run emits no MFU at all
}

A100_BASELINE_MFU = 6 * 7.0e9 * 890 / 312e12  # = 0.1198


def detect_peak(device) -> float:
    kind = getattr(device, "device_kind", "cpu")
    for k, v in PEAK_FLOPS.items():
        if kind.lower().startswith(k.lower()):
            return v
    return PEAK_FLOPS.get("TPU v4")


def run_config(dev, model, micro_bs, n_micro, iters, warmup):
    from megatron_tpu.config import (MegatronConfig, OptimizerConfig,
                                     TrainingConfig)
    from megatron_tpu.training import init_train_state, make_train_step

    cfg = MegatronConfig(
        model=model,
        optimizer=OptimizerConfig(lr=1e-4, clip_grad=1.0),
        training=TrainingConfig(micro_batch_size=micro_bs,
                                global_batch_size=micro_bs * n_micro,
                                train_iters=iters),
    ).validate(n_devices=1)

    rng = jax.random.PRNGKey(0)
    state = init_train_state(rng, cfg)
    step = make_train_step(cfg)
    seq = cfg.model.seq_length
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (n_micro, micro_bs, seq + 1), 0,
        cfg.model.vocab_size, dtype=jnp.int32)
    batch = {"tokens": tokens,
             "loss_mask": jnp.ones((n_micro, micro_bs, seq), jnp.float32)}

    # param count for the FLOP model
    n_params = sum(p.size for p in jax.tree.leaves(state.params))

    for i in range(warmup):
        state, m = step(state, batch, jax.random.fold_in(rng, i))
    jax.block_until_ready(m["lm_loss"])

    t0 = time.perf_counter()
    for i in range(iters):
        state, m = step(state, batch, jax.random.fold_in(rng, warmup + i))
    jax.block_until_ready(m["lm_loss"])
    dt = time.perf_counter() - t0

    tokens_per_iter = n_micro * micro_bs * seq
    tok_s = tokens_per_iter * iters / dt
    kind = getattr(dev, "device_kind", dev.platform)
    if dev.platform != "tpu" or _CPU_FALLBACK:
        # CPU (or any non-TPU) run: there is no meaningful peak to compute
        # an MFU against and no hardware-normalized baseline ratio — a
        # fallback record must be impossible to mistake for a chip result
        # (VERDICT r2 "What's weak" #1).
        note = ("CPU FALLBACK" if _CPU_FALLBACK else f"{dev.platform} run")
        record = _no_measurement_record(
            f"tok/s ({n_params/1e9:.2f}B params, {kind}, "
            f"{note} — not an accelerator number)",
            value=round(tok_s, 1), cpu_fallback=_CPU_FALLBACK)
        record["device_kind"] = kind
        return record
    flops_per_token = 6 * n_params  # fwd+bwd dense FLOPs, attention excluded
    mfu = tok_s * flops_per_token / detect_peak(dev)
    return {
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": f"tok/s ({n_params/1e9:.2f}B params, {kind}, "
                f"MFU={mfu:.3f})",
        "vs_baseline": round(mfu / A100_BASELINE_MFU, 3),
        "device_kind": kind,
    }


def main():
    from megatron_tpu.config import llama2_config

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        # Try largest-first; fall back so a flaky backend / OOM still yields
        # a recorded number (VERDICT round-1 item 1).
        attempts = [
            # ~0.74B llama-architecture model at seq 2048. Params fp32 + two
            # Adam moments + fp32 grads = 16 bytes/param -> ~12 GB of the v5e's
            # 16 GB HBM; 1.1B (17.6 GB) can NOT fit, which is what round 1
            # tried. micro_bs=2 + full remat: the axon remote-compile helper
            # reproducibly dies (HTTP 500) on [4, 2048, 2048] activation
            # shapes and on the selective-remat policy at this size.
            # recompute=none at this shape MIGHT be faster (~1/4 of the
            # step FLOPs is remat recompute) — but it is NOT attempted
            # here: a fits-but-slower run (XLA spilling at ~15/16 GB)
            # would REPLACE this proven record, and the tunnel windows
            # are short. tools/bench_remat.py measures that A/B off the
            # driver path; promote only with on-chip data.
            (llama2_config(
                "tiny", num_layers=12, hidden_size=2048,
                num_attention_heads=16, num_kv_heads=16, ffn_hidden_size=5504,
                vocab_size=32000, seq_length=2048, compute_dtype="bfloat16",
                attention_impl="flash", recompute_granularity="full"),
             2, 4, 10, 3),
            # ~440M fallback: best single-chip MFU observed (52%), compiles
            # fast, fits anywhere
            (llama2_config(
                "tiny", num_layers=12, hidden_size=1536,
                num_attention_heads=12, num_kv_heads=12, ffn_hidden_size=4096,
                vocab_size=32000, seq_length=1024, compute_dtype="bfloat16",
                attention_impl="flash", recompute_granularity="selective"),
             4, 2, 10, 2),
        ]
    else:  # smoke mode for CPU dev runs
        attempts = [
            (llama2_config("tiny", seq_length=256, compute_dtype="bfloat16"),
             2, 1, 3, 1),
        ]

    last_err = None
    for model, micro_bs, n_micro, iters, warmup in attempts:
        try:
            result = run_config(dev, model, micro_bs, n_micro, iters, warmup)
        except Exception as e:  # OOM / lowering failure: try the next size.
            # Keep only the repr: holding `e` itself pins the failed
            # attempt's train state in HBM via e.__traceback__, which would
            # OOM the fallback config too.
            last_err = f"{type(e).__name__}: {str(e)[:500]}"
            print(f"bench: config failed ({last_err})", file=sys.stderr)
            continue
        print(json.dumps(result), flush=True)
        global _PHASE
        _PHASE = "emitted"
        # outside the try: an extras failure must never re-enter the
        # attempt loop and print a second JSON line after the real record
        if on_tpu and not _CPU_FALLBACK:
            _run_extras()
        return
    raise SystemExit(f"bench: all configs failed; last error: {last_err}")


def _run_extras():
    """Spend whatever driver window remains AFTER the main record is out on
    the kernel/32k suites (VERDICT r3 item 1: measure first, extras after,
    so a late kill still leaves a measurement). Results go to files +
    stderr only — stdout stays one JSON line. Disable with BENCH_EXTRAS=0;
    each suite gets an independent timeout so a hang cannot eat the other."""
    if os.environ.get("BENCH_EXTRAS", "1") == "0":
        return
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        budget = float(os.environ.get("BENCH_EXTRAS_TIMEOUT_S", "900"))
    except ValueError:
        budget = 900.0
    suites = [
        ("bench_kernels.py", [], "/tmp/bench_extras_kernels.log"),
        # uniform-head overhead measurement (VERDICT r3 weak #6): two
        # small jits, runs in well under a minute on-chip
        ("bench_head.py", [], "/tmp/bench_extras_head.log"),
        # BASELINE configs 1-2 slice (seq 4096) before the 32k one: it
        # compiles/runs faster, so a mid-extras kill still leaves it
        ("bench_32k.py", ["--seq_length", "4096"],
         "/tmp/bench_extras_4k.log"),
        # remat-policy A/B at the headline config (three full
        # train-compiles — heavy, so AFTER the kill-safe 4k record): if
        # "none"/"selective" fits HBM it sheds the full-remat recompute
        # (~25% step time) — promote the winner to the attempt list above
        ("bench_remat.py", [], "/tmp/bench_extras_remat.log"),
        # serving prefill+decode throughput with an HBM roofline — after
        # the BASELINE slice so a wedge here can't starve that record;
        # the int8-weights arm measures the halved weight stream
        ("bench_decode.py", ["--int8_weights", "--int8_kv"],
         "/tmp/bench_extras_decode.log"),
        # continuous-batching engine under concurrent load (TTFT
        # percentiles + aggregate tok/s over the slot grid) — the
        # serving-side complement to bench_decode's single stream
        ("serving_bench.py", ["--requests", "32", "--slots", "8"],
         "/tmp/bench_extras_serving.log"),
        # overload arm: offered load > slot capacity with deadlines +
        # early shedding (docs/serving.md "Overload & failure
        # behavior") — shed rate / goodput / p99 queue delay, the
        # numbers an admission-control regression moves first
        ("serving_bench.py", ["--overload", "--requests", "48",
                              "--slots", "4", "--new", "16"],
         "/tmp/bench_extras_serving_overload.log"),
        # host-sync cadence A/B (PERF_NOTES "batch K steps per sync"):
        # per-step vs per-window metrics fetch in the train loop, and
        # decode_sync_interval 1-vs-K in the engine — ON CHIP the
        # ms/step delta is the dispatch gap the per-step sync cost
        ("bench_sync.py", [], "/tmp/bench_extras_sync.log"),
        # prefix-cache + chunked-prefill A/B on a shared-prefix
        # workload (PERF_NOTES serving section): hit rate, REAL prefill
        # forward tokens removed by KV reuse, TTFT with/without
        # chunking — ON CHIP this is the pending on-chip record for
        # the PR-5 serving work
        ("bench_prefix.py", [], "/tmp/bench_extras_prefix.log"),
        # speculative-decoding A/B (PERF_NOTES serving section):
        # k=0 baseline vs k in {2,4,8} on a decode-heavy workload —
        # greedy arms assert token-agreement, the record is acceptance
        # rate + accepted-tok/s vs the bench_decode HBM roofline; ON
        # CHIP this is the pending record for the ISSUE-8 serving work
        ("bench_spec.py", [], "/tmp/bench_extras_spec.log"),
        # block-native attention A/B (PERF_NOTES serving section):
        # gather/scatter-bracketed vs block-native kernel decode at
        # matched block size/dtype — greedy arms assert token
        # agreement and the kernel arm pins kv_gather_bytes_per_step
        # == 0; ON CHIP this is the pending record for the ISSUE-11
        # bracket-removal claim (B in {16,64,256} x bf16/int8)
        ("bench_block_attn.py", ["--smoke"],
         "/tmp/bench_extras_block_attn.log"),
        # multi-tenant LoRA adapter A/B (PERF_NOTES queue item 9):
        # base vs one-adapter vs mixed-8 decode on the slot grid —
        # every row token-exact vs its own adapter's merged-weights
        # serial oracle, one decode compile per arm; ON CHIP the
        # record is the mixed-arm tok/s ratio judged against the
        # adapter-gather bytes/step the tool reports
        ("bench_lora.py", ["--smoke"], "/tmp/bench_extras_lora.log"),
        # interleave-vs-disaggregated serving A/B + serving-tp decode
        # scaling (PERF_NOTES queue item 10): greedy arms assert token
        # agreement, the disagg arm pins handoff_bytes_per_req ==
        # ceil(plen/B) * block bytes; ON CHIP the record is the TTFT /
        # inter-token-p99 split and the tp=2 decode tok/s ratio
        ("bench_disagg.py", ["--smoke"],
         "/tmp/bench_extras_disagg.log"),
        # symmetric-vs-asymmetric per-phase topology A/B (PERF_NOTES
        # queue item 12): disaggregated arms at (1,1)/(1,2)/(2,1)
        # prefill:decode splits over one staggered workload — greedy
        # arms assert token agreement (the P!=D handoff reshards the
        # kv-head axis inside the one device_put) and the handoff
        # bytes stay pinned; ON CHIP the record is the decode-heavy
        # ITL ratio + the prefill-heavy TTFT ratio vs symmetric
        ("bench_phase_topology.py", ["--smoke"],
         "/tmp/bench_extras_phase_topology.log"),
        # pipeline-sharded serving A/B (PERF_NOTES queue item 13):
        # mono vs serving_pp=2 at pp_waves 1 and 2 over one staggered
        # workload — greedy arms assert token agreement (staging is a
        # placement change, not a semantics change) and the
        # pp_stage_bubble gauge is pinned to (S-1)/(W+S-1); ON CHIP
        # the record is the staged tok/s tax vs the analytic bubble
        # and whether the second wave claws it back
        ("bench_pp_serving.py", ["--smoke"],
         "/tmp/bench_extras_pp_serving.log"),
        # structured-output + n-best A/B (PERF_NOTES serving section):
        # constrained-vs-free decode (mask uploads ONLY on FSM state
        # change, outputs assert-parsed) and n=1x4-vs-n=4 COW fan-out
        # (one real prefill, samples token-exact vs serial twins) on
        # ONE compiled decode step; ON CHIP the record is the
        # constrained overhead ratio + the fan-out prefill reduction
        ("bench_structured.py", ["--smoke"],
         "/tmp/bench_extras_structured.log"),
        # resilience smoke: scripted chaos run (transient write fault +
        # NaN-streak rollback + corrupt-checkpoint fallback) — the
        # recovery-latency record makes regressions in the resilience
        # subsystem show up next to the perf numbers
        ("chaos_train.py", ["--smoke"], "/tmp/bench_extras_chaos.log"),
        # serving chaos drill: overload + NaN slot + wedged iteration +
        # crash loop through a REAL engine — asserts no stranded
        # futures, watchdog-restart recovery, and the crash-loop
        # circuit breaker (docs/serving.md "Overload & failure
        # behavior"); the hang-recovery latency is the record
        ("chaos_serve.py", ["--smoke"],
         "/tmp/bench_extras_chaos_serve.log"),
        # front-door chaos drill: replica kill / wedge / host-tier
        # corruption over a REAL 2-replica router — zero lost
        # requests, retried completions token-exact, checksum-gated
        # host restores (docs/serving.md "Front door")
        ("chaos_router.py", ["--smoke"],
         "/tmp/bench_extras_chaos_router.log"),
        # multi-PROCESS front-door drill: a real 2-replica fleet of
        # --replica_mode server processes behind the remote router,
        # one SIGKILLed mid-decode — zero stranded futures, failed-
        # over completions token-exact, respawn re-admitted via the
        # half-open canary, fleet invariants aggregated over HTTP
        # (docs/serving.md "Front door")
        ("chaos_fleet.py", ["--smoke"],
         "/tmp/bench_extras_chaos_fleet.log"),
        # live-weight chaos drill: rolling upgrade under load with the
        # draining replica killed mid-swap, a corrupt checkpoint
        # publish mid-watch, and an upgrade racing the disaggregated
        # handoff — zero 503s, every completion token-exact at its
        # admitted version, refused swaps contained (docs/serving.md
        # "Live weights & rolling upgrade")
        ("chaos_upgrade.py", ["--smoke"],
         "/tmp/bench_extras_chaos_upgrade.log"),
        # seeded chaos-mesh conformance (docs/resilience.md "Chaos
        # conformance"): sampled configs across the serving capability
        # matrix (adapters / disaggregation / live-weight swap in the
        # smoke set) under randomized fault schedules, every
        # system-wide invariant checked — a failing seed's record IS
        # its repro line
        ("chaos_mesh.py", ["--smoke"],
         "/tmp/bench_extras_chaos_mesh.log"),
        # seeded SLO-storm conformance (docs/serving.md "Overload,
        # degradation & SLO conformance"): trace-driven load at
        # 0.5x/1x/2x the calibrated sustainable rate against the
        # brownout ladder — TTFT/ITL bounds, goodput floor, shed
        # monotonicity, degrade-and-fully-revert, token-exact degraded
        # completions, plus one injected SLO regression the perf laws
        # must catch
        ("chaos_storm.py", ["--smoke"],
         "/tmp/bench_extras_chaos_storm.log"),
        # corrupt-dataset detection smoke: inject truncated-.bin /
        # garbage-.idx / out-of-range-pointer faults, prove each raises
        # a typed DatasetCorruptionError at open (docs/resilience.md
        # "corrupt-data detection")
        ("validate_dataset.py", ["--smoke"],
         "/tmp/bench_extras_validate_dataset.log"),
        ("bench_32k.py", [], "/tmp/bench_extras_32k.log"),
        # 1F1B bubble curve vs n_micro (VERDICT r4 #7): tick-count
        # analysis on one chip, full fit on a multi-device mesh
        ("bench_bubble.py", [], "/tmp/bench_extras_bubble.log"),
    ]
    for tool, extra_args, out in suites:
        cmd = [sys.executable, os.path.join(here, "tools", tool),
               "--out", out] + extra_args
        print(f"bench: extras: {tool} {' '.join(extra_args)} -> {out}",
              file=sys.stderr)
        try:
            subprocess.run(cmd, stdout=sys.stderr, stderr=sys.stderr,
                           timeout=budget)
        except Exception as e:
            print(f"bench: extras {tool} failed: {e!r}", file=sys.stderr)


if __name__ == "__main__":
    main()
