"""Benchmark: training-step throughput on the available accelerator.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

Metric: tokens/sec on a Llama-2-architecture training step (bf16 compute,
fp32 params/Adam), sized to the chip. vs_baseline compares achieved MFU
against the reference's published A100 number — Llama2-7B at 890 tokens/s/GPU
(ref: docs/guide/getting_started.md:200-201), i.e. 6*7e9*890/312e12 = 12.0%
MFU on A100-80GB bf16 — so the ratio is hardware-normalized.
"""
from __future__ import annotations

import dataclasses
import json
import time

import jax
import jax.numpy as jnp

from megatron_tpu.utils.platform import ensure_env_platform
ensure_env_platform()

# bf16 peak FLOP/s per chip by device kind (public spec sheets)
PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
    "TPU v6 lite": 918e12,
    "TPU7x": 2307e12,
    "cpu": 1e11,
}

A100_BASELINE_MFU = 6 * 7.0e9 * 890 / 312e12  # = 0.1198


def detect_peak(device) -> float:
    kind = getattr(device, "device_kind", "cpu")
    for k, v in PEAK_FLOPS.items():
        if kind.lower().startswith(k.lower()):
            return v
    return PEAK_FLOPS.get("TPU v4")


def main():
    from megatron_tpu.config import (MegatronConfig, OptimizerConfig,
                                     TrainingConfig, llama2_config)
    from megatron_tpu.training import init_train_state, make_train_step

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        # ~1.1B llama-architecture model: fits 1 chip with fp32 Adam state
        model = llama2_config(
            "tiny", num_layers=16, hidden_size=2048, num_attention_heads=16,
            num_kv_heads=16, ffn_hidden_size=5504, vocab_size=32000,
            seq_length=2048, compute_dtype="bfloat16",
            attention_impl="flash", recompute_granularity="selective")
        micro_bs, n_micro, iters, warmup = 4, 2, 10, 3
    else:  # smoke mode for CPU dev runs
        model = llama2_config("tiny", seq_length=256,
                              compute_dtype="bfloat16")
        micro_bs, n_micro, iters, warmup = 2, 1, 3, 1

    cfg = MegatronConfig(
        model=model,
        optimizer=OptimizerConfig(lr=1e-4, clip_grad=1.0),
        training=TrainingConfig(micro_batch_size=micro_bs,
                                global_batch_size=micro_bs * n_micro,
                                train_iters=iters),
    ).validate(n_devices=1)

    rng = jax.random.PRNGKey(0)
    state = init_train_state(rng, cfg)
    step = make_train_step(cfg)
    seq = cfg.model.seq_length
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (n_micro, micro_bs, seq + 1), 0,
        cfg.model.vocab_size, dtype=jnp.int32)
    batch = {"tokens": tokens,
             "loss_mask": jnp.ones((n_micro, micro_bs, seq), jnp.float32)}

    # param count for the FLOP model
    n_params = sum(p.size for p in jax.tree.leaves(state.params))

    for i in range(warmup):
        state, m = step(state, batch, jax.random.fold_in(rng, i))
    jax.block_until_ready(m["lm_loss"])

    t0 = time.perf_counter()
    for i in range(iters):
        state, m = step(state, batch, jax.random.fold_in(rng, warmup + i))
    jax.block_until_ready(m["lm_loss"])
    dt = time.perf_counter() - t0

    tokens_per_iter = n_micro * micro_bs * seq
    tok_s = tokens_per_iter * iters / dt
    flops_per_token = 6 * n_params  # fwd+bwd dense FLOPs, attention excluded
    mfu = tok_s * flops_per_token / detect_peak(dev)
    vs_baseline = mfu / A100_BASELINE_MFU

    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(tok_s, 1),
        "unit": f"tok/s ({n_params/1e9:.2f}B params, {dev.device_kind}, "
                f"MFU={mfu:.3f})",
        "vs_baseline": round(vs_baseline, 3),
    }))


if __name__ == "__main__":
    main()
