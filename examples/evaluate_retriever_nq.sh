#!/bin/bash
# ORQA retriever eval on Natural Questions
# (ref: examples/evaluate_retriever_nq.sh): embed the evidence once, then
# score top-k retrieval accuracy.
set -e
CKPT=${CKPT:-ckpts/ict}
EVIDENCE=${EVIDENCE:-psgs_w100.tsv}
VOCAB=${VOCAB:-vocab.txt}

python tools/create_doc_index.py \
    --load "$CKPT" --evidence_data_path "$EVIDENCE" \
    --embedding_path evidence.npz --vocab_file "$VOCAB"

python -m tasks.main --task NQ \
    --load "$CKPT" --valid_data nq-test.csv \
    --evidence_data_path "$EVIDENCE" --embedding_path evidence.npz \
    --tokenizer_type BertWordPieceLowerCase --vocab_file "$VOCAB" \
    --faiss_topk_retrievals 100
