#!/bin/bash
# WikiText-103 PPL + LAMBADA accuracy (ref: examples/evaluate_zeroshot_gpt.sh).
set -e
CKPT=${CKPT:-ckpts/llama2-7b-ft}
TOK=${TOK:-meta-llama/Llama-2-7b-hf}

python -m tasks.main --task WIKITEXT103 \
    --valid_data wiki.test.tokens \
    --load "$CKPT" --tokenizer_type HFTokenizer --tokenizer_model "$TOK" \
    --overlapping_eval 32

python -m tasks.main --task LAMBADA \
    --valid_data lambada_test.jsonl --strict_lambada \
    --load "$CKPT" --tokenizer_type HFTokenizer --tokenizer_model "$TOK"
