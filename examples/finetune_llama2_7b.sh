#!/bin/bash
# Llama-2-7B finetune on a v5e-8 (TP=8 + SP + ZeRO-1) — the TPU-native
# equivalent of the reference's examples/finetune.sh llama2 recipe.
# Prereqs: converted weights (tools/convert_hf_checkpoint.py import) and a
# preprocessed .bin/.idx corpus (tools/preprocess_data.py).

CKPT=${CKPT:-ckpts/llama2-7b}
DATA=${DATA:-data/corpus}
SAVE=${SAVE:-ckpts/llama2-7b-ft}

python finetune.py \
    --model llama2-7b \
    --load "$CKPT" --finetune \
    --tensor_model_parallel_size 8 \
    --sequence_parallel \
    --use_distributed_optimizer \
    --bf16 --use_flash_attn --recompute_granularity selective \
    --data_path "$DATA" --split 989,10,1 \
    --train_iters 500 --global_batch_size 1000 --micro_batch_size 2 \
    --lr 1e-5 --lr_decay_style cosine --lr_warmup_iters 50 \
    --weight_decay 0.1 --clip_grad 1.0 \
    --log_interval 1 --save_interval 100 --eval_interval 100 \
    --save "$SAVE" --tensorboard_dir runs/llama2-7b-ft
