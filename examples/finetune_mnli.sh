#!/bin/bash
# GLUE MNLI classification finetune from a pretrained BERT checkpoint
# (ref: examples/finetune_mnli_distributed.sh). QQP: swap --task QQP and
# the TSV paths.
VOCAB=${VOCAB:-vocab.txt}
CKPT=${CKPT:-ckpts/bert}

python -m tasks.main --task MNLI \
    --train_data glue/MNLI/train.tsv \
    --valid_data glue/MNLI/dev_matched.tsv glue/MNLI/dev_mismatched.tsv \
    --pretrained_checkpoint "$CKPT" \
    --tokenizer_type BertWordPieceLowerCase --vocab_file "$VOCAB" \
    --seq_length 128 --micro_batch_size 32 --epochs 3 --lr 5e-5
