#!/bin/bash
# RACE multiple-choice finetune from a pretrained BERT checkpoint
# (ref: examples/finetune_race_distributed.sh).
VOCAB=${VOCAB:-vocab.txt}
CKPT=${CKPT:-ckpts/bert}

python -m tasks.main --task RACE \
    --train_data race/train/middle race/train/high \
    --valid_data race/dev/middle race/dev/high \
    --pretrained_checkpoint "$CKPT" \
    --tokenizer_type BertWordPieceLowerCase --vocab_file "$VOCAB" \
    --seq_length 384 --micro_batch_size 8 --epochs 3 --lr 1e-5
