#!/bin/bash
# Supervised DPR-style retriever finetuning on NQ
# (ref: examples/finetune_retriever_distributed.sh).
VOCAB=${VOCAB:-vocab.txt}

python -m tasks.main --task RET-FINETUNE-NQ \
    --train_data nq-train.json --valid_data nq-dev.json \
    --pretrained_checkpoint ckpts/ict \
    --vocab_file "$VOCAB" --retriever_seq_length 256 \
    --micro_batch_size 8 --epochs 2 --lr 2e-5 \
    --train_with_neg --train_hard_neg 1 --retriever_score_scaling
