#!/bin/bash
# MSDP multi-stage pipeline (ref: examples/msdp/*.sh): prep the WoW/WoI
# TSVs, select prompts, generate knowledge/responses via the serving API,
# then F1-evaluate. Stages 3/4 need a running text-generation server
# (examples/serve.sh).
set -e
D=${D:-msdp}

# 1. dataset prep (writes 4-col TSVs + knowledge/response ref files)
python -m tasks.msdp.preprocessing --func process_wow_dataset \
    --raw_file "$D/wow_test.json" --processed_file "$D/wow_test.tsv" \
    --knwl_ref_file "$D/knwl_ref.txt" --resp_ref_file "$D/resp_ref.txt"

# 2. knowledge-generation prompt selection (dense retrieval over train)
python -m tasks.msdp.preprocessing --func prompt_selection_for_knowledge_generation \
    --test_file "$D/wow_test.tsv" --train_file "$D/wow_train.tsv" \
    --model_file ckpts/biencoder --processed_file "$D/knwl_prompts.json" \
    --data_type wow_seen

# 3. generate knowledge via the serving API (response stage: rerun with
#    --prompt_type response on the spliced TSV from step 4)
python -m tasks.msdp.main --task MSDP-PROMPT --prompt_type knowledge \
    --sample_input_file "$D/wow_test.tsv" --prompt_file "$D/knwl_prompts.json" \
    --sample_output_file "$D/knwl_gen.txt" --megatron_api_url localhost:5000/api
python -m tasks.msdp.preprocessing --func prepare_input_for_response_generation \
    --test_file "$D/wow_test.tsv" --knwl_gen_file "$D/knwl_gen.txt" \
    --processed_file "$D/resp_input.tsv"

# 5. F1 against the reference files
python -m tasks.msdp.main --task MSDP-EVAL-F1 \
    --guess_file "$D/knwl_gen.txt" --answer_file "$D/knwl_ref.txt"
