#!/bin/bash
# BERT MLM+NSP pretraining (ref: examples/pretrain_bert.sh).
DATA=${DATA:-data/bert_corpus}
VOCAB=${VOCAB:-vocab.txt}

python pretrain_bert.py \
    --num_layers 12 --hidden_size 768 --num_attention_heads 12 \
    --seq_length 512 --max_position_embeddings 512 \
    --tokenizer_type BertWordPieceLowerCase --vocab_file "$VOCAB" \
    --data_path "$DATA" \
    --train_iters 100000 --global_batch_size 256 --micro_batch_size 8 \
    --lr 1e-4 --lr_decay_style linear --lr_warmup_fraction 0.01 \
    --weight_decay 0.01 --clip_grad 1.0 --mask_prob 0.15 \
    --log_interval 100 --save_interval 2000 \
    --save ckpts/bert
