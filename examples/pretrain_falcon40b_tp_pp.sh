#!/bin/bash
# Falcon-40B (MQA) on a v5p-32 slice: TP=8 x PP=4 x DP — BASELINE config 3.
# The pipeline runs the hand-scheduled 1F1B (default): per-stage activation
# memory is flat in the microbatch count, so large global batches
# (n_micro >> pp) shrink the bubble for free. On memory headroom, add
# --pipeline_store_activations to drop the backward-slot recompute
# (the reference's no-recompute mode; pair with a lighter
# --recompute_granularity).
# Prereqs: converted weights (tools/convert_hf_checkpoint.py --model
# falcon-40b) and a preprocessed .bin/.idx corpus.

CKPT=${CKPT:-ckpts/falcon-40b}
DATA=${DATA:-data/corpus}
SAVE=${SAVE:-ckpts/falcon-40b-ft}

python finetune.py \
    --model falcon-40b \
    --load "$CKPT" --finetune \
    --tensor_model_parallel_size 8 \
    --pipeline_model_parallel_size 4 \
    --sequence_parallel \
    --use_distributed_optimizer \
    --bf16 --use_flash_attn --recompute_granularity full \
    --data_path "$DATA" --split 989,10,1 \
    --train_iters 500 --global_batch_size 1024 --micro_batch_size 1 \
    --lr 1e-5 --lr_decay_style cosine --lr_warmup_iters 50 \
    --weight_decay 0.1 --clip_grad 1.0 \
    --log_interval 1 --save_interval 100 --eval_interval 100 \
    --save "$SAVE" --tensorboard_dir runs/falcon-40b-ft
