#!/bin/bash
# GPT-2-style pretraining from scratch on one host
# (ref: examples/pretrain_gpt.sh / pretrain_gpt_distributed_with_mp.sh).
# finetune.py without --load trains from init; --model gpt2 gives the
# GPT-2 arch preset (learned positions, gelu, tied head).
DATA=${DATA:-data/corpus}

python finetune.py \
    --model gpt2 \
    --data_path "$DATA" --split 949,50,1 \
    --train_iters 500000 --global_batch_size 512 --micro_batch_size 8 \
    --bf16 --lr 1.5e-4 --lr_decay_style cosine --lr_warmup_iters 2000 \
    --weight_decay 0.1 --clip_grad 1.0 \
    --log_interval 10 --save_interval 1000 --eval_interval 1000 \
    --save ckpts/gpt2 --tensorboard_dir runs/gpt2
