#!/bin/bash
# Llama-2-70B (GQA) on a v5p-128 slice: TP=8 x PP=4 x DP=4 — BASELINE
# config 4 and the north-star shape (>=45% MFU, loss-curve-matched).
# ZeRO-1 (--use_distributed_optimizer) dp-shards the Adam state; the
# non-stacked-param exclusion under pp costs <0.5% HBM at this shape
# (PERF_NOTES.md). vpp keeps the reference's interleaved checkpoint
# layout under the 1F1B memory bound if you need layout parity:
# add --num_layers_per_virtual_pipeline_stage 10 (80 layers / pp4 / 2).
# Prereqs: converted weights (tools/convert_hf_checkpoint.py --model
# llama2-70b) and a preprocessed .bin/.idx corpus. Launch once per host
# under multi-host (parallel/multihost.py picks up the JAX coordinator
# env; all hosts run the identical command).

CKPT=${CKPT:-ckpts/llama2-70b}
DATA=${DATA:-data/corpus}
SAVE=${SAVE:-ckpts/llama2-70b-pt}

python finetune.py \
    --model llama2-70b \
    --load "$CKPT" --finetune \
    --tensor_model_parallel_size 8 \
    --pipeline_model_parallel_size 4 \
    --sequence_parallel \
    --use_distributed_optimizer \
    --bf16 --recompute_granularity selective \
    --data_path "$DATA" --split 989,10,1 \
    --train_iters 1000 --global_batch_size 1024 --micro_batch_size 1 \
    --lr 1.5e-4 --lr_decay_style cosine --lr_warmup_iters 100 \
    --adam_beta1 0.9 --adam_beta2 0.95 \
    --weight_decay 0.1 --clip_grad 1.0 \
    --log_interval 1 --save_interval 200 --eval_interval 200 \
    --save "$SAVE" --tensorboard_dir runs/llama2-70b
