#!/bin/bash
# 32k long-context training: flash kernel + RoPE scaling + full remat +
# context parallelism (BASELINE config 5; PERF_NOTES has on-chip numbers).
DATA=${DATA:-data/corpus}

python finetune.py \
    --model llama2-7b --seq_length 32768 --rope_scaling_factor 8.0 \
    --use_flash_attn --recompute_granularity full \
    --context_parallel_size 4 --context_parallel_algo ring \
    --bf16 --use_distributed_optimizer \
    --data_path "$DATA" \
    --train_iters 1000 --global_batch_size 32 --micro_batch_size 1 \
    --lr 1e-5 --save ckpts/llama2-32k
