#!/bin/bash
# Mixture-of-Experts pretraining (beyond the reference — SURVEY.md §2.8
# lists expert parallelism as absent there; models/moe.py).
# 8 experts top-2 over a llama-style backbone; experts shard over the
# tensor axis (tp=8 -> one expert per device), so dp scales the batch on
# top. num_experts must divide evenly by tp; pipeline_parallel stays 1.
DATA=${DATA:-data/corpus}
TOKENIZER=${TOKENIZER:-tokenizer.model}

python finetune.py \
    --num_layers 24 --hidden_size 2048 --num_attention_heads 16 \
    --seq_length 2048 --max_position_embeddings 2048 \
    --use_rms_norm --glu_activation swiglu \
    --position_embedding_type rotary \
    --num_experts 8 --moe_top_k 2 \
    --moe_capacity_factor 1.25 --moe_aux_loss_coeff 0.01 \
    --tensor_model_parallel_size 8 --sequence_parallel \
    --tokenizer_type SentencePieceTokenizer --tokenizer_model "$TOKENIZER" \
    --data_path "$DATA" --split 949,50,1 \
    --train_iters 100000 --global_batch_size 256 --micro_batch_size 2 \
    --bf16 --lr 3e-4 --lr_decay_style cosine --lr_warmup_iters 1000 \
    --weight_decay 0.1 --clip_grad 1.0 --attention_impl flash \
    --log_interval 10 --save_interval 1000 --eval_interval 1000 \
    --save ckpts/moe8x --tensorboard_dir runs/moe8x
