#!/bin/bash
# Text-generation REST server + CLI client
# (ref: examples/run_text_generation_server_345M.sh).
set -e
CKPT=${CKPT:-ckpts/llama2-7b-ft}
TOK=${TOK:-meta-llama/Llama-2-7b-hf}
PORT=${PORT:-5000}

python tools/run_text_generation_server.py \
    --load "$CKPT" --tokenizer_type HFTokenizer --tokenizer_model "$TOK" \
    --port "$PORT" &
SERVER_PID=$!
trap 'kill $SERVER_PID 2>/dev/null' EXIT

# wait for the server (checkpoint load + first compile can take minutes)
for _ in $(seq 1 120); do
    if curl -s -o /dev/null "http://localhost:$PORT/api" -X PUT \
         -H 'Content-Type: application/json' \
         -d '{"prompts": ["hi"], "tokens_to_generate": 1}'; then
        break
    fi
    sleep 5
done

python tools/text_generation_cli.py "localhost:$PORT"
