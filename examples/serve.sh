#!/bin/bash
# Text-generation REST server + CLI client
# (ref: examples/run_text_generation_server_345M.sh).
#
# The server runs the continuous-batching engine by default
# (megatron_tpu/serving): NUM_SLOTS concurrent decode slots over a
# pooled KV cache, bounded admission queue, 429 backpressure.
# SERIAL=1 restores the reference's one-lock serial path.
# LOAD=1 runs the concurrent-load micro-bench against the live server
# instead of the interactive CLI (tools/serving_bench.py --url).
set -e
CKPT=${CKPT:-ckpts/llama2-7b-ft}
TOK=${TOK:-meta-llama/Llama-2-7b-hf}
PORT=${PORT:-5000}
NUM_SLOTS=${NUM_SLOTS:-8}
MAX_QUEUE=${MAX_QUEUE:-64}

EXTRA=()
[ -n "$SERIAL" ] && EXTRA+=(--serial)

python tools/run_text_generation_server.py \
    --load "$CKPT" --tokenizer_type HFTokenizer --tokenizer_model "$TOK" \
    --port "$PORT" --num_slots "$NUM_SLOTS" --max_queue "$MAX_QUEUE" \
    "${EXTRA[@]}" &
SERVER_PID=$!
trap 'kill $SERVER_PID 2>/dev/null' EXIT

# wait for the server (checkpoint load + first compile can take minutes)
for _ in $(seq 1 120); do
    if curl -s -o /dev/null "http://localhost:$PORT/api" -X PUT \
         -H 'Content-Type: application/json' \
         -d '{"prompts": ["hi"], "tokens_to_generate": 1}'; then
        break
    fi
    sleep 5
done

if [ -n "$LOAD" ]; then
    # concurrent-load mode: offered load vs latency/throughput record
    python tools/serving_bench.py --url "localhost:$PORT" \
        --requests "${REQUESTS:-32}" --rps "${RPS:-0}" \
        --new "${NEW_TOKENS:-32}" --out /tmp/serving_bench.log
    curl -s "http://localhost:$PORT/metrics"; echo
else
    python tools/text_generation_cli.py "localhost:$PORT"
fi
