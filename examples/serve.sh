#!/bin/bash
# Text-generation REST server + CLI client
# (ref: examples/run_text_generation_server_345M.sh).
CKPT=${CKPT:-ckpts/llama2-7b-ft}
TOK=${TOK:-meta-llama/Llama-2-7b-hf}

python tools/run_text_generation_server.py \
    --load "$CKPT" --tokenizer_type HFTokenizer --tokenizer_model "$TOK" \
    --port 5000 &
sleep 30
python tools/text_generation_cli.py localhost:5000
