"""Main training entry point: pretrain/finetune GPT, Llama, or Falcon.

TPU-native equivalent of the reference's finetune.py (the primary entry,
ref: /root/reference/finetune.py:92-151) and the `pretrain` driver it calls
(ref: megatron/training.py:54-167). One process drives all local devices —
no torchrun; the mesh replaces process groups (SURVEY.md §7).

  python finetune.py --model llama2-7b --data_path 1.0 /data/corpus_document \
      --tokenizer_type SentencePieceTokenizer --tokenizer_model tok.model \
      --tensor_model_parallel_size 8 --train_iters 1000 --save ckpts/run1
"""
from __future__ import annotations

import sys

import jax

from megatron_tpu.utils.platform import ensure_env_platform
ensure_env_platform()


def build_data(cfg, tokenizer, consumed_samples: int, mesh=None):
    """(ref: megatron/training.py:855-939 build_train_valid_test_data_iterators
    + finetune.py:107 dataset provider)"""
    from megatron_tpu.data import BatchIterator, build_train_valid_test_datasets

    tr = cfg.training
    dp = cfg.parallel.data_parallel or 1
    eval_iters = ((tr.train_iters // max(tr.eval_interval, 1)) + 1) * tr.eval_iters
    samples = (tr.train_iters * tr.global_batch_size,
               eval_iters * tr.global_batch_size,
               tr.eval_iters * tr.global_batch_size)
    if cfg.data.train_data_path or cfg.data.valid_data_path \
            or cfg.data.test_data_path:
        # per-split corpora (ref: --train_data_path/--valid_data_path/
        # --test_data_path). The train corpus may also come from
        # --data_path (arguments.py forbids both train sources at once);
        # --split is ignored in this mode — each corpus IS its split.
        def one(paths, n):
            if not paths:
                return None
            ds, _, _ = build_train_valid_test_datasets(
                list(paths), "1,0,0", cfg.model.seq_length, tr.seed,
                n, 0, 0, strict_data=cfg.data.strict_data)
            return ds
        train_ds = one(cfg.data.train_data_path or cfg.data.data_path,
                       samples[0])
        valid_ds = one(cfg.data.valid_data_path, samples[1])
        test_ds = one(cfg.data.test_data_path, samples[2])
    else:
        train_ds, valid_ds, test_ds = build_train_valid_test_datasets(
            cfg.data.data_path, cfg.data.split, cfg.model.seq_length,
            tr.seed, *samples, strict_data=cfg.data.strict_data)

    host_rows = None
    if mesh is not None and jax.process_count() > 1:
        # pod-scale: this host only tokenizes its own dp rows (see
        # multihost.make_global_batch — other rows are never read here).
        # THE mesh from main(): host_rows must match the exact device
        # layout make_global_batch shards against
        from megatron_tpu.parallel.multihost import process_batch_rows
        host_rows = process_batch_rows(mesh, tr.micro_batch_size * dp)

    def make_iter(ds, consumed):
        if ds is None:
            return None
        return BatchIterator(
            ds, tr.micro_batch_size, dp, cfg.num_microbatches,
            consumed_samples=consumed, dataloader_type=cfg.data.dataloader_type,
            seed=tr.seed, eod_token=tokenizer.eod if tokenizer else None,
            reset_position_ids=cfg.data.reset_position_ids,
            reset_attention_mask=cfg.data.reset_attention_mask,
            eod_mask_loss=cfg.data.eod_mask_loss,
            host_rows=host_rows)

    return (make_iter(train_ds, consumed_samples), make_iter(valid_ds, 0),
            make_iter(test_ds, 0))


def main(argv=None):
    from megatron_tpu.arguments import parse_cli
    from megatron_tpu.config import MegatronConfig
    from megatron_tpu.data import build_tokenizer, restore_data_state
    from megatron_tpu.parallel.mesh import build_mesh
    from megatron_tpu.training import init_train_state
    from megatron_tpu.training import checkpointing as ckpt
    from megatron_tpu.training.loop import train
    from megatron_tpu.utils.logging import print_rank_0

    n_devices = len(jax.devices())
    cfg, args = parse_cli(argv, n_devices=n_devices)

    # --use_checkpoint_args: architecture comes from the checkpoint
    # (ref: megatron/checkpointing.py:476-558)
    if args.use_checkpoint_args and cfg.training.load_dir:
        loaded_cfg = ckpt.load_config_from_checkpoint(cfg.training.load_dir)
        if loaded_cfg is not None:
            import dataclasses
            cfg = dataclasses.replace(cfg, model=loaded_cfg.model)
            cfg = cfg.validate(n_devices=n_devices)

    print_rank_0(f"devices: {n_devices} | mesh: tp={cfg.parallel.tensor_parallel} "
                 f"pp={cfg.parallel.pipeline_parallel} "
                 f"dp={cfg.parallel.data_parallel} "
                 f"sp={cfg.parallel.sequence_parallel}")
    mesh = build_mesh(cfg.parallel) if n_devices > 1 else None

    tokenizer = None
    if cfg.data.tokenizer_model or cfg.data.vocab_file:
        tokenizer = build_tokenizer(
            cfg.data.tokenizer_type, vocab_file=cfg.data.vocab_file,
            merge_file=cfg.data.merge_file,
            tokenizer_model=cfg.data.tokenizer_model,
            vocab_extra_ids=cfg.data.vocab_extra_ids,
            vocab_extra_ids_list=cfg.data.vocab_extra_ids_list,
            new_tokens=cfg.data.new_tokens)
        import dataclasses
        cfg = dataclasses.replace(cfg, model=dataclasses.replace(
            cfg.model, vocab_size=tokenizer.vocab_size))

    rng = jax.random.PRNGKey(cfg.training.seed)
    state = init_train_state(rng, cfg)
    start_iteration, consumed = 0, 0
    data_state, quarantine = None, []
    load_dir = cfg.training.load_dir or cfg.training.checkpoint_dir
    if load_dir:
        loaded = ckpt.load_checkpoint(
            load_dir, state, finetune=cfg.training.finetune,
            no_load_optim=cfg.training.no_load_optim,
            resilience=cfg.resilience)
        _, start_iteration, consumed = loaded
        data_state, quarantine = loaded.data_state, loaded.quarantine
        if loaded.state is not None:
            state = loaded.state

    train_it, valid_it, _ = build_data(cfg, tokenizer, consumed, mesh=mesh)
    assert train_it is not None, "--data_path produced no training data"
    restore_data_state(train_it, data_state)

    if getattr(args, "lora_rank", 0):
        # LoRA finetune: train ONLY the low-rank adapter factors with
        # the (possibly checkpoint-loaded) base frozen, then export the
        # versioned .npz the serving bank loads (--adapter_slots /
        # ServingEngine.register_adapter) — the training side feeding
        # the serving side end to end (training/lora.py).
        from megatron_tpu.training.lora import run_lora_finetune
        export = args.lora_export or (
            f"{cfg.training.checkpoint_dir}/adapter.npz"
            if cfg.training.checkpoint_dir else "adapter.npz")
        _, last_loss = run_lora_finetune(
            cfg, state.params, train_it, rank=args.lora_rank,
            alpha=args.lora_alpha, iters=cfg.training.train_iters,
            lr=cfg.optimizer.lr, seed=cfg.training.seed,
            export_path=export,
            log_interval=cfg.training.log_interval)
        print_rank_0(f"lora finetune done: final loss {last_loss:.4f}, "
                     f"adapter at {export}")
        return 0

    save_fn = None
    if cfg.training.checkpoint_dir:
        def save_fn(st, iteration, consumed_samples, data_state=None,
                    quarantine=None):
            # data_state/quarantine: the loop's exact-resume snapshot of
            # the training iterator, persisted in checkpoint metadata so
            # a restart replays the identical batch sequence
            ckpt.save_checkpoint(cfg.training.checkpoint_dir, st, cfg,
                                 iteration, consumed_samples,
                                 data_state=data_state,
                                 quarantine=quarantine)

    # divergence-rollback hooks (docs/resilience.md): restore the newest
    # valid checkpoint and rebuild the data stream at its EXACT saved
    # position — the loop replays the identical order and quarantines
    # the poisoned step window (never a re-seeded order). Rollback only
    # targets checkpoints THIS run writes (--save): restoring the --load
    # base would resurrect its iteration counter / optimizer state (a
    # finetune base "resumes" at its pretraining iteration and the loop
    # would just exit)
    load_fn = None
    if cfg.training.checkpoint_dir:
        def load_fn():
            return ckpt.load_checkpoint(cfg.training.checkpoint_dir,
                                        state,
                                        resilience=cfg.resilience)

    def reset_data_fn(consumed_samples, rollbacks, data_state=None):
        it, _, _ = build_data(cfg, tokenizer, consumed_samples,
                              mesh=mesh)
        restore_data_state(it, data_state)
        return it

    state, consumed = train(
        cfg, train_it, valid_it, mesh=mesh, state=state, rng=rng,
        start_iteration=start_iteration, consumed_samples=consumed,
        save_fn=save_fn, load_fn=load_fn, reset_data_fn=reset_data_fn,
        quarantine_log=quarantine)
    print_rank_0(f"training done at consumed_samples={consumed}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
