"""megatron_tpu: TPU-native Megatron-capability LLM training framework.

Importing the package installs jax compatibility shims for older jax
releases (utils/jax_compat.py) — a no-op on current jax — so the
parallelism code's `jax.set_mesh` / `jax.shard_map` call sites work
across the jax versions the deployment images actually carry.
"""
from megatron_tpu.utils.jax_compat import ensure_jax_compat

ensure_jax_compat()
del ensure_jax_compat
