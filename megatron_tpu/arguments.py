"""Megatron-compatible CLI flag surface -> MegatronConfig.

TPU-native bridge for the reference's argparse config system
(ref: megatron/arguments.py:14-1073 — ~170 flags in 16 groups, stored in a
mutable global namespace). Here flags parse into the frozen dataclass tree
(megatron_tpu/config.py); the flag NAMES match the reference so launch
scripts port by changing only the launcher. `extra_args_provider` mirrors
the extension hook (ref: megatron/arguments.py:14-20, finetune.py:129-138).
Validation/derivation lives in MegatronConfig.validate
(ref: arguments.py:52-345 validate_args).
"""
from __future__ import annotations

import argparse
from typing import Callable, Optional

from megatron_tpu.config import (DataConfig, MegatronConfig, ModelConfig,
                                 OptimizerConfig, ParallelConfig,
                                 ResilienceConfig, ServingConfig,
                                 TrainingConfig)


def build_parser(extra_args_provider: Optional[Callable] = None
                 ) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="megatron_tpu",
                                allow_abbrev=False)

    g = p.add_argument_group("model")
    # default=None so an EXPLICIT "--num_layers 2" is distinguishable from
    # a defaulted one (resolved to 2 in _apply_compat after the
    # --encoder_num_layers alias is considered)
    g.add_argument("--num_layers", type=int, default=None)
    g.add_argument("--hidden_size", type=int, default=128)
    g.add_argument("--ffn_hidden_size", type=int, default=None)
    g.add_argument("--num_attention_heads", type=int, default=4)
    g.add_argument("--num_attention_heads_kv", type=int, default=None,
                   dest="num_kv_heads")
    g.add_argument("--kv_channels", type=int, default=None)
    # default None so model presets keep their native seq_length
    g.add_argument("--seq_length", type=int, default=None)
    g.add_argument("--max_position_embeddings", type=int, default=None)
    g.add_argument("--make_vocab_size_divisible_by", type=int, default=128)
    g.add_argument("--layernorm_epsilon", type=float, default=1e-5,
                   dest="norm_epsilon")
    g.add_argument("--use_rms_norm", action="store_true")
    g.add_argument("--use_post_ln", action="store_true")
    g.add_argument("--use_bias", action="store_true")
    g.add_argument("--parallel_attn", action="store_true")
    g.add_argument("--parallel_layernorm", action="store_true")
    g.add_argument("--use_rotary_emb", action="store_true", default=True)
    g.add_argument("--no_rotary_emb", dest="use_rotary_emb",
                   action="store_false")
    g.add_argument("--position_embedding", action="store_true",
                   dest="use_position_embedding")
    g.add_argument("--rope_theta", type=float, default=10000.0)
    # Mistral-style banded causal attention (None = full causal)
    g.add_argument("--sliding_window", type=int, default=None)
    g.add_argument("--rope_scaling_factor", type=float, default=1.0)
    g.add_argument("--glu_activation", type=str, default=None,
                   choices=["swiglu", "geglu", "reglu", "liglu"])
    g.add_argument("--activation", type=str, default=None)
    g.add_argument("--hidden_dropout", type=float, default=0.0)
    g.add_argument("--attention_dropout", type=float, default=0.0)
    g.add_argument("--lima_dropout", action="store_true")
    g.add_argument("--drop_path_rate", type=float, default=0.0)
    g.add_argument("--tie_embed_logits", action="store_true")
    g.add_argument("--init_method_std", type=float, default=0.02)
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--fp32", action="store_true")
    g.add_argument("--use_flash_attn", action="store_true")
    # explicit impl selection (beyond the reference's boolean): overrides
    # preset defaults in BOTH directions — e.g. `--model llama2-7b
    # --attention_impl dot` opts out of the preset's flash default
    g.add_argument("--attention_impl", type=str, default=None,
                   choices=["dot", "flash", "ring", "ulysses"])
    g.add_argument("--recompute_granularity", type=str, default="none",
                   choices=["none", "selective", "full"])
    # TPU-native counterpart of the reference's TE fp8 mode (the --fp8_*
    # flags below stay inert: v5e/v5p have no fp8 datapath; int8 is the
    # hardware's low-precision GEMM lever — see ops/quantized.py)
    g.add_argument("--quantized_gemm", type=str, default="none",
                   choices=["none", "int8"])
    # Mixture-of-Experts (beyond the reference — SURVEY.md §2.8 lists EP
    # as absent there; models/moe.py)
    g.add_argument("--num_experts", type=int, default=1)
    g.add_argument("--moe_top_k", type=int, default=2)
    g.add_argument("--moe_capacity_factor", type=float, default=1.25)
    g.add_argument("--moe_aux_loss_coeff", type=float, default=1e-2)
    g.add_argument("--moe_dispatch", type=str, default="sort",
                   choices=["sort", "dense"])
    g.add_argument("--model", type=str, default=None,
                   help="preset name (llama2-7b, falcon-40b, gpt2, ...)")

    g = p.add_argument_group("parallel")
    g.add_argument("--tensor_model_parallel_size", type=int, default=1,
                   dest="tensor_parallel")
    g.add_argument("--pipeline_model_parallel_size", type=int, default=1,
                   dest="pipeline_parallel")
    g.add_argument("--context_parallel_size", type=int, default=1,
                   dest="context_parallel")
    g.add_argument("--num_layers_per_virtual_pipeline_stage", type=int,
                   default=None)
    g.add_argument("--pipeline_schedule", type=str, default="1f1b",
                   choices=["1f1b", "gpipe"],
                   help="pp execution schedule: 1f1b bounds per-stage "
                        "memory by pp; gpipe is the lockstep fallback "
                        "(required for vpp>1 interleaving)")
    g.add_argument("--pipeline_store_activations", action="store_true",
                   help="1F1B: carry forward vjp residuals instead of "
                        "recomputing chunk forwards in the backward slot "
                        "(the reference's no-recompute default; ~1/3 less "
                        "pipeline compute, more memory)")
    g.add_argument("--sequence_parallel", action="store_true")
    g.add_argument("--expert_axis", type=str, default="tp",
                   choices=["tp", "dp"],
                   help="mesh axis the MoE expert bank shards over: tp "
                        "(default) or dp (GShard-style expert "
                        "parallelism over the data axis)")
    g.add_argument("--use_distributed_optimizer", action="store_true")
    g.add_argument("--context_parallel_algo", type=str, default="ring",
                   choices=["ring", "ulysses"],
                   help="cp>1 attention: K/V-rotation ring (no head "
                        "constraint) or all-to-all head-parallel ulysses "
                        "(heads %% cp == 0, lower comm volume)")

    g = p.add_argument_group("training")
    g.add_argument("--micro_batch_size", type=int, default=1)
    g.add_argument("--global_batch_size", type=int, default=None)
    g.add_argument("--rampup_batch_size", nargs=3, type=int, default=None)
    g.add_argument("--train_iters", type=int, default=100)
    g.add_argument("--eval_interval", type=int, default=1000)
    g.add_argument("--eval_iters", type=int, default=10)
    g.add_argument("--log_interval", type=int, default=10)
    g.add_argument("--save_interval", type=int, default=None)
    g.add_argument("--exit_interval", type=int, default=None)
    g.add_argument("--exit_duration_in_mins", type=float, default=None)
    g.add_argument("--seed", type=int, default=1234)
    # jax.profiler trace window (SURVEY.md §5 profiling)
    g.add_argument("--profile", action="store_true")
    g.add_argument("--profile_step_start", type=int, default=10)
    g.add_argument("--profile_step_end", type=int, default=12)
    g.add_argument("--profile_dir", type=str, default=None)
    g.add_argument("--save", type=str, default=None, dest="checkpoint_dir")
    g.add_argument("--load", type=str, default=None, dest="load_dir")
    g.add_argument("--finetune", action="store_true")
    g.add_argument("--no_load_optim", action="store_true")
    g.add_argument("--no_load_rng", action="store_true")
    g.add_argument("--use_checkpoint_args", action="store_true")
    g.add_argument("--wandb_logger", action="store_true")
    g.add_argument("--tensorboard_dir", type=str, default=None)
    g.add_argument("--sync_metrics", action="store_true",
                   help="fetch loss/found_inf every step (step-exact "
                        "debugging); default is ONE metrics transfer "
                        "per log window with the loop dispatching "
                        "ahead of the device (training/loop.py)")

    g = p.add_argument_group("optimizer")
    g.add_argument("--optimizer", type=str, default="adam",
                   choices=["adam", "sgd"])
    g.add_argument("--lr", type=float, default=3e-4)
    g.add_argument("--min_lr", type=float, default=0.0)
    g.add_argument("--lr_decay_style", type=str, default="cosine")
    g.add_argument("--lr_decay_iters", type=int, default=None)
    g.add_argument("--lr_warmup_iters", type=int, default=0)
    g.add_argument("--lr_warmup_fraction", type=float, default=None)
    g.add_argument("--weight_decay", type=float, default=0.01)
    g.add_argument("--start_weight_decay", type=float, default=None)
    g.add_argument("--end_weight_decay", type=float, default=None)
    g.add_argument("--weight_decay_incr_style", type=str, default="constant")
    g.add_argument("--adam_beta1", type=float, default=0.9)
    g.add_argument("--adam_beta2", type=float, default=0.999)
    g.add_argument("--adam_eps", type=float, default=1e-8)
    g.add_argument("--sgd_momentum", type=float, default=0.9)
    g.add_argument("--clip_grad", type=float, default=1.0)
    g.add_argument("--loss_scale", type=float, default=None)
    g.add_argument("--initial_loss_scale", type=float, default=2.0 ** 32)
    g.add_argument("--min_loss_scale", type=float, default=1.0)
    g.add_argument("--loss_scale_window", type=int, default=1000)
    g.add_argument("--hysteresis", type=int, default=2)
    g.add_argument("--log_num_zeros_in_grad", action="store_true")

    g = p.add_argument_group("data")
    g.add_argument("--data_path", nargs="*", default=None)
    g.add_argument("--split", type=str, default="969,30,1")
    g.add_argument("--tokenizer_type", type=str,
                   default="SentencePieceTokenizer")
    g.add_argument("--vocab_file", type=str, default=None)
    g.add_argument("--merge_file", type=str, default=None)
    g.add_argument("--tokenizer_model", type=str, default=None,
                   dest="tokenizer_model")
    g.add_argument("--vocab_size", type=int, default=32000)
    g.add_argument("--dataloader_type", type=str, default="single",
                   choices=["single", "cyclic"])
    g.add_argument("--num_workers", type=int, default=2)
    g.add_argument("--reset_position_ids", action="store_true")
    g.add_argument("--reset_attention_mask", action="store_true")
    g.add_argument("--eod_mask_loss", action="store_true")
    g.add_argument("--vocab_extra_ids", type=int, default=0)
    g.add_argument("--vocab_extra_ids_list", type=str, default=None)
    g.add_argument("--no_new_tokens", dest="new_tokens",
                   action="store_false", default=True)
    g.add_argument("--data_impl", type=str, default="mmap")
    g.add_argument("--strict_data", action="store_true",
                   help="fail fast (DatasetCorruptionError) on "
                        "out-of-bounds documents or corrupt blend "
                        "prefixes instead of the default "
                        "skip-and-count (docs/resilience.md)")
    g.add_argument("--mask_prob", type=float, default=0.15,
                   dest="masked_lm_prob",
                   help="masked-LM probability (ref: --mask_prob)")
    g.add_argument("--short_seq_prob", type=float, default=0.1)
    g.add_argument("--train_data_path", nargs="*", default=None)
    g.add_argument("--valid_data_path", nargs="*", default=None)
    g.add_argument("--test_data_path", nargs="*", default=None)

    g = p.add_argument_group(
        "resilience",
        "fault tolerance for long preemptible runs (docs/resilience.md)")
    g.add_argument("--no_checkpoint_integrity", action="store_true",
                   help="skip writing/verifying per-checkpoint SHA-256 "
                        "manifests")
    g.add_argument("--keep_last_k", type=int, default=None,
                   help="retain only the newest K iter_* checkpoints "
                        "(the last VALID one always survives)")
    g.add_argument("--io_retries", type=int, default=4,
                   help="max attempts for checkpoint/tracker I/O "
                        "(1 = no retry)")
    g.add_argument("--io_backoff_s", type=float, default=0.5)
    g.add_argument("--io_backoff_max_s", type=float, default=30.0)
    g.add_argument("--max_consecutive_nonfinite", type=int, default=3,
                   help="NaN/inf steps in a row before rolling back to "
                        "the last checkpoint (0 disables)")
    g.add_argument("--loss_spike_factor", type=float, default=None,
                   help="roll back when a finite loss exceeds this "
                        "multiple of the rolling mean (None disables)")
    g.add_argument("--loss_spike_window", type=int, default=32)
    g.add_argument("--max_rollbacks", type=int, default=2,
                   help="divergence rollbacks before aborting with "
                        "TrainingDivergedError")
    g.add_argument("--step_timeout_s", type=float, default=None,
                   help="hung-step watchdog deadline; on expiry dump "
                        "stacks, attempt a final checkpoint, exit with "
                        "--watchdog_exit_code (None disables)")
    g.add_argument("--watchdog_exit_code", type=int, default=43)
    g.add_argument("--request_deadline_s", type=float, default=None,
                   help="serving: per-request wall-clock deadline "
                        "(expired requests are evicted with a "
                        "504-style error)")
    g.add_argument("--decode_sync_interval", type=int, default=1,
                   help="serving: decode steps dispatched per host "
                        "sync — 1/K syncs per token, up to K-1 wasted "
                        "steps per finished request (docs/serving.md)")
    g.add_argument("--prefill_max_batch", type=int, default=8,
                   help="serving: max same-bucket admissions coalesced "
                        "into one batched prefill call (1 disables)")
    g.add_argument("--enable_prefix_cache", action="store_true",
                   help="serving: retain finished slots' KV on an LRU "
                        "and reuse bucket-aligned shared prefixes "
                        "through one on-device region copy (token-"
                        "exact vs off; rolling sliding-window pools "
                        "need --kv_block_size — docs/serving.md)")
    g.add_argument("--prefill_chunk", type=int, default=None,
                   help="serving: split prompts/suffixes longer than "
                        "this into chunks interleaved with decode "
                        "steps (bounds ITL of running requests during "
                        "long prefills; None = monolithic prefill)")
    g.add_argument("--retained_slots", type=int, default=None,
                   help="serving: prefix-cache retained-slot budget — "
                        "at most this many finished slots keep their "
                        "KV for reuse (None retains all; they are "
                        "reclaimed lazily when admission needs a slot)")
    g.add_argument("--kv_block_size", type=int, default=None,
                   help="serving: block-granular KV pool — carve each "
                        "slot's region into this many-token blocks "
                        "over one arena with a per-slot block map "
                        "resolved at dispatch (bit-identical outputs, "
                        "one decode compile). Retention pins blocks "
                        "instead of whole regions and holds no grid "
                        "row, prefix hits alias shared blocks, and "
                        "rolling pools become cloneable/preemptible. "
                        "Must divide the slot capacity; None keeps "
                        "whole-region layout (docs/serving.md)")
    g.add_argument("--block_native_attn", action="store_true",
                   help="serving: block-NATIVE decode attention — the "
                        "Pallas kernel reads the KV arena through the "
                        "per-slot block map directly, dropping the "
                        "per-step resolve/scatter full-pool bracket "
                        "(gather bytes -> 0 on the decode/verify hot "
                        "path) and scattering only the touched block "
                        "on append; token-exact vs off, one compile. "
                        "Inert without --kv_block_size; rejected on "
                        "sliding-window models (docs/serving.md)")
    g.add_argument("--speculative_k", type=int, default=0,
                   help="serving: speculative decoding — propose this "
                        "many draft tokens per running slot each "
                        "iteration (self-drafting n-gram prompt-lookup "
                        "by default) and verify all slots' drafts in "
                        "one [slots, k+1]-token forward; greedy output "
                        "stays token-exact vs non-speculative "
                        "(0 disables; unsupported on rolling pools — "
                        "docs/serving.md)")
    g.add_argument("--priority_levels", type=int, default=1,
                   help="serving: distinct request priority classes — "
                        "requests carry priority in [0, levels); "
                        "higher wins admission ordering and (with "
                        "--preemption) may evict lower-priority "
                        "running slots (1 = all requests equal)")
    g.add_argument("--shed_on_overload", action="store_true",
                   help="serving: fail a new request at SUBMIT time "
                        "(retryable 429 + Retry-After) when its "
                        "estimated queue delay already exceeds its "
                        "deadline, instead of queue-then-504 "
                        "(docs/serving.md overload section)")
    g.add_argument("--degrade_ladder", type=int, default=0,
                   help="serving: graceful-degradation brownout ladder "
                        "max level — under sustained overload walk "
                        "1: no speculative decoding, 2: + cap "
                        "best_of/max_new_tokens for new admissions, "
                        "3: + shed lowest priority class, 4: shed all, "
                        "with hysteresis on both edges (0 disables — "
                        "bit-identical to the ladderless engine; "
                        "docs/serving.md 'Overload, degradation & SLO "
                        "conformance')")
    g.add_argument("--slo_ttft_ms", type=float, default=None,
                   help="serving: TTFT SLO target in ms — first tokens "
                        "arriving later count slo_ttft_violations and "
                        "the request's tokens leave goodput_tokens "
                        "(observability only; None = unset)")
    g.add_argument("--slo_itl_p99_ms", type=float, default=None,
                   help="serving: inter-token-latency SLO target in ms "
                        "— a host-visible token gap beyond it counts "
                        "slo_itl_violations (observability only; "
                        "None = unset)")
    g.add_argument("--preemption", action="store_true",
                   help="serving: a queued higher-priority request "
                        "with no allocatable slot evicts the lowest-"
                        "priority running slot; the victim's KV parks "
                        "and it resumes token-exact later (rolling "
                        "pools need --kv_block_size)")
    g.add_argument("--max_engine_restarts", type=int, default=2,
                   help="serving: supervisor loop restarts after a "
                        "crashed/hung engine step before the crash-"
                        "loop circuit breaker trips (engine goes "
                        "unhealthy, submits 503)")
    g.add_argument("--engine_step_timeout_s", type=float, default=None,
                   help="serving: hung-iteration watchdog deadline — "
                        "no engine-loop progress within this many "
                        "seconds fails the in-flight requests and "
                        "restarts the loop (None disables; must "
                        "exceed the worst prefill compile time)")
    g.add_argument("--num_replicas", type=int, default=1,
                   help="serving: engine replicas behind the in-process "
                        "prefix-affinity router — requests route to the "
                        "replica whose prefix cache holds the longest "
                        "match (ties: least-loaded); unhealthy replicas "
                        "are ejected and their work retries on a "
                        "survivor (1 = no router, docs/serving.md "
                        "'Front door')")
    g.add_argument("--router_max_retries", type=int, default=2,
                   help="serving: bounded failover retries per request "
                        "before its error surfaces (503 only when "
                        "every replica is down)")
    g.add_argument("--replica_mode", action="store_true",
                   help="serving: run this server as one fleet replica "
                        "process — accepts the pre-tokenized "
                        "prompt_tokens wire format plus the /admin, "
                        "/invariants and /affinity control-plane "
                        "routes a remote front tier (--fleet) drives "
                        "(docs/serving.md 'Front door')")
    g.add_argument("--fleet", type=str, default=None,
                   help="serving: run the router as a thin front tier "
                        "over remote replica processes at these "
                        "host:port addresses (comma-separated) — "
                        "health polling, typed transport faults, "
                        "token-exact failover, and rolling upgrades "
                        "over TCP; this process loads no weights")
    g.add_argument("--remote_connect_timeout_s", type=float,
                   default=2.0,
                   help="serving (fleet): per-call TCP connect and "
                        "health-probe read budget to a replica")
    g.add_argument("--remote_read_timeout_s", type=float, default=30.0,
                   help="serving (fleet): per-call read budget on "
                        "replica responses and SSE inter-frame gaps")
    g.add_argument("--remote_max_retries", type=int, default=2,
                   help="serving (fleet): bounded transport-level "
                        "retries per remote call (backoff + jitter, "
                        "Retry-After honored); request-level failover "
                        "is --router_max_retries on top")
    g.add_argument("--remote_digest_interval_s", type=float,
                   default=2.0,
                   help="serving (fleet): refresh cadence of each "
                        "replica's prefix-affinity digest "
                        "(GET /affinity); staleness skews routing "
                        "hints only, never tokens")
    g.add_argument("--host_kv_bytes", type=int, default=0,
                   help="serving: host-RAM KV tier byte budget — "
                        "retained prefix block lists evicted under "
                        "block pressure demote to host memory "
                        "(checksum-verified on restore) and restore "
                        "via device_put on a later hit; needs "
                        "--enable_prefix_cache + --kv_block_size "
                        "(0 disables)")
    g.add_argument("--serving_tp", type=int, default=1,
                   help="serving: tensor-parallel width of the serving "
                        "mesh — weights, the KV arena, and prefill "
                        "subs shard over 'tp' on the head axes with "
                        "the same GSPMD rules training uses; dispatch "
                        "data (block map, lengths, sampling state) "
                        "stays replicated, so decode/verify/prefill "
                        "keep one compile each (1 = no serving mesh, "
                        "bit-identical; docs/serving.md 'Sharded & "
                        "disaggregated serving')")
    g.add_argument("--disaggregate_prefill", action="store_true",
                   help="serving: split prefill and decode onto "
                        "separate serving_tp-wide chip groups "
                        "(DistServe) — prompts prefill on the prefill "
                        "group and hand off to decode as a "
                        "device-to-device copy of the sequence's live "
                        "KV blocks only; needs --kv_block_size "
                        "(docs/serving.md)")
    g.add_argument("--prefill_tp", type=int, default=None,
                   help="serving: tensor-parallel width of the PREFILL "
                        "group (defaults to --serving_tp) — prefill is "
                        "compute-bound, so a disaggregated engine may "
                        "run it wider or narrower than decode; unequal "
                        "widths need --disaggregate_prefill, and the "
                        "handoff device_put reshards the kv-head axis "
                        "P->D in the one transfer (docs/serving.md "
                        "'Per-phase topology & placement')")
    g.add_argument("--decode_tp", type=int, default=None,
                   help="serving: tensor-parallel width of the DECODE "
                        "group (defaults to --serving_tp) — decode is "
                        "HBM-bound; see --prefill_tp")
    g.add_argument("--serving_pp", type=int, default=1,
                   help="serving: pipeline-stage count for the decode "
                        "group — the group's devices split into "
                        "serving_pp layer-stage sub-meshes (each "
                        "decode_tp wide); stage i holds layers "
                        "[i*L/S,(i+1)*L/S) plus embedding on stage 0 "
                        "and head/final-norm on the last stage, the "
                        "KV arena partitions on the layer axis, and "
                        "decode runs as a staged program chain with "
                        "one [slots, hidden] device_put between "
                        "stages; needs --kv_block_size and "
                        "num_layers divisible by serving_pp; 1 = no "
                        "staged topology, bit-identical "
                        "(docs/serving.md 'Pipeline-sharded serving')")
    g.add_argument("--pp_waves", type=int, default=1,
                   help="serving: interleaved wave count under "
                        "--serving_pp (1F1B on the slot grid) — the "
                        "slot grid splits into this many waves so "
                        "stage i works wave k while stage i+1 works "
                        "wave k-1; bubble fraction "
                        "(S-1)/(W+S-1) exports as pp_stage_bubble; "
                        "needs num_slots divisible by pp_waves")
    g.add_argument("--placement_auto", action="store_true",
                   help="serving: let serving/placement.py choose the "
                        "prefill:decode split and per-phase tp widths "
                        "from the replica's device budget at build, "
                        "re-planned from observed busy/queue/TTFT "
                        "signals ONLY at the rolling-upgrade drain "
                        "barrier; the chosen plan is exported through "
                        "health() and /metrics (needs "
                        "--disaggregate_prefill)")
    g.add_argument("--placement_budget", type=int, default=None,
                   help="serving: device budget per replica for "
                        "--placement_auto (the optimizer picks "
                        "prefill_tp + decode_tp <= budget; default = "
                        "what the explicit widths occupy)")
    g.add_argument("--adapter_slots", type=int, default=0,
                   help="serving: device-resident LoRA adapters "
                        "servable concurrently (multi-tenant serving, "
                        "docs/serving.md 'Multi-tenant LoRA serving') "
                        "— a per-slot adapter index selects each "
                        "request's A/B factors from a stacked bank "
                        "inside the one compiled decode step; 0 "
                        "disables (bit-identical engine)")
    g.add_argument("--adapter_rank", type=int, default=8,
                   help="serving: LoRA rank the adapter bank "
                        "allocates for (smaller exported ranks "
                        "zero-pad up; larger are rejected)")
    g.add_argument("--adapter_host_bytes", type=int, default=0,
                   help="serving: host-RAM overflow budget for "
                        "adapters evicted from a full bank "
                        "(checksum-verified on restore; a corrupt "
                        "copy reloads from disk — never wrong "
                        "weights; 0 = evictions drop to disk reload)")
    g.add_argument("--swap_timeout_s", type=float, default=120.0,
                   help="serving: how long a live-weight hot swap "
                        "waits for in-flight work to drain at the "
                        "swap barrier before it is cancelled (typed "
                        "refusal; the engine keeps serving — "
                        "docs/serving.md 'Live weights & rolling "
                        "upgrade')")
    g.add_argument("--watch_checkpoints", type=str, default=None,
                   help="serving: training checkpoint root to watch — "
                        "every newly published (tracker-named, "
                        "manifest-verified) checkpoint hot-swaps onto "
                        "the running engine, or rolling-upgrades the "
                        "replica fleet drain->swap->canary->re-admit "
                        "with zero 503s; a corrupt publish is refused "
                        "and retried only on the NEXT publish "
                        "(docs/serving.md)")
    g.add_argument("--watch_interval_s", type=float, default=5.0,
                   help="serving: tracker poll cadence for "
                        "--watch_checkpoints")
    g.add_argument("--lora_rank", type=int, default=0,
                   help="finetune: train ONLY LoRA low-rank adapter "
                        "factors at this rank (base frozen) and "
                        "export them for the serving adapter bank "
                        "(0 = normal full finetune)")
    g.add_argument("--lora_alpha", type=float, default=16.0,
                   help="finetune: LoRA alpha — the delta scales by "
                        "alpha/rank (folded at serving load)")
    g.add_argument("--lora_export", type=str, default=None,
                   help="finetune: path for the trained adapter .npz "
                        "(default <save>/adapter.npz)")

    g = p.add_argument_group(
        "reference compat",
        "reference flags accepted with equivalent TPU semantics")
    g.add_argument("--train_samples", type=int, default=None,
                   help="sample-based run length; converted to iters via "
                        "global_batch_size (ref: --train_samples)")
    g.add_argument("--lr_decay_samples", type=int, default=None)
    g.add_argument("--lr_warmup_samples", type=int, default=None)
    g.add_argument("--position_embedding_type", type=str, default=None,
                   choices=["rope", "rotary", "learned_absolute",
                            "absolute"])
    g.add_argument("--encoder_num_layers", type=int, default=None)
    g.add_argument("--encoder_seq_length", type=int, default=None)
    g.add_argument("--decoder_num_layers", type=int, default=None)
    g.add_argument("--decoder_seq_length", type=int, default=128,
                   dest="max_seq_length_dec")
    g.add_argument("--no_save_optim", action="store_true")
    g.add_argument("--no_save_rng", action="store_true")
    g.add_argument("--recompute_activations", action="store_true",
                   help="alias for --recompute_granularity selective")
    g.add_argument("--recompute_method", type=str, default=None,
                   choices=["uniform", "block"],
                   help="accepted; the scan-stacked formulation remats "
                        "uniformly per layer either way")
    g.add_argument("--recompute_num_layers", type=int, default=None)
    g.add_argument("--attention_softmax_in_fp32", action="store_true",
                   dest="softmax_compute_fp32", default=True)
    g.add_argument("--exit_signal_handler", action="store_true",
                   help="accepted; SIGTERM checkpoint-and-exit is always "
                        "installed")
    g.add_argument("--override_opt_param_scheduler", action="store_true",
                   help="accepted; CLI schedule always wins unless "
                        "--use_checkpoint_args")
    g.add_argument("--use_checkpoint_opt_param_scheduler",
                   action="store_true",
                   help="accepted; subsumed by --use_checkpoint_args")
    g.add_argument("--log_params_norm", action="store_true")
    g.add_argument("--log_timers_to_tensorboard", action="store_true")
    g.add_argument("--log_validation_ppl_to_tensorboard",
                   action="store_true")
    g.add_argument("--wandb_project", type=str, default=None)
    g.add_argument("--wandb_entity", type=str, default=None)
    g.add_argument("--wandb_id", type=str, default=None)
    g.add_argument("--wandb_resume", action="store_true")
    # retrieval stack paths (ref: arguments.py retriever/biencoder args;
    # the ict-specific ones live on pretrain_ict.py / tasks.main)
    g.add_argument("--bert_load", type=str, default=None)
    g.add_argument("--ict_load", type=str, default=None)
    g.add_argument("--biencoder_projection_dim", type=int, default=0)
    g.add_argument("--block_data_path", type=str, default=None)
    g.add_argument("--embedding_path", type=str, default=None)
    g.add_argument("--evidence_data_path", type=str, default=None)
    g.add_argument("--indexer_batch_size", type=int, default=128)
    g.add_argument("--indexer_log_interval", type=int, default=1000)
    g.add_argument("--retriever_report_topk_accuracies", nargs="+",
                   type=int, default=[])
    g.add_argument("--retriever_score_scaling", action="store_true")
    g.add_argument("--retriever_seq_length", type=int, default=256)

    # CUDA/cluster-mechanics flags that dissolve under XLA/TPU: accepted so
    # reference launch scripts run unmodified; a note is logged when one is
    # set (ref: arguments.py — fused-kernel toggles, NCCL/DDP knobs, fp8/TE,
    # vision/DINO, ADLR autoresume)
    for flag in _NOOP_FLAGS:
        p.add_argument(flag, nargs="?", const=True, default=None,
                       help=argparse.SUPPRESS)

    if extra_args_provider is not None:
        p = extra_args_provider(p)
    return p


# Reference flags with no TPU-side effect (the mechanism they tune does not
# exist under XLA: stream ordering, fused CUDA kernels, NCCL backends, fp8
# Transformer Engine, vision/DINO models, ADLR cluster autoresume).
_NOOP_FLAGS = [
    "--DDP_impl",  # local-vs-torch DDP choice; dp is a mesh axis here
    "--accumulate_allreduce_grads_in_fp32",  # grads are always fp32 here
    "--adlr_autoresume", "--adlr_autoresume_interval",
    "--barrier_with_L1_time",  # timers design differs (block_until_ready)
    "--apply_residual_connection_post_layernorm",
    "--classes_fraction", "--data_parallel_random_init",
    "--data_per_class_fraction",
    "--dino_bottleneck_size", "--dino_freeze_last_layer",
    "--dino_head_hidden_size", "--dino_local_crops_number",
    "--dino_local_img_size", "--dino_norm_last_layer",
    "--dino_teacher_temp", "--dino_warmup_teacher_temp",
    "--dino_warmup_teacher_temp_epochs",
    "--distribute_saved_activations", "--distributed_backend",
    "--empty_unused_memory_level", "--fp16_lm_cross_entropy",
    "--fp32_residual_connection",
    # fp8/TE: no fp8 datapath on v5e/v5p — the TPU-native low-precision
    # GEMM mode is --quantized_gemm int8 (ops/quantized.py)
    "--fp8_amax_compute_algo", "--fp8_amax_history_len", "--fp8_e4m3",
    "--fp8_hybrid", "--fp8_interval", "--fp8_margin", "--no_fp8_wgrad",
    "--head_lr_mult", "--img_h", "--img_w",
    "--inference_batch_times_seqlen_threshold",
    "--init_method_xavier_uniform", "--iter_per_epoch", "--local_rank",
    "--log_batch_size_to_tensorboard", "--log_memory_to_tensorboard",
    "--log_world_size_to_tensorboard", "--max_tokens_to_oom",
    "--no_async_tensor_model_parallel_allreduce",
    "--no_bias_dropout_fusion", "--no_bias_gelu_fusion",
    "--no_contiguous_buffers_in_local_ddp", "--no_data_sharding",
    "--no_gradient_accumulation_fusion", "--no_initialization",
    "--mmap_warmup",  # np.memmap needs no page-in pass
    "--no_masked_softmax_fusion", "--no_persist_layer_norm",
    "--no_query_key_layer_scaling",
    "--sample_rate",  # BERT-dataset subsampling knob of the CUDA loader
    "--no_scatter_gather_tensors_in_pipeline",
    "--num_channels", "--num_classes", "--onnx_safe", "--patch_dim",
    "--pipeline_model_parallel_split_rank", "--standalone_embedding_stage",
    "--tensorboard_log_interval", "--tensorboard_queue_size",
    "--timing_log_level", "--timing_log_option", "--transformer_impl",
    "--use_cpu_initialization", "--use_one_sent_docs",
    "--use_ring_exchange_p2p",
]


def _pick(ns: argparse.Namespace, cls, **renames):
    import dataclasses
    fields = {f.name for f in dataclasses.fields(cls)}
    d = {k: v for k, v in vars(ns).items() if k in fields}
    d.update({k: v for k, v in renames.items() if v is not None})
    return d


def _apply_compat(args: argparse.Namespace) -> None:
    """Resolve reference-compat aliases into the native arg surface and
    warn for accepted-but-inert CUDA-mechanics flags."""
    # aliases (mutating the namespace keeps _pick/_preset logic unchanged);
    # an explicit --num_layers (even "--num_layers 2") beats
    # --encoder_num_layers; unset resolves to the alias, then to 2. The
    # sentinel tells the preset-override loop a resolved 2 was NOT explicit
    # (a preset's layer count must not be clobbered by the fallback default).
    # hasattr-guarded so re-running compat on the same namespace (e.g.
    # config_from_args called twice) stays idempotent.
    if not hasattr(args, "_num_layers_defaulted"):
        args._num_layers_defaulted = False
        if args.num_layers is None:
            enc = getattr(args, "encoder_num_layers", None)
            args.num_layers = enc if enc is not None else 2
            args._num_layers_defaulted = enc is None
    if getattr(args, "encoder_seq_length", None) and not args.seq_length:
        args.seq_length = args.encoder_seq_length
    if getattr(args, "recompute_activations", False) and \
            args.recompute_granularity == "none":
        args.recompute_granularity = "selective"
    pet = getattr(args, "position_embedding_type", None)
    if pet in ("rope", "rotary"):
        args.use_rotary_emb = True
    elif pet in ("learned_absolute", "absolute"):
        args.use_rotary_emb = False
        args.use_position_embedding = True
    # sample-based run length -> iterations (ref: --train_samples; the
    # reference's samples-mode microbatch calculator is equivalent to this
    # conversion when no batch rampup is active)
    if getattr(args, "train_samples", None):
        assert args.rampup_batch_size is None, (
            "--train_samples with --rampup_batch_size is not supported; "
            "use --train_iters")
        assert args.global_batch_size, (
            "--train_samples needs an explicit --global_batch_size (the "
            "derived gbs depends on dp size, which is unknown at parse "
            "time)")
        gbs = args.global_batch_size
        args.train_iters = -(-args.train_samples // gbs)
        if getattr(args, "lr_decay_samples", None) and \
                not args.lr_decay_iters:
            args.lr_decay_iters = -(-args.lr_decay_samples // gbs)
        if getattr(args, "lr_warmup_samples", None) and \
                not args.lr_warmup_iters:
            args.lr_warmup_iters = -(-args.lr_warmup_samples // gbs)
    if args.data_path and getattr(args, "train_data_path", None):
        raise SystemExit(
            "--data_path and --train_data_path are mutually exclusive — "
            "pick one train corpus (ref: arguments.py validate_args). "
            "--valid/test_data_path MAY combine with --data_path: "
            "data_path trains, the per-split paths evaluate.")
    # inert flags: say so once, loudly enough to audit
    set_noops = [f for f in _NOOP_FLAGS
                 if getattr(args, f.lstrip("-"), None) is not None]
    if set_noops:
        from megatron_tpu.utils.logging import print_rank_0
        print_rank_0("compat: accepted with no TPU-side effect: "
                     + ", ".join(set_noops))


def config_from_args(args: argparse.Namespace,
                     n_devices: Optional[int] = None,
                     defaults: Optional[dict] = None) -> MegatronConfig:
    from megatron_tpu.config import MODEL_PRESETS

    _apply_compat(args)

    if args.model:
        model = MODEL_PRESETS[args.model]()
        import dataclasses
        # a preset is a baseline, not a gag order: any model-field flag the
        # user EXPLICITLY set (differs from the parser default) overrides
        # the preset — e.g. --model llama2-7b --drop_path_rate 0.1
        overrides = {}
        if defaults:
            handled = {"seq_length", "recompute_granularity",
                       "attention_impl"}
            for f in dataclasses.fields(type(model)):
                if f.name in handled or f.name not in defaults:
                    continue
                if f.name == "num_layers" and args._num_layers_defaulted:
                    continue  # resolved fallback, not a user choice
                v = getattr(args, f.name, None)
                if v != defaults[f.name]:
                    overrides[f.name] = v
        model = dataclasses.replace(
            model, seq_length=args.seq_length or model.seq_length,
            recompute_granularity=args.recompute_granularity,
            attention_impl=(args.attention_impl or
                            ("flash" if args.use_flash_attn
                             else model.attention_impl)), **overrides)
    else:
        activation = (args.glu_activation or args.activation or
                      ("swiglu" if args.use_rms_norm else "gelu"))
        params_dtype = ("bfloat16" if args.bf16 else
                        "float16" if args.fp16 else "float32")
        md = _pick(args, ModelConfig)
        if md.get("seq_length") is None:
            md["seq_length"] = 512
        md.update(dict(
            norm_type="rmsnorm" if args.use_rms_norm else "layernorm",
            activation=activation,
            params_dtype=params_dtype,
            compute_dtype="bfloat16" if args.bf16 or args.fp16 else "float32",
            attention_impl=(args.attention_impl or
                            ("flash" if args.use_flash_attn else "dot")),
        ))
        model = ModelConfig(**md)

    if args.context_parallel > 1 and \
            model.attention_impl not in ("ring", "ulysses"):
        # cp>1 needs a context-parallel attention impl; the algo flag
        # picks ring vs ulysses (both run flash on the local block)
        import dataclasses
        model = dataclasses.replace(
            model, attention_impl=args.context_parallel_algo)

    vpp = 1
    if args.num_layers_per_virtual_pipeline_stage:
        per_stage = model.num_layers // max(args.pipeline_parallel, 1)
        vpp = per_stage // args.num_layers_per_virtual_pipeline_stage

    cfg = MegatronConfig(
        model=model,
        parallel=ParallelConfig(
            tensor_parallel=args.tensor_parallel,
            pipeline_parallel=args.pipeline_parallel,
            context_parallel=args.context_parallel,
            sequence_parallel=args.sequence_parallel,
            expert_axis=args.expert_axis,
            virtual_pipeline_chunks=vpp,
            pipeline_schedule=args.pipeline_schedule,
            pipeline_store_activations=args.pipeline_store_activations,
            use_distributed_optimizer=args.use_distributed_optimizer,
        ),
        optimizer=OptimizerConfig(**_pick(args, OptimizerConfig)),
        training=TrainingConfig(**{
            **_pick(args, TrainingConfig),
            "rampup_batch_size": tuple(args.rampup_batch_size)
            if args.rampup_batch_size else None}),
        data=DataConfig(**_pick(args, DataConfig)),
        serving=ServingConfig(
            request_deadline_s=args.request_deadline_s,
            decode_sync_interval=args.decode_sync_interval,
            prefill_max_batch=args.prefill_max_batch,
            enable_prefix_cache=args.enable_prefix_cache,
            prefill_chunk=args.prefill_chunk,
            retained_slots=args.retained_slots,
            kv_block_size=args.kv_block_size,
            block_native_attn=args.block_native_attn,
            speculative_k=args.speculative_k,
            priority_levels=args.priority_levels,
            shed_on_overload=args.shed_on_overload,
            degrade_ladder=args.degrade_ladder,
            slo_ttft_ms=args.slo_ttft_ms,
            slo_itl_p99_ms=args.slo_itl_p99_ms,
            preemption=args.preemption,
            max_engine_restarts=args.max_engine_restarts,
            engine_step_timeout_s=args.engine_step_timeout_s,
            num_replicas=args.num_replicas,
            router_max_retries=args.router_max_retries,
            replica_mode=args.replica_mode,
            fleet=args.fleet,
            remote_connect_timeout_s=args.remote_connect_timeout_s,
            remote_read_timeout_s=args.remote_read_timeout_s,
            remote_max_retries=args.remote_max_retries,
            remote_digest_interval_s=args.remote_digest_interval_s,
            host_kv_bytes=args.host_kv_bytes,
            serving_tp=args.serving_tp,
            disaggregate_prefill=args.disaggregate_prefill,
            prefill_tp=args.prefill_tp,
            decode_tp=args.decode_tp,
            serving_pp=args.serving_pp,
            pp_waves=args.pp_waves,
            placement_auto=args.placement_auto,
            placement_budget=args.placement_budget,
            adapter_slots=args.adapter_slots,
            adapter_rank=args.adapter_rank,
            adapter_host_bytes=args.adapter_host_bytes,
            swap_timeout_s=args.swap_timeout_s,
            watch_checkpoints=args.watch_checkpoints,
            watch_interval_s=args.watch_interval_s),
        resilience=ResilienceConfig(**{
            **_pick(args, ResilienceConfig),
            "checkpoint_integrity": not args.no_checkpoint_integrity}),
    )
    return cfg.validate(n_devices=n_devices)


def parse_cli(argv=None, extra_args_provider=None, n_devices=None
              ) -> tuple[MegatronConfig, argparse.Namespace]:
    # multi-host bring-up first: jax.distributed must initialize before
    # any backend query so jax.devices() sees the whole pod (no-op on
    # single-host runs; ref: initialize.py:124-151 ordering)
    from megatron_tpu.parallel.multihost import initialize_distributed
    initialize_distributed()
    parser = build_parser(extra_args_provider)
    args = parser.parse_args(argv)
    defaults = {a.dest: a.default for a in parser._actions}
    return config_from_args(args, n_devices=n_devices,
                            defaults=defaults), args
