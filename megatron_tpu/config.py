"""Typed configuration system for megatron_tpu.

TPU-native replacement for the reference's flat-argparse config
(ref: megatron/arguments.py:14-1073, megatron/global_vars.py:76-78).
Instead of ~170 flags stored in a mutable global namespace, configuration is a
tree of frozen dataclasses: architecture (`ModelConfig`), parallelism layout
(`ParallelConfig`), optimization (`OptimizerConfig`), training-loop
(`TrainingConfig`), data pipeline (`DataConfig`) — combined into `MegatronConfig`.
`validate()` performs the same derivations/consistency checks as the reference's
`validate_args` (ref: megatron/arguments.py:52-345), and an argparse bridge
(`parse_cli`) keeps a megatron-compatible flag surface for the entry points.
"""
from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import jax.numpy as jnp

# ---------------------------------------------------------------------------
# dtype policy
# ---------------------------------------------------------------------------

_DTYPES = {
    "float32": jnp.float32,
    "fp32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "bf16": jnp.bfloat16,
    "float16": jnp.float16,
    "fp16": jnp.float16,
}


def as_dtype(name: str):
    return _DTYPES[name]


@dataclass(frozen=True)
class ModelConfig:
    """Transformer architecture config.

    Mirrors the architecture slice of the reference's argument namespace
    (ref: megatron/arguments.py:367-520) and the assertions made by
    LlamaModel/FalconModel (ref: megatron/model/llama_model.py:10-43,
    megatron/model/falcon_model.py:10-41).
    """

    num_layers: int = 2
    hidden_size: int = 128
    ffn_hidden_size: Optional[int] = None  # derived: 4h, or 8/3 h for GLU
    num_attention_heads: int = 4
    # GQA/MQA: number of kv heads; == num_attention_heads -> MHA, == 1 -> MQA
    # (ref: megatron/model/transformer.py:313-333, --num_attention_heads_kv)
    num_kv_heads: Optional[int] = None
    kv_channels: Optional[int] = None  # head dim; derived h / n_heads
    seq_length: int = 512
    max_position_embeddings: Optional[int] = None
    vocab_size: int = 32000
    make_vocab_size_divisible_by: int = 128

    # positional encoding
    use_rotary_emb: bool = True
    rope_theta: float = 10000.0
    # linear position-interpolation scaling (ref: --rope_scaling_factor,
    # megatron/model/positional_embeddings.py:10-12)
    rope_scaling_factor: float = 1.0
    # learned absolute position embedding (GPT/BERT style, ref: language_model.py:155-163)
    use_position_embedding: bool = False

    # norms / activations / structure
    norm_type: str = "rmsnorm"  # "rmsnorm" | "layernorm"
    norm_epsilon: float = 1e-5
    activation: str = "swiglu"  # swiglu|geglu|reglu|liglu|gelu|relu|squared_relu
    use_bias: bool = False  # bias on linear layers (ref: --use_bias)
    use_post_ln: bool = False  # post-LN instead of pre-LN (ref: transformer.py:629-633)
    # Falcon-style parallel attention+MLP block (ref: transformer.py:647,773-805)
    parallel_attn: bool = False
    # dedicated MLP layernorm for Falcon-40B (ref: transformer.py:604,612-628)
    parallel_layernorm: bool = False
    tie_embed_logits: bool = False  # tied embedding/lm-head (ref: language_model.py:436-457)

    # dropout / regularization
    hidden_dropout: float = 0.0
    attention_dropout: float = 0.0
    # LIMA-style per-layer dropout ramp (ref: transformer.py:963-970)
    lima_dropout: bool = False
    # stochastic depth, ramped linspace(0, rate, L) over layers
    # (ref: transformer.py:43-63 DropPath, :961 drop_path_rates)
    drop_path_rate: float = 0.0

    # numerics
    params_dtype: str = "float32"  # master/param dtype
    compute_dtype: str = "bfloat16"  # activation/matmul dtype
    softmax_compute_fp32: bool = True  # attention-softmax in fp32
    # scale q @ k^T by 1/layer_number like apply_query_key_layer_scaling
    apply_query_key_layer_scaling: bool = False
    attention_softmax_in_fp32: bool = True
    init_method_std: float = 0.02
    use_scaled_init: bool = True  # scale output-layer init by 1/sqrt(2*num_layers)

    # attention implementation: "flash" (blockwise/Pallas) | "dot" (xla
    # einsum) | "ring" (context-parallel K/V-rotation over 'cp') |
    # "ulysses" (context-parallel all-to-all head sharding over 'cp')
    attention_impl: str = "dot"
    # Mistral-style sliding-window (banded causal) attention: each token
    # attends at most the previous `sliding_window` positions. None =
    # full causal. The flash kernel skips whole blocks outside the band.
    sliding_window: Optional[int] = None
    # activation recompute: "none" | "selective" | "full" (ref: arguments.py:601-629)
    recompute_granularity: str = "none"
    # low-precision GEMM path: "none" | "int8" (forward attention/MLP GEMMs
    # on the int8 MXU datapath with current-scaling quantization; the
    # TPU-native counterpart of the reference's TE fp8 mode — see
    # ops/quantized.py; ref: transformer.py:931-950)
    quantized_gemm: str = "none"

    # Mixture-of-Experts (ABSENT in the reference — SURVEY.md §2.8; the
    # TPU formulation is an 'experts'-sharded weight bank + sort-based
    # dispatch, models/moe.py). num_experts > 1 replaces every MLP with a
    # top-k-routed expert bank; composes with dp/tp/sp/pp (router aux
    # threads through every pipeline schedule).
    num_experts: int = 1
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_loss_coeff: float = 1e-2
    # dispatch implementation: "sort" (stable-sort routing, one
    # scatter/gather — O(s) memory, the long-context-safe default) |
    # "dense" (GShard [b,s,E,C] one-hot einsums — the semantic oracle)
    moe_dispatch: str = "sort"

    # glu activations double the first MLP projection
    @property
    def is_glu(self) -> bool:
        return self.activation in ("swiglu", "geglu", "reglu", "liglu")

    def derived(self) -> "ModelConfig":
        """Fill derived fields (ffn size, kv heads, head dim, max positions)."""
        assert self.attention_impl in ("dot", "flash", "ring",
                                       "ulysses"), (
            f"attention_impl must be 'dot', 'flash', 'ring' or "
            f"'ulysses', got {self.attention_impl!r}")
        assert self.quantized_gemm in ("none", "int8"), (
            f"quantized_gemm must be 'none' or 'int8', "
            f"got {self.quantized_gemm!r}")
        d: dict[str, Any] = {}
        if self.num_kv_heads is None:
            d["num_kv_heads"] = self.num_attention_heads
        else:
            assert self.num_attention_heads % self.num_kv_heads == 0, (
                f"num_attention_heads={self.num_attention_heads} must be a "
                f"multiple of num_kv_heads={self.num_kv_heads} (GQA groups)")
        if self.kv_channels is None:
            assert self.hidden_size % self.num_attention_heads == 0
            d["kv_channels"] = self.hidden_size // self.num_attention_heads
        if self.ffn_hidden_size is None:
            if self.is_glu:
                # llama convention: 2/3 * 4h rounded to multiple of 256
                ffn = int(8 * self.hidden_size / 3)
                ffn = 256 * ((ffn + 255) // 256)
                d["ffn_hidden_size"] = ffn
            else:
                d["ffn_hidden_size"] = 4 * self.hidden_size
        if self.max_position_embeddings is None:
            d["max_position_embeddings"] = self.seq_length
        return dataclasses.replace(self, **d)

    @property
    def padded_vocab_size(self) -> int:
        """Vocab padded for clean sharding (ref: tokenizer.py:42-62 pads to
        make_vocab_size_divisible_by * tp; we pad to the lcm-friendly multiple
        independent of tp so checkpoints are layout-free)."""
        m = self.make_vocab_size_divisible_by
        return m * ((self.vocab_size + m - 1) // m)


@dataclass(frozen=True)
class ParallelConfig:
    """Device-mesh layout.

    The reference builds explicit NCCL process groups for tp/pp/dp
    (ref: megatron/core/parallel_state.py:51-205). Here the same grid is one
    `jax.sharding.Mesh` with axes ('dp', 'pp', 'tp'); sequence parallelism
    shards activations along 'tp' outside attention/MLP blocks
    (ref: --sequence_parallel, arguments.py:681-682) and context parallelism
    adds a 'cp' axis for ring attention (absent in the reference; see
    SURVEY.md §2.8).
    """

    tensor_parallel: int = 1
    pipeline_parallel: int = 1
    data_parallel: Optional[int] = None  # derived from world size
    context_parallel: int = 1
    expert_parallel: int = 1  # unused; kept for config compatibility
    # which mesh axis the MoE expert bank's 'experts' dim shards over:
    # "tp" (default — each tp rank holds E/tp whole experts, router
    # all-to-alls ride the tp ICI) or "dp" (GShard-style expert
    # parallelism over the data axis — the classic layout when E is
    # large and tp is small; moments/grads stay aligned since the bank
    # is dp-sharded end-to-end)
    expert_axis: str = "tp"
    sequence_parallel: bool = False
    # virtual pipeline (interleaved 1F1B) chunks per stage (ref: arguments.py:117-128)
    virtual_pipeline_chunks: int = 1
    # pp execution schedule: "1f1b" = hand-scheduled one-forward-one-backward
    # with per-stage memory flat in n_micro (ref: schedules.py:606-722);
    # "gpipe" = lockstep fill-drain with autodiff-derived backward (memory
    # grows with n_micro; required for vpp>1 interleaving)
    pipeline_schedule: str = "1f1b"
    # 1F1B backward sourcing: False (default) stashes chunk INPUTS and
    # recomputes each chunk forward in the backward slot (the reference's
    # --recompute-granularity=full under 1F1B — lowest memory); True
    # carries the forward vjp RESIDUALS instead (the reference's
    # no-recompute default — ~1/3 less pipeline compute, memory grows to
    # the in-flight residual footprint; pair with
    # recompute_granularity="none"/"selective")
    pipeline_store_activations: bool = False
    # ZeRO-1-style optimizer state sharding over dp (ref: optimizer/distrib_optimizer.py)
    use_distributed_optimizer: bool = False

    def world_size(self, n_devices: int) -> int:
        return n_devices

    def derive_dp(self, n_devices: int) -> int:
        denom = (self.tensor_parallel * self.pipeline_parallel *
                 self.context_parallel)
        assert n_devices % denom == 0, (
            f"world size {n_devices} not divisible by tp*pp*cp={denom}")
        return n_devices // denom


@dataclass(frozen=True)
class OptimizerConfig:
    """Adam/SGD + lr schedule + clipping + loss scaling.

    (ref: megatron/optimizer/__init__.py:63-144, optimizer_param_scheduler.py,
    grad_scaler.py:40-120, clip_grads.py:16-136)
    """

    optimizer: str = "adam"
    lr: float = 3e-4
    min_lr: float = 0.0
    lr_decay_style: str = "cosine"  # constant|linear|cosine|inverse-square-root
    lr_decay_iters: Optional[int] = None
    lr_warmup_iters: int = 0
    lr_warmup_fraction: Optional[float] = None
    weight_decay: float = 0.01
    start_weight_decay: Optional[float] = None
    end_weight_decay: Optional[float] = None
    weight_decay_incr_style: str = "constant"
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_eps: float = 1e-8
    sgd_momentum: float = 0.9
    clip_grad: float = 1.0
    # loss scaling (needed only for fp16; bf16 trains unscaled)
    loss_scale: Optional[float] = None  # None -> dynamic if fp16
    initial_loss_scale: float = 2.0 ** 32
    min_loss_scale: float = 1.0
    loss_scale_window: int = 1000
    hysteresis: int = 2
    log_num_zeros_in_grad: bool = False
    override_opt_param_scheduler: bool = False
    use_checkpoint_opt_param_scheduler: bool = False


@dataclass(frozen=True)
class TrainingConfig:
    """Training-loop config (ref: megatron/training.py, microbatches.py)."""

    micro_batch_size: int = 1
    global_batch_size: Optional[int] = None
    rampup_batch_size: Optional[tuple[int, int, int]] = None  # (start, incr, samples)
    train_iters: int = 100
    eval_interval: int = 1000
    eval_iters: int = 10
    log_interval: int = 10
    save_interval: Optional[int] = None
    exit_interval: Optional[int] = None
    exit_duration_in_mins: Optional[float] = None
    seed: int = 1234
    checkpoint_dir: Optional[str] = None
    load_dir: Optional[str] = None
    finetune: bool = False  # load weights only, reset iteration/optimizer
    no_load_optim: bool = False
    no_load_rng: bool = False
    wandb_logger: bool = False
    tensorboard_dir: Optional[str] = None
    # Host/device sync cadence (training/loop.py). False (default): the
    # loop never blocks on a step's metrics — per-step scalars stay
    # device-resident and are fetched in ONE transfer per log window
    # (guard/skip accounting replays the window at the flush, at most
    # log_interval-1 steps late; rollback restores a checkpoint either
    # way, so decisions are identical — see docs/resilience.md). True
    # restores the step-exact fetch-every-iteration behavior for
    # debugging; profile=True implies it so trace windows stay
    # step-aligned.
    sync_metrics: bool = False
    # jax.profiler trace capture over a step window (SURVEY.md §5: the TPU
    # equivalent of the reference's named-span-only profiling). Traces are
    # viewable in TensorBoard / Perfetto.
    profile: bool = False
    profile_step_start: int = 10
    profile_step_end: int = 12
    profile_dir: Optional[str] = None  # defaults to tensorboard_dir or /tmp
    # checkpoint write scope (ref: --no_save_optim/--no_save_rng)
    no_save_optim: bool = False
    no_save_rng: bool = False
    # extra metrics (ref: --log_params_norm and friends)
    log_params_norm: bool = False
    log_timers_to_tensorboard: bool = False
    log_validation_ppl_to_tensorboard: bool = False
    # wandb run identity (ref: --wandb_project/_entity/_id/_resume)
    wandb_project: Optional[str] = None
    wandb_entity: Optional[str] = None
    wandb_id: Optional[str] = None
    wandb_resume: bool = False


@dataclass(frozen=True)
class DataConfig:
    """Data pipeline config (ref: megatron/data/*, tokenizer/*)."""

    data_path: Optional[Sequence[Any]] = None  # [weight, prefix, ...] or [prefix]
    split: str = "969,30,1"
    tokenizer_type: str = "SentencePieceTokenizer"
    vocab_file: Optional[str] = None
    merge_file: Optional[str] = None
    tokenizer_model: Optional[str] = None
    dataloader_type: str = "single"  # single | cyclic
    num_workers: int = 2
    reset_position_ids: bool = False
    reset_attention_mask: bool = False
    eod_mask_loss: bool = False
    vocab_extra_ids: int = 0
    vocab_extra_ids_list: Optional[str] = None
    # masked-LM data knobs (ref: arguments.py --mask_prob,
    # --short_seq_prob, --max_seq_length_dec for T5)
    masked_lm_prob: float = 0.15
    short_seq_prob: float = 0.1
    max_seq_length_dec: int = 128
    # per-split dataset prefixes; alternative to `split` fractions over one
    # corpus (ref: --train_data_path/--valid_data_path/--test_data_path)
    train_data_path: Optional[Sequence[Any]] = None
    valid_data_path: Optional[Sequence[Any]] = None
    test_data_path: Optional[Sequence[Any]] = None
    new_tokens: bool = True
    data_impl: str = "mmap"
    mmap_warmup: bool = False
    # corrupt-data policy (docs/resilience.md): False (default) skips
    # and counts out-of-bounds documents / corrupt blend prefixes with
    # loud warnings; True fails fast with DatasetCorruptionError
    strict_data: bool = False


# serving KV-pool dtypes: the model dtype spellings plus int8 (the
# quantized pool) — one map feeds BOTH ServingConfig.validate and the
# engine's resolution (serving/engine.py) so the two can never drift
SERVING_KV_DTYPES = {**_DTYPES, "int8": jnp.int8}


@dataclass(frozen=True)
class ServingConfig:
    """Continuous-batching engine config (serving/engine.py — ABSENT in
    the reference, whose server is strictly serial;
    ref: megatron/text_generation_server.py:37 one-lock serving).

    num_slots: batch slots in the persistent decode grid = max requests
    decoding concurrently. max_queue: bounded admission queue; overflow
    is rejected with 429-style backpressure. max_len: per-slot KV region
    length (prompt + generated; defaults to max_position_embeddings).
    kv_dtype: pool dtype — "bfloat16" | "float32" | "int8" (quantized
    pool with per-(token, head) scales), or None to inherit the
    Generator's kv_cache_dtype. prefill_bucket: prompts pad up to this
    multiple so the prefill jit cache hits across lengths (rolling
    sliding-window pools prefill exact-length instead). serial_fallback:
    route /api through the old one-lock serial path."""

    num_slots: int = 8
    max_queue: int = 64
    max_len: Optional[int] = None
    kv_dtype: Optional[str] = None
    prefill_bucket: int = 16
    serial_fallback: bool = False
    # per-request wall-clock deadline measured from submit: queued or
    # running requests past it are evicted and fail with
    # DeadlineExceededError (→ HTTP 504). None = no deadline.
    request_deadline_s: Optional[float] = None
    # decode steps dispatched per host sync: the engine chains K async
    # decode calls on device state and fetches all K tokens in ONE
    # transfer, so syncs/token = 1/K. EOS/eviction/admission happen at
    # sync boundaries, so a finished request's slot burns up to K-1
    # wasted steps and queued requests wait up to K-1 extra steps for a
    # slot. Seeded outputs are token-exact vs K=1 (per-slot rng/logits
    # chains are independent of the sync cadence). 1 = the pre-window
    # behavior (sync every token).
    decode_sync_interval: int = 1
    # admission coalescing: up to this many same-bucket queued prompts
    # prefill in ONE batched call (amortizes the per-call weight stream;
    # batch sizes round up to powers of two so the jit cache stays
    # bounded at O(log slots) entries per length bucket). 1 disables.
    prefill_max_batch: int = 8
    # prefix-cache KV reuse (SGLang's RadixAttention, slot-grid native):
    # finished slots RETAIN their KV on an LRU list instead of freeing;
    # a new prompt sharing a bucket-aligned prefix with a retained (or
    # running) slot's prompt reuses it through ONE on-device region copy
    # and prefills only the suffix. Seeded outputs stay token-exact vs
    # the cache-off engine (the clone copies KV — int8 blocks + scales —
    # verbatim). On ROLLING (sliding-window) pools this additionally
    # requires the block-granular pool (kv_block_size): validate()
    # rejects rolling whole-region retention, whose idle ring writes
    # would clobber retained content.
    enable_prefix_cache: bool = False
    # chunked prefill (Sarathi-Serve): prompts/suffixes longer than this
    # split into chunks the engine interleaves with decode steps, so a
    # long prompt's prefill no longer stalls every in-flight decode for
    # its whole duration. None disables (one monolithic prefill call).
    # Also unsupported on ROLLING pools (an offset>0 chunk would need
    # ring history the W-slot buffer already dropped).
    prefill_chunk: Optional[int] = None
    # retained-slot budget for the prefix cache: at most this many
    # finished slots keep their KV for reuse (the oldest demotes to the
    # free list beyond it). None retains every finished slot — they are
    # reclaimed lazily when admission needs a slot anyway, so the only
    # cost of None is colder free-list slots. With kv_block_size set
    # this caps retained ENTRIES (each pins only its own blocks).
    retained_slots: Optional[int] = None
    # block-granular KV pool (docs/serving.md "Block-granular KV
    # pool"): carve each slot's cap-token region into cap/B fixed
    # blocks over one flat arena, addressed through a device-resident
    # per-slot block map resolved at dispatch time — static shapes and
    # the one-compile decode trace are preserved (only block INDICES
    # are data), but retention pins blocks instead of whole regions
    # (a retained 3-block prefix costs 3 blocks and NO grid row), a
    # prefix hit aliases shared blocks into the new slot's map, and
    # rolling pools become retainable/cloneable/preemptible for the
    # first time (the ring's garbage writes for idle rows land in a
    # shared trash block instead of the retained ring). Seeded outputs
    # are BIT-IDENTICAL with blocks on vs off for every pool flavor
    # (the map resolve is pure data movement). Must divide the slot
    # capacity (rolling W, else max_len); with the prefix cache it
    # must also be a multiple of prefill_bucket so hits stay aligned
    # to both block and jit-bucket boundaries. None (default) keeps
    # the whole-region layout bit-compatibly.
    kv_block_size: Optional[int] = None
    # block-NATIVE decode attention (docs/serving.md "Block-native
    # decode attention"): the Pallas kernel
    # (ops/block_attention_pallas.py) reads the block arena THROUGH
    # the per-slot block map — grid over (slot, kv block), online
    # softmax carried across each slot's block chain, GQA head
    # mapping, int8 dequant in kernel — so the decode / speculative-
    # verify hot path drops the resolve_view/scatter_view bracket
    # entirely: zero O(pool-bytes) gather/scatter traffic per step
    # (the kv_gather_bytes_per_step gauge pins it at 0), and the
    # step's KV append scatters only the touched blocks. Seeded
    # outputs stay token-exact kernel-on vs off (bf16 AND int8 pools;
    # test-pinned across decode / prefix-hit / chunked / preemption /
    # speculative) and decode + verify keep ONE compile each. Inert
    # without kv_block_size (auto-off: there is no arena to index);
    # SLIDING-WINDOW models are EXCLUDED outright — the kernel has no
    # window-band mask (a non-rolling windowed pool would silently
    # attend outside the band), and ROLLING layouts additionally
    # break its contiguous position arithmetic — so windowed pools
    # keep the resolve/scatter bracket (validate() rejects the
    # combination loudly; the engine re-asserts). On CPU the kernel
    # runs in pallas interpret mode (the tier-1 test path).
    block_native_attn: bool = False
    # speculative decoding on the slot grid (docs/serving.md
    # "Speculative decoding"): each engine iteration proposes k draft
    # tokens per running slot (self-drafting n-gram prompt-lookup by
    # default; ServingEngine(drafter=...) is the pluggable seam) and
    # verifies ALL slots' drafts in ONE batched [slots, k+1]-token
    # forward — k+1 committed tokens per weight stream when drafts
    # accept, on the HBM-bandwidth-bound decode path. k is a
    # compile-time bucket like prefill_bucket: one verify trace per
    # enabled k, compiled alongside the (kept) plain decode step.
    # Greedy rows accept by exact match (temperature=0 output is
    # token-exact vs non-speculative); stochastic rows accept by
    # standard point-mass rejection sampling (distribution-correct,
    # not bit-reproducing the non-speculative RNG stream). 0 disables.
    # Unsupported on ROLLING pools (a rejected draft's ring write
    # already evicted history — the rewind invariant can't hold, with
    # or without kv_block_size): validate() rejects it, the engine
    # re-asserts on the RESOLVED pool layout. flash-impl int8 pools
    # are supported (the int8 prefill takes the cached dot path —
    # models/attention.py).
    speculative_k: int = 0
    # --- overload & failure knobs (docs/serving.md "Overload &
    # failure behavior") -----------------------------------------------
    # distinct priority classes: requests carry priority in
    # [0, priority_levels) (higher wins admission ordering and, with
    # `preemption`, may evict lower-priority running slots). 1 = every
    # request equal (the pre-SLO behavior).
    priority_levels: int = 1
    # early load shedding: when the estimated queue delay for a new
    # request already exceeds its (per-request or engine-default)
    # deadline, fail it at SUBMIT time with a retryable 429 +
    # Retry-After instead of letting it burn its whole deadline in the
    # queue and then 504. Only sheds once at least one completion has
    # been observed (the estimate needs a service-time sample).
    shed_on_overload: bool = False
    # graceful degradation (serving/degrade.py, docs/serving.md
    # "Overload, degradation & SLO conformance"): the brownout ladder's
    # maximum level — under SUSTAINED overload the controller walks
    # from full service toward shed one rung at a time (1: disable
    # speculative decoding; 2: + cap best_of to n and max_new_tokens to
    # degrade_max_new_tokens for NEW admissions; 3: + shed the lowest
    # priority class; 4: shed all new admissions — today's cliff),
    # lowering with hysteresis as pressure drains. 0 = no controller at
    # all, behaviorally bit-identical to the pre-ladder engine
    # (test-pinned).
    degrade_ladder: int = 0
    # per-level raise thresholds on the pressure signal
    # (queue_depth/num_slots * occupancy) — None uses the built-in
    # doubling ladder (degrade.DEFAULT_RAISE_AT) truncated to
    # degrade_ladder levels; an explicit tuple must be strictly
    # increasing with one entry per level
    degrade_raise_at: Optional[tuple] = None
    # the lower edge of each rung is hysteresis * its raise edge, and
    # a transition needs this many CONSECUTIVE supervisor-loop
    # evaluations past the edge — one bursty sync window can neither
    # raise nor lower a level
    degrade_hysteresis: float = 0.5
    degrade_dwell_up: int = 2
    degrade_dwell_down: int = 4
    # level-2 cap on max_new_tokens for new admissions (the request's
    # EFFECTIVE config — its serial oracle keys off the clamped value,
    # so degraded completions stay token-exact)
    degrade_max_new_tokens: int = 64
    # SLO targets (None = unset, the counters stay 0): first token
    # later than slo_ttft_ms counts slo_ttft_violations and excludes
    # the request's tokens from goodput_tokens; a host-visible
    # inter-token gap over slo_itl_p99_ms counts slo_itl_violations.
    # Pure observability — neither changes scheduling; tools/
    # chaos_storm.py turns them into per-seed perf laws.
    slo_ttft_ms: Optional[float] = None
    slo_itl_p99_ms: Optional[float] = None
    # priority preemption: a queued higher-priority request with no
    # allocatable slot evicts the lowest-priority running slot. The
    # victim's KV is PARKED in a batch-1 sub-cache (slice_slot — the
    # read half of clone_prefix) together with its carried logits and
    # PRNG key, and it resumes later with one insert_prefill — no
    # re-prefill, token-exact vs never-preempted, and the decode trace
    # stays one compile (preemption is slot bookkeeping + two region
    # copies, never a new program). On ROLLING pools this requires the
    # block-granular pool (kv_block_size) — see validate().
    preemption: bool = False
    # engine supervisor: a crashed engine-loop step fails only the
    # slotted requests it must, requeues the rest, resets the device
    # state and restarts the loop — up to this many times, after which
    # the crash-loop circuit breaker trips (engine goes unhealthy,
    # submits raise EngineUnhealthyError → HTTP 503, /healthz reports
    # unhealthy). 0 = any crash trips the breaker immediately.
    max_engine_restarts: int = 2
    # hung-iteration watchdog (resilience/watchdog.py in detection-only
    # mode): no engine-loop progress within this many seconds fails the
    # in-flight requests (no stranded futures) and restarts the loop
    # when the wedged dispatch returns. None disables. Must comfortably
    # exceed the worst prefill-bucket compile time.
    engine_step_timeout_s: Optional[float] = None
    # --- front door knobs (docs/serving.md "Front door") --------------
    # engine replicas behind the in-process prefix-affinity router
    # (serving/router.py): each replica is a full ServingEngine (own KV
    # pool, queue, supervisor) over the SAME weights; the router routes
    # each request to the replica whose prefix cache holds the longest
    # match (ties: least-loaded), ejects unhealthy replicas from
    # rotation (failed work retries on a survivor, token-exact), and
    # re-admits recovered ones through a half-open canary. 1 = no
    # router at all — the server drives the engine directly,
    # bit-identical to the single-replica build (test-pinned).
    num_replicas: int = 1
    # bounded failover retries per request before its error surfaces
    # (503 only when every replica is down)
    router_max_retries: int = 2
    # a replica that produced no healthy `health()` snapshot for this
    # long is ejected from rotation (wedged replicas get this grace —
    # their watchdog may restart them — hard-down states eject at once)
    router_heartbeat_timeout_s: float = 5.0
    # host-RAM KV tier byte budget (serving/host_tier.py): retained
    # prefix BLOCK LISTS evicted under block pressure demote to host
    # memory (checksum per entry, verified on restore — a corrupt
    # demotion is a miss, never wrong tokens) and restore on a later
    # prefix hit via one device_put, multiplying effective prefix-cache
    # capacity ~10x beyond the grid. Requires enable_prefix_cache +
    # kv_block_size. 0 = off, bit-identical to the tier-less engine
    # (test-pinned).
    host_kv_bytes: int = 0
    # SSE stream registry TTL: a finished stream's request (and its
    # committed tokens) stays resumable via Last-Event-ID for this long
    stream_ttl_s: float = 600.0
    # --- serving mesh (docs/serving.md "Sharded & disaggregated
    # serving"; serving/topology.py) --------------------------------
    # tensor-parallel width of the serving mesh: the engine's compiled
    # programs run under the SAME GSPMD mesh treatment training uses —
    # weights by the training tp rules, the KV arena / slot regions /
    # batch-1 prefill subs sharded over 'tp' on the kv-head axis, the
    # adapter bank's B factors by their projection specs — while the
    # per-slot block map, lengths, adapter indices, and sampling state
    # stay replicated dispatch DATA, so decode / speculative verify /
    # batched prefill keep ONE compile each. The Pallas block-native
    # kernel runs under shard_map on the head-sharded arena (the GQA
    # head loop shrinks per shard). Requires query/kv head counts and
    # the padded vocab divisible by tp. 1 (default) builds no serving
    # mesh at all — the engine lowers bit-identically to today's
    # single-device graph (test-pinned).
    serving_tp: int = 1
    # prefill/decode disaggregation (DistServe, PAPERS.md): the two
    # phases have opposite rooflines (compute-bound vs HBM-bound), so
    # each engine splits its serving devices into a (prefill-group,
    # decode-group) pair of serving_tp-wide meshes. EVERY admission
    # prefills on the prefill group through the standalone batch-1
    # chunk path (`generation.prefill_chunk` — outside the pool, the
    # exact unit to relocate), and "hand off to decode" is a
    # device-to-device copy of the sequence's ceil(plen/B) live
    # physical blocks ONLY (slice -> transfer -> insert_blocks; never
    # a cap-region copy — handoff_bytes_per_req pins it). Requires
    # kv_block_size (the handoff unit is the block) and excludes
    # ROLLING pools; chunk-interleave on one chip group stays the
    # fallback with the knob off (bit-identical, test-pinned). The
    # EngineRouter is the control plane: a replica is a
    # (prefill-group, decode-group) pair and the existing
    # UP->DOWN->PROBING failover + token-exact resubmission cover a
    # dead half.
    disaggregate_prefill: bool = False
    # --- per-phase serving topology (docs/serving.md "Per-phase
    # topology & placement"; serving/topology.py) --------------------
    # per-phase tensor-parallel widths (DistServe's second half):
    # prefill is compute-bound and decode is HBM-bound, so the optimal
    # width differs per phase — a disaggregated engine's prefill group
    # runs `prefill_tp` wide and its decode group `decode_tp` wide,
    # the replica's device budget becomes decode_tp + prefill_tp, and
    # the one handoff device_put reshards the kv-head axis P->D inside
    # the transfer (no extra copy). None (default) = `serving_tp` for
    # both — the symmetric layout, bit-compatible. Unequal widths
    # require disaggregate_prefill (one shared mesh has one width),
    # and each width must divide the head counts and the padded vocab.
    prefill_tp: Optional[int] = None
    decode_tp: Optional[int] = None
    # --- pipeline-sharded serving (docs/serving.md "Pipeline-sharded
    # serving"; serving/topology.py + serving/pp.py) ------------------
    # layer-stage count for the DECODE group: the group's devices
    # split into serving_pp sub-meshes of decode_tp devices each,
    # stage i holds layers [i*L/S, (i+1)*L/S) of the stacked pytree
    # (parallel/pipeline.stage_params_reshape) plus the embedding on
    # stage 0 and the final-norm/LM-head on stage S-1, and the
    # per-layer KV arena partitions on the LAYER axis so each stage
    # holds only its own layers' blocks. The decode step becomes a
    # staged program chain — stage i's compiled segment runs its layer
    # slice and the [num_slots, hidden] activation crosses to stage
    # i+1 via one device_put (the P->D handoff seam) — while the block
    # map, lengths, and sampling state stay replicated dispatch data,
    # so decode/verify/prefill keep ONE compile each PER STAGE.
    # Requires kv_block_size and num_layers % serving_pp == 0;
    # composes with decode_tp/serving_tp (the per-stage width) and
    # REJECTS disaggregate_prefill / explicit prefill_tp /
    # block_native_attn / host_kv_bytes / placement_auto /
    # sliding-window models loudly. 1 (default) builds no staged
    # topology at all — bit-identical pre-pp code paths (test-pinned).
    serving_pp: int = 1
    # interleaved wave count (1F1B on the slot grid): split the
    # num_slots slot grid into pp_waves micro-batches so stage i works
    # wave k while stage i+1 works wave k-1 — depth becomes throughput
    # instead of pure latency; the bubble fraction
    # (serving_pp-1)/(pp_waves+serving_pp-1) exports as the
    # pp_stage_bubble gauge. Requires serving_pp > 1 and
    # num_slots % pp_waves == 0; rejects speculative_k (the verify
    # chain runs whole-grid). 1 (default) = one wave, the plain chain.
    pp_waves: int = 1
    # signal-driven placement (serving/placement.py): let the engine
    # choose the prefill:decode split and per-phase widths from its
    # device budget at build (and from the observed
    # prefill_group_busy / decode_group_busy / queue-depth / TTFT
    # signals at the rolling-upgrade drain barrier — the ONE moment a
    # replica is already quiesced; never mid-serve). Explicit
    # prefill_tp/decode_tp act as the initial plan. The chosen plan is
    # exported through health() and the router aggregate, and every
    # re-plan counts `placement_replans`.
    placement_auto: bool = False
    # device budget per replica for placement_auto (the optimizer
    # picks prefill_tp + decode_tp <= budget). None = the budget the
    # explicit/default widths already occupy (devices_per_engine).
    placement_budget: Optional[int] = None
    # --- multi-tenant LoRA serving (docs/serving.md "Multi-tenant
    # LoRA serving"; serving/adapters.py) ------------------------------
    # device-resident LoRA adapters servable concurrently: the engine
    # allocates a stacked per-layer A/B factor bank of this many rows
    # (plus the reserved identity row 0 — base-model requests ride the
    # same trace with a zero delta) and a per-slot adapter_idx carried
    # next to the KV block map. Indices are data: decode / speculative
    # verify / prefill keep ONE compile each with adapters on, and 0
    # (off) compiles bit-identically to the adapterless engine
    # (test-pinned). Works on every pool flavor — bf16/f32/int8,
    # block/whole-region, rolling — because the low-rank delta rides
    # the q/k/v/o projections, orthogonal to KV layout.
    adapter_slots: int = 0
    # LoRA rank the bank allocates for (static shape). Adapters
    # exported at a smaller rank zero-pad up (same delta); a larger
    # rank is rejected at registration.
    adapter_rank: int = 8
    # host-RAM overflow budget for evicted adapters (bytes): loading
    # adapter N+1 into a full bank demotes the LRU unpinned adapter to
    # a checksummed host copy instead of failing; restore verifies the
    # checksum and a corrupt demotion degrades to a reload of the
    # adapter's .npz — a miss, never wrong weights. 0 = evictions drop
    # the device copy (misses reload from disk).
    adapter_host_bytes: int = 0
    # optional hard ceiling on the device bank's bytes — reject a
    # (slots, rank) combination that would silently eat the KV pool's
    # HBM at validate time instead of OOMing at engine construction.
    # None = no check.
    adapter_max_bank_bytes: Optional[int] = None
    # --- live-weight serving (docs/serving.md "Live weights & rolling
    # upgrade"; serving/weights.py) --------------------------------
    # how long engine.swap_weights waits at the swap barrier for
    # in-flight slots/prefills to finish under the current weights
    # before the swap is cancelled (typed refusal; the engine keeps
    # serving — admissions resume immediately)
    swap_timeout_s: float = 120.0
    # training checkpoint root to WATCH: poll its tracker and hot-swap
    # (single engine) or rolling-upgrade (router fleet) to every newly
    # published checkpoint — trainers drive the serving fleet with
    # zero operator action. A refused (corrupt/mid-publish) checkpoint
    # is counted and NOT retried until the tracker names a new one.
    # None = off.
    watch_checkpoints: Optional[str] = None
    # tracker poll cadence for --watch_checkpoints
    watch_interval_s: float = 5.0
    # --- networked front door (serving/remote.py; docs/serving.md
    # "Front door") -----------------------------------------------------
    # run THIS server as one fleet replica: the engine serves the
    # token-level wire surface a remote front tier consumes —
    # `prompt_tokens` payloads (pre-tokenized admission), GET
    # /invariants (the replica runs its own strict sweep on its live
    # objects and serves the report — KV accounting cannot be checked
    # over the wire), stream cancel, and the admin swap/register
    # endpoints rolling_upgrade drives over HTTP
    replica_mode: bool = False
    # run the ROUTER as a thin front tier over remote replicas:
    # comma-separated "host:port,host:port" of replica-mode servers.
    # The server builds EngineRouter over RemoteReplica handles and
    # holds no model weights at all. None = in-process replicas
    # (num_replicas) as before.
    fleet: Optional[str] = None
    # RemoteReplica transport knobs: per-call connect/read timeouts and
    # bounded transport retries (exponential backoff + jitter,
    # Retry-After honored). These govern the CLIENT side of one HTTP
    # call — whole-request failover retries stay router_max_retries.
    remote_connect_timeout_s: float = 2.0
    remote_read_timeout_s: float = 30.0
    remote_max_retries: int = 2
    # cadence for refreshing each remote replica's affinity digest
    # (prefix_peek/adapter residency snapshot) — affinity stays a HINT;
    # admission re-resolves on the replica
    remote_digest_interval_s: float = 2.0

    def validate(self, model: Optional["ModelConfig"] = None
                 ) -> "ServingConfig":
        assert self.num_slots >= 1, self.num_slots
        assert self.max_queue >= 1, self.max_queue
        assert self.prefill_bucket >= 1, self.prefill_bucket
        assert self.decode_sync_interval >= 1, self.decode_sync_interval
        assert self.prefill_max_batch >= 1, self.prefill_max_batch
        assert self.prefill_chunk is None or self.prefill_chunk >= 1, (
            self.prefill_chunk)
        assert self.retained_slots is None or self.retained_slots >= 0, (
            self.retained_slots)
        assert self.kv_block_size is None or self.kv_block_size >= 1, (
            self.kv_block_size)
        if self.kv_block_size is not None:
            if self.enable_prefix_cache:
                # prefix hits must stay aligned to BOTH the jit-bucket
                # grid (so suffix shapes keep hitting the existing
                # compile cache) and block boundaries (so a hit is
                # pure block-map aliasing, no partial-block
                # copy-on-write)
                assert self.kv_block_size % self.prefill_bucket == 0, (
                    f"kv_block_size={self.kv_block_size} must be a "
                    f"multiple of prefill_bucket="
                    f"{self.prefill_bucket} when enable_prefix_cache "
                    "is set (hits must align to block AND jit-bucket "
                    "boundaries)")
            if model is not None:
                cap = self.max_len or model.max_position_embeddings
                if (model.sliding_window is not None
                        and model.attention_impl == "flash"):
                    cap = min(cap, model.sliding_window)
                assert cap % self.kv_block_size == 0 \
                    or self.kv_block_size >= cap, (
                    f"kv_block_size={self.kv_block_size} must divide "
                    f"the slot capacity ({cap})")
        assert self.priority_levels >= 1, self.priority_levels
        # preemption triggers only when a QUEUED request outranks a
        # RUNNING one; with a single priority class every request
        # clamps to 0 and it can never fire — reject the silently
        # inert combination instead of shipping a no-op knob
        assert not (self.preemption and self.priority_levels < 2), (
            "preemption requires priority_levels >= 2: with one "
            "priority class every request clamps to priority 0 and "
            "no arrival can ever outrank a running slot")
        # graceful degradation (serving/degrade.py): the ladder's
        # shape is validated here so a bad spec fails at config time,
        # not mid-storm
        assert 0 <= self.degrade_ladder <= 4, (
            f"degrade_ladder={self.degrade_ladder} must be in 0..4 "
            "(0 disables; 4 is the full brownout ladder)")
        if self.degrade_raise_at is not None:
            assert self.degrade_ladder, (
                "degrade_raise_at without degrade_ladder is inert: the "
                "thresholds parameterize the controller — set "
                "degrade_ladder >= 1 or drop the thresholds")
            ra = tuple(self.degrade_raise_at)
            assert len(ra) == self.degrade_ladder, (
                f"degrade_raise_at needs one threshold per level: "
                f"degrade_ladder={self.degrade_ladder} but got "
                f"{len(ra)} thresholds")
            assert all(x > 0 for x in ra) and \
                all(b > a for a, b in zip(ra, ra[1:])), (
                f"degrade_raise_at must be positive and strictly "
                f"increasing (a monotone ladder), got {ra}")
        if self.degrade_ladder:
            assert 0.0 < self.degrade_hysteresis < 1.0, (
                f"degrade_hysteresis={self.degrade_hysteresis} must be "
                "a ratio in (0, 1): the lower edge of each rung is "
                "hysteresis * its raise edge")
            assert self.degrade_dwell_up >= 1 and \
                self.degrade_dwell_down >= 1, (
                "degrade dwell counts must be >= 1 supervisor-loop "
                "evaluations")
            assert self.degrade_max_new_tokens >= 1, (
                f"degrade_max_new_tokens={self.degrade_max_new_tokens} "
                "must be >= 1: level 2 clamps new admissions' "
                "max_new_tokens to it")
        assert self.slo_ttft_ms is None or self.slo_ttft_ms > 0.0, (
            self.slo_ttft_ms)
        assert self.slo_itl_p99_ms is None or \
            self.slo_itl_p99_ms > 0.0, self.slo_itl_p99_ms
        assert self.max_engine_restarts >= 0, self.max_engine_restarts
        assert self.engine_step_timeout_s is None or \
            self.engine_step_timeout_s > 0.0, self.engine_step_timeout_s
        if self.block_native_attn and model is not None:
            # the block kernel implements plain causal masking only:
            # no banded-window mask (a non-rolling sliding-window pool
            # would silently need one) and no ring slot->position map
            # (a ROLLING pool's layout breaks the kernel's contiguous
            # position arithmetic) — sliding-window models keep the
            # resolve_view/scatter_view bracket either way
            assert model.sliding_window is None, (
                "block_native_attn is unsupported on sliding-window "
                "models: the block kernel has no window-band mask, "
                "and ROLLING layouts additionally break its "
                "contiguous position arithmetic — sliding-window "
                "pools keep the resolve_view/scatter_view bracket. "
                "Serve this model without --block_native_attn.")
        assert self.speculative_k >= 0, self.speculative_k
        if self.speculative_k:
            max_len = self.max_len
            if max_len is None and model is not None:
                max_len = model.max_position_embeddings
            assert max_len is None or self.speculative_k < max_len, (
                f"speculative_k={self.speculative_k} must be smaller "
                f"than the slot capacity (max_len={max_len})")
        if model is not None and model.sliding_window is not None:
            # ROLLING pools (flash impl caps the region to W < max_len)
            # hold the last W positions ring-ordered by position % W.
            # WHOLE-REGION rolling pools cannot retain, clone, or park:
            # a retained ring row still rides every decode step and its
            # idle garbage writes (at final_length % W) wrap INTO the
            # live ring content. The BLOCK-GRANULAR pool
            # (kv_block_size) lifts prefix-cache and preemption —
            # retained ring blocks hold no grid row, so idle writes
            # land in the shared trash block and the ring content
            # survives verbatim; clones continue a retained sequence
            # at its exact length (or any prefix, while the ring has
            # not wrapped). Two exclusions REMAIN regardless of
            # blocks, each pinned by tests:
            # - prefill_chunk: an offset>0 multi-token chunk's ring
            #   writes evict history its own early queries still need
            #   (write-before-read breaks inside one dispatch);
            # - speculative_k: a rejected draft's ring write already
            #   evicted the position it displaced, so the
            #   accepted-length rewind cannot restore it.
            max_len = self.max_len or model.max_position_embeddings
            rolling = (model.attention_impl == "flash"
                       and model.sliding_window < max_len)
            blocks = self.kv_block_size is not None
            assert not (rolling and self.enable_prefix_cache
                        and not blocks), (
                "enable_prefix_cache on a ROLLING (sliding-window) KV "
                "pool requires the block-granular pool "
                "(--kv_block_size): a retained whole-region ring row "
                "still rides the decode grid and its idle writes wrap "
                "into the live ring. Set kv_block_size (dividing the "
                "window) or serve with the prefix cache off.")
            assert not (rolling and self.preemption and not blocks), (
                "preemption on a ROLLING (sliding-window) KV pool "
                "requires the block-granular pool (--kv_block_size): "
                "whole-region rolling rows cannot park/resume without "
                "their idle ring writes clobbering retained state. "
                "Set kv_block_size or serve without preemption.")
            assert not (rolling and self.prefill_chunk is not None), (
                "prefill_chunk is unsupported on ROLLING "
                "(sliding-window) KV pools (with or without "
                "kv_block_size): an offset>0 chunk's ring writes "
                "evict history its own queries still need within one "
                "dispatch. Serve this model unchunked — rolling "
                "prefix-hit suffixes append single-token steps "
                "instead.")
            assert not (rolling and self.speculative_k), (
                "speculative_k is unsupported on ROLLING "
                "(sliding-window) KV pools (with or without "
                "kv_block_size): the verify window's ring writes "
                "evict history as they land, so rewinding to the "
                "accepted length cannot restore what a rejected "
                "draft overwrote — the write-before-read rewind "
                "invariant breaks. Serve this model without "
                "speculative decoding.")
        # flash-impl int8 pools: NO exclusions anymore. The offset-0
        # flash prefill shortcut is disabled for quantized caches
        # (models/attention.py): every cached int8 forward — prefill,
        # chunk, prefix suffix, preemption replay, verify window —
        # reads the same dequantized cache through the same dot path,
        # so the token-exact cache-on/off contract holds structurally.
        # (Rolling int8 keeps the flash shortcut for prompts longer
        # than W but feeds it the quantize->dequantize round-trip of
        # the fresh k/v — the values the ring actually stores.)
        assert self.request_deadline_s is None or \
            self.request_deadline_s > 0.0, self.request_deadline_s
        assert self.kv_dtype is None or \
            self.kv_dtype in SERVING_KV_DTYPES, self.kv_dtype
        assert self.num_replicas >= 1, self.num_replicas
        assert self.router_max_retries >= 0, self.router_max_retries
        # --- serving mesh (serving/topology.py) -----------------------
        assert self.serving_tp >= 1, self.serving_tp
        assert self.prefill_tp is None or self.prefill_tp >= 1, \
            self.prefill_tp
        assert self.decode_tp is None or self.decode_tp >= 1, \
            self.decode_tp
        eff_pre = self.prefill_tp or self.serving_tp
        eff_dec = self.decode_tp or self.serving_tp
        if self.serving_pp > 1:
            # pipeline-sharded serving runs BOTH phases through the
            # same stage chain at the per-stage width: there is no
            # independent prefill width (prefill_tp is rejected below)
            eff_pre = eff_dec
        if eff_pre != eff_dec:
            assert self.disaggregate_prefill, (
                f"prefill_tp={eff_pre} != decode_tp={eff_dec} requires "
                "disaggregate_prefill: a single-group engine runs both "
                "phases on ONE mesh, so the widths must agree — enable "
                "disaggregation or drop the per-phase overrides")
        if eff_pre > 1 or eff_dec > 1:
            assert not self.serial_fallback, (
                "serving_tp/prefill_tp/decode_tp > 1 requires the "
                "continuous-batching engine: the serial fallback path "
                "builds no serving mesh — drop serial_fallback or the "
                "tp widths")
            if model is not None:
                for phase, tp in (("prefill", eff_pre),
                                  ("decode", eff_dec)):
                    assert model.num_attention_heads % tp == 0 and \
                        model.num_kv_heads % tp == 0, (
                        f"{phase} serving width {tp} (prefill_tp/"
                        "decode_tp/serving_tp) must divide both the "
                        "query head count "
                        f"({model.num_attention_heads}) and the kv "
                        f"head count ({model.num_kv_heads}): the KV "
                        "arena and the attention projections shard on "
                        "the head axes (block_native_attn's "
                        "shard_map'd kernel requires it too — fall "
                        "back to width 1 or the resolve/scatter "
                        "bracket)")
                    assert model.padded_vocab_size % tp == 0, (
                        f"{phase} serving width {tp} must divide the "
                        f"padded vocab ({model.padded_vocab_size}): "
                        "the embedding / LM head shard on the vocab "
                        "dim — adjust make_vocab_size_divisible_by")
        if self.disaggregate_prefill:
            assert not self.serial_fallback, (
                "disaggregate_prefill requires the continuous-batching "
                "engine (the serial path has no prefill group)")
            assert self.kv_block_size is not None, (
                "disaggregate_prefill requires kv_block_size: the "
                "prefill->decode handoff unit is the physical KV "
                "block (ceil(plen/B) live blocks move, never a whole "
                "cap region) — set --kv_block_size or serve "
                "single-group")
            if model is not None and model.sliding_window is not None:
                max_len = self.max_len or model.max_position_embeddings
                rolling = (model.attention_impl == "flash"
                           and model.sliding_window < max_len)
                assert not rolling, (
                    "disaggregate_prefill is unsupported on ROLLING "
                    "(sliding-window) KV pools: the ring's exact-"
                    "length block handoff is not defined — serve "
                    "rolling models single-group "
                    "(chunk-interleave fallback)")
        # --- pipeline-sharded serving (serving/topology.py stages) ----
        assert self.serving_pp >= 1, self.serving_pp
        assert self.pp_waves >= 1, self.pp_waves
        if self.serving_pp > 1:
            assert not self.serial_fallback, (
                "serving_pp > 1 requires the continuous-batching "
                "engine: the serial fallback path builds no serving "
                "mesh — drop serial_fallback or serving_pp")
            assert self.kv_block_size is not None, (
                "serving_pp requires kv_block_size: the per-layer KV "
                "arena partitions on the LAYER axis across stages and "
                "each stage's slice is a block arena — set "
                "--kv_block_size or serve with serving_pp=1")
            assert not self.disaggregate_prefill, (
                "serving_pp does not compose with disaggregate_prefill"
                ": the staged decode chain already owns the cross-mesh "
                "activation seam, and a third (prefill) group would "
                "need its own full-depth weight copy — pick pipeline "
                "stages OR a disaggregated prefill group, not both")
            assert self.prefill_tp is None, (
                "serving_pp rejects an explicit prefill_tp: prefill "
                "runs through the SAME stage chain as decode (each "
                "stage is decode_tp wide) — drop prefill_tp; "
                "decode_tp/serving_tp set the per-stage width")
            assert not getattr(self, "block_native_attn", False), (
                "serving_pp is unsupported with block_native_attn: "
                "the staged arena slices dispatch through the "
                "resolve/scatter bracket — drop block_native_attn or "
                "serving_pp")
            assert not self.host_kv_bytes, (
                "host_kv_bytes is unsupported with serving_pp: the "
                "host tier gathers/restores whole-depth block lists, "
                "but a staged arena splits every block across stage "
                "meshes — disable the host tier or serving_pp")
            assert not self.placement_auto, (
                "placement_auto is unsupported with serving_pp: the "
                "barrier re-mesh re-plans tp widths only — the stage "
                "depth is pinned from config (re-staging the layer "
                "partition is not a placement decision); set "
                "serving_pp explicitly")
            if model is not None:
                assert model.num_layers % self.serving_pp == 0, (
                    f"serving_pp={self.serving_pp} must divide "
                    f"num_layers={model.num_layers}: stages hold "
                    "equal contiguous layer slices "
                    "(parallel/pipeline.stage_params_reshape)")
                assert model.sliding_window is None, (
                    "serving_pp is unsupported on sliding-window "
                    "models: the rolling ring's per-layer offset "
                    "arithmetic does not survive the staged arena "
                    "partition — serve with serving_pp=1")
        if self.pp_waves > 1:
            assert self.serving_pp > 1, (
                "pp_waves > 1 without serving_pp > 1 is inert: waves "
                "interleave the slot grid ACROSS stages — set "
                "serving_pp or drop pp_waves")
            assert self.num_slots % self.pp_waves == 0, (
                f"pp_waves={self.pp_waves} must divide "
                f"num_slots={self.num_slots}: each wave is an equal "
                "slot-grid slice (the compiled per-stage programs "
                "run at one wave shape)")
            assert not self.speculative_k, (
                "speculative_k is unsupported with pp_waves > 1: the "
                "staged verify chain runs whole-grid (W=1) — drop "
                "pp_waves or speculative decoding")
        # --- placement optimizer (serving/placement.py) ---------------
        if self.placement_budget is not None:
            assert self.placement_auto, (
                "placement_budget without placement_auto is inert: the "
                "budget is the optimizer's search space — enable "
                "placement_auto or drop the budget")
            assert self.placement_budget >= 2, (
                f"placement_budget={self.placement_budget} cannot fit "
                "a prefill:decode split (each group needs >= 1 device)")
        if self.placement_auto:
            assert self.disaggregate_prefill, (
                "placement_auto plans the prefill:decode device split "
                "— it requires disaggregate_prefill (a single-group "
                "engine has no split to plan)")
        assert self.router_heartbeat_timeout_s > 0.0, \
            self.router_heartbeat_timeout_s
        assert self.stream_ttl_s > 0.0, self.stream_ttl_s
        assert self.host_kv_bytes >= 0, self.host_kv_bytes
        if self.host_kv_bytes:
            # the tier demotes/restores retained BLOCK LISTS — the unit
            # the block-granular pool pins and the prefix index routes
            # hits through; without either there is nothing to demote
            assert self.enable_prefix_cache \
                and self.kv_block_size is not None, (
                "host_kv_bytes requires enable_prefix_cache AND "
                "kv_block_size: the host tier demotes retained prefix "
                "BLOCK lists (docs/serving.md 'Front door')")
        assert not (self.num_replicas > 1 and self.serial_fallback), (
            "num_replicas > 1 routes through the continuous-batching "
            "engine; serial_fallback has no replicas to route over")
        # --- networked front door (serving/remote.py) ----------------
        assert self.remote_connect_timeout_s > 0.0, \
            self.remote_connect_timeout_s
        assert self.remote_read_timeout_s > 0.0, self.remote_read_timeout_s
        assert self.remote_max_retries >= 0, self.remote_max_retries
        assert self.remote_digest_interval_s > 0.0, \
            self.remote_digest_interval_s
        if self.fleet is not None:
            addrs = [a for a in self.fleet.split(",") if a.strip()]
            assert addrs, "fleet must name at least one host:port"
            for a in addrs:
                assert ":" in a, (
                    f"fleet address {a!r} must be host:port")
            assert not self.serial_fallback, (
                "fleet mode routes over remote replicas; the serial "
                "fallback path has no router to run")
            assert self.num_replicas == 1, (
                "fleet mode and in-process replicas are exclusive: "
                "the front tier holds no engines — drop num_replicas "
                "or fleet")
            assert not self.replica_mode, (
                "a server is either one fleet replica (replica_mode) "
                "or the front tier over them (fleet), not both")
        if self.replica_mode:
            assert not self.serial_fallback, (
                "replica_mode serves the continuous-batching engine's "
                "wire surface; the serial path has none")
        # --- live-weight serving (serving/weights.py) ----------------
        assert self.swap_timeout_s > 0.0, self.swap_timeout_s
        assert self.watch_interval_s > 0.0, self.watch_interval_s
        assert not (self.watch_checkpoints and self.serial_fallback), (
            "watch_checkpoints requires the continuous-batching "
            "engine: the serial fallback path has no engine to "
            "hot-swap — drop serial_fallback or the watcher")
        # --- multi-tenant LoRA serving (serving/adapters.py) ----------
        assert self.adapter_slots >= 0, self.adapter_slots
        assert self.adapter_host_bytes >= 0, self.adapter_host_bytes
        if self.adapter_slots:
            assert self.adapter_rank >= 1, (
                f"adapter_slots={self.adapter_slots} requires "
                f"adapter_rank >= 1 (got {self.adapter_rank}): a "
                "rank-0 bank holds no delta at all — disable adapters "
                "(adapter_slots=0) or pick a positive rank")
            assert not self.serial_fallback, (
                "adapter_slots > 0 requires the continuous-batching "
                "engine: the serial fallback path threads no adapter "
                "bank, so adapter requests would silently decode the "
                "BASE model. Drop serial_fallback or adapter_slots.")
            if model is not None:
                # the exactness contract (engine == merged-weights
                # serial oracle) requires the projection be LINEAR in
                # the weights: quantize(W)·x + A·B·x differs from
                # quantize(W + A·B)·x because the int8 quantizer is
                # not linear — per-tenant outputs would silently drift
                # from any merged reference. int8 KV pools
                # (kv_dtype="int8") stay fully supported: the cache
                # quantizes the adapted k/v like any other values.
                assert model.quantized_gemm == "none", (
                    "adapter_slots > 0 is unsupported with "
                    "quantized_gemm='int8': the low-rank delta rides "
                    "OUTSIDE the quantized projection, so factored "
                    "serving and a merged-weights reference are not "
                    "token-equivalent (the quantizer is nonlinear). "
                    "Serve adapters with fp GEMMs — int8 KV pools "
                    "(kv_dtype='int8') and int8-resident base WEIGHTS "
                    "via quantize_weights remain available.")
            if self.adapter_max_bank_bytes is not None \
                    and model is not None:
                from megatron_tpu.serving.adapters import \
                    adapter_bank_nbytes
                need = adapter_bank_nbytes(model, self.adapter_slots,
                                           self.adapter_rank)
                assert need <= self.adapter_max_bank_bytes, (
                    f"adapter bank of {self.adapter_slots} slots at "
                    f"rank {self.adapter_rank} needs {need} device "
                    f"bytes, exceeding adapter_max_bank_bytes="
                    f"{self.adapter_max_bank_bytes}: lower the slot "
                    "count or rank, or raise the budget")
        else:
            assert self.adapter_host_bytes == 0, (
                "adapter_host_bytes > 0 without adapter_slots: there "
                "is no bank to overflow — set adapter_slots or drop "
                "the host budget")
        if self.max_len is not None:
            assert self.max_len >= 1
            if model is not None and model.max_position_embeddings:
                assert self.max_len <= model.max_position_embeddings, (
                    f"serving max_len={self.max_len} exceeds "
                    f"max_position_embeddings="
                    f"{model.max_position_embeddings}")
        return self


@dataclass(frozen=True)
class ResilienceConfig:
    """Fault-tolerance knobs (megatron_tpu/resilience/ — ABSENT in the
    reference beyond SIGTERM + NaN counting; see docs/resilience.md).

    Checkpoint integrity: `checkpoint_integrity` writes a per-checkpoint
    SHA-256 manifest on save and verifies it on load, falling back to
    the newest valid checkpoint when the tracker names a torn/corrupt
    one. `keep_last_k` prunes old iter_* dirs after each save but never
    deletes the last valid checkpoint. Retrying I/O: checkpoint/tracker
    reads+writes retry `io_retries` times with exponential backoff
    (`io_backoff_s` doubling up to `io_backoff_max_s`, ±`io_jitter`).
    Divergence guard: after `max_consecutive_nonfinite` NaN/inf steps
    (0 disables) or a finite loss above `loss_spike_factor` × the
    rolling `loss_spike_window`-step mean, the loop rolls back to the
    last checkpoint, replays the exact data order from its saved
    iterator state, and quarantines the poisoned step window; more than
    `max_rollbacks` rollbacks aborts with TrainingDivergedError.
    Watchdog: a train step exceeding `step_timeout_s` (None disables)
    dumps stacks, attempts a final checkpoint, and exits with
    `watchdog_exit_code` so a supervisor can distinguish hangs."""

    checkpoint_integrity: bool = True
    keep_last_k: Optional[int] = None
    io_retries: int = 4
    io_backoff_s: float = 0.5
    io_backoff_max_s: float = 30.0
    io_jitter: float = 0.25
    max_consecutive_nonfinite: int = 3
    loss_spike_factor: Optional[float] = None
    loss_spike_window: int = 32
    max_rollbacks: int = 2
    step_timeout_s: Optional[float] = None
    watchdog_exit_code: int = 43

    def validate(self) -> "ResilienceConfig":
        assert self.io_retries >= 1, self.io_retries
        assert self.io_backoff_s >= 0.0
        assert self.io_backoff_max_s >= self.io_backoff_s
        assert 0.0 <= self.io_jitter <= 1.0, self.io_jitter
        assert self.keep_last_k is None or self.keep_last_k >= 1, (
            f"keep_last_k={self.keep_last_k} must be >= 1 (None keeps "
            "all)")
        assert self.max_consecutive_nonfinite >= 0
        assert self.loss_spike_factor is None or \
            self.loss_spike_factor > 1.0, (
            f"loss_spike_factor={self.loss_spike_factor} must exceed "
            "1.0 (it multiplies the rolling mean)")
        assert self.loss_spike_window >= 1
        assert self.max_rollbacks >= 0
        assert self.step_timeout_s is None or self.step_timeout_s > 0.0
        return self


@dataclass(frozen=True)
class MegatronConfig:
    model: ModelConfig = field(default_factory=ModelConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    training: TrainingConfig = field(default_factory=TrainingConfig)
    data: DataConfig = field(default_factory=DataConfig)
    serving: ServingConfig = field(default_factory=ServingConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)

    def validate(self, n_devices: Optional[int] = None) -> "MegatronConfig":
        """Derive + consistency-check, mirroring validate_args
        (ref: megatron/arguments.py:52-345)."""
        model = self.model.derived()
        par = self.parallel
        tr = self.training
        assert model.num_attention_heads % par.tensor_parallel == 0 or \
            par.tensor_parallel % model.num_attention_heads == 0, (
            "attention heads must shard evenly over tp")
        if model.num_kv_heads is not None and par.tensor_parallel > 1:
            q_per_kv = model.num_attention_heads // max(model.num_kv_heads, 1)
            del q_per_kv  # kv heads may be < tp; they get replicated
        if par.sequence_parallel:
            assert par.tensor_parallel >= 1
            assert model.seq_length % max(par.tensor_parallel, 1) == 0, (
                "sequence parallel requires seq_length divisible by tp")
        if model.num_experts > 1:
            assert 1 <= model.moe_top_k <= model.num_experts, (
                f"moe_top_k={model.moe_top_k} must be in "
                f"[1, num_experts={model.num_experts}]")
            assert model.moe_dispatch in ("sort", "dense"), (
                f"moe_dispatch={model.moe_dispatch!r} "
                "(expected 'sort' or 'dense')")
            assert par.expert_axis in ("tp", "dp"), par.expert_axis
            if par.expert_axis == "tp":
                ep_size = max(par.tensor_parallel, 1)
            else:
                ep_size = (par.data_parallel
                           or (par.derive_dp(n_devices)
                               if n_devices else None))
                # an unknown dp cannot be assumed 1: the pp>1 guard
                # below would pass vacuously and the run would die in
                # the partitioner SIGABRT instead of here
                assert ep_size is not None or par.pipeline_parallel == 1, (
                    "expert_axis='dp' with pipeline_parallel>1 needs dp "
                    "known at validate time — pass n_devices to "
                    "validate() or set ParallelConfig.data_parallel")
            if ep_size is not None:
                assert model.num_experts % max(ep_size, 1) == 0, (
                    f"num_experts={model.num_experts} must shard evenly "
                    f"over the '{par.expert_axis}' mesh axis "
                    f"(size {ep_size}) — parallel/sharding.py "
                    "'experts' rule")
            # XLA's SPMD partitioner CHECK-fails (spmd_partitioner_util.
            # cc:495 — a hard SIGABRT, not a python error) when the
            # expert bank's sharded 'experts' dim meets the pipeline's
            # partial-manual shard_map region; verified on current jax
            # for BOTH expert_axis choices and both dispatch impls
            # (PERF_NOTES "MoE under pp"). Same CHECK family as the
            # ZeRO-1 pp exclusion. MoE+pp therefore requires the expert
            # axis be UNSPLIT (size-1); expert sharding composes freely
            # at pp=1, and pp MoE composes with dp/sp.
            # (ep_size is None only when pipeline_parallel == 1 — the
            # unknown-dp case was rejected above — so the short-circuit
            # below never compares against None)
            assert par.pipeline_parallel == 1 or ep_size == 1, (
                f"MoE with pipeline_parallel={par.pipeline_parallel} "
                f"requires the expert mesh axis be unsplit (got "
                f"'{par.expert_axis}' size {ep_size}): sharded experts "
                "inside the pp shard_map trip an XLA partitioner CHECK "
                "(hard abort; see PERF_NOTES 'MoE under pp'). Use "
                "pp=1 for expert parallelism, or pp>1 with "
                "tensor_parallel=1 / expert_axis='tp'-on-tp1")
        if model.sliding_window is not None:
            assert model.sliding_window >= 1, (
                f"sliding_window={model.sliding_window} must be >= 1 "
                "(0/negative would mask EVERY key)")
            if model.attention_impl in ("ring", "ulysses"):
                from megatron_tpu.utils.logging import print_rank_0
                print_rank_0(
                    f"warning: attention_impl={model.attention_impl!r} "
                    "has no sliding-window plumbing — attention falls "
                    "back to the unfused dot path (O(s^2) scores); use "
                    "attention_impl=flash for banded attention")
        if model.attention_impl in ("ring", "ulysses") and \
                model.attention_dropout > 0.0:
            # the cp ring paths have no dropout plumbing; training traces
            # with active attention dropout route to the unfused dot path
            # (models/attention.py dropout_active) — correct, but the user
            # should know the cp impl they asked for will not run. flash
            # carries dropout natively (blockwise per-block masks).
            from megatron_tpu.utils.logging import print_rank_0
            print_rank_0(
                f"warning: attention_impl={model.attention_impl!r} with "
                f"attention_dropout={model.attention_dropout} falls back "
                "to the unfused dot path during training (the cp rings "
                "have no dropout plumbing); eval keeps the fused path, "
                "and attention_impl=flash carries dropout natively")
        if model.attention_impl == "ulysses" and par.context_parallel > 1:
            # fail at config time, not first jit trace
            nkv = model.num_kv_heads or model.num_attention_heads
            assert model.num_attention_heads % par.context_parallel == 0 \
                and nkv % par.context_parallel == 0, (
                f"ulysses needs query AND kv head counts divisible by "
                f"cp={par.context_parallel} (got "
                f"nq={model.num_attention_heads}, nkv={nkv}); use "
                f"--context_parallel_algo ring")
        assert model.num_layers % par.pipeline_parallel == 0, (
            f"num_layers {model.num_layers} must divide evenly into "
            f"pp={par.pipeline_parallel} stages")
        if par.virtual_pipeline_chunks > 1:
            per_stage = model.num_layers // par.pipeline_parallel
            assert per_stage % par.virtual_pipeline_chunks == 0
        assert par.pipeline_schedule in ("1f1b", "gpipe"), (
            f"unknown pipeline_schedule {par.pipeline_schedule!r}")
        # vpp>1 + 1f1b runs the interleaved 1F1B schedule (memory flat in
        # n_micro; parallel/pipeline.py _pipeline_train_1f1b_interleaved) —
        # the r3 demotion to gpipe is gone (VERDICT r3 missing #2)
        if par.pipeline_store_activations and \
                par.pipeline_schedule != "1f1b":
            from megatron_tpu.utils.logging import print_rank_0
            print_rank_0(
                "warning: --pipeline_store_activations only applies to "
                "the 1f1b schedule; ignoring it for "
                f"pipeline_schedule={par.pipeline_schedule!r}")
            par = dataclasses.replace(par,
                                      pipeline_store_activations=False)
        gbs = tr.global_batch_size
        if gbs is None:
            dp = par.data_parallel or (par.derive_dp(n_devices) if n_devices else 1)
            gbs = tr.micro_batch_size * dp
            tr = dataclasses.replace(tr, global_batch_size=gbs)
        if n_devices is not None and par.data_parallel is None:
            par = dataclasses.replace(par, data_parallel=par.derive_dp(n_devices))
        if par.data_parallel:
            assert tr.global_batch_size % (tr.micro_batch_size * par.data_parallel) == 0, (
                f"global batch {tr.global_batch_size} must be divisible by "
                f"micro_batch*dp={tr.micro_batch_size * par.data_parallel}")
        self.serving.validate(model)
        self.resilience.validate()
        return dataclasses.replace(self, model=model, parallel=par, training=tr)

    @property
    def num_microbatches(self) -> int:
        dp = self.parallel.data_parallel or 1
        return self.training.global_batch_size // (self.training.micro_batch_size * dp)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), default=str, indent=2)

    @staticmethod
    def from_dict(d: dict) -> "MegatronConfig":
        def build(cls, sub):
            fields = {f.name for f in dataclasses.fields(cls)}
            return cls(**{k: v for k, v in sub.items() if k in fields})
        return MegatronConfig(
            model=build(ModelConfig, d.get("model", {})),
            parallel=build(ParallelConfig, d.get("parallel", {})),
            optimizer=build(OptimizerConfig, d.get("optimizer", {})),
            training=build(TrainingConfig, d.get("training", {})),
            data=build(DataConfig, d.get("data", {})),
            serving=build(ServingConfig, d.get("serving", {})),
            resilience=build(ResilienceConfig, d.get("resilience", {})),
        )


# ---------------------------------------------------------------------------
# Model presets (ref: weights2megatron/weights2megatron.py:16-261 per-size
# configs; llama_model.py / falcon_model.py assertions)
# ---------------------------------------------------------------------------

def llama2_config(size: str = "7b", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(num_layers=2, hidden_size=256, num_attention_heads=4,
                     vocab_size=32000, seq_length=512,
                     attention_impl="dot"),
        "7b": dict(num_layers=32, hidden_size=4096, num_attention_heads=32,
                   ffn_hidden_size=11008, vocab_size=32000, seq_length=4096),
        "13b": dict(num_layers=40, hidden_size=5120, num_attention_heads=40,
                    ffn_hidden_size=13824, vocab_size=32000, seq_length=4096),
        "70b": dict(num_layers=80, hidden_size=8192, num_attention_heads=64,
                    num_kv_heads=8, ffn_hidden_size=28672, vocab_size=32000,
                    seq_length=4096),
    }
    base = dict(
        use_rotary_emb=True, norm_type="rmsnorm", norm_epsilon=1e-5,
        activation="swiglu", use_bias=False, use_post_ln=False,
        parallel_attn=False, tie_embed_logits=False,
        # TPU-first default: real-model presets take the Pallas flash path
        # (the reference gates it behind --use_flash_attn; here dot would
        # materialize O(s^2) scores in HBM for no reason). The dispatch
        # still auto-falls back to dot where flash cannot apply (KV-cache
        # decode, segment/EOD-reset masks, active attention dropout —
        # models/attention.py). The "tiny" presets keep dot: they exist
        # for cheap CPU tests. Opt out with --attention_impl dot.
        attention_impl="flash",
    )
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base).derived()


def falcon_config(size: str = "7b", **overrides) -> ModelConfig:
    presets = {
        "tiny": dict(num_layers=2, hidden_size=256, num_attention_heads=4,
                     num_kv_heads=1, vocab_size=65024, seq_length=512,
                     attention_impl="dot"),
        "7b": dict(num_layers=32, hidden_size=4544, num_attention_heads=71,
                   num_kv_heads=1, vocab_size=65024, seq_length=2048),
        "40b": dict(num_layers=60, hidden_size=8192, num_attention_heads=128,
                    num_kv_heads=8, vocab_size=65024, seq_length=2048,
                    parallel_layernorm=True),
    }
    base = dict(
        use_rotary_emb=True, norm_type="layernorm", norm_epsilon=1e-5,
        activation="gelu", use_bias=False, use_post_ln=False,
        parallel_attn=True, tie_embed_logits=True,
        attention_impl="flash",  # see llama2_config
    )
    base.update(presets[size])
    base.update(overrides)
    return ModelConfig(**base).derived()


def mixtral_config(size: str = "8x7b", **overrides) -> ModelConfig:
    """Mixtral presets (beyond the reference — it has no MoE).
    moe_capacity_factor defaults to num_experts/moe_top_k: Mixtral is
    DROPLESS, and that capacity guarantees no token ever drops, making
    converted-checkpoint inference bit-faithful (convert/hf.py
    hf_mixtral_to_params). Lower it for capacity-bounded training."""
    presets = {
        "tiny": dict(num_layers=2, hidden_size=256, num_attention_heads=8,
                     num_kv_heads=2, ffn_hidden_size=512, vocab_size=32000,
                     seq_length=512, num_experts=4, attention_impl="dot"),
        # seq_length 4096 is a working default (the dense dispatch is
        # O(s^2) — see models/moe.py); the WEIGHTS support 32k positions,
        # so max_position_embeddings carries the real context window
        "8x7b": dict(num_layers=32, hidden_size=4096,
                     num_attention_heads=32, num_kv_heads=8,
                     ffn_hidden_size=14336, vocab_size=32000,
                     seq_length=4096, max_position_embeddings=32768,
                     num_experts=8),
    }
    if size not in presets:
        raise ValueError(f"unknown mixtral size {size!r}; "
                         f"valid: {sorted(presets)}")
    base = dict(
        use_rotary_emb=True, rope_theta=1e6, norm_type="rmsnorm",
        norm_epsilon=1e-5, activation="swiglu", use_bias=False,
        use_post_ln=False, tie_embed_logits=False, moe_top_k=2,
        attention_impl="flash",  # see llama2_config
    )
    base.update(presets[size])
    base.update(overrides)
    # AFTER overrides: the dropless default must track the FINAL E and K
    # (an explicit user capacity_factor still wins)
    base.setdefault("moe_capacity_factor",
                    base["num_experts"] / base["moe_top_k"])
    return ModelConfig(**base).derived()


def gpt_config(**overrides) -> ModelConfig:
    base = dict(
        num_layers=12, hidden_size=768, num_attention_heads=12,
        vocab_size=50257, seq_length=1024, use_rotary_emb=False,
        use_position_embedding=True, norm_type="layernorm",
        activation="gelu", use_bias=True, tie_embed_logits=True,
    )
    base.update(overrides)
    return ModelConfig(**base).derived()


MODEL_PRESETS = {
    "llama2-tiny": lambda: llama2_config("tiny"),
    "llama2-7b": lambda: llama2_config("7b"),
    "llama2-13b": lambda: llama2_config("13b"),
    "llama2-70b": lambda: llama2_config("70b"),
    "falcon-tiny": lambda: falcon_config("tiny"),
    "falcon-7b": lambda: falcon_config("7b"),
    "falcon-40b": lambda: falcon_config("40b"),
    "mixtral-tiny": lambda: mixtral_config("tiny"),
    "mixtral-8x7b": lambda: mixtral_config("8x7b"),
    "gpt2": gpt_config,
}
