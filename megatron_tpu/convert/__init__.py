from megatron_tpu.convert.hf import (  # noqa: F401
    hf_falcon_to_params, hf_llama_to_params, params_to_hf_llama)
