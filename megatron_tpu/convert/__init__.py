from megatron_tpu.convert.hf import (  # noqa: F401
    hf_falcon_to_params, hf_llama_to_params, hf_mixtral_to_params,
    params_to_hf_falcon, params_to_hf_llama, params_to_hf_mixtral)
from megatron_tpu.convert.meta import (  # noqa: F401
    merge_meta_llama, meta_llama_to_params)
from megatron_tpu.convert.megatron import (  # noqa: F401
    config_from_megatron_args, load_megatron_checkpoint, megatron_to_params,
    params_to_megatron, save_megatron_checkpoint)
