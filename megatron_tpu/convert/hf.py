"""Weight conversion: HuggingFace Llama/Falcon <-> megatron_tpu param trees.

TPU-native equivalent of the reference's conversion toolchain
(ref: weights2megatron/weights2megatron.py:16-261 — HF/Meta -> Megatron,
weights2megatron/megatron2hf.py:60-471 — Megatron -> HF, and the rotary
QKV permutation permute_qkv.py:12-81).

Layout notes:
- HF nn.Linear stores W as [out, in] and computes y = x @ W^T; our params
  store [in, out], so every projection transposes on the way in.
- RoPE convention: HF applies rotate-half (pairs (i, i+hd/2)); we use the
  Meta interleaved-pair convention (pairs (2i, 2i+1)) like the reference
  (ref: permute_qkv.py docstring + megatron/model/positional_embeddings.py).
  Conversion reorders each head's output channels so
  new[2i], new[2i+1] = hf[i], hf[i + hd/2] — numerics then match end-to-end.
- Vocab padding: the embedding/lm_head are zero-padded to
  cfg.padded_vocab_size (ref: megatron/tokenizer/tokenizer.py:42-62).
- The result is the layout-free logical tree; sharding/stacking for the
  device mesh happens at load time (unlike the reference, which bakes
  tp/pp into checkpoint files and needs the offline resharder
  tools/checkpoint_util.py).
"""
from __future__ import annotations

from typing import Mapping

import numpy as np

from megatron_tpu.config import ModelConfig


def _t(w) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(w).T)


def interleave_rope_rows(w: np.ndarray, n_heads: int, head_dim: int
                         ) -> np.ndarray:
    """Reorder a [n_heads*head_dim, in] projection's output rows from HF
    rotate-half order to Meta interleaved order
    (ref: weights2megatron/permute_qkv.py:12-81, inverse direction)."""
    out, inp = w.shape
    assert out == n_heads * head_dim
    w = w.reshape(n_heads, head_dim, inp)
    half = head_dim // 2
    inter = np.empty_like(w)
    inter[:, 0::2] = w[:, :half]
    inter[:, 1::2] = w[:, half:]
    return inter.reshape(out, inp)


def deinterleave_rope_rows(w: np.ndarray, n_heads: int, head_dim: int
                           ) -> np.ndarray:
    """Inverse of interleave_rope_rows (ours -> HF)."""
    out, inp = w.shape
    w = w.reshape(n_heads, head_dim, inp)
    half = head_dim // 2
    de = np.empty_like(w)
    de[:, :half] = w[:, 0::2]
    de[:, half:] = w[:, 1::2]
    return de.reshape(out, inp)


def _pad_vocab(w: np.ndarray, padded: int) -> np.ndarray:
    v = w.shape[0]
    if v == padded:
        return w
    assert v < padded
    return np.concatenate(
        [w, np.zeros((padded - v, w.shape[1]), w.dtype)], axis=0)


def _llama_backbone_import(sd: Mapping[str, np.ndarray], cfg: ModelConfig,
                           dtype, mlp_import) -> dict:
    """Shared Llama-backbone import (attention/norms/embedding/head);
    `mlp_import(get, prefix) -> {name: array}` supplies the per-layer MLP
    mapping — dense GLU for Llama, block_sparse_moe for Mixtral."""
    hd = cfg.kv_channels
    nq = cfg.num_attention_heads
    nkv = cfg.num_kv_heads
    L = cfg.num_layers

    def get(name):
        return np.asarray(sd[name], dtype=dtype)

    layers = {"attention": {"wq": [], "wkv": [], "wo": []},
              "mlp": None,
              "input_norm": {"scale": []},
              "post_attn_norm": {"scale": []}}
    for i in range(L):
        p = f"model.layers.{i}."
        wq = interleave_rope_rows(get(p + "self_attn.q_proj.weight"), nq, hd)
        wk = interleave_rope_rows(get(p + "self_attn.k_proj.weight"), nkv, hd)
        wv = get(p + "self_attn.v_proj.weight")
        layers["attention"]["wq"].append(_t(wq))
        layers["attention"]["wkv"].append(
            np.concatenate([_t(wk), _t(wv)], axis=1))
        layers["attention"]["wo"].append(_t(get(p + "self_attn.o_proj.weight")))
        mlp = mlp_import(get, p)
        if layers["mlp"] is None:
            layers["mlp"] = {k: [] for k in mlp}
        for k, v in mlp.items():
            layers["mlp"][k].append(v)
        layers["input_norm"]["scale"].append(get(p + "input_layernorm.weight"))
        layers["post_attn_norm"]["scale"].append(
            get(p + "post_attention_layernorm.weight"))

    stacked = {k: ({kk: np.stack(vv) for kk, vv in v.items()})
               for k, v in layers.items()}
    params = {
        "embedding": {"word_embeddings": _pad_vocab(
            get("model.embed_tokens.weight"), cfg.padded_vocab_size)},
        "transformer": stacked,
        "final_norm": {"scale": get("model.norm.weight")},
    }
    if not cfg.tie_embed_logits:
        params["lm_head"] = _t(_pad_vocab(get("lm_head.weight"),
                                          cfg.padded_vocab_size))
    return params


def hf_llama_to_params(sd: Mapping[str, np.ndarray], cfg: ModelConfig,
                       dtype=np.float32) -> dict:
    """HF LlamaForCausalLM state dict -> megatron_tpu param tree
    (ref: weights2megatron.py llama_to_megatron + permute_qkv)."""

    def mlp_import(get, p):
        gate = _t(get(p + "mlp.gate_proj.weight"))  # [h, ffn]
        up = _t(get(p + "mlp.up_proj.weight"))
        return {"w1": np.stack([gate, up], axis=1),  # [h, 2, ffn]
                "w2": _t(get(p + "mlp.down_proj.weight"))}

    return _llama_backbone_import(sd, cfg, dtype, mlp_import)


def _llama_backbone_export(params, cfg: ModelConfig, dtype,
                           mlp_export) -> dict:
    """Shared Llama-backbone export; `mlp_export(t, i, prefix) ->
    {hf_name: array}` supplies the per-layer MLP mapping."""
    hd = cfg.kv_channels
    nq = cfg.num_attention_heads
    nkv = cfg.num_kv_heads
    L = cfg.num_layers
    t = params["transformer"]
    sd = {}
    v = cfg.vocab_size
    sd["model.embed_tokens.weight"] = np.asarray(
        params["embedding"]["word_embeddings"], dtype)[:v]
    sd["model.norm.weight"] = np.asarray(params["final_norm"]["scale"], dtype)
    if not cfg.tie_embed_logits:
        sd["lm_head.weight"] = _t(np.asarray(params["lm_head"], dtype))[:v]
    else:
        sd["lm_head.weight"] = sd["model.embed_tokens.weight"]
    for i in range(L):
        p = f"model.layers.{i}."
        wq = _t(np.asarray(t["attention"]["wq"][i], dtype))  # [nq*hd, h]
        sd[p + "self_attn.q_proj.weight"] = deinterleave_rope_rows(wq, nq, hd)
        wkv = np.asarray(t["attention"]["wkv"][i], dtype)  # [h, 2*nkv*hd]
        wk, wv = wkv[:, :nkv * hd], wkv[:, nkv * hd:]
        sd[p + "self_attn.k_proj.weight"] = deinterleave_rope_rows(
            _t(wk), nkv, hd)
        sd[p + "self_attn.v_proj.weight"] = _t(wv)
        sd[p + "self_attn.o_proj.weight"] = _t(
            np.asarray(t["attention"]["wo"][i], dtype))
        sd.update(mlp_export(t, i, p))
        sd[p + "input_layernorm.weight"] = np.asarray(
            t["input_norm"]["scale"][i], dtype)
        sd[p + "post_attention_layernorm.weight"] = np.asarray(
            t["post_attn_norm"]["scale"][i], dtype)
    return sd


def params_to_hf_llama(params, cfg: ModelConfig, dtype=np.float32) -> dict:
    """megatron_tpu param tree -> HF LlamaForCausalLM state dict
    (ref: megatron2hf.py:60-471, inverse QKV permute)."""

    def mlp_export(t, i, p):
        w1 = np.asarray(t["mlp"]["w1"][i], dtype)  # [h, 2, ffn]
        return {p + "mlp.gate_proj.weight": _t(w1[:, 0]),
                p + "mlp.up_proj.weight": _t(w1[:, 1]),
                p + "mlp.down_proj.weight": _t(
                    np.asarray(t["mlp"]["w2"][i], dtype))}

    return _llama_backbone_export(params, cfg, dtype, mlp_export)


def hf_falcon_to_params(sd: Mapping[str, np.ndarray], cfg: ModelConfig,
                        dtype=np.float32) -> dict:
    """HF FalconForCausalLM state dict -> megatron_tpu param tree
    (ref: weights2megatron.py falcon_to_megatron).

    Falcon fuses QKV as nkv groups of (q_per_group + 2) heads
    [nkv, q_per_kv + 2, hd, h] — the last two heads of each group are that
    group's K and V (same grouped layout the reference reshapes to at
    megatron/model/transformer.py:440-455)."""
    hd = cfg.kv_channels
    nq = cfg.num_attention_heads
    nkv = cfg.num_kv_heads
    qpg = nq // nkv
    L = cfg.num_layers
    h = cfg.hidden_size

    def get(name):
        return np.asarray(sd[name], dtype=dtype)

    layers: dict = {
        "attention": {"wq": [], "wkv": [], "wo": []},
        "mlp": {"w1": [], "w2": []},
    }
    if cfg.use_post_ln or not cfg.parallel_attn:
        raise NotImplementedError("falcon conversion expects parallel_attn")
    layers["input_norm"] = {"scale": [], "bias": []}
    if cfg.parallel_layernorm:
        layers["mlp_norm"] = {"scale": [], "bias": []}

    for i in range(L):
        p = f"transformer.h.{i}."
        qkv = get(p + "self_attention.query_key_value.weight")
        qkv = qkv.reshape(nkv, qpg + 2, hd, h)
        q = qkv[:, :qpg].reshape(nq * hd, h)
        k = qkv[:, qpg].reshape(nkv * hd, h)
        v = qkv[:, qpg + 1].reshape(nkv * hd, h)
        q = interleave_rope_rows(q, nq, hd)
        k = interleave_rope_rows(k, nkv, hd)
        layers["attention"]["wq"].append(_t(q))
        layers["attention"]["wkv"].append(np.concatenate([_t(k), _t(v)], 1))
        layers["attention"]["wo"].append(
            _t(get(p + "self_attention.dense.weight")))
        layers["mlp"]["w1"].append(_t(get(p + "mlp.dense_h_to_4h.weight")))
        layers["mlp"]["w2"].append(_t(get(p + "mlp.dense_4h_to_h.weight")))
        if cfg.parallel_layernorm:  # falcon-40b: ln_attn + ln_mlp
            layers["input_norm"]["scale"].append(get(p + "ln_attn.weight"))
            layers["input_norm"]["bias"].append(get(p + "ln_attn.bias"))
            layers["mlp_norm"]["scale"].append(get(p + "ln_mlp.weight"))
            layers["mlp_norm"]["bias"].append(get(p + "ln_mlp.bias"))
        else:  # falcon-7b: single input_layernorm
            layers["input_norm"]["scale"].append(
                get(p + "input_layernorm.weight"))
            layers["input_norm"]["bias"].append(
                get(p + "input_layernorm.bias"))

    stacked = {k: {kk: np.stack(vv) for kk, vv in v.items()}
               for k, v in layers.items()}
    params = {
        "embedding": {"word_embeddings": _pad_vocab(
            get("transformer.word_embeddings.weight"),
            cfg.padded_vocab_size)},
        "transformer": stacked,
        "final_norm": {"scale": get("transformer.ln_f.weight"),
                       "bias": get("transformer.ln_f.bias")},
    }
    if not cfg.tie_embed_logits:
        # released falcons tie embeddings; an untied config (e.g. after
        # finetuning with untied head) round-trips through lm_head.weight
        params["lm_head"] = _t(_pad_vocab(get("lm_head.weight"),
                                          cfg.padded_vocab_size))
    return params


def params_to_hf_falcon(params, cfg: ModelConfig, dtype=np.float32) -> dict:
    """megatron_tpu param tree -> HF FalconForCausalLM state dict — the
    inverse of hf_falcon_to_params, completing the export direction the
    reference covers at megatron2hf.py:60-471 (Falcon branch).

    Rebuilds the fused grouped QKV [nkv*(q_per_kv+2)*hd, h] with each
    group's K and V as its last two heads, and un-permutes the rotary row
    order back to HF rotate-half convention."""
    if cfg.use_post_ln or not cfg.parallel_attn or cfg.use_bias:
        # mirror of the import-side guard (hf_falcon_to_params): other
        # layouts would silently drop norm/bias tensors
        raise NotImplementedError(
            "falcon export expects parallel_attn, pre-LN, no biases")
    hd = cfg.kv_channels
    nq = cfg.num_attention_heads
    nkv = cfg.num_kv_heads
    qpg = nq // nkv
    h = cfg.hidden_size
    L = cfg.num_layers
    t = params["transformer"]
    v = cfg.vocab_size

    sd = {}
    sd["transformer.word_embeddings.weight"] = np.asarray(
        params["embedding"]["word_embeddings"], dtype)[:v]
    if cfg.tie_embed_logits:
        sd["lm_head.weight"] = sd["transformer.word_embeddings.weight"]
    else:
        sd["lm_head.weight"] = _t(np.asarray(params["lm_head"], dtype))[:v]
    sd["transformer.ln_f.weight"] = np.asarray(params["final_norm"]["scale"],
                                               dtype)
    sd["transformer.ln_f.bias"] = np.asarray(params["final_norm"]["bias"],
                                             dtype)
    for i in range(L):
        p = f"transformer.h.{i}."
        q = deinterleave_rope_rows(
            _t(np.asarray(t["attention"]["wq"][i], dtype)), nq, hd)
        wkv = np.asarray(t["attention"]["wkv"][i], dtype)  # [h, 2*nkv*hd]
        k = deinterleave_rope_rows(_t(wkv[:, :nkv * hd]), nkv, hd)
        vv = _t(wkv[:, nkv * hd:])
        qkv = np.concatenate(
            [q.reshape(nkv, qpg, hd, h), k.reshape(nkv, 1, hd, h),
             vv.reshape(nkv, 1, hd, h)], axis=1)
        sd[p + "self_attention.query_key_value.weight"] = qkv.reshape(
            nkv * (qpg + 2) * hd, h)
        sd[p + "self_attention.dense.weight"] = _t(
            np.asarray(t["attention"]["wo"][i], dtype))
        sd[p + "mlp.dense_h_to_4h.weight"] = _t(
            np.asarray(t["mlp"]["w1"][i], dtype))
        sd[p + "mlp.dense_4h_to_h.weight"] = _t(
            np.asarray(t["mlp"]["w2"][i], dtype))
        if cfg.parallel_layernorm:  # falcon-40b
            sd[p + "ln_attn.weight"] = np.asarray(
                t["input_norm"]["scale"][i], dtype)
            sd[p + "ln_attn.bias"] = np.asarray(
                t["input_norm"]["bias"][i], dtype)
            sd[p + "ln_mlp.weight"] = np.asarray(
                t["mlp_norm"]["scale"][i], dtype)
            sd[p + "ln_mlp.bias"] = np.asarray(
                t["mlp_norm"]["bias"][i], dtype)
        else:  # falcon-7b
            sd[p + "input_layernorm.weight"] = np.asarray(
                t["input_norm"]["scale"][i], dtype)
            sd[p + "input_layernorm.bias"] = np.asarray(
                t["input_norm"]["bias"][i], dtype)
    return sd


def hf_mixtral_to_params(sd: Mapping[str, np.ndarray], cfg: ModelConfig,
                         dtype=np.float32) -> dict:
    """HF MixtralForCausalLM state dict -> megatron_tpu param tree.

    Beyond the reference (it has no MoE at all — SURVEY.md §2.8): the
    attention/norm/embedding mapping is exactly the Llama one (Mixtral IS
    a Llama backbone: GQA + RMSNorm + rotate-half RoPE at theta 1e6), and
    each block_sparse_moe maps onto models/moe.py:
      gate.weight [E, h]            -> router [h, E]
      experts.{e}.w1 (gate proj)    -> w1[e, :, 0, :]
      experts.{e}.w3 (up proj)      -> w1[e, :, 1, :]
      experts.{e}.w2 (down proj)    -> w2[e]
    Routing semantics match by construction: Mixtral's softmax-then-top-k
    renormalization equals our renormalized top-k of the full softmax.
    Mixtral is DROPLESS — set moe_capacity_factor >= num_experts /
    moe_top_k for bit-faithful inference (guarantees no capacity drops).
    """
    assert cfg.num_experts > 1, "mixtral conversion needs num_experts > 1"
    E = cfg.num_experts

    def mlp_import(get, p):
        m = p + "block_sparse_moe."
        w1 = np.stack([
            np.stack([_t(get(m + f"experts.{e}.w1.weight")),   # gate
                      _t(get(m + f"experts.{e}.w3.weight"))],  # up
                     axis=1)
            for e in range(E)])                                # [E, h, 2, ffn]
        return {"router": _t(get(m + "gate.weight")),
                "w1": w1,
                "w2": np.stack([_t(get(m + f"experts.{e}.w2.weight"))
                                for e in range(E)])}

    return _llama_backbone_import(sd, cfg, dtype, mlp_import)


def params_to_hf_mixtral(params, cfg: ModelConfig, dtype=np.float32) -> dict:
    """megatron_tpu MoE param tree -> HF MixtralForCausalLM state dict
    (inverse of hf_mixtral_to_params)."""
    E = cfg.num_experts

    def mlp_export(t, i, p):
        m = p + "block_sparse_moe."
        out = {m + "gate.weight": _t(np.asarray(t["mlp"]["router"][i],
                                                dtype))}
        w1 = np.asarray(t["mlp"]["w1"][i], dtype)   # [E, h, 2, ffn]
        w2 = np.asarray(t["mlp"]["w2"][i], dtype)   # [E, ffn, h]
        for e in range(E):
            out[m + f"experts.{e}.w1.weight"] = _t(w1[e, :, 0])
            out[m + f"experts.{e}.w3.weight"] = _t(w1[e, :, 1])
            out[m + f"experts.{e}.w2.weight"] = _t(w2[e])
        return out

    return _llama_backbone_export(params, cfg, dtype, mlp_export)
