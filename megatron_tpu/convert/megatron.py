"""Import/export reference-Megatron checkpoints (mp_rank .pt layout).

The reference trains and serves from torch checkpoints laid out as

    <load_dir>/latest_checkpointed_iteration.txt        # "release" or int
    <load_dir>/iter_0000500/mp_rank_00/model_optim_rng.pt        # pp == 1
    <load_dir>/iter_0000500/mp_rank_01_003/model_optim_rng.pt    # tp 1, pp 3

(ref: megatron/checkpointing.py:77-140 get_checkpoint_name). Each shard's
payload is {"iteration", "checkpoint_version", "args": Namespace, "model":
{"language_model": {...}}} — or "model0".."model{vpp-1}" chunks under
interleaved virtual pipelining (ref: checkpointing.py:275-281). This module
ingests that layout directly so a reference-produced checkpoint (the exact
artifact a loss-curve-matched continuation run starts from) can be loaded
into a megatron_tpu param tree, and exports the reverse direction so our
checkpoints remain readable by the reference.

Format facts reproduced here (each verified against the reference source):
- tp merge axes (ref: tools/checkpoint_loader_megatron.py:211-300):
  qkv/embedding/lm_head/h_to_4h concat on dim 0, attention-dense and
  4h_to_h concat on dim 1, norms + biases of row-parallel layers replicated.
- GLU h_to_4h shards are PER-RANK [up; gate] halves: merge as
  chunk(2, dim=0) per rank, then concat all ups + all gates
  (ref: checkpoint_loader_megatron.py:291-297; the [up; gate] order —
  w3 before w1 — is fixed by weights2megatron.py:126-130).
- QKV rows are GROUPED per kv-head: [q_0..q_{nq/nkv-1}, k, v] blocks of
  head_dim rows each (ref: weights2megatron.py:87-99 rearrange_qkv), in the
  Meta interleaved-pair RoPE convention — the same convention our wq/wkv
  use, so un-grouping is a pure row permutation with NO rope reorder
  (ref: permute_qkv.py:12-30 converts HF->interleaved at import time;
  megatron/model/positional_embeddings.py applies complex-pair rotary).
- checkpoint_version < 2.0 stores qkv rows [num_splits*np*hn] (v0) or
  [np*hn*num_splits] (v1) instead of the grouped [np*num_splits*hn]; the
  legacy fixup transposes them back (ref: checkpointing.py:341-411
  fix_query_key_value_ordering/_transpose_first_dim; MHA only — the
  reference skips the fixup when num_attention_heads_kv differs).
- vpp chunk c on pp rank r holds global layers
  c*(L/vpp) + r*(L/(pp*vpp)) + local (ref: megatron/model/transformer.py:
  1030-1032).
- Release checkpoints written by weights2megatron use the key spelling
  {"transformer": {"layers.N.attention..."}} with a flat
  "word_embeddings.weight"; training checkpoints use {"encoder":
  {"layers.N.self_attention..."}} with a nested
  {"word_embeddings": {"weight"}} (ref: megatron/model/language_model.py:
  394-409 _embedding_key/_encoder_key vs weights2megatron.py:216-221;
  megatron2hf.py:115-121 normalizes the same way).

Optimizer moments are NOT imported: torch-Adam state is keyed by flat param
index against the reference's module order, and a continuation on different
hardware re-warms in a few hundred steps — the reference itself offers the
same fresh-optimizer semantics via --no_load_optim/--finetune
(ref: megatron/checkpointing.py:569-599).
"""
from __future__ import annotations

import os
import re
import sys
from typing import Mapping, Optional

import numpy as np

from megatron_tpu.config import ModelConfig

TRACKER = "latest_checkpointed_iteration.txt"
PAYLOAD = "model_optim_rng.pt"


# ---------------------------------------------------------------------------
# layout discovery
# ---------------------------------------------------------------------------

def read_tracker(load_dir: str) -> str:
    with open(os.path.join(load_dir, TRACKER)) as f:
        return f.read().strip()


def iter_dirname(iteration) -> str:
    if iteration == "release":
        return "release"
    return f"iter_{int(iteration):07d}"


def discover_shards(ckpt_dir: str) -> dict[tuple[int, int], str]:
    """Map (tp_rank, pp_rank) -> payload path under one iteration dir.

    Handles both `mp_rank_XX` (pp==1) and `mp_rank_XX_YYY` naming
    (ref: checkpointing.py:96-103); a distributed-optimizer layout's
    extra `mp_rank_XX_dpr` optim dirs contain no PAYLOAD and are skipped.
    """
    shards: dict[tuple[int, int], str] = {}
    for name in sorted(os.listdir(ckpt_dir)):
        m = re.fullmatch(r"mp_rank_(\d{2})(?:_(\d{3}))?", name)
        if not m:
            continue
        path = os.path.join(ckpt_dir, name, PAYLOAD)
        if not os.path.exists(path):
            continue
        shards[(int(m.group(1)), int(m.group(2) or 0))] = path
    if not shards:
        raise FileNotFoundError(f"no mp_rank_*/{PAYLOAD} under {ckpt_dir}")
    tp = 1 + max(t for t, _ in shards)
    pp = 1 + max(p for _, p in shards)
    missing = [(t, p) for t in range(tp) for p in range(pp)
               if (t, p) not in shards]
    if missing:
        raise FileNotFoundError(f"incomplete shard grid {tp}x{pp}: "
                                f"missing {missing}")
    return shards


# ---------------------------------------------------------------------------
# per-shard normalization
# ---------------------------------------------------------------------------

def _normalize_lm(lm: Mapping) -> dict:
    """One shard's language_model dict -> {"embedding": flat, "encoder":
    flat (self_attention spelling), "lm_head": arr|None} regardless of
    which key-spelling era produced it."""
    out = {"embedding": {}, "encoder": {}, "lm_head": None}
    enc = lm.get("encoder", lm.get("transformer"))
    if enc is not None:
        for k, v in enc.items():
            out["encoder"][k.replace(".attention.", ".self_attention.")] = v
    emb = lm.get("embedding", {})
    for k, v in emb.items():
        if isinstance(v, Mapping):  # nested {"word_embeddings": {"weight"}}
            for kk, vv in v.items():
                out["embedding"][f"{k}.{kk}"] = vv
        else:
            out["embedding"][k] = v
    if "lm_head" in lm:
        out["lm_head"] = lm["lm_head"]
    return out


def _fix_qkv_legacy(w: np.ndarray, version: float, n_heads: int,
                    head_dim: int) -> np.ndarray:
    """checkpoint_version < 2.0 row-order fixup (MHA qkv only).

    v0 stored [num_splits, np, hn, ...]; v1 stored [np, hn, num_splits,
    ...]; canonical (>=2.0) is [np, num_splits, hn, ...]
    (ref: checkpointing.py:341-377 _transpose_first_dim, 379-411)."""
    tail = w.shape[1:]
    if version == 0:
        r = w.reshape((3, n_heads, head_dim) + tail)
        return r.transpose(1, 0, *range(2, r.ndim)).reshape(w.shape)
    if version == 1.0:
        r = w.reshape((n_heads, head_dim, 3) + tail)
        return r.transpose(0, 2, 1, *range(3, r.ndim)).reshape(w.shape)
    raise ValueError(f"invalid legacy checkpoint version {version}")


# ---------------------------------------------------------------------------
# tp merge
# ---------------------------------------------------------------------------

def _merge_tp(key: str, parts: list[np.ndarray], glu: bool) -> np.ndarray:
    """Merge one encoder tensor's tp shards (rules in module docstring)."""
    if len(parts) == 1:
        return parts[0]
    if ".mlp.dense_h_to_4h." in key and glu:
        ups, gates = [], []
        for p in parts:
            u, g = np.split(p, 2, axis=0)
            ups.append(u)
            gates.append(g)
        return np.concatenate(ups + gates, axis=0)
    if (".self_attention.query_key_value." in key
            or ".mlp.dense_h_to_4h." in key
            or key in ("word_embeddings.weight", "lm_head")):
        return np.concatenate(parts, axis=0)
    if key.endswith((".self_attention.dense.weight",
                     ".mlp.dense_4h_to_h.weight")):
        return np.concatenate(parts, axis=1)
    # norms, row-parallel biases, anything replicated
    for t, p in enumerate(parts[1:], 1):
        np.testing.assert_allclose(
            parts[0], p, rtol=0, atol=0,
            err_msg=f"{key}: replicated shard {t} differs from rank 0")
    return parts[0]


def _install_enum_stubs():
    """Make reference-pickled enums loadable WITHOUT the reference tree.

    Checkpoints written by the reference's own save_checkpoint pickle
    enum members from megatron.model.enums inside the args namespace
    (validate_args converts position_embedding_type to the enum,
    ref: megatron/arguments.py:245-246; values ref: model/enums.py).
    When `megatron` is not importable, install stub modules holding
    value-identical enums so unpickling reconstructs members whose
    str() the config mapping below understands. Never shadows a real
    megatron package."""
    import enum
    import importlib.util
    import sys
    import types

    if importlib.util.find_spec("megatron") is not None:
        return []
    root = types.ModuleType("megatron")
    model = types.ModuleType("megatron.model")
    enums = types.ModuleType("megatron.model.enums")
    for name, members in (
            ("ModelType", ("encoder_or_decoder", "encoder_and_decoder")),
            ("LayerType", ("encoder", "decoder")),
            ("AttnType", ("self_attn", "cross_attn")),
            ("AttnMaskType", ("padding", "causal")),
            ("PositionEmbeddingType", ("rotary", "absolute")),
    ):
        setattr(enums, name,
                enum.Enum(name, {m: i + 1 for i, m in enumerate(members)}))
    root.model = model
    model.enums = enums
    names = ["megatron", "megatron.model", "megatron.model.enums"]
    sys.modules.update(zip(names, (root, model, enums)))
    return names


def _tolerant_torch_load(path: str, installed: list):
    """`installed` accumulates stub module names across calls; the
    CALLER removes them when the whole checkpoint is loaded (the stubs
    must not outlive the load — they would shadow a real megatron tree
    put on sys.path later in the process)."""
    import torch
    try:
        return torch.load(path, map_location="cpu", weights_only=False)
    except ModuleNotFoundError as e:
        if "megatron" not in str(e):
            raise
        if not installed:
            installed.extend(_install_enum_stubs())
        return torch.load(path, map_location="cpu", weights_only=False)


# ---------------------------------------------------------------------------
# load + merge
# ---------------------------------------------------------------------------

def load_megatron_checkpoint(load_dir: str, iteration=None
                             ) -> tuple[dict, dict, dict]:
    """Load a reference-layout checkpoint, merging tp/pp/vpp shards.

    Returns (sd, args, meta): `sd` is a flat global-layer-indexed dict in
    the self_attention spelling plus "word_embeddings.weight" /
    "position_embeddings.weight" / "final_layernorm.*" / "lm_head"; `args`
    is the reference argparse namespace as a plain dict; `meta` carries
    iteration / checkpoint_version / tp / pp."""
    import torch

    if iteration is None:
        iteration = read_tracker(load_dir)
    ckpt_dir = os.path.join(load_dir, iter_dirname(iteration))
    shards = discover_shards(ckpt_dir)
    tp = 1 + max(t for t, _ in shards)
    pp = 1 + max(p for _, p in shards)

    # torch.load(weights_only=False): the payload embeds an
    # argparse.Namespace; these files are the user's own checkpoints.
    # Stub installation state is carried across shards so a 32-shard
    # enum-bearing checkpoint pays at most ONE failed load, not one per
    # shard.
    loaded = {}
    installed: list = []
    try:
        for rank, path in shards.items():
            loaded[rank] = _tolerant_torch_load(path, installed)
    finally:
        for m in installed:
            sys.modules.pop(m, None)
    first = loaded[(0, 0)]
    version = float(first.get("checkpoint_version", 0))
    args_ns = first.get("args")
    args = dict(vars(args_ns)) if args_ns is not None else {}
    vpp = int(args.get("virtual_pipeline_model_parallel_size") or 1)
    glu = bool(args.get("glu_activation"))
    n_heads = int(args.get("num_attention_heads", 0))
    n_kv = int(args.get("num_attention_heads_kv", n_heads) or n_heads)
    hidden = int(args.get("hidden_size", 0))
    head_dim = hidden // n_heads if n_heads else 0

    def model_chunks(payload) -> list[dict]:
        if "model" in payload:
            return [_normalize_lm(payload["model"]["language_model"])]
        return [_normalize_lm(payload[f"model{c}"]["language_model"])
                for c in range(vpp)]

    grid = {rank: model_chunks(p) for rank, p in loaded.items()}
    n_chunks = len(grid[(0, 0)])

    # count total layers to place each (pp, chunk)'s local block globally
    per_block = None
    for (t, p), chunks in grid.items():
        for chunk in chunks:
            n_local = len({m.group(1) for k in chunk["encoder"]
                           for m in [re.match(r"layers\.(\d+)\.", k)] if m})
            if per_block is None:
                per_block = n_local
            elif n_local != per_block:
                raise ValueError("ragged layer blocks across shards "
                                 f"({n_local} vs {per_block})")
    total_layers = per_block * pp * n_chunks
    if "num_layers" in args and args["num_layers"] is not None:
        declared = int(args["num_layers"])
        if declared != total_layers:
            raise ValueError(f"args.num_layers={declared} but shards hold "
                             f"{total_layers}")

    sd: dict[str, np.ndarray] = {}
    to_np = lambda v: np.asarray(v.float().numpy() if hasattr(v, "float")
                                 else v)
    # the legacy (<2.0) qkv row orders are PER-SHARD layouts over that
    # rank's heads — the fixup must run on each tp shard BEFORE the merge
    # (the reference fixes per rank at load: checkpointing.py:379-411)
    fix_legacy_qkv = (version < 2.0 and n_heads == n_kv)

    def put(key, parts):
        arrs = [to_np(p) for p in parts]
        if fix_legacy_qkv and ".query_key_value." in key:
            arrs = [_fix_qkv_legacy(a, version, n_heads // len(arrs),
                                    head_dim) for a in arrs]
        sd[key] = _merge_tp(key, arrs, glu)

    # encoder tensors, re-keyed to global layer indices
    for c in range(n_chunks):
        for p in range(pp):
            offset = (c * (total_layers // n_chunks)
                      + p * per_block)
            keys = grid[(0, p)][c]["encoder"].keys()
            for k in keys:
                m = re.match(r"layers\.(\d+)\.(.*)", k)
                if m:
                    gk = f"layers.{int(m.group(1)) + offset}.{m.group(2)}"
                elif p == pp - 1 and c == n_chunks - 1:
                    gk = k  # final_layernorm rides the last block
                else:
                    continue
                put(gk, [grid[(t, p)][c]["encoder"][k] for t in range(tp)])

    # embedding (first stage, first chunk) / lm_head (last stage, last chunk)
    emb = [grid[(t, 0)][0]["embedding"] for t in range(tp)]
    put("word_embeddings.weight",
        [e["word_embeddings.weight"] for e in emb])
    if "position_embeddings.weight" in emb[0]:
        put("position_embeddings.weight",
            [emb[0]["position_embeddings.weight"]])
    heads = [grid[(t, pp - 1)][n_chunks - 1]["lm_head"] for t in range(tp)]
    if heads[0] is not None:
        put("lm_head", heads)

    meta = {"iteration": iteration, "checkpoint_version": version,
            "tp": tp, "pp": pp, "vpp": n_chunks}
    return sd, args, meta


# ---------------------------------------------------------------------------
# merged sd -> our param tree
# ---------------------------------------------------------------------------

def _t(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(w.T)


def _fit_vocab(w: np.ndarray, padded: int) -> np.ndarray:
    """Slice or zero-pad checkpoint vocab rows to our padded size (the two
    sides may pad differently: make_vocab_size_divisible_by * tp)."""
    if w.shape[0] > padded:
        return w[:padded]
    if w.shape[0] < padded:
        return np.concatenate(
            [w, np.zeros((padded - w.shape[0], w.shape[1]), w.dtype)])
    return w


def _ungroup_qkv(qkv: np.ndarray, nq: int, nkv: int, hd: int
                 ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Grouped [(q..q,k,v) x nkv] rows -> (wq, wk, wv) with sequential
    global head order (inverse of weights2megatron.py:87-99)."""
    per = nq // nkv
    g = qkv.reshape((nkv, (per + 2) * hd) + qkv.shape[1:])
    qs = g[:, :per * hd]
    k = g[:, per * hd:(per + 1) * hd]
    v = g[:, (per + 1) * hd:]
    return (qs.reshape((nq * hd,) + qkv.shape[1:]),
            k.reshape((nkv * hd,) + qkv.shape[1:]),
            v.reshape((nkv * hd,) + qkv.shape[1:]))


def _group_qkv(q: np.ndarray, k: np.ndarray, v: np.ndarray, nkv: int,
               per: int, hd: int) -> np.ndarray:
    """Sequential-head (q, k, v) rows -> the grouped [(q..q,k,v) x nkv]
    layout — the inverse of _ungroup_qkv; serves weights AND biases."""
    groups = []
    for g in range(nkv):
        groups.append(q[g * per * hd:(g + 1) * per * hd])
        groups.append(k[g * hd:(g + 1) * hd])
        groups.append(v[g * hd:(g + 1) * hd])
    return np.concatenate(groups)


def megatron_to_params(sd: Mapping[str, np.ndarray], cfg: ModelConfig,
                       dtype=np.float32) -> dict:
    """Merged reference sd (from load_megatron_checkpoint) -> our stacked
    param tree (the same layout convert/hf.py produces)."""
    hd = cfg.kv_channels
    nq = cfg.num_attention_heads
    nkv = cfg.num_kv_heads
    L = cfg.num_layers
    has_bias = cfg.use_bias
    norm_has_bias = cfg.norm_type == "layernorm"

    def get(name):
        return np.asarray(sd[name], dtype=dtype)

    layers: dict = {"attention": {"wq": [], "wkv": [], "wo": []},
                    "mlp": {"w1": [], "w2": []},
                    "input_norm": {"scale": []},
                    "post_attn_norm": {"scale": []}}
    if has_bias:
        layers["attention"].update({"bq": [], "bkv": [], "bo": []})
        layers["mlp"].update({"b1": [], "b2": []})
    if norm_has_bias:
        layers["input_norm"]["bias"] = []
        layers["post_attn_norm"]["bias"] = []

    for i in range(L):
        p = f"layers.{i}."
        wq, wk, wv = _ungroup_qkv(
            get(p + "self_attention.query_key_value.weight"), nq, nkv, hd)
        layers["attention"]["wq"].append(_t(wq))
        layers["attention"]["wkv"].append(
            np.concatenate([_t(wk), _t(wv)], axis=1))
        layers["attention"]["wo"].append(
            _t(get(p + "self_attention.dense.weight")))
        w_in = get(p + "mlp.dense_h_to_4h.weight")
        if cfg.is_glu:
            up, gate = np.split(w_in, 2, axis=0)
            layers["mlp"]["w1"].append(np.stack([_t(gate), _t(up)], axis=1))
        else:
            layers["mlp"]["w1"].append(_t(w_in))
        layers["mlp"]["w2"].append(_t(get(p + "mlp.dense_4h_to_h.weight")))
        layers["input_norm"]["scale"].append(
            get(p + "input_layernorm.weight"))
        layers["post_attn_norm"]["scale"].append(
            get(p + "post_attention_layernorm.weight"))
        if norm_has_bias:
            layers["input_norm"]["bias"].append(
                get(p + "input_layernorm.bias"))
            layers["post_attn_norm"]["bias"].append(
                get(p + "post_attention_layernorm.bias"))
        if has_bias:
            bq, bk, bv = _ungroup_qkv(
                get(p + "self_attention.query_key_value.bias"), nq, nkv, hd)
            layers["attention"]["bq"].append(bq)
            layers["attention"]["bkv"].append(np.concatenate([bk, bv]))
            layers["attention"]["bo"].append(
                get(p + "self_attention.dense.bias"))
            b_in = get(p + "mlp.dense_h_to_4h.bias")
            layers["mlp"]["b1"].append(
                np.stack(np.split(b_in, 2)[::-1]) if cfg.is_glu else b_in)
            layers["mlp"]["b2"].append(get(p + "mlp.dense_4h_to_h.bias"))

    params = {
        "embedding": {"word_embeddings": _fit_vocab(
            get("word_embeddings.weight"), cfg.padded_vocab_size)},
        "transformer": {k: {kk: np.stack(vv) for kk, vv in v.items()}
                        for k, v in layers.items()},
        "final_norm": {"scale": get("final_layernorm.weight")},
    }
    if norm_has_bias:
        params["final_norm"]["bias"] = get("final_layernorm.bias")
    if cfg.use_position_embedding:
        params["embedding"]["position_embeddings"] = get(
            "position_embeddings.weight")
    if not cfg.tie_embed_logits:
        params["lm_head"] = _t(_fit_vocab(get("lm_head"),
                                          cfg.padded_vocab_size))
    return params


def config_from_megatron_args(args: Mapping, **overrides) -> ModelConfig:
    """Best-effort ModelConfig from the checkpoint's embedded reference
    argparse namespace (ref: megatron/arguments.py names)."""
    n_heads = int(args["num_attention_heads"])
    fields = dict(
        num_layers=int(args["num_layers"]),
        hidden_size=int(args["hidden_size"]),
        ffn_hidden_size=(int(args["ffn_hidden_size"])
                         if args.get("ffn_hidden_size") else None),
        num_attention_heads=n_heads,
        num_kv_heads=int(args.get("num_attention_heads_kv") or n_heads),
        seq_length=int(args.get("seq_length") or 2048),
        max_position_embeddings=(int(args["max_position_embeddings"])
                                 if args.get("max_position_embeddings")
                                 else None),
        vocab_size=int(args.get("padded_vocab_size")
                       or args.get("vocab_size") or 32000),
        make_vocab_size_divisible_by=1,
        use_rotary_emb=(str(args.get("position_embedding_type", "rotary"))
                        .endswith("rotary")),
        use_position_embedding=(str(args.get("position_embedding_type", ""))
                                .endswith("absolute")),
        norm_type="rmsnorm" if args.get("use_rms_norm") else "layernorm",
        norm_epsilon=float(args.get("layernorm_epsilon") or 1e-5),
        activation=str(args.get("glu_activation") or "gelu"),
        use_bias=bool(args.get("use_bias", False)),
        parallel_attn=bool(args.get("parallel_attn", False)),
        parallel_layernorm=bool(args.get("parallel_layernorm", False)),
        tie_embed_logits=bool(args.get("tie_embed_logits", False)),
    )
    fields.update(overrides)
    return ModelConfig(**fields).derived()


# ---------------------------------------------------------------------------
# export: our params -> reference layout (release, tp1/pp1)
# ---------------------------------------------------------------------------

def params_to_megatron(params, cfg: ModelConfig, dtype=np.float32) -> dict:
    """Our param tree -> the reference's language_model dict (release
    spelling, single shard) — the inverse of megatron_to_params."""
    hd = cfg.kv_channels
    nq = cfg.num_attention_heads
    nkv = cfg.num_kv_heads
    per = nq // nkv
    t = params["transformer"]
    enc: dict[str, np.ndarray] = {}
    for i in range(cfg.num_layers):
        p = f"layers.{i}."
        wq = _t(np.asarray(t["attention"]["wq"][i], dtype))  # [nq*hd, h]
        wkv = np.asarray(t["attention"]["wkv"][i], dtype)
        wk = _t(wkv[:, :nkv * hd])
        wv = _t(wkv[:, nkv * hd:])
        enc[p + "attention.query_key_value.weight"] = _group_qkv(
            wq, wk, wv, nkv, per, hd)
        enc[p + "attention.dense.weight"] = _t(
            np.asarray(t["attention"]["wo"][i], dtype))
        w1 = np.asarray(t["mlp"]["w1"][i], dtype)
        if cfg.is_glu:  # [h, 2, ffn] (gate, up) -> [up; gate] rows
            enc[p + "mlp.dense_h_to_4h.weight"] = np.concatenate(
                [_t(w1[:, 1]), _t(w1[:, 0])])
        else:
            enc[p + "mlp.dense_h_to_4h.weight"] = _t(w1)
        enc[p + "mlp.dense_4h_to_h.weight"] = _t(
            np.asarray(t["mlp"]["w2"][i], dtype))
        enc[p + "input_layernorm.weight"] = np.asarray(
            t["input_norm"]["scale"][i], dtype)
        enc[p + "post_attention_layernorm.weight"] = np.asarray(
            t["post_attn_norm"]["scale"][i], dtype)
        if cfg.norm_type == "layernorm":
            enc[p + "input_layernorm.bias"] = np.asarray(
                t["input_norm"]["bias"][i], dtype)
            enc[p + "post_attention_layernorm.bias"] = np.asarray(
                t["post_attn_norm"]["bias"][i], dtype)
        if cfg.use_bias:
            bq = np.asarray(t["attention"]["bq"][i], dtype)
            bkv = np.asarray(t["attention"]["bkv"][i], dtype)
            bk, bv = bkv[:nkv * hd], bkv[nkv * hd:]
            enc[p + "attention.query_key_value.bias"] = _group_qkv(
                bq, bk, bv, nkv, per, hd)
            enc[p + "attention.dense.bias"] = np.asarray(
                t["attention"]["bo"][i], dtype)
            b1 = np.asarray(t["mlp"]["b1"][i], dtype)
            enc[p + "mlp.dense_h_to_4h.bias"] = (
                np.concatenate([b1[1], b1[0]])  # (gate, up) -> [up; gate]
                if cfg.is_glu else b1)
            enc[p + "mlp.dense_4h_to_h.bias"] = np.asarray(
                t["mlp"]["b2"][i], dtype)
    enc["final_layernorm.weight"] = np.asarray(
        params["final_norm"]["scale"], dtype)
    if cfg.norm_type == "layernorm":
        enc["final_layernorm.bias"] = np.asarray(
            params["final_norm"]["bias"], dtype)
    emb = {"word_embeddings.weight": np.asarray(
        params["embedding"]["word_embeddings"], dtype)}
    if cfg.use_position_embedding:
        emb["position_embeddings.weight"] = np.asarray(
            params["embedding"]["position_embeddings"], dtype)
    lm = {"embedding": emb, "transformer": enc}
    if not cfg.tie_embed_logits:
        lm["lm_head"] = _t(np.asarray(params["lm_head"], dtype))
    return lm


def save_megatron_checkpoint(load_dir: str, params, cfg: ModelConfig,
                             iteration="release",
                             args_extra: Optional[Mapping] = None) -> str:
    """Write a reference-readable release checkpoint (tp1/pp1):
    tracker + release/mp_rank_00/model_optim_rng.pt
    (ref: weights2megatron.py:214-224's output contract)."""
    import torch
    from argparse import Namespace

    lm = params_to_megatron(params, cfg)
    args = {
        "num_layers": cfg.num_layers, "hidden_size": cfg.hidden_size,
        "ffn_hidden_size": cfg.ffn_hidden_size,
        "num_attention_heads": cfg.num_attention_heads,
        "num_attention_heads_kv": cfg.num_kv_heads,
        "padded_vocab_size": cfg.padded_vocab_size,
        "make_vocab_size_divisible_by": 1,
        "glu_activation": cfg.activation if cfg.is_glu else None,
        "use_rms_norm": cfg.norm_type == "rmsnorm",
        "use_bias": cfg.use_bias,
        "tie_embed_logits": cfg.tie_embed_logits,
        "parallel_attn": cfg.parallel_attn,
        "layernorm_epsilon": cfg.norm_epsilon,
        "seq_length": cfg.seq_length,
        "max_position_embeddings": cfg.max_position_embeddings,
        "position_embedding_type": "absolute"
        if cfg.use_position_embedding else "rotary",
        "tensor_model_parallel_size": 1,
        "pipeline_model_parallel_size": 1,
        "iteration": iteration,
    }
    if args_extra:
        args.update(args_extra)
    shard_dir = os.path.join(load_dir, iter_dirname(iteration), "mp_rank_00")
    os.makedirs(shard_dir, exist_ok=True)
    payload = {"iteration": iteration, "checkpoint_version": 3.0,
               "args": Namespace(**args),
               "model": {"language_model": {
                   k: ({kk: torch.from_numpy(np.ascontiguousarray(vv))
                        for kk, vv in v.items()}
                       if isinstance(v, dict)
                       else torch.from_numpy(np.ascontiguousarray(v)))
                   for k, v in lm.items()}}}
    path = os.path.join(shard_dir, PAYLOAD)
    torch.save(payload, path)
    with open(os.path.join(load_dir, TRACKER), "w") as f:
        f.write(str(iteration))
    return path
