"""Raw Meta-llama checkpoint import: multi-shard merge + param mapping.

TPU-native equivalent of the reference's Meta-format path
(ref: weights2megatron/merge_llama.py:59-86 merge_meta_llama + :117
merge_llama dispatch, weights2megatron/weights2megatron.py:80-147
llama_to_megatron with source="meta").

Meta ships `consolidated.{00..NN}.pth` shards cut along the original
tensor-parallel axes. Per-tensor shard axis (the published llama layout,
ref: merge_llama.py:21-34 key_to_dim):

  dim 0 (row-stacked):   attention wq/wk/wv, feed_forward w1/w3, output
  dim 1 (col-stacked):   attention wo, feed_forward w2, tok_embeddings
  replicated:            attention_norm, ffn_norm, norm; rope.freqs skipped

RoPE convention: Meta weights already use the interleaved-pair rotary
layout this model family implements (the reference's permute_qkv is a
no-op for source="meta", ref: weights2megatron.py:82-86), so unlike the
HF path no row permutation is applied.
"""
from __future__ import annotations

import os
import re
from typing import Mapping, Sequence

import numpy as np

from megatron_tpu.config import ModelConfig
from megatron_tpu.convert.hf import _pad_vocab, _t

# short param name (second-to-last dotted component) -> shard concat axis
_SHARD_AXIS = {
    "wq": 0, "wk": 0, "wv": 0, "w1": 0, "w3": 0, "output": 0,
    "wo": 1, "w2": 1, "tok_embeddings": 1,
    "attention_norm": None, "ffn_norm": None, "norm": None,
}


def _short(name: str) -> str:
    parts = name.split(".")
    return parts[-2] if len(parts) >= 2 else parts[0]


def list_meta_shards(root_dir: str) -> list[str]:
    names = [n for n in os.listdir(root_dir)
             if re.fullmatch(r"consolidated\.[0-9]+\.pth", n)]
    if not names:
        raise FileNotFoundError(
            f"no consolidated.NN.pth shards under {root_dir}")
    # numeric sort: lexicographic order would misplace consolidated.10.pth
    # before consolidated.2.pth for unpadded indices
    names.sort(key=lambda n: int(n.split(".")[1]))
    return [os.path.join(root_dir, n) for n in names]


def merge_meta_llama(root_dir: str) -> dict:
    """Load + merge all consolidated shards into full numpy tensors
    (ref: merge_llama.py:59-86). Streams one shard at a time."""
    import torch

    paths = list_meta_shards(root_dir)
    per_key: dict[str, list] = {}
    for path in paths:
        shard = torch.load(path, map_location="cpu", weights_only=True)
        for name, tensor in shard.items():
            if _short(name) == "rope":  # rope.freqs: recomputed, not stored
                continue
            # merge in the checkpoint's native dtype where numpy can hold
            # it (fp16/fp32); only bf16 (no numpy dtype) upcasts
            if tensor.dtype == torch.bfloat16:
                tensor = tensor.to(torch.float32)
            per_key.setdefault(name, []).append(tensor.numpy())
        del shard
    merged = {}
    for name, pieces in per_key.items():
        short = _short(name)
        if short not in _SHARD_AXIS:
            raise KeyError(
                f"unrecognized meta checkpoint tensor {name!r}: no shard "
                "axis known — refusing to merge silently")
        axis = _SHARD_AXIS[short]
        if axis is None or len(pieces) == 1:
            merged[name] = pieces[0]
        else:
            merged[name] = np.concatenate(pieces, axis=axis)
    return merged


def meta_llama_to_params(sd: Mapping[str, np.ndarray], cfg: ModelConfig,
                         dtype=np.float32) -> dict:
    """Merged Meta state dict -> megatron_tpu param tree
    (ref: weights2megatron.py:80-147, source="meta": no rotary permute)."""
    L = cfg.num_layers

    def get(name):
        return np.asarray(sd[name], dtype=dtype)

    layers = {"attention": {"wq": [], "wkv": [], "wo": []},
              "mlp": {"w1": [], "w2": []},
              "input_norm": {"scale": []},
              "post_attn_norm": {"scale": []}}
    for i in range(L):
        p = f"layers.{i}."
        wq = _t(get(p + "attention.wq.weight"))           # [h, nq*hd]
        wk = _t(get(p + "attention.wk.weight"))
        wv = _t(get(p + "attention.wv.weight"))
        layers["attention"]["wq"].append(wq)
        layers["attention"]["wkv"].append(np.concatenate([wk, wv], axis=1))
        layers["attention"]["wo"].append(_t(get(p + "attention.wo.weight")))
        gate = _t(get(p + "feed_forward.w1.weight"))      # [h, ffn]
        up = _t(get(p + "feed_forward.w3.weight"))
        layers["mlp"]["w1"].append(np.stack([gate, up], axis=1))
        layers["mlp"]["w2"].append(_t(get(p + "feed_forward.w2.weight")))
        layers["input_norm"]["scale"].append(get(p + "attention_norm.weight"))
        layers["post_attn_norm"]["scale"].append(get(p + "ffn_norm.weight"))

    stacked = {k: {kk: np.stack(vv) for kk, vv in v.items()}
               for k, v in layers.items()}
    params = {
        "embedding": {"word_embeddings": _pad_vocab(
            get("tok_embeddings.weight"), cfg.padded_vocab_size)},
        "transformer": stacked,
        "final_norm": {"scale": get("norm.weight")},
    }
    if not cfg.tie_embed_logits:
        params["lm_head"] = _t(_pad_vocab(get("output.weight"),
                                          cfg.padded_vocab_size))
    return params
