from megatron_tpu.data.indexed_dataset import (  # noqa: F401
    DatasetCorruptionError, IndexedDatasetBuilder, MMapIndexedDataset,
    best_fitting_dtype, infer_dataset_exists, make_dataset)
from megatron_tpu.data.gpt_dataset import (  # noqa: F401
    GPTDataset, build_train_valid_test_datasets, get_train_valid_test_split_)
from megatron_tpu.data.blendable import BlendableDataset  # noqa: F401
from megatron_tpu.data.samplers import (  # noqa: F401
    BatchIterator, DictBatchIterator, MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler, PrefetchIterator,
    get_ltor_masks_and_position_ids, restore_data_state)
from megatron_tpu.data.tokenizers import build_tokenizer  # noqa: F401
