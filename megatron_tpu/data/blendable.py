"""Weighted mixture of datasets.

TPU-native port of BlendableDataset (ref: megatron/data/blendable_dataset.py:
12-53) whose index assignment comes from the C++ `build_blending_indices`
(ref: megatron/data/helpers.cpp:20-80): a greedy scheduler that, for each
output index, picks the dataset whose emitted count is furthest behind its
weight target. Native C++ via megatron_tpu/data/helpers.py with a numpy
fallback.
"""
from __future__ import annotations

from typing import Sequence

import numpy as np


def normalize_blend_weights(data_prefix: Sequence):
    """[w0, p0, w1, p1, ...] -> (prefixes, normalized weights)
    (ref: megatron/data/dataset_utils.py get_datasets_weights_and_num_samples)."""
    if len(data_prefix) % 2 != 0:
        raise ValueError("blended data_path must alternate weight, prefix "
                         f"(got {len(data_prefix)} items)")
    weights = [float(w) for w in data_prefix[0::2]]
    prefixes = [str(p) for p in data_prefix[1::2]]
    s = sum(weights)
    if s <= 0:
        raise ValueError(f"blend weights must sum > 0 (got {weights})")
    return prefixes, [w / s for w in weights]


def build_blending_indices(weights: np.ndarray, size: int):
    """Greedy weight-balancing assignment
    (ref: megatron/data/helpers.cpp:20-80). Returns (dataset_index uint8,
    dataset_sample_index int64)."""
    try:
        from megatron_tpu.data.helpers import build_blending_indices_native
        return build_blending_indices_native(weights, size)
    except Exception:
        pass
    n = len(weights)
    dataset_index = np.zeros(size, dtype=np.uint8)
    dataset_sample_index = np.zeros(size, dtype=np.int64)
    current = np.zeros(n, dtype=np.int64)
    for i in range(size):
        # error_i = w_i * (i+1) - emitted_i ; pick the max
        errors = weights * (i + 1) - current
        d = int(np.argmax(errors))
        dataset_index[i] = d
        dataset_sample_index[i] = current[d]
        current[d] += 1
    return dataset_index, dataset_sample_index


class BlendableDataset:
    def __init__(self, datasets: Sequence, weights: Sequence[float],
                 size: int):
        assert len(datasets) == len(weights)
        self.datasets = list(datasets)
        w = np.asarray(weights, dtype=np.float64)
        w = w / w.sum()
        self.size = size
        self.dataset_index, self.dataset_sample_index = \
            build_blending_indices(w, size)

    def __len__(self) -> int:
        return self.size

    def __getitem__(self, idx: int):
        d = self.dataset_index[idx]
        s = self.dataset_sample_index[idx]
        ds = self.datasets[d]
        return ds[int(s) % len(ds)]
