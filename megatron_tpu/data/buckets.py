"""Bucketed sequence-length batching for variable-length finetuning.

The reference supports variable sequence lengths across microbatches by
shape-handshaking every pipeline p2p transfer
(ref: megatron/p2p_communication.py:134-146; the `variable_seq_lengths`
switch is set by dataloaders that produce them, arguments.py:171-178).
Under XLA every distinct shape is a fresh compilation, so the TPU-native
formulation is BUCKETING: pad each batch to the smallest member of a
fixed bucket ladder. Compilation count is bounded by the ladder length
(each bucket's program — including the full pp/tp/dp-sharded train step —
compiles once and is cached), padding waste is bounded by the ladder's
spacing, and the loss mask keeps padded positions out of the objective,
so a bucketed run optimizes the identical objective as a ragged one.

Usage (finetune-style):

    buckets = make_buckets(cfg.model.seq_length)       # e.g. 256..4096
    batch = collate_bucketed(samples, micro_bs, n_micro, buckets, pad_id)
    # -> {"tokens": [n_micro, b, B+1], "loss_mask": [n_micro, b, B]}

The train step reads shapes from the batch, so feeding different buckets
through ONE jitted step just populates its compile cache — see
tests/test_buckets.py for the cache-bound and loss-equality gates.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def make_buckets(max_seq: int, min_seq: int = 256,
                 multiple: int = 64) -> list[int]:
    """Power-of-two ladder [min_seq, ..., max_seq], max always included.

    `multiple` guards TPU-friendliness: every bucket stays a multiple of
    the MXU/lane tiling (and of tp*cp sharding factors in practice)."""
    assert max_seq % multiple == 0, (
        f"max_seq {max_seq} not a multiple of {multiple}")
    assert min_seq % multiple == 0, (
        f"min_seq {min_seq} not a multiple of {multiple} — every rung "
        "would be silently skipped, degenerating to one max-size bucket")
    out = []
    b = min_seq
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return out


def bucket_for(length: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= length; raises if none fits (caller truncates
    or filters overlong samples explicitly — silent truncation here
    would corrupt labels)."""
    for b in sorted(buckets):
        if length <= b:
            return b
    raise ValueError(f"sequence length {length} exceeds the largest "
                     f"bucket {max(buckets)}")


def collate_bucketed(samples: Sequence[np.ndarray], micro_bs: int,
                     n_micro: int, buckets: Sequence[int], pad_id: int,
                     loss_masks: Optional[Sequence[np.ndarray]] = None
                     ) -> dict:
    """Pack `n_micro * micro_bs` variable-length token sequences into one
    global batch padded to the bucket of the LONGEST sample.

    One bucket per global batch (not per microbatch): all microbatches of
    a step must share a shape — under pp they interleave through the same
    ring buffers (the reference pays a handshake per transfer instead).
    Each sample is `tokens` of length L_i >= 2 (input+shifted-label form:
    the model consumes [:, :-1] and predicts [:, 1:]); optional
    `loss_masks[i]` of length L_i - 1 (defaults to all-ones). Padded
    positions get pad_id and mask 0, so the masked-mean loss equals the
    unpadded computation exactly."""
    n = micro_bs * n_micro
    assert len(samples) == n, f"need {n} samples, got {len(samples)}"
    if loss_masks is not None:
        assert len(loss_masks) == n
    longest = max(len(s) for s in samples)
    B = bucket_for(longest - 1, buckets)  # model seq dim is L-1
    tokens = np.full((n_micro, micro_bs, B + 1), pad_id, dtype=np.int32)
    mask = np.zeros((n_micro, micro_bs, B), dtype=np.float32)
    for i, s in enumerate(samples):
        m, b = divmod(i, micro_bs)
        ln = len(s)
        tokens[m, b, :ln] = np.asarray(s, dtype=np.int32)
        if loss_masks is not None:
            mask[m, b, :ln - 1] = np.asarray(loss_masks[i],
                                             dtype=np.float32)
        else:
            mask[m, b, :ln - 1] = 1.0
    return {"tokens": tokens, "loss_mask": mask}


def bucket_batches(dataset, micro_bs: int, n_micro: int,
                   buckets: Sequence[int], pad_id: int,
                   drop_last: bool = False):
    """Generator: length-sort-free streaming collation — consume the
    dataset in order, emit one bucketed global batch per n_micro*micro_bs
    samples. (Length-grouped sampling reduces padding further; that is a
    sampler concern — this keeps consumption order == sampler order so
    consumed-samples checkpoint resume stays exact.)

    A trailing partial group is padded to a full batch with dummy
    fully-masked rows (zero loss weight — the objective is untouched and
    every real sample trains), so sample accounting stays exact for
    small finetuning sets. `drop_last=True` discards it instead (the
    fixed-shape pretraining convention)."""
    group, masks = [], []

    def flush():
        lm = None if all(m is None for m in masks) else [
            m if m is not None else np.ones(len(t) - 1, np.float32)
            for m, t in zip(masks, group)]
        return collate_bucketed(group, micro_bs, n_micro, buckets,
                                pad_id, loss_masks=lm)

    for item in dataset:
        if isinstance(item, dict):
            group.append(item["tokens"])
            masks.append(item.get("loss_mask"))
        else:
            group.append(item)
            masks.append(None)
        if len(group) == micro_bs * n_micro:
            yield flush()
            group, masks = [], []
    if group and not drop_last:
        n_fill = micro_bs * n_micro - len(group)
        filler = np.full(2, pad_id, dtype=np.int32)
        group.extend([filler] * n_fill)
        masks.extend([np.zeros(1, np.float32)] * n_fill)
        yield flush()
