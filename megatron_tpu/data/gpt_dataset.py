"""GPT pretraining dataset: doc/sample/shuffle index mappings.

TPU-native port of GPTDataset (ref: megatron/data/gpt_dataset.py:221-513).
The index-construction SEMANTICS are kept bit-identical — same RandomState
seed discipline, same separate-last-epoch rule, same sample walk with its
1-token overlap — because loss-curve comparability with the reference
requires sample-for-sample identical data order (SURVEY.md §7 hard parts).

The sample-index walk is O(num_samples) sequential in the reference and is
done by a C++ pybind helper (ref: megatron/data/helpers.cpp:83-166). Here the
fast path is the closed form: sample i starts at global token i*seq_length,
so (position, offset) = searchsorted over the cumulative doc lengths — fully
vectorized numpy, no native code needed for exactness when all docs are
non-empty. A C++ ctypes helper (megatron_tpu/data/helpers.cpp) provides the
exact sequential walk for corpora with empty documents and as the
high-throughput path.

Caching: mappings are built once and memory-mapped thereafter under the same
`{prefix}_{name}_indexmap_{ns}ns_{sl}sl_{seed}s_*.npy` naming scheme
(ref: gpt_dataset.py:285-292) so caches interchange with the reference.
"""
from __future__ import annotations

import os
import time
from typing import Optional, Sequence

import numpy as np

from megatron_tpu.data.indexed_dataset import (DatasetCorruptionError,
                                               MMapIndexedDataset,
                                               make_dataset)
from megatron_tpu.utils.logging import print_rank_0


def num_epochs_for(tokens_per_epoch: int, seq_length: int,
                   num_samples: int) -> int:
    """Smallest E with (E*tokens - 1) // seq_length >= num_samples
    (ref: gpt_dataset.py:415-427 _num_epochs; -1 for the 1-token overlap)."""
    assert tokens_per_epoch > 0
    e = 0
    total = 0
    while True:
        e += 1
        total += tokens_per_epoch
        if (total - 1) // seq_length >= num_samples:
            return e


def build_doc_idx(documents: np.ndarray, num_epochs: int,
                  np_rng: np.random.RandomState,
                  separate_last_epoch: bool) -> np.ndarray:
    """Shuffled concatenation of `num_epochs` copies of `documents`
    (ref: gpt_dataset.py:430-443). separate_last_epoch shuffles the final
    epoch independently so a partial last epoch still sees every doc."""
    if not separate_last_epoch or num_epochs == 1:
        idx = np.tile(np.asarray(documents, dtype=np.int32), num_epochs)
        np_rng.shuffle(idx)
        return idx
    first = build_doc_idx(documents, num_epochs - 1, np_rng, False)
    last = build_doc_idx(documents, 1, np_rng, False)
    return np.concatenate((first, last))


def build_sample_idx(sizes: np.ndarray, doc_idx: np.ndarray, seq_length: int,
                     num_epochs: int, tokens_per_epoch: int) -> np.ndarray:
    """[num_samples+1, 2] of (doc_idx position, in-doc offset) per sample
    (ref: gpt_dataset.py:446-493 _build_sample_idx / helpers.cpp:83-166).

    Closed form of the reference's walk: sample i spans global tokens
    [i*L, i*L + L] (1-token overlap), so its start position is a searchsorted
    over cumulative doc lengths. Falls back to the C++ sequential walk when
    empty documents make the closed form ambiguous."""
    doc_lens = sizes[doc_idx].astype(np.int64)
    if (doc_lens == 0).any():
        from megatron_tpu.data.helpers import build_sample_idx_native
        return build_sample_idx_native(sizes, doc_idx, seq_length, num_epochs,
                                       tokens_per_epoch)
    num_samples = (num_epochs * tokens_per_epoch - 1) // seq_length
    starts = np.arange(num_samples + 1, dtype=np.int64) * seq_length
    cum = np.concatenate(([0], np.cumsum(doc_lens)))
    pos = np.searchsorted(cum, starts, side="right") - 1
    # the final entry may point one past the last doc when the stream divides
    # exactly; clamp like the sequential walk does (it never advances past a
    # doc it just finished without the -1 overlap)
    pos = np.minimum(pos, len(doc_idx) - 1)
    offs = starts - cum[pos]
    out = np.empty((num_samples + 1, 2), dtype=np.int32)
    out[:, 0] = pos
    out[:, 1] = offs
    return out


def build_shuffle_idx(num_samples: int, total_size: int,
                      np_rng: np.random.RandomState) -> np.ndarray:
    """(ref: gpt_dataset.py:496-513): shuffle [0, num_samples) and
    [num_samples, total_size) separately, concatenate."""
    dtype_ = np.uint32
    if total_size >= (np.iinfo(np.uint32).max - 1):
        dtype_ = np.int64
    first = np.arange(num_samples, dtype=dtype_)
    np_rng.shuffle(first)
    if num_samples == total_size:
        return first
    last = np.arange(num_samples, total_size, dtype=dtype_)
    np_rng.shuffle(last)
    return np.concatenate((first, last))


def build_index_mappings(name: str, data_prefix: str, documents: np.ndarray,
                         sizes: np.ndarray, num_samples: int, seq_length: int,
                         seed: int, cache: bool = True):
    """(ref: gpt_dataset.py:270-406 _build_index_mappings). Single-controller:
    no rank-0-builds-others-mmap barrier dance — one process builds, every
    process that shares the filesystem reuses the cache."""
    tokens_per_epoch = int(np.sum(sizes[documents]))
    num_epochs = num_epochs_for(tokens_per_epoch, seq_length, num_samples)
    np_rng = np.random.RandomState(seed=seed)

    base = (f"{data_prefix}_{name}_indexmap_{num_samples}ns_{seq_length}sl"
            f"_{seed}s")
    doc_f, sample_f, shuffle_f = (base + "_doc_idx.npy",
                                  base + "_sample_idx.npy",
                                  base + "_shuffle_idx.npy")

    if cache and all(os.path.isfile(f) for f in (doc_f, sample_f, shuffle_f)):
        doc_idx = np.load(doc_f, allow_pickle=True, mmap_mode="r")
        sample_idx = np.load(sample_f, allow_pickle=True, mmap_mode="r")
        shuffle_idx = np.load(shuffle_f, allow_pickle=True, mmap_mode="r")
        # a mapping cached against a previous version of the corpus can
        # name documents the current index no longer has (corpus
        # re-preprocessed smaller under the same prefix, or ids the
        # caller's out-of-bounds filtering just removed) — serving it
        # would bypass the skip-and-count policy and die downstream in
        # numpy instead of here
        if (doc_idx.size > 0 and int(doc_idx.min()) >= 0
                and int(doc_idx.max()) < len(sizes)):
            return doc_idx, sample_idx, shuffle_idx
        print_rank_0(f"warning: cached index mapping {base}_* names "
                     f"documents outside the current index of "
                     f"{len(sizes)} sequences (stale cache from a "
                     "rewritten corpus); rebuilding")

    t0 = time.time()
    if num_epochs == 1:
        separate_last_epoch = False
    else:
        # (ref: gpt_dataset.py:313-339) separate the last epoch from the
        # global shuffle when it contributes <80% of an epoch's samples
        samples_sans_last = ((num_epochs - 1) * tokens_per_epoch - 1
                             ) // seq_length
        last_epoch_samples = num_samples - samples_sans_last
        samples_per_epoch = (tokens_per_epoch - 1) // seq_length
        assert 0 <= last_epoch_samples <= samples_per_epoch + 1
        separate_last_epoch = (last_epoch_samples <
                               int(0.80 * samples_per_epoch))

    doc_idx = build_doc_idx(documents, num_epochs, np_rng,
                            separate_last_epoch)
    sample_idx = build_sample_idx(sizes, doc_idx, seq_length, num_epochs,
                                  tokens_per_epoch)
    if separate_last_epoch:
        n_shuffle = ((num_epochs - 1) * tokens_per_epoch - 1) // seq_length
    else:
        n_shuffle = sample_idx.shape[0] - 1
    shuffle_idx = build_shuffle_idx(n_shuffle, sample_idx.shape[0] - 1,
                                    np_rng)
    if not cache:
        return doc_idx, sample_idx, shuffle_idx
    np.save(doc_f, doc_idx, allow_pickle=True)
    np.save(sample_f, sample_idx, allow_pickle=True)
    np.save(shuffle_f, shuffle_idx, allow_pickle=True)
    print_rank_0(f"built index mappings for {name} in "
                 f"{time.time()-t0:.2f}s ({num_epochs} epochs, "
                 f"{sample_idx.shape[0]-1} samples)")
    doc_idx = np.load(doc_f, allow_pickle=True, mmap_mode="r")
    sample_idx = np.load(sample_f, allow_pickle=True, mmap_mode="r")
    shuffle_idx = np.load(shuffle_f, allow_pickle=True, mmap_mode="r")
    return doc_idx, sample_idx, shuffle_idx


class GPTDataset:
    """Map-style dataset of [seq_length+1]-token samples
    (ref: gpt_dataset.py:221-269).

    Document ids outside the index are SKIPPED and counted
    (`skipped_documents`, logged) by default — one bad split boundary
    or stale doc list must not kill a multi-week run; `strict_data=True`
    (`--strict_data`) fails fast with `DatasetCorruptionError` instead."""

    def __init__(self, name: str, data_prefix: str,
                 documents: np.ndarray, indexed: MMapIndexedDataset,
                 num_samples: int, seq_length: int, seed: int,
                 cache: bool = True, strict_data: bool = False):
        self.name = name
        self.data_prefix = data_prefix
        self.indexed = indexed
        documents = np.asarray(documents)
        oob = (documents < 0) | (documents >= len(indexed.sizes))
        self.skipped_documents = int(oob.sum())
        if self.skipped_documents:
            msg = (f"dataset {name}: {self.skipped_documents}/"
                   f"{documents.size} document ids out of bounds for an "
                   f"index of {len(indexed.sizes)} sequences (stale doc "
                   "split or corrupt index)")
            if strict_data:
                raise DatasetCorruptionError(
                    data_prefix, msg + " — re-run preprocessing, or drop "
                    "--strict_data to skip them")
            print_rank_0(f"warning: {msg}; skipping them "
                         "(--strict_data fails fast instead)")
            documents = documents[~oob]
        if documents.size == 0:
            raise DatasetCorruptionError(
                data_prefix, f"dataset {name}: no in-bounds documents "
                "left to sample from")
        self.doc_idx, self.sample_idx, self.shuffle_idx = build_index_mappings(
            name, data_prefix, documents, np.asarray(indexed.sizes),
            num_samples, seq_length, seed, cache=cache)
        self.seq_length = seq_length

    def __len__(self) -> int:
        # -1 because sample i needs sample_idx[i+1] (ref: gpt_dataset.py:244)
        return self.sample_idx.shape[0] - 1

    def __getitem__(self, idx: int) -> dict:
        """(ref: gpt_dataset.py:248-269) gather seq_length+1 tokens spanning
        one or more documents."""
        idx = self.shuffle_idx[idx]
        doc_index_f, offset_f = self.sample_idx[idx]
        doc_index_l, offset_l = self.sample_idx[idx + 1]
        if doc_index_f == doc_index_l:
            sample = self.indexed.get(self.doc_idx[doc_index_f],
                                      offset=int(offset_f),
                                      length=int(offset_l - offset_f + 1))
        else:
            parts = [self.indexed.get(self.doc_idx[doc_index_f],
                                      offset=int(offset_f))]
            for i in range(doc_index_f + 1, doc_index_l):
                parts.append(self.indexed[self.doc_idx[i]])
            parts.append(self.indexed.get(self.doc_idx[doc_index_l],
                                          length=int(offset_l + 1)))
            sample = np.concatenate(parts)
        if len(sample) != self.seq_length + 1:
            # typed (not an assert: gone under python -O) — a
            # wrong-length sample means the on-disk index and data
            # disagree, and silently feeding it would corrupt training
            raise DatasetCorruptionError(
                self.data_prefix,
                f"dataset {self.name}: sample {idx} gathered "
                f"{len(sample)} tokens, want {self.seq_length + 1} — "
                "index/data mismatch (was the corpus rewritten under a "
                "cached index mapping?)")
        return {"text": sample.astype(np.int64)}


def get_train_valid_test_split_(splits_string: str, size: int):
    """'969,30,1' -> index boundaries (ref: megatron/data/dataset_utils.py
    get_train_valid_test_split_ semantics)."""
    splits = [float(s) for s in splits_string.replace("/", ",").split(",")]
    while len(splits) < 3:
        splits.append(0.0)
    splits = splits[:3]
    total = sum(splits)
    assert total > 0.0
    splits = [s / total for s in splits]
    splits_index = [0]
    for s in splits:
        splits_index.append(splits_index[-1] + int(round(s * float(size))))
    diff = splits_index[-1] - size
    for i in range(1, len(splits_index)):
        splits_index[i] -= diff
    assert splits_index[-1] == size
    return splits_index


def build_train_valid_test_datasets(
    data_prefix: Sequence, splits_string: str, seq_length: int, seed: int,
    train_samples: int, valid_samples: int, test_samples: int,
    cache: bool = True, strict_data: bool = False,
):
    """(ref: gpt_dataset.py:20-127). Single prefix or weighted blend
    [w0, p0, w1, p1, ...].

    Corrupt-data policy (`strict_data` / `--strict_data`): a blend
    prefix that fails validation (`DatasetCorruptionError`) is skipped
    with a loud count and the surviving prefixes re-weighted — unless
    strict, which fails fast. A single (sole-source) corrupt prefix
    always raises: there is nothing left to train on."""
    from megatron_tpu.data.blendable import BlendableDataset, \
        normalize_blend_weights

    if len(data_prefix) == 1:
        return _single_train_valid_test(
            data_prefix[0], splits_string, seq_length, seed,
            (train_samples, valid_samples, test_samples), cache,
            strict_data)

    prefixes, weights = normalize_blend_weights(data_prefix)
    counts = (train_samples, valid_samples, test_samples)
    # (dataset, weight) pairs per split so a prefix that yields no data for
    # one split cannot shift the weights of the survivors
    per_ds: list[list] = [[], [], []]
    per_w: list[list] = [[], [], []]
    skipped_prefixes: list[str] = []
    for prefix, w in zip(prefixes, weights):
        n = tuple(int(np.ceil(w * c * 1.005)) for c in counts)
        try:
            tr, va, te = _single_train_valid_test(
                prefix, splits_string, seq_length, seed, n, cache,
                strict_data)
        except DatasetCorruptionError as e:
            if strict_data:
                raise
            skipped_prefixes.append(prefix)
            print_rank_0(f"warning: skipping corrupt blend prefix "
                         f"({e}); surviving prefixes re-weighted "
                         "(--strict_data fails fast instead)")
            continue
        for i, d in enumerate((tr, va, te)):
            if d is not None:
                per_ds[i].append(d)
                per_w[i].append(w)
    if skipped_prefixes and not any(per_ds):
        raise DatasetCorruptionError(
            ", ".join(skipped_prefixes),
            f"all {len(skipped_prefixes)} blend prefixes failed "
            "validation — no data left to train on")
    if skipped_prefixes:
        print_rank_0(f"blend: skipped {len(skipped_prefixes)}/"
                     f"{len(prefixes)} corrupt prefixes: "
                     f"{', '.join(skipped_prefixes)}")
    out = []
    for lst, ws, c in zip(per_ds, per_w, counts):
        out.append(BlendableDataset(lst, ws, c) if lst and c > 0 else None)
    return tuple(out)


def _single_train_valid_test(prefix, splits_string, seq_length, seed, counts,
                             cache, strict_data=False):
    indexed = make_dataset(prefix)
    total_docs = indexed.doc_idx.shape[0] - 1
    splits = get_train_valid_test_split_(splits_string, total_docs)
    names = ("train", "valid", "test")
    out = []
    for i, name in enumerate(names):
        if splits[i + 1] > splits[i] and counts[i] > 0:
            documents = np.arange(splits[i], splits[i + 1], dtype=np.int32)
            out.append(GPTDataset(name, prefix, documents, indexed, counts[i],
                                  seq_length, seed, cache=cache,
                                  strict_data=strict_data))
        else:
            out.append(None)
    return tuple(out)
