// Native data-pipeline helpers for megatron_tpu.
//
// TPU-native equivalent of the reference's pybind11 CPU extension
// (ref: megatron/data/helpers.cpp — build_sample_idx :83-166,
// build_blending_indices :20-80). Same algorithms, re-expressed as a plain
// extern "C" shared library consumed through ctypes (pybind11 is not in this
// image). Compiled on demand by megatron_tpu/data/helpers.py.
//
// Build: g++ -O3 -shared -fPIC -o _helpers.so helpers.cpp

#include <cstdint>

extern "C" {

// Sequential sample-index walk. `sizes`: tokens per sequence in the indexed
// dataset; `doc_idx`: epoch-replicated shuffled document ids; out:
// [num_samples+1, 2] int32 of (doc_idx position, in-doc token offset).
// Mirrors the -1 one-token-overlap bookkeeping of the reference walk.
void build_sample_idx(const int32_t* sizes, const int32_t* doc_idx,
                      int64_t doc_idx_len, int32_t seq_length,
                      int32_t num_epochs, int64_t tokens_per_epoch,
                      int32_t* out /* [(num_samples+1)*2] */) {
    const int64_t num_samples =
        (static_cast<int64_t>(num_epochs) * tokens_per_epoch - 1) / seq_length;

    int64_t sample_index = 0;
    int64_t doc_idx_index = 0;
    int32_t doc_offset = 0;

    out[0] = static_cast<int32_t>(doc_idx_index);
    out[1] = doc_offset;
    ++sample_index;

    while (sample_index <= num_samples) {
        int32_t remaining = seq_length + 1;
        while (remaining != 0) {
            const int32_t doc_id = doc_idx[doc_idx_index];
            const int32_t doc_length = sizes[doc_id] - doc_offset;
            remaining -= doc_length;
            if (remaining <= 0) {
                doc_offset += remaining + doc_length - 1;
                remaining = 0;
            } else {
                if (doc_idx_index + 1 >= doc_idx_len) {
                    // stream exhausted (can only happen on the final +1
                    // sentinel entry); clamp at the end
                    doc_offset = sizes[doc_id];
                    remaining = 0;
                } else {
                    ++doc_idx_index;
                    doc_offset = 0;
                }
            }
        }
        out[2 * sample_index] = static_cast<int32_t>(doc_idx_index);
        out[2 * sample_index + 1] = doc_offset;
        ++sample_index;
    }
}

// Greedy weight-balancing blend: for each output position pick the dataset
// whose emitted count is furthest behind weight * position.
void build_blending_indices(const double* weights, int32_t num_datasets,
                            int64_t size, uint8_t* dataset_index,
                            int64_t* dataset_sample_index) {
    int64_t current[256] = {0};
    for (int64_t i = 0; i < size; ++i) {
        double max_error = -1e300;
        int32_t best = 0;
        for (int32_t d = 0; d < num_datasets; ++d) {
            const double error =
                weights[d] * static_cast<double>(i + 1) -
                static_cast<double>(current[d]);
            if (error > max_error) {
                max_error = error;
                best = d;
            }
        }
        dataset_index[i] = static_cast<uint8_t>(best);
        dataset_sample_index[i] = current[best];
        ++current[best];
    }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Sentence-pair / block mappings for BERT-style and ICT/REALM datasets.
//
// Contract of the reference's build_mapping / build_blocks_mapping
// (ref: megatron/data/helpers.cpp:188-670): walk documents of sentences,
// cut them into samples of ~target length, record (start sentence, end
// sentence, extra) triples/quads, then Fisher-Yates shuffle with
// mt19937_64(seed+1). Sample-length randomness uses mt19937(seed) with the
// same ratio trick, so maps are bit-identical to the reference's for the
// same inputs. Exposed through extern "C" in two-call form: pass
// out == nullptr to size the map, then call again to fill + shuffle.
// ---------------------------------------------------------------------------

#include <cmath>
#include <random>

namespace {

const int32_t kLongSentenceLen = 512;

inline int32_t target_sample_len(int32_t short_seq_ratio, int32_t max_length,
                                 std::mt19937& gen) {
    if (short_seq_ratio == 0) return max_length;
    const uint32_t r = gen();
    if (r % short_seq_ratio == 0) return 2 + r % (max_length - 1);
    return max_length;
}

inline void shuffle_rows(int64_t* maps, int64_t n, int width, int32_t seed) {
    std::mt19937_64 gen(seed + 1);
    for (int64_t i = n - 1; i > 0; --i) {
        const int64_t j = static_cast<int64_t>(gen() % (i + 1));
        for (int c = 0; c < width; ++c) {
            const int64_t t = maps[width * i + c];
            maps[width * i + c] = maps[width * j + c];
            maps[width * j + c] = t;
        }
    }
}

}  // namespace

extern "C" {

// Sentence-pair mapping (ref: helpers.cpp:188-420 build_mapping_impl).
// docs: [n_docs+1] sentence-index offsets; sizes: tokens per sentence.
// Returns the sample count; when out != nullptr also fills out[n*3] with
// (start sentence, end sentence (exclusive), target seq length) rows and
// shuffles them.
int64_t build_mapping(const int64_t* docs, int64_t n_docs,
                      const int32_t* sizes,
                      int32_t num_epochs, uint64_t max_num_samples,
                      int32_t max_seq_length, double short_seq_prob,
                      int32_t seed, int32_t min_num_sent,
                      int64_t* out) {
    int32_t short_seq_ratio = 0;
    if (short_seq_prob > 0)
        short_seq_ratio =
            static_cast<int32_t>(lround(1.0 / short_seq_prob));

    std::mt19937 gen(seed);
    uint64_t map_index = 0;
    for (int32_t epoch = 0; epoch < num_epochs; ++epoch) {
        if (map_index >= max_num_samples) break;
        for (int64_t doc = 0; doc < n_docs; ++doc) {
            const int64_t first = docs[doc];
            const int64_t last = docs[doc + 1];
            int64_t prev_start = first;
            int64_t remain = last - first;

            bool has_long = false;
            if (remain > 1) {
                for (int64_t s = first; s < last; ++s) {
                    if (sizes[s] > kLongSentenceLen) { has_long = true; break; }
                }
            }
            if (remain < min_num_sent || has_long) continue;

            int32_t seq_len = 0;
            int32_t num_sent = 0;
            int32_t target = target_sample_len(short_seq_ratio,
                                               max_seq_length, gen);
            for (int64_t s = first; s < last; ++s) {
                seq_len += sizes[s];
                ++num_sent;
                --remain;
                if ((seq_len >= target && remain > 1 &&
                     num_sent >= min_num_sent) || remain == 0) {
                    if (out != nullptr) {
                        out[3 * map_index] = prev_start;
                        out[3 * map_index + 1] = s + 1;
                        out[3 * map_index + 2] = target;
                    }
                    ++map_index;
                    prev_start = s + 1;
                    target = target_sample_len(short_seq_ratio,
                                               max_seq_length, gen);
                    seq_len = 0;
                    num_sent = 0;
                }
            }
        }
    }
    if (out != nullptr)
        shuffle_rows(out, static_cast<int64_t>(map_index), 3, seed);
    return static_cast<int64_t>(map_index);
}

// ICT/REALM block mapping (ref: helpers.cpp:453-670
// build_blocks_mapping_impl). Rows are (start sentence, end sentence,
// document index, block id); target length shrinks by the document's title
// size so title + block fit max_seq_length together.
int64_t build_blocks_mapping(const int64_t* docs, int64_t n_docs,
                             const int32_t* sizes,
                             const int32_t* titles_sizes,
                             int32_t num_epochs, uint64_t max_num_samples,
                             int32_t max_seq_length, int32_t seed,
                             int32_t use_one_sent_blocks,
                             int64_t* out) {
    const int32_t min_num_sent = use_one_sent_blocks ? 1 : 2;
    uint64_t map_index = 0;
    for (int32_t epoch = 0; epoch < num_epochs; ++epoch) {
        int64_t block_id = 0;
        if (map_index >= max_num_samples) break;
        for (int64_t doc = 0; doc < n_docs; ++doc) {
            const int64_t first = docs[doc];
            const int64_t last = docs[doc + 1];
            const int32_t target = max_seq_length - titles_sizes[doc];
            int64_t prev_start = first;
            int64_t remain = last - first;

            bool has_long = false;
            if (remain >= min_num_sent) {
                for (int64_t s = first; s < last; ++s) {
                    if (sizes[s] > kLongSentenceLen) { has_long = true; break; }
                }
            }
            if (remain < min_num_sent || has_long) continue;

            int32_t seq_len = 0;
            int32_t num_sent = 0;
            for (int64_t s = first; s < last; ++s) {
                seq_len += sizes[s];
                ++num_sent;
                --remain;
                if ((seq_len >= target && remain >= min_num_sent &&
                     num_sent >= min_num_sent) || remain == 0) {
                    if (out != nullptr) {
                        out[4 * map_index] = prev_start;
                        out[4 * map_index + 1] = s + 1;
                        out[4 * map_index + 2] = doc;
                        out[4 * map_index + 3] = block_id;
                    }
                    ++map_index;
                    ++block_id;
                    prev_start = s + 1;
                    seq_len = 0;
                    num_sent = 0;
                }
            }
        }
    }
    if (out != nullptr)
        shuffle_rows(out, static_cast<int64_t>(map_index), 4, seed);
    return static_cast<int64_t>(map_index);
}

}  // extern "C"
