// Native data-pipeline helpers for megatron_tpu.
//
// TPU-native equivalent of the reference's pybind11 CPU extension
// (ref: megatron/data/helpers.cpp — build_sample_idx :83-166,
// build_blending_indices :20-80). Same algorithms, re-expressed as a plain
// extern "C" shared library consumed through ctypes (pybind11 is not in this
// image). Compiled on demand by megatron_tpu/data/helpers.py.
//
// Build: g++ -O3 -shared -fPIC -o _helpers.so helpers.cpp

#include <cstdint>

extern "C" {

// Sequential sample-index walk. `sizes`: tokens per sequence in the indexed
// dataset; `doc_idx`: epoch-replicated shuffled document ids; out:
// [num_samples+1, 2] int32 of (doc_idx position, in-doc token offset).
// Mirrors the -1 one-token-overlap bookkeeping of the reference walk.
void build_sample_idx(const int32_t* sizes, const int32_t* doc_idx,
                      int64_t doc_idx_len, int32_t seq_length,
                      int32_t num_epochs, int64_t tokens_per_epoch,
                      int32_t* out /* [(num_samples+1)*2] */) {
    const int64_t num_samples =
        (static_cast<int64_t>(num_epochs) * tokens_per_epoch - 1) / seq_length;

    int64_t sample_index = 0;
    int64_t doc_idx_index = 0;
    int32_t doc_offset = 0;

    out[0] = static_cast<int32_t>(doc_idx_index);
    out[1] = doc_offset;
    ++sample_index;

    while (sample_index <= num_samples) {
        int32_t remaining = seq_length + 1;
        while (remaining != 0) {
            const int32_t doc_id = doc_idx[doc_idx_index];
            const int32_t doc_length = sizes[doc_id] - doc_offset;
            remaining -= doc_length;
            if (remaining <= 0) {
                doc_offset += remaining + doc_length - 1;
                remaining = 0;
            } else {
                if (doc_idx_index + 1 >= doc_idx_len) {
                    // stream exhausted (can only happen on the final +1
                    // sentinel entry); clamp at the end
                    doc_offset = sizes[doc_id];
                    remaining = 0;
                } else {
                    ++doc_idx_index;
                    doc_offset = 0;
                }
            }
        }
        out[2 * sample_index] = static_cast<int32_t>(doc_idx_index);
        out[2 * sample_index + 1] = doc_offset;
        ++sample_index;
    }
}

// Greedy weight-balancing blend: for each output position pick the dataset
// whose emitted count is furthest behind weight * position.
void build_blending_indices(const double* weights, int32_t num_datasets,
                            int64_t size, uint8_t* dataset_index,
                            int64_t* dataset_sample_index) {
    int64_t current[256] = {0};
    for (int64_t i = 0; i < size; ++i) {
        double max_error = -1e300;
        int32_t best = 0;
        for (int32_t d = 0; d < num_datasets; ++d) {
            const double error =
                weights[d] * static_cast<double>(i + 1) -
                static_cast<double>(current[d]);
            if (error > max_error) {
                max_error = error;
                best = d;
            }
        }
        dataset_index[i] = static_cast<uint8_t>(best);
        dataset_sample_index[i] = current[best];
        ++current[best];
    }
}

}  // extern "C"
