"""ctypes loader for the native data helpers.

TPU-native replacement for the reference's runtime-compiled pybind11 module
(ref: megatron/data/Makefile:1-9, megatron/data/dataset_utils.py:82-92
`compile_helper`). Same compile-on-first-use behavior, but via g++ + ctypes —
pybind11 is not available in this image.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "helpers.cpp")
_SO = os.path.join(_HERE, "_helpers.so")
_lock = threading.Lock()
_lib = None


def _compile():
    subprocess.run(
        ["g++", "-O3", "-shared", "-fPIC", "-o", _SO, _SRC],
        check=True, capture_output=True)


def _load():
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            _compile()
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            # stale artifact from a different arch/libc: rebuild from source
            _compile()
            lib = ctypes.CDLL(_SO)
        lib.build_sample_idx.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32)]
        lib.build_sample_idx.restype = None
        lib.build_blending_indices.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int32, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int64)]
        lib.build_blending_indices.restype = None
        lib.build_mapping.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32, ctypes.c_uint64,
            ctypes.c_int32, ctypes.c_double, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64)]
        lib.build_mapping.restype = ctypes.c_int64
        lib.build_blocks_mapping.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32, ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int64)]
        lib.build_blocks_mapping.restype = ctypes.c_int64
        _lib = lib
        return lib


def _ptr(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def build_sample_idx_native(sizes: np.ndarray, doc_idx: np.ndarray,
                            seq_length: int, num_epochs: int,
                            tokens_per_epoch: int) -> np.ndarray:
    lib = _load()
    sizes = np.ascontiguousarray(sizes, dtype=np.int32)
    doc_idx = np.ascontiguousarray(doc_idx, dtype=np.int32)
    num_samples = (num_epochs * tokens_per_epoch - 1) // seq_length
    out = np.zeros((num_samples + 1, 2), dtype=np.int32)
    lib.build_sample_idx(
        _ptr(sizes, ctypes.c_int32), _ptr(doc_idx, ctypes.c_int32),
        ctypes.c_int64(len(doc_idx)), ctypes.c_int32(seq_length),
        ctypes.c_int32(num_epochs), ctypes.c_int64(tokens_per_epoch),
        _ptr(out, ctypes.c_int32))
    return out


def build_mapping_native(docs: np.ndarray, sizes: np.ndarray, *,
                         num_epochs: int, max_num_samples: int,
                         max_seq_length: int, short_seq_prob: float,
                         seed: int, min_num_sent: int = 2) -> np.ndarray:
    """Sentence-pair sample map [n, 3] of (start sentence, end sentence,
    target seq len) — the reference's build_mapping contract
    (ref: megatron/data/helpers.cpp:188-451)."""
    lib = _load()
    docs = np.ascontiguousarray(docs, dtype=np.int64)
    sizes = np.ascontiguousarray(sizes, dtype=np.int32)
    args = [_ptr(docs, ctypes.c_int64), ctypes.c_int64(len(docs) - 1),
            _ptr(sizes, ctypes.c_int32), ctypes.c_int32(num_epochs),
            ctypes.c_uint64(max_num_samples),
            ctypes.c_int32(max_seq_length),
            ctypes.c_double(short_seq_prob), ctypes.c_int32(seed),
            ctypes.c_int32(min_num_sent)]
    n = lib.build_mapping(*args, None)
    out = np.zeros((n, 3), dtype=np.int64)
    lib.build_mapping(*args, _ptr(out, ctypes.c_int64))
    return out


def build_blocks_mapping_native(docs: np.ndarray, sizes: np.ndarray,
                                titles_sizes: np.ndarray, *,
                                num_epochs: int, max_num_samples: int,
                                max_seq_length: int, seed: int,
                                use_one_sent_blocks: bool = False
                                ) -> np.ndarray:
    """ICT/REALM block map [n, 4] of (start sentence, end sentence, doc,
    block id) — the reference's build_blocks_mapping contract
    (ref: megatron/data/helpers.cpp:453-670)."""
    lib = _load()
    docs = np.ascontiguousarray(docs, dtype=np.int64)
    sizes = np.ascontiguousarray(sizes, dtype=np.int32)
    titles_sizes = np.ascontiguousarray(titles_sizes, dtype=np.int32)
    args = [_ptr(docs, ctypes.c_int64), ctypes.c_int64(len(docs) - 1),
            _ptr(sizes, ctypes.c_int32), _ptr(titles_sizes, ctypes.c_int32),
            ctypes.c_int32(num_epochs), ctypes.c_uint64(max_num_samples),
            ctypes.c_int32(max_seq_length), ctypes.c_int32(seed),
            ctypes.c_int32(int(use_one_sent_blocks))]
    n = lib.build_blocks_mapping(*args, None)
    out = np.zeros((n, 4), dtype=np.int64)
    lib.build_blocks_mapping(*args, _ptr(out, ctypes.c_int64))
    return out


def build_blending_indices_native(weights: np.ndarray, size: int):
    lib = _load()
    weights = np.ascontiguousarray(weights, dtype=np.float64)
    assert len(weights) <= 256
    dataset_index = np.zeros(size, dtype=np.uint8)
    dataset_sample_index = np.zeros(size, dtype=np.int64)
    lib.build_blending_indices(
        _ptr(weights, ctypes.c_double), ctypes.c_int32(len(weights)),
        ctypes.c_int64(size), _ptr(dataset_index, ctypes.c_uint8),
        _ptr(dataset_sample_index, ctypes.c_int64))
    return dataset_index, dataset_sample_index
