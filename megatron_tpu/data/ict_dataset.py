"""Mapping-backed sentence-pair (BERT) and ICT block datasets.

TPU-native ports of the reference's sentence-level data pipeline
(ref: megatron/data/bert_dataset.py:25-180, dataset_utils.py:95-124
get_a_and_b_segments / truncate_segments, ict_dataset.py:50-137
ICTDataset). Both are backed by the native mapping builders in
helpers.cpp (build_mapping / build_blocks_mapping — the reference's
helpers.cpp:188-670 contract): documents are lists of sentences; samples
are (start sentence, end sentence, ...) rows precomputed over epochs and
shuffled.

`sentences[i]` must return the token ids of sentence i; `docs` is the
[n_docs+1] offsets array delimiting each document's sentences.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from megatron_tpu.data.helpers import (build_blocks_mapping_native,
                                       build_mapping_native)
from megatron_tpu.data.masked_dataset import create_masked_lm_predictions


def _split_a_b(sents: list, rng: np.random.RandomState):
    """(ref: dataset_utils.py:95-124 get_a_and_b_segments): random split
    point, 50% A/B swap -> is_next_random."""
    n = len(sents)
    assert n > 1, "sentence-pair samples need >= 2 sentences"
    a_end = 1
    if n >= 3:
        a_end = int(rng.randint(1, n))
    a = [t for s in sents[:a_end] for t in s]
    b = [t for s in sents[a_end:] for t in s]
    is_random = False
    if rng.random() < 0.5:
        is_random = True
        a, b = b, a
    return a, b, is_random


def _truncate_pair(a: list, b: list, budget: int,
                   rng: np.random.RandomState):
    """(ref: dataset_utils.py truncate_segments): trim the longer segment
    one token at a time, from front or back at random."""
    while len(a) + len(b) > budget:
        seg = a if len(a) >= len(b) else b
        if rng.random() < 0.5:
            seg.pop(0)
        else:
            seg.pop()
    return a, b


class BertSentencePairDataset:
    """[CLS] A [SEP] B [SEP] MLM+NSP samples drawn through the native
    sentence-pair mapping (ref: bert_dataset.py:25-180)."""

    def __init__(self, sentences, docs: np.ndarray, *, num_epochs: int,
                 max_num_samples: int, max_seq_length: int,
                 short_seq_prob: float, vocab_size: int, cls_id: int,
                 sep_id: int, mask_id: int, pad_id: int, seed: int = 1234,
                 masked_lm_prob: float = 0.15, sizes=None):
        self.sentences = sentences
        self.max_seq_length = max_seq_length
        self.vocab_size = vocab_size
        self.cls_id, self.sep_id = cls_id, sep_id
        self.mask_id, self.pad_id = mask_id, pad_id
        self.seed = seed
        self.masked_lm_prob = masked_lm_prob
        # sizes: pass the indexed dataset's precomputed array at scale —
        # deriving it loads every sentence up front
        if sizes is None:
            sizes = [len(sentences[i]) for i in range(int(docs[-1]))]
        sizes = np.asarray(sizes, np.int32)
        self.mapping = build_mapping_native(
            docs, sizes, num_epochs=num_epochs,
            max_num_samples=max_num_samples,
            # -3 for [CLS] .. [SEP] .. [SEP] (ref: bert_dataset.py:47)
            max_seq_length=max_seq_length - 3,
            short_seq_prob=short_seq_prob, seed=seed)

    def __len__(self):
        return len(self.mapping)

    def __getitem__(self, idx):
        start, end, target_len = (int(x) for x in self.mapping[idx])
        rng = np.random.RandomState((self.seed + idx) % 2**32)
        sents = [list(np.asarray(self.sentences[i], np.int64))
                 for i in range(start, end)]
        a, b, is_random = _split_a_b(sents, rng)
        a, b = _truncate_pair(a, b, target_len, rng)
        if not b:
            b = [a.pop()] if len(a) > 1 else [self.sep_id]
        tokens = np.asarray([self.cls_id] + a + [self.sep_id] + b
                            + [self.sep_id], np.int64)
        tokentype = np.concatenate([np.zeros(len(a) + 2, np.int64),
                                    np.ones(len(b) + 1, np.int64)])
        masked, labels, loss_mask = create_masked_lm_predictions(
            tokens, self.vocab_size, self.mask_id, rng,
            self.masked_lm_prob, special_ids=(self.cls_id, self.sep_id))
        L = self.max_seq_length
        out = {
            "tokens": np.full(L, self.pad_id, np.int64),
            "tokentype_ids": np.zeros(L, np.int64),
            "labels": np.zeros(L, np.int64),
            "loss_mask": np.zeros(L, np.float32),
            "padding_mask": np.zeros(L, np.int64),
            "is_random": np.int64(is_random),
        }
        n = len(tokens)
        out["tokens"][:n] = masked
        out["tokentype_ids"][:n] = tokentype
        out["labels"][:n] = np.where(labels < 0, 0, labels)
        out["loss_mask"][:n] = loss_mask
        out["padding_mask"][:n] = 1
        return out


class ICTDataset:
    """Inverse-cloze-task samples: a pseudo-query sentence and the block it
    came from (ref: megatron/data/ict_dataset.py:50-137).

    `titles[d]` returns the title token ids of document d (or None to skip
    titles). Context layout: [CLS] title [SEP] block [SEP]; query layout:
    [CLS] query [SEP]."""

    def __init__(self, sentences, docs: np.ndarray, titles=None, *,
                 num_epochs: int = 1, max_num_samples: int = 2**62,
                 max_seq_length: int, query_in_block_prob: float = 0.1,
                 cls_id: int, sep_id: int, pad_id: int, seed: int = 1234,
                 use_one_sent_blocks: bool = False, sizes=None,
                 titles_sizes=None):
        self.sentences = sentences
        self.titles = titles
        self.max_seq_length = max_seq_length
        self.query_in_block_prob = query_in_block_prob
        self.cls_id, self.sep_id, self.pad_id = cls_id, sep_id, pad_id
        self.seed = seed
        # sizes: pass the indexed dataset's precomputed array at scale —
        # deriving it loads every sentence up front
        if sizes is None:
            sizes = [len(sentences[i]) for i in range(int(docs[-1]))]
        sizes = np.asarray(sizes, np.int32)
        if titles_sizes is None:
            if titles is not None:
                titles_sizes = [len(titles[d]) for d in range(len(docs) - 1)]
            else:
                titles_sizes = np.zeros(len(docs) - 1, np.int32)
        titles_sizes = np.asarray(titles_sizes, np.int32)
        self.mapping = build_blocks_mapping_native(
            docs, sizes, titles_sizes, num_epochs=num_epochs,
            max_num_samples=max_num_samples,
            # -3 for [CLS] title [SEP] ... [SEP] specials, matching the
            # sentence-pair builder's budget convention
            max_seq_length=max_seq_length - 3,
            seed=seed, use_one_sent_blocks=use_one_sent_blocks)

    def __len__(self):
        return len(self.mapping)

    def _pad(self, toks: list) -> tuple[np.ndarray, np.ndarray]:
        L = self.max_seq_length
        out = np.full(L, self.pad_id, np.int64)
        mask = np.zeros(L, np.int64)
        n = min(len(toks), L)
        out[:n] = toks[:n]
        mask[:n] = 1
        return out, mask

    def __getitem__(self, idx):
        start, end, doc, block_id = (int(x) for x in self.mapping[idx])
        rng = np.random.RandomState((self.seed + idx) % 2**32)
        block = [list(np.asarray(self.sentences[i], np.int64))
                 for i in range(start, end)]
        title = (list(np.asarray(self.titles[doc], np.int64))
                 if self.titles is not None else None)
        title_pad = 3 + len(title) if title is not None else 2

        q_idx = int(rng.randint(0, len(block)))
        if rng.random() < self.query_in_block_prob:
            query = list(block[q_idx])  # query stays in its block
        else:
            query = block.pop(q_idx)
        query = query[:self.max_seq_length - 2]
        flat = [t for s in block for t in s][:self.max_seq_length - title_pad]

        q_toks = [self.cls_id] + query + [self.sep_id]
        if title is not None:
            c_toks = [self.cls_id] + title + [self.sep_id] + flat + \
                [self.sep_id]
        else:
            c_toks = [self.cls_id] + flat + [self.sep_id]
        query_tokens, query_pad_mask = self._pad(q_toks)
        context_tokens, context_pad_mask = self._pad(c_toks)
        return {
            "query_tokens": query_tokens,
            "query_pad_mask": query_pad_mask,
            "context_tokens": context_tokens,
            "context_pad_mask": context_pad_mask,
            "block_data": np.asarray([start, end, doc, block_id], np.int64),
        }
