"""Indexed binary dataset: the `.bin` + `.idx` on-disk format.

TPU-native reimplementation of the reference's mmap indexed dataset
(ref: megatron/data/indexed_dataset.py:341-600 MMapIndexedDataset,
:462-545 Builder/merge). The FILE FORMAT is kept byte-compatible so corpora
preprocessed by either stack interchange:

  .idx:  magic b"MMIDIDX\\x00\\x00" | u64 version=1 | u8 dtype_code
         | u64 num_sequences | u64 num_documents
         | i32 sizes[num_sequences]          (tokens per sequence)
         | i64 pointers[num_sequences]       (byte offset of each sequence)
         | i64 doc_idx[num_documents+1]      (sequence index of doc starts)
  .bin:  raw token arrays back to back, dtype per dtype_code.

Only the mmap implementation is provided — the reference's lazy/cached
variants (ref: indexed_dataset.py:128-263) existed for pre-mmap torch eras
and add nothing on a modern host.
"""
from __future__ import annotations

import os
import shutil
import struct
from typing import Optional, Sequence

import numpy as np

_MAGIC = b"MMIDIDX\x00\x00"
_HEADER_BYTES = 34  # magic(9) + version(8) + dtype(1) + len(8) + docs(8)


class DatasetCorruptionError(RuntimeError):
    """A `.idx`/`.bin` pair failed validation at open. Typed (never an
    assert — asserts vanish under `python -O` — and never a downstream
    numpy error) so callers can distinguish corrupt input data from
    code bugs; carries the offending path and an actionable message.
    `tools/validate_dataset.py` runs the same checks offline."""

    def __init__(self, path: str, reason: str):
        self.path = path
        self.reason = reason
        super().__init__(f"{path}: {reason}")

# dtype codes shared with the reference (ref: indexed_dataset.py:90-100)
DTYPES = {
    1: np.uint8,
    2: np.int8,
    3: np.int16,
    4: np.int32,
    5: np.int64,
    6: np.float32,
    7: np.float64,
    8: np.uint16,
}
DTYPE_CODES = {np.dtype(v): k for k, v in DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


def infer_dataset_exists(prefix: str) -> bool:
    return (os.path.exists(data_file_path(prefix))
            and os.path.exists(index_file_path(prefix)))


def best_fitting_dtype(vocab_size: Optional[int]) -> np.dtype:
    """(ref: indexed_dataset.py:24-29) uint16 when the vocab fits."""
    if vocab_size is not None and vocab_size < 65500:
        return np.dtype(np.uint16)
    return np.dtype(np.int32)


class MMapIndexedDataset:
    """Read-side mmap dataset (ref: indexed_dataset.py:341-461).

    Validates the pair ON OPEN — header fields, index size arithmetic
    vs the actual `.idx` bytes, every pointer/size against the actual
    `.bin` bytes, doc_idx bounds + monotonicity — raising a typed
    `DatasetCorruptionError` up front instead of letting a truncated
    `.bin` or bit-rotted `.idx` surface 30 hours later as an
    inscrutable numpy error (or, worse, as silently garbage tokens)."""

    def __init__(self, prefix: str):
        self.prefix = prefix
        idx_path = index_file_path(prefix)
        bin_path = data_file_path(prefix)
        for path in (idx_path, bin_path):
            # typed, so the blend-level skip-and-count policy catches a
            # half-deleted corpus the same way it catches a corrupt one
            if not os.path.isfile(path):
                raise DatasetCorruptionError(
                    path, "file missing — deleted corpus half or wrong "
                    "prefix; re-run preprocessing or fix --data_path")
        with open(idx_path, "rb") as f:
            header = f.read(_HEADER_BYTES)
        if len(header) < _HEADER_BYTES:
            raise DatasetCorruptionError(
                idx_path, f"index header truncated ({len(header)} of "
                f"{_HEADER_BYTES} bytes) — re-run preprocessing")
        magic = header[:9]
        if magic != _MAGIC:
            raise DatasetCorruptionError(
                idx_path, f"bad magic {magic!r} — not an indexed-dataset "
                "index file (overwritten header?); rebuild with "
                "tools/preprocess_data.py")
        (version,) = struct.unpack("<Q", header[9:17])
        if version != 1:
            raise DatasetCorruptionError(
                idx_path, f"unsupported index version {version} "
                "(expected 1) — corrupt header or a newer format")
        code = header[17]
        if code not in DTYPES:
            raise DatasetCorruptionError(
                idx_path, f"unknown dtype code {code} (valid: "
                f"{sorted(DTYPES)}) — corrupt header byte")
        self.dtype = np.dtype(DTYPES[code])
        (self._len,) = struct.unpack("<Q", header[18:26])
        (self._doc_count,) = struct.unpack("<Q", header[26:34])
        offset = _HEADER_BYTES

        # size arithmetic: the header fully determines the index length
        expected = (offset + 4 * self._len + 8 * self._len
                    + 8 * self._doc_count)
        actual = os.path.getsize(idx_path)
        if actual != expected:
            kind = ("truncated" if actual < expected
                    else "has trailing garbage")
            raise DatasetCorruptionError(
                idx_path, f"index size mismatch: header promises "
                f"{self._len} sequences + {self._doc_count} doc entries "
                f"= {expected} bytes, file has {actual} ({kind}) — "
                "re-run preprocessing")

        self._index_mmap = np.memmap(idx_path, mode="r", order="C")
        self.sizes = np.frombuffer(self._index_mmap, dtype=np.int32,
                                   count=self._len, offset=offset)
        offset += self.sizes.nbytes
        self._pointers = np.frombuffer(self._index_mmap, dtype=np.int64,
                                       count=self._len, offset=offset)
        offset += self._pointers.nbytes
        self.doc_idx = np.frombuffer(self._index_mmap, dtype=np.int64,
                                     count=self._doc_count, offset=offset)

        bin_size = os.path.getsize(bin_path)
        if self._len:
            if int(self.sizes.min()) < 0:
                i = int(np.argmin(self.sizes))
                raise DatasetCorruptionError(
                    idx_path, f"negative size {int(self.sizes[i])} at "
                    f"sequence {i} — corrupt sizes table")
            if int(self._pointers.min()) < 0:
                i = int(np.argmin(self._pointers))
                raise DatasetCorruptionError(
                    idx_path, f"negative pointer {int(self._pointers[i])} "
                    f"at sequence {i} — corrupt pointers table")
            # chunked scan: a single vectorized `pointers + sizes*item`
            # materializes O(len) int64 temporaries — multi-GB spikes on
            # billion-sequence corpora — for what is just a running max
            chunk = 1 << 22
            for lo in range(0, self._len, chunk):
                ends = (self._pointers[lo:lo + chunk]
                        + self.sizes[lo:lo + chunk].astype(np.int64)
                        * self.dtype.itemsize)
                if int(ends.max()) > bin_size:
                    i = lo + int(np.argmax(ends))
                    raise DatasetCorruptionError(
                        bin_path, f"sequence {i} spans bytes "
                        f"[{int(self._pointers[i])}, "
                        f"{int(self._pointers[i]) + int(self.sizes[i]) * self.dtype.itemsize}) "
                        f"but the data file is only {bin_size} bytes — "
                        "truncated .bin or stale index; re-run "
                        "preprocessing or restore the corpus")
        if self._doc_count:
            if (int(self.doc_idx.min()) < 0
                    or int(self.doc_idx.max()) > self._len):
                raise DatasetCorruptionError(
                    idx_path, "doc_idx entries outside "
                    f"[0, {self._len}] — corrupt document table")
            if self._doc_count > 1 and bool(
                    (np.diff(self.doc_idx) < 0).any()):
                raise DatasetCorruptionError(
                    idx_path, "doc_idx is not monotonically "
                    "non-decreasing — corrupt document table")
        self._data_mmap = np.memmap(bin_path, mode="r",
                                    order="C") if bin_size else \
            np.empty(0, dtype=np.uint8)

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, idx):
        if isinstance(idx, (int, np.integer)):
            ptr = self._pointers[idx]
            size = self.sizes[idx]
            return np.frombuffer(self._data_mmap, dtype=self.dtype,
                                 count=size, offset=ptr)
        raise TypeError(f"unsupported index type {type(idx)}")

    def get(self, idx: int, offset: int = 0, length: Optional[int] = None):
        """Read a slice of sequence `idx` (ref: indexed_dataset.py:436-446)."""
        size = int(self.sizes[idx])
        if length is None:
            length = size - offset
        ptr = int(self._pointers[idx]) + offset * self.dtype.itemsize
        return np.frombuffer(self._data_mmap, dtype=self.dtype, count=length,
                             offset=ptr)


class IndexedDatasetBuilder:
    """Write-side builder (ref: indexed_dataset.py:462-545)."""

    def __init__(self, prefix: str, dtype=np.int32):
        self.prefix = prefix
        self.dtype = np.dtype(dtype)
        self._data = open(data_file_path(prefix), "wb")
        self._sizes: list[int] = []
        self._doc_idx: list[int] = [0]

    def add_item(self, tokens: Sequence[int]) -> None:
        arr = np.asarray(tokens, dtype=self.dtype)
        self._data.write(arr.tobytes(order="C"))
        self._sizes.append(len(arr))

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def merge_file(self, other_prefix: str) -> None:
        """Append another dataset with the same dtype
        (ref: indexed_dataset.py:524-538 merge_file_)."""
        other = MMapIndexedDataset(other_prefix)
        if other.dtype != self.dtype:
            raise ValueError(
                f"cannot merge {other_prefix} (dtype {other.dtype}) "
                f"into a {self.dtype} builder")
        base = len(self._sizes)
        self._sizes.extend(int(s) for s in other.sizes)
        # skip the leading 0 of the other doc_idx
        self._doc_idx.extend(base + int(d) for d in other.doc_idx[1:])
        with open(data_file_path(other_prefix), "rb") as f:
            shutil.copyfileobj(f, self._data)

    def finalize(self) -> None:
        self._data.close()
        sizes = np.asarray(self._sizes, dtype=np.int32)
        itemsize = self.dtype.itemsize
        pointers = np.zeros(len(sizes), dtype=np.int64)
        if len(sizes) > 1:
            np.cumsum(sizes[:-1] * itemsize, out=pointers[1:])
        if self._doc_idx[-1] != len(sizes):
            self._doc_idx.append(len(sizes))
        doc_idx = np.asarray(self._doc_idx, dtype=np.int64)
        with open(index_file_path(self.prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<Q", 1))
            f.write(struct.pack("<B", DTYPE_CODES[self.dtype]))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(struct.pack("<Q", len(doc_idx)))
            f.write(sizes.tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))
            f.write(doc_idx.tobytes(order="C"))


# handle cache keyed on (mtime_ns, size) of BOTH files — a plain
# lru_cache(prefix) kept serving stale (or corrupt) mmaps after the
# files were rewritten by re-preprocessing, and a failed open must
# never pin a broken entry
_DATASET_CACHE: dict = {}


def _file_signature(prefix: str) -> tuple:
    si = os.stat(index_file_path(prefix))
    sb = os.stat(data_file_path(prefix))
    return (si.st_mtime_ns, si.st_size, sb.st_mtime_ns, sb.st_size)


def _dataset_cache_clear() -> None:
    _DATASET_CACHE.clear()


def make_dataset(prefix: str, impl: str = "mmap") -> MMapIndexedDataset:
    """(ref: indexed_dataset.py:58-73 make_dataset) — mmap only.

    Re-validates freshness per call: the cached handle is reused only
    while both files' (mtime, size) are unchanged; a rewritten pair
    re-opens (and re-validates), a failed open evicts."""
    if impl not in ("mmap", "infer"):
        raise ValueError(f"only mmap supported, got {impl!r}")
    try:
        sig = _file_signature(prefix)
    except FileNotFoundError as e:
        _DATASET_CACHE.pop(prefix, None)
        raise DatasetCorruptionError(
            e.filename or prefix, "file missing — deleted corpus half "
            "or wrong prefix; re-run preprocessing or fix --data_path"
        ) from e
    hit = _DATASET_CACHE.get(prefix)
    if hit is not None and hit[0] == sig:
        return hit[1]
    _DATASET_CACHE.pop(prefix, None)  # stale or first open: drop first
    ds = MMapIndexedDataset(prefix)   # may raise DatasetCorruptionError
    _DATASET_CACHE[prefix] = (sig, ds)
    return ds


make_dataset.cache_clear = _dataset_cache_clear  # lru_cache-compat API
