"""Indexed binary dataset: the `.bin` + `.idx` on-disk format.

TPU-native reimplementation of the reference's mmap indexed dataset
(ref: megatron/data/indexed_dataset.py:341-600 MMapIndexedDataset,
:462-545 Builder/merge). The FILE FORMAT is kept byte-compatible so corpora
preprocessed by either stack interchange:

  .idx:  magic b"MMIDIDX\\x00\\x00" | u64 version=1 | u8 dtype_code
         | u64 num_sequences | u64 num_documents
         | i32 sizes[num_sequences]          (tokens per sequence)
         | i64 pointers[num_sequences]       (byte offset of each sequence)
         | i64 doc_idx[num_documents+1]      (sequence index of doc starts)
  .bin:  raw token arrays back to back, dtype per dtype_code.

Only the mmap implementation is provided — the reference's lazy/cached
variants (ref: indexed_dataset.py:128-263) existed for pre-mmap torch eras
and add nothing on a modern host.
"""
from __future__ import annotations

import os
import shutil
import struct
from functools import lru_cache
from typing import Optional, Sequence

import numpy as np

_MAGIC = b"MMIDIDX\x00\x00"

# dtype codes shared with the reference (ref: indexed_dataset.py:90-100)
DTYPES = {
    1: np.uint8,
    2: np.int8,
    3: np.int16,
    4: np.int32,
    5: np.int64,
    6: np.float32,
    7: np.float64,
    8: np.uint16,
}
DTYPE_CODES = {np.dtype(v): k for k, v in DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


def infer_dataset_exists(prefix: str) -> bool:
    return (os.path.exists(data_file_path(prefix))
            and os.path.exists(index_file_path(prefix)))


def best_fitting_dtype(vocab_size: Optional[int]) -> np.dtype:
    """(ref: indexed_dataset.py:24-29) uint16 when the vocab fits."""
    if vocab_size is not None and vocab_size < 65500:
        return np.dtype(np.uint16)
    return np.dtype(np.int32)


class MMapIndexedDataset:
    """Read-side mmap dataset (ref: indexed_dataset.py:341-461)."""

    def __init__(self, prefix: str):
        self.prefix = prefix
        with open(index_file_path(prefix), "rb") as f:
            magic = f.read(9)
            assert magic == _MAGIC, (
                f"{index_file_path(prefix)}: bad magic {magic!r} — not an "
                "indexed dataset index file")
            (version,) = struct.unpack("<Q", f.read(8))
            assert version == 1, f"unsupported index version {version}"
            (code,) = struct.unpack("<B", f.read(1))
            self.dtype = np.dtype(DTYPES[code])
            (self._len,) = struct.unpack("<Q", f.read(8))
            (self._doc_count,) = struct.unpack("<Q", f.read(8))
            offset = f.tell()
        self._index_mmap = np.memmap(index_file_path(prefix), mode="r",
                                     order="C")
        self.sizes = np.frombuffer(self._index_mmap, dtype=np.int32,
                                   count=self._len, offset=offset)
        offset += self.sizes.nbytes
        self._pointers = np.frombuffer(self._index_mmap, dtype=np.int64,
                                       count=self._len, offset=offset)
        offset += self._pointers.nbytes
        self.doc_idx = np.frombuffer(self._index_mmap, dtype=np.int64,
                                     count=self._doc_count, offset=offset)
        self._data_mmap = np.memmap(data_file_path(prefix), mode="r",
                                    order="C")

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, idx):
        if isinstance(idx, (int, np.integer)):
            ptr = self._pointers[idx]
            size = self.sizes[idx]
            return np.frombuffer(self._data_mmap, dtype=self.dtype,
                                 count=size, offset=ptr)
        raise TypeError(f"unsupported index type {type(idx)}")

    def get(self, idx: int, offset: int = 0, length: Optional[int] = None):
        """Read a slice of sequence `idx` (ref: indexed_dataset.py:436-446)."""
        size = int(self.sizes[idx])
        if length is None:
            length = size - offset
        ptr = int(self._pointers[idx]) + offset * self.dtype.itemsize
        return np.frombuffer(self._data_mmap, dtype=self.dtype, count=length,
                             offset=ptr)


class IndexedDatasetBuilder:
    """Write-side builder (ref: indexed_dataset.py:462-545)."""

    def __init__(self, prefix: str, dtype=np.int32):
        self.prefix = prefix
        self.dtype = np.dtype(dtype)
        self._data = open(data_file_path(prefix), "wb")
        self._sizes: list[int] = []
        self._doc_idx: list[int] = [0]

    def add_item(self, tokens: Sequence[int]) -> None:
        arr = np.asarray(tokens, dtype=self.dtype)
        self._data.write(arr.tobytes(order="C"))
        self._sizes.append(len(arr))

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def merge_file(self, other_prefix: str) -> None:
        """Append another dataset with the same dtype
        (ref: indexed_dataset.py:524-538 merge_file_)."""
        other = MMapIndexedDataset(other_prefix)
        assert other.dtype == self.dtype
        base = len(self._sizes)
        self._sizes.extend(int(s) for s in other.sizes)
        # skip the leading 0 of the other doc_idx
        self._doc_idx.extend(base + int(d) for d in other.doc_idx[1:])
        with open(data_file_path(other_prefix), "rb") as f:
            shutil.copyfileobj(f, self._data)

    def finalize(self) -> None:
        self._data.close()
        sizes = np.asarray(self._sizes, dtype=np.int32)
        itemsize = self.dtype.itemsize
        pointers = np.zeros(len(sizes), dtype=np.int64)
        if len(sizes) > 1:
            np.cumsum(sizes[:-1] * itemsize, out=pointers[1:])
        if self._doc_idx[-1] != len(sizes):
            self._doc_idx.append(len(sizes))
        doc_idx = np.asarray(self._doc_idx, dtype=np.int64)
        with open(index_file_path(self.prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<Q", 1))
            f.write(struct.pack("<B", DTYPE_CODES[self.dtype]))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(struct.pack("<Q", len(doc_idx)))
            f.write(sizes.tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))
            f.write(doc_idx.tobytes(order="C"))


@lru_cache(maxsize=None)
def make_dataset(prefix: str, impl: str = "mmap") -> MMapIndexedDataset:
    """(ref: indexed_dataset.py:58-73 make_dataset) — mmap only."""
    assert impl in ("mmap", "infer"), f"only mmap supported, got {impl!r}"
    return MMapIndexedDataset(prefix)
