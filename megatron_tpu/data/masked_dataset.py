"""Masked-LM datasets: BERT (MLM + NSP) and T5 (span corruption).

TPU-native port of the reference's masked-LM data pipeline
(ref: megatron/data/dataset_utils.py:create_masked_lm_predictions + ~729 LoC
of helpers, bert_dataset.py:182, t5_dataset.py:257). Semantics kept:

- 15% of tokens selected for prediction; of those 80% -> [MASK], 10% ->
  random token, 10% unchanged (ref: dataset_utils.py masked-lm rates);
- BERT samples sentence pairs A/B with a 50% random-B swap for NSP
  (ref: bert_dataset.py build_training_sample);
- T5 replaces contiguous spans (mean length 3) with sentinel tokens and
  trains the decoder to emit sentinel+span sequences
  (ref: t5_dataset.py build_training_sample).

Simplification by design: the reference pre-builds sentence-pair mappings
with the C++ `build_mapping` helpers over a sentence-split corpus
(ref: helpers.cpp:188-670); here pairs are drawn directly from document
halves at __getitem__ time under a per-sample seeded RNG — deterministic
given (seed, index), no index-build pass needed.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def create_masked_lm_predictions(
    tokens: np.ndarray,
    vocab_size: int,
    mask_id: int,
    rng: np.random.RandomState,
    masked_lm_prob: float = 0.15,
    max_predictions: Optional[int] = None,
    special_ids: Sequence[int] = (),
):
    """(ref: dataset_utils.py create_masked_lm_predictions). Returns
    (masked_tokens, labels, loss_mask): labels hold the original token at
    masked positions, -1 elsewhere (callers build their own loss mask)."""
    tokens = np.asarray(tokens)
    n = len(tokens)
    cand = np.asarray([i for i in range(n) if tokens[i] not in special_ids])
    num_pred = max(1, int(round(len(cand) * masked_lm_prob)))
    if max_predictions is not None:
        num_pred = min(num_pred, max_predictions)
    picked = rng.choice(cand, size=min(num_pred, len(cand)), replace=False)

    masked = tokens.copy()
    labels = np.full(n, -1, np.int64)
    loss_mask = np.zeros(n, np.float32)
    for i in picked:
        labels[i] = tokens[i]
        loss_mask[i] = 1.0
        r = rng.random()
        if r < 0.8:
            masked[i] = mask_id
        elif r < 0.9:
            masked[i] = rng.randint(0, vocab_size)
        # else keep original
    return masked, labels, loss_mask


class BertDataset:
    """Sentence-pair MLM+NSP samples (ref: megatron/data/bert_dataset.py).

    Emits {tokens, tokentype_ids, labels, loss_mask, padding_mask,
    is_random} with [CLS] A [SEP] B [SEP] packing."""

    def __init__(self, indexed, num_samples: int, max_seq_length: int,
                 vocab_size: int, cls_id: int, sep_id: int, mask_id: int,
                 pad_id: int, seed: int = 1234,
                 masked_lm_prob: float = 0.15):
        self.indexed = indexed
        self.num_samples = num_samples
        self.max_seq_length = max_seq_length
        self.vocab_size = vocab_size
        self.cls_id, self.sep_id = cls_id, sep_id
        self.mask_id, self.pad_id = mask_id, pad_id
        self.seed = seed
        self.masked_lm_prob = masked_lm_prob
        self.n_docs = len(indexed)

    def __len__(self):
        return self.num_samples

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + int(idx))
        doc_a = int(rng.randint(self.n_docs))
        a = np.asarray(self.indexed[doc_a], np.int64)
        half = max(len(a) // 2, 1)
        is_random = int(rng.random() < 0.5)  # (ref: bert_dataset NSP 50/50)
        if is_random:
            doc_b = int(rng.randint(self.n_docs))
            b = np.asarray(self.indexed[doc_b], np.int64)
            b = b[:max(len(b) // 2, 1)]
            a = a[:half]
        else:
            b = a[half:]
            a = a[:half]
        # truncate pair to fit [CLS] A [SEP] B [SEP]
        budget = self.max_seq_length - 3
        while len(a) + len(b) > budget:
            if len(a) >= len(b):
                a = a[:-1]
            else:
                b = b[:-1]
        if len(b) == 0:
            b = np.asarray([self.sep_id])
        tokens = np.concatenate([[self.cls_id], a, [self.sep_id], b,
                                 [self.sep_id]])
        tokentype = np.concatenate([np.zeros(len(a) + 2, np.int64),
                                    np.ones(len(b) + 1, np.int64)])
        special = (self.cls_id, self.sep_id)
        masked, labels, loss_mask = create_masked_lm_predictions(
            tokens, self.vocab_size, self.mask_id, rng,
            self.masked_lm_prob, special_ids=special)
        L = self.max_seq_length
        out = {
            "tokens": np.full(L, self.pad_id, np.int64),
            "tokentype_ids": np.zeros(L, np.int64),
            "labels": np.full(L, -1, np.int64),
            "loss_mask": np.zeros(L, np.float32),
            "padding_mask": np.zeros(L, np.int64),
            "is_random": np.int64(is_random),
        }
        n = len(tokens)
        out["tokens"][:n] = masked
        out["tokentype_ids"][:n] = tokentype
        out["labels"][:n] = labels
        out["loss_mask"][:n] = loss_mask
        out["padding_mask"][:n] = 1
        # labels must be valid gather indices even where unused
        out["labels"][out["labels"] < 0] = 0
        return out


class T5Dataset:
    """Span-corruption samples (ref: megatron/data/t5_dataset.py).

    Emits {text_enc, text_dec, labels, loss_mask, enc_mask}: encoder sees
    the text with spans replaced by sentinels; decoder emits
    sentinel+span... [EOS]."""

    def __init__(self, indexed, num_samples: int, max_seq_length: int,
                 max_seq_length_dec: int, vocab_size: int,
                 sentinel_ids: Sequence[int], bos_id: int, eos_id: int,
                 pad_id: int, seed: int = 1234,
                 masked_lm_prob: float = 0.15, mean_span: int = 3):
        self.indexed = indexed
        self.num_samples = num_samples
        self.L_enc = max_seq_length
        self.L_dec = max_seq_length_dec
        self.vocab_size = vocab_size
        self.sentinels = list(sentinel_ids)
        self.bos_id, self.eos_id, self.pad_id = bos_id, eos_id, pad_id
        self.seed = seed
        self.masked_lm_prob = masked_lm_prob
        self.mean_span = mean_span
        self.n_docs = len(indexed)

    def __len__(self):
        return self.num_samples

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + int(idx))
        doc = np.asarray(self.indexed[int(rng.randint(self.n_docs))],
                         np.int64)
        doc = doc[:self.L_enc - 1]
        n = len(doc)
        num_mask = max(1, int(round(n * self.masked_lm_prob)))
        # draw spans until the mask budget is spent
        spans = []
        covered = np.zeros(n, bool)
        budget = num_mask
        tries = 0
        while budget > 0 and tries < 100:
            tries += 1
            ln = max(1, int(rng.poisson(self.mean_span)))
            ln = min(ln, budget)
            start = int(rng.randint(0, max(n - ln, 1)))
            if covered[start:start + ln].any():
                continue
            covered[start:start + ln] = True
            spans.append((start, ln))
            budget -= ln
        spans.sort()

        enc, dec = [], [self.bos_id]
        prev = 0
        for si, (start, ln) in enumerate(spans[:len(self.sentinels)]):
            sentinel = self.sentinels[si]
            enc.extend(doc[prev:start])
            enc.append(sentinel)
            dec.append(sentinel)
            dec.extend(doc[start:start + ln])
            prev = start + ln
        enc.extend(doc[prev:])
        dec.append(self.eos_id)

        labels = dec[1:] + [self.pad_id]
        out = {
            "text_enc": np.full(self.L_enc, self.pad_id, np.int64),
            "text_dec": np.full(self.L_dec, self.pad_id, np.int64),
            "labels": np.full(self.L_dec, self.pad_id, np.int64),
            "loss_mask": np.zeros(self.L_dec, np.float32),
            "enc_mask": np.zeros(self.L_enc, np.int64),
        }
        ne, nd = min(len(enc), self.L_enc), min(len(dec), self.L_dec)
        out["text_enc"][:ne] = enc[:ne]
        out["enc_mask"][:ne] = 1
        out["text_dec"][:nd] = dec[:nd]
        out["labels"][:nd] = labels[:nd]
        out["loss_mask"][:nd] = 1.0
        return out
