"""Open-retrieval (ORQA/DPR-style) evidence and question datasets.

TPU-native equivalents of the reference's retrieval data loaders
(ref: megatron/data/orqa_wiki_dataset.py:16-135 OpenRetrievalEvidenceDataset,
tasks/orqa/unsupervised/nq.py:19-215 NQDataset). Pure numpy — batches are
assembled host-side and fed to jitted embedding functions whole.

Evidence file format (DPR "psgs_w100.tsv" layout): TSV with a header row,
columns `id  text  title`. Question file format: TSV/CSV rows of
`question  answers` where answers is a python-list literal (DPR NQ layout),
or JSONL rows {"question": ..., "answers": [...]}.
"""
from __future__ import annotations

import ast
import csv
import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def build_tokens_types_paddings_from_ids(text_ids: Sequence[int],
                                         max_seq_length: int, cls_id: int,
                                         sep_id: int, pad_id: int):
    """[CLS] ids [SEP] + pad -> (tokens, tokentypes, pad_mask), each
    [max_seq_length] (ref: orqa_wiki_dataset.py:68-110). pad_mask is 1 on
    real tokens, 0 on padding."""
    ids = [cls_id] + list(text_ids)[:max_seq_length - 2] + [sep_id]
    n = len(ids)
    tokens = np.full(max_seq_length, pad_id, np.int64)
    tokens[:n] = ids
    types = np.zeros(max_seq_length, np.int64)
    pad_mask = np.zeros(max_seq_length, np.int64)
    pad_mask[:n] = 1
    return tokens, types, pad_mask


class OpenRetrievalEvidenceDataset:
    """Wikipedia evidence passages for open retrieval
    (ref: megatron/data/orqa_wiki_dataset.py:16-135). Each sample is the
    tokenized `[CLS] title [SEP] text [SEP]` block plus its row id; `id2text`
    maps row id -> (text, title) for answer matching
    (ref: tasks/orqa/evaluate_utils.py evidence usage)."""

    def __init__(self, evidence_path: str, tokenizer, max_seq_length: int):
        self.tokenizer = tokenizer
        self.max_seq_length = max_seq_length
        self.rows: List[Tuple[int, str, str]] = []  # (row_id, text, title)
        with open(evidence_path, newline="", encoding="utf-8") as f:
            reader = csv.reader(f, delimiter="\t")
            for i, row in enumerate(reader):
                if i == 0 and row and row[0].strip().lower() == "id":
                    continue  # header
                if len(row) < 3:
                    continue
                self.rows.append((int(row[0]), row[1], row[2]))
        self._id2text: Optional[Dict[int, Tuple[str, str]]] = None

    @property
    def id2text(self) -> Dict[int, Tuple[str, str]]:
        """doc_id -> (text, title), built lazily: only answer matching
        (evaluation) needs it — the indexing pass over a 21M-passage DPR
        dump must not pay gigabytes for an unused dict."""
        if self._id2text is None:
            self._id2text = {rid: (text, title)
                             for rid, text, title in self.rows}
        return self._id2text

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, idx: int):
        row_id, text, title = self.rows[idx]
        ids = (self.tokenizer.tokenize(title) + [self.tokenizer.sep]
               + self.tokenizer.tokenize(text))
        tokens, types, pad_mask = build_tokens_types_paddings_from_ids(
            ids, self.max_seq_length, self.tokenizer.cls,
            self.tokenizer.sep, self.tokenizer.pad)
        return {"row_id": row_id, "context": tokens,
                "context_types": types, "context_pad_mask": pad_mask}

    def batches(self, batch_size: int, *, shard: int = 0,
                num_shards: int = 1):
        """Yield stacked batches of this dataset's `shard`-th slice (round-
        robin over `num_shards` — the dp sharding of the reference's
        IndexBuilder, ref: megatron/indexer.py:36-37,86-90). The final
        partial batch is padded by repeating the last row; `n_real` marks
        how many rows are genuine."""
        idxs = list(range(shard, len(self), num_shards))
        for lo in range(0, len(idxs), batch_size):
            chunk = idxs[lo:lo + batch_size]
            n_real = len(chunk)
            while len(chunk) < batch_size:
                chunk.append(chunk[-1])
            samples = [self[i] for i in chunk]
            yield {
                "row_id": np.asarray([s["row_id"] for s in samples]),
                "context": np.stack([s["context"] for s in samples]),
                "context_types": np.stack(
                    [s["context_types"] for s in samples]),
                "context_pad_mask": np.stack(
                    [s["context_pad_mask"] for s in samples]),
                "n_real": n_real,
            }


def _read_qa_rows(path: str) -> List[Tuple[str, List[str]]]:
    """DPR NQ csv/tsv (`question\\tanswers-literal`) or JSONL
    (ref: tasks/orqa/unsupervised/nq.py:118-137)."""
    rows: List[Tuple[str, List[str]]] = []
    with open(path, newline="", encoding="utf-8") as f:
        first = f.read(1)
        f.seek(0)
        if first == "{":
            for line in f:
                if not line.strip():
                    continue
                d = json.loads(line)
                rows.append((d["question"], list(d["answers"])))
        else:
            for row in csv.reader(f, delimiter="\t"):
                if len(row) < 2:
                    continue
                try:
                    answers = ast.literal_eval(row[1])
                except (ValueError, SyntaxError):
                    answers = [row[1]]
                rows.append((row[0], [str(a) for a in answers]))
    return rows


class NQDataset:
    """Natural-Questions open-domain eval queries
    (ref: tasks/orqa/unsupervised/nq.py:84-215): tokenized question plus the
    reference answer list."""

    def __init__(self, qa_path: str, tokenizer, max_seq_length: int):
        self.tokenizer = tokenizer
        self.max_seq_length = max_seq_length
        self.rows = _read_qa_rows(qa_path)

    def __len__(self):
        return len(self.rows)

    def __getitem__(self, idx: int):
        question, answers = self.rows[idx]
        ids = self.tokenizer.tokenize(question)
        tokens, types, pad_mask = build_tokens_types_paddings_from_ids(
            ids, self.max_seq_length, self.tokenizer.cls,
            self.tokenizer.sep, self.tokenizer.pad)
        return {"token_ids": tokens, "token_types": types,
                "token_mask": pad_mask, "reference": answers}

    def batches(self, batch_size: int):
        """Sequential, keep-last batches (the reference's NQ dataloader is
        explicitly non-distributed with drop_last=False,
        ref: nq.py:64-83)."""
        for lo in range(0, len(self), batch_size):
            chunk = [self[i] for i in range(lo, min(lo + batch_size,
                                                    len(self)))]
            n_real = len(chunk)
            while len(chunk) < batch_size:
                chunk.append(chunk[-1])
            yield {
                "token_ids": np.stack([s["token_ids"] for s in chunk]),
                "token_types": np.stack([s["token_types"] for s in chunk]),
                "token_mask": np.stack([s["token_mask"] for s in chunk]),
                "reference": [s["reference"] for s in chunk[:n_real]],
                "n_real": n_real,
            }
