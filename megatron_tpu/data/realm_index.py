"""Persistent block-embedding store for open retrieval (REALM/ORQA).

TPU-native equivalent of the reference's OpenRetreivalDataStore
(ref: megatron/data/realm_index.py:17-115). The reference pickles a
{row_id: embedding} dict per rank into `<path>_tmp/<rank>.pkl` shards and
merges them; we store the same mapping as a single compressed .npz
(`ids` [N] int64 + `embeds` [N, d] float16) — mmap-friendly, arch-neutral,
and directly consumable by the matmul MIPS index
(megatron_tpu/models/biencoder.py MIPSIndex).
"""
from __future__ import annotations

import glob
import os
from typing import Dict, Iterable, Optional

import numpy as np


class OpenRetrievalDataStore:
    """row_id -> block embedding, with shard/merge persistence
    (ref: realm_index.py:17-115). Embeddings are stored fp16 on disk like
    the reference (`embed_data[row_id] = np.float16(...)`,
    ref: realm_index.py:75-82)."""

    def __init__(self, embedding_path: Optional[str] = None,
                 load_from_path: bool = True, rank: Optional[int] = None):
        self.embed_data: Dict[int, np.ndarray] = {}
        self.embedding_path = embedding_path
        self.rank = rank
        if load_from_path and embedding_path and \
                os.path.exists(embedding_path):
            self.load_from_file()

    # -- shard temp-file naming (ref: realm_index.py:33-34,84-115) --
    @property
    def temp_dir_name(self) -> str:
        assert self.embedding_path
        return os.path.splitext(self.embedding_path)[0] + "_tmp"

    def state(self):
        return {"embed_data": self.embed_data}

    def clear(self):
        """(ref: realm_index.py:41-47)"""
        self.embed_data = {}

    def add_block_data(self, row_ids: Iterable[int], block_embeds,
                       allow_overwrite: bool = False):
        """(ref: realm_index.py:75-82)"""
        embeds = np.asarray(block_embeds, np.float16)
        for rid, emb in zip(np.asarray(row_ids).ravel(), embeds):
            rid = int(rid)
            if not allow_overwrite and rid in self.embed_data:
                raise ValueError(f"duplicate row id {rid} in datastore")
            self.embed_data[rid] = emb

    def __len__(self):
        return len(self.embed_data)

    def _pack(self):
        ids = np.fromiter(self.embed_data.keys(), np.int64,
                          len(self.embed_data))
        order = np.argsort(ids)
        ids = ids[order]
        mat = np.stack(list(self.embed_data.values()))[order] \
            if len(ids) else np.zeros((0, 0), np.float16)
        return ids, mat.astype(np.float16)

    def save_shard(self, rank: Optional[int] = None) -> str:
        """Write this process's embeddings into the temp shard dir
        (ref: realm_index.py:84-94 save_shard)."""
        rank = self.rank if rank is None else rank
        os.makedirs(self.temp_dir_name, exist_ok=True)
        path = os.path.join(self.temp_dir_name, f"{rank or 0}.npz")
        ids, mat = self._pack()
        np.savez_compressed(path, ids=ids, embeds=mat)
        return path

    def merge_shards_and_save(self, remove_temp: bool = True):
        """Combine all shard files into the final embedding_path
        (ref: realm_index.py:96-112 merge_shards_and_save)."""
        seen = 0
        for path in sorted(glob.glob(
                os.path.join(self.temp_dir_name, "*.npz"))):
            with np.load(path) as z:
                self.add_block_data(z["ids"], z["embeds"])
                seen += len(z["ids"])
        assert seen == len(self), \
            "duplicate row ids across datastore shards"
        self.save()
        if remove_temp:
            for path in glob.glob(os.path.join(self.temp_dir_name, "*.npz")):
                os.remove(path)
            os.rmdir(self.temp_dir_name)

    def save(self):
        assert self.embedding_path
        ids, mat = self._pack()
        np.savez_compressed(self.embedding_path, ids=ids, embeds=mat)

    def load_from_file(self):
        """(ref: realm_index.py:49-60)"""
        assert self.embedding_path
        with np.load(self.embedding_path) as z:
            self.embed_data = {int(i): e for i, e in
                               zip(z["ids"], z["embeds"])}


def build_mips_index(store: OpenRetrievalDataStore, embed_dim=None):
    """Datastore -> exact matmul MIPS index (the reference feeds
    OpenRetreivalDataStore into FaissMIPSIndex the same way,
    ref: realm_index.py:118-160)."""
    from megatron_tpu.models.biencoder import MIPSIndex
    ids, mat = store._pack()
    index = MIPSIndex(int(mat.shape[-1] if embed_dim is None else embed_dim))
    if len(ids):
        index.add_block_data(ids, mat.astype(np.float32))
    return index
