"""Pretraining samplers and the batch feeder.

TPU-native port of megatron/data/data_samplers.py (:48-95
MegatronPretrainingSampler, :119-186 random variant, :14-45
build_pretraining_data_loader). Semantics kept:

- sequential sampler resumes from `consumed_samples` (checkpoint resume
  fast-forwards the stream, ref: data_samplers.py:50-60);
- the random variant reshuffles per epoch with seed = base_seed + epoch
  (ref: data_samplers.py:119-166) and equally dp-shards the pool;
- drop_last batching.

Beyond the reference: every sampler/iterator here speaks the
`state_dict()` / `load_state_dict()` exact-resume protocol
(consumed_samples, epoch, shuffle seed, within-epoch cursor, prefetch
depth). The state rides in checkpoint metadata
(training/checkpointing.py) so an interrupted run — or a divergence
rollback (training/loop.py poison-batch quarantine) — replays the
IDENTICAL batch sequence instead of fast-forwarding by luck
(docs/resilience.md "Exact resume & poison-batch quarantine").

Difference by design: the reference yields per-dp-rank microbatches from a
per-rank torch DataLoader and broadcasts over TP (ref: training.py:855-939).
Single-controller JAX wants the GLOBAL batch on the host: `BatchIterator`
yields {"tokens": [n_micro, micro_bs*dp, seq+1]} ready for device_put against
the dp-sharded spec — the tp/pp broadcast machinery dissolves.
"""
from __future__ import annotations

from typing import Iterator, Optional

import numpy as np


class MegatronPretrainingSampler:
    """Sequential dp-sharded sampler (ref: data_samplers.py:48-95).
    Yields lists of global dataset indices, one per (micro_bs * dp) chunk.

    `consumed_samples` is the live within-epoch cursor: it advances as
    batches are yielded, so `state_dict()` taken at any batch boundary
    and restored via `load_state_dict()` resumes the identical stream
    (the exact-resume protocol, docs/resilience.md). `consumed_samples
    == total_samples` is a valid (empty) stream — a run checkpointed
    exactly at epoch end resumes by wrapping to the next epoch, not by
    crashing."""

    def __init__(self, total_samples: int, consumed_samples: int,
                 micro_batch_size: int, data_parallel_size: int,
                 drop_last: bool = True):
        if total_samples <= 0:
            raise ValueError(f"total_samples={total_samples} must be > 0")
        if not 0 <= consumed_samples <= total_samples:
            raise ValueError(
                f"consumed_samples={consumed_samples} outside "
                f"[0, {total_samples}] — the resume offset must be a "
                "within-epoch cursor (callers wrap epochs via "
                "BatchIterator)")
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self.micro_batch_times_dp = micro_batch_size * data_parallel_size
        self.drop_last = drop_last

    def __len__(self):
        return self.total_samples

    def __iter__(self):
        batch = []
        for idx in range(self.consumed_samples, self.total_samples):
            batch.append(idx)
            if len(batch) == self.micro_batch_times_dp:
                self.consumed_samples += self.micro_batch_times_dp
                yield batch
                batch = []
        if batch and not self.drop_last:
            self.consumed_samples += len(batch)
            yield batch

    def state_dict(self) -> dict:
        return {"consumed_samples": int(self.consumed_samples)}

    def load_state_dict(self, sd: dict) -> None:
        c = int(sd["consumed_samples"])
        if not 0 <= c <= self.total_samples:
            raise ValueError(
                f"sampler state consumed_samples={c} outside "
                f"[0, {self.total_samples}] — checkpoint from a "
                "different dataset?")
        self.consumed_samples = c


class MegatronPretrainingRandomSampler:
    """Per-epoch reshuffling sampler (ref: data_samplers.py:119-186).

    `consumed_samples` is GLOBAL (monotonic across epochs); the epoch
    and within-epoch cursor derive from it, so `state_dict()` /
    `load_state_dict()` resume the identical shuffled stream."""

    def __init__(self, total_samples: int, consumed_samples: int,
                 micro_batch_size: int, data_parallel_size: int,
                 seed: int = 1234):
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self.micro_batch_times_dp = micro_batch_size * data_parallel_size
        self.seed = seed
        self.last_batch_size = (self.total_samples
                                % self.micro_batch_times_dp)
        if self.total_samples - self.last_batch_size <= 0:
            raise ValueError(
                f"total_samples={total_samples} holds no full "
                f"micro_batch_size*dp={self.micro_batch_times_dp} batch")

    def __len__(self):
        return self.total_samples

    def __iter__(self):
        active_total = self.total_samples - self.last_batch_size
        self.epoch = self.consumed_samples // active_total
        current_epoch_samples = self.consumed_samples % active_total
        if current_epoch_samples % self.micro_batch_times_dp != 0:
            raise ValueError(
                f"consumed_samples={self.consumed_samples} is not "
                f"batch-aligned (micro_batch_size*dp="
                f"{self.micro_batch_times_dp})")

        g = np.random.RandomState(self.seed + self.epoch)
        idx_range = g.permutation(active_total)[current_epoch_samples:]

        batch = []
        for idx in idx_range:
            batch.append(int(idx))
            if len(batch) == self.micro_batch_times_dp:
                self.consumed_samples += self.micro_batch_times_dp
                yield batch
                batch = []

    def state_dict(self) -> dict:
        return {"consumed_samples": int(self.consumed_samples),
                "seed": int(self.seed)}

    def load_state_dict(self, sd: dict) -> None:
        if "seed" in sd and int(sd["seed"]) != self.seed:
            raise ValueError(
                f"sampler state was written with seed={sd['seed']}, "
                f"this run uses seed={self.seed} — the shuffled order "
                "differs; resume with the original --seed for a "
                "bit-exact replay")
        self.consumed_samples = int(sd["consumed_samples"])


class BatchIterator:
    """Assemble {"tokens", "loss_mask", "position_ids"} global batches of
    shape [n_micro, micro_bs*dp, ...] from a map-style dataset.

    The train loop's view of the data pipeline; replaces torch DataLoader +
    get_batch/broadcast_data (ref: finetune.py:65-90,
    core/tensor_parallel/data.py:65)."""

    def __init__(self, dataset, micro_batch_size: int, data_parallel: int,
                 num_microbatches: int, consumed_samples: int = 0,
                 dataloader_type: str = "single", seed: int = 1234,
                 drop_last: bool = True,
                 eod_token: Optional[int] = None,
                 reset_position_ids: bool = False,
                 reset_attention_mask: bool = False,
                 eod_mask_loss: bool = False,
                 host_rows: Optional[tuple] = None):
        self.dataset = dataset
        self.num_microbatches = num_microbatches
        self.eod_token = eod_token
        self.reset_position_ids = reset_position_ids
        self.reset_attention_mask = reset_attention_mask
        self.eod_mask_loss = eod_mask_loss
        # pod-scale: (lo, hi) global-batch rows THIS host feeds (from
        # multihost.process_batch_rows). Rows outside stay zero-filled —
        # make_array_from_callback never reads them on this host, so the
        # per-host tokenization cost is O(rows/hosts), replacing the
        # reference's "tp-rank-0 loads then broadcasts" trick
        # (ref: training.py:855-939)
        self.host_rows = host_rows
        self._zero_row = None  # cached unowned-row template
        if not drop_last and num_microbatches > 1:
            # an epoch-tail partial microbatch cannot stack with the
            # wrapped epoch's full-size ones — the combination has no
            # rectangular batch; accumulate with drop_last instead
            raise ValueError(
                "drop_last=False requires num_microbatches == 1 "
                f"(got {num_microbatches})")
        self._sampler_args = (micro_batch_size, data_parallel, seed,
                              drop_last)
        self._dataloader_type = dataloader_type
        self._position(consumed_samples)

    def _make_sampler(self, consumed_samples: int):
        mbs, dp, seed, drop_last = self._sampler_args
        if self._dataloader_type == "single":
            return MegatronPretrainingSampler(
                len(self.dataset), consumed_samples, mbs, dp, drop_last)
        if self._dataloader_type == "cyclic":
            return MegatronPretrainingRandomSampler(
                len(self.dataset), consumed_samples, mbs, dp, seed)
        raise ValueError(f"unknown dataloader_type {self._dataloader_type!r}")

    def _epoch_len(self) -> int:
        """Samples one sequential epoch actually yields: drop_last drops
        the non-batch-aligned tail, so the resume modulus must be the
        aligned prefix — len(dataset) would leak dropped tail samples
        into the resumed stream's arithmetic."""
        chunk = self._sampler_args[0] * self._sampler_args[1]
        total = len(self.dataset)
        drop_last = self._sampler_args[3]
        return max(total - total % chunk if drop_last else total, 1)

    def _position(self, consumed_samples: int) -> None:
        """Rebuild the sampler at a monotonic consumed-samples count,
        deriving (epoch, within-epoch cursor). A resumed run past one
        epoch no longer crashes the sequential sampler's range check —
        the cursor wraps exactly as the live stream did."""
        self.samples_yielded = int(consumed_samples)
        if self._dataloader_type == "cyclic":
            # the random sampler's epoch arithmetic is internal (global
            # consumed_samples)
            self._epoch = 0
            self.sampler = self._make_sampler(consumed_samples)
        else:
            el = self._epoch_len()
            self._epoch = consumed_samples // el
            self.sampler = self._make_sampler(consumed_samples % el)
        self._it = iter(self.sampler)

    def state_dict(self) -> dict:
        """Exact-resume state at the current batch boundary: restored
        via `load_state_dict`, the stream replays the identical batch
        sequence (docs/resilience.md "exact resume & quarantine")."""
        mbs, dp, seed, drop_last = self._sampler_args
        return {
            "version": 1,
            "dataloader_type": self._dataloader_type,
            "seed": int(seed),
            "drop_last": bool(drop_last),
            "micro_batch_times_dp": int(mbs * dp),
            "dataset_len": int(len(self.dataset)),
            "epoch": int(self._epoch),
            "samples_yielded": int(self.samples_yielded),
            "sampler": self.sampler.state_dict(),
        }

    def load_state_dict(self, sd: dict) -> None:
        """Restore an exact stream position. Mismatched stream identity
        (dataloader type / seed / batch geometry) raises ValueError —
        silently resuming a DIFFERENT order would corrupt the replay
        guarantees the checkpoint promises."""
        mbs, dp, seed, drop_last = self._sampler_args
        for key, ours in (("dataloader_type", self._dataloader_type),
                          ("seed", int(seed)),
                          ("drop_last", bool(drop_last)),
                          ("micro_batch_times_dp", int(mbs * dp))):
            if key in sd and sd[key] != ours:
                raise ValueError(
                    f"data-iterator state mismatch: checkpoint has "
                    f"{key}={sd[key]!r}, this run uses {ours!r} — "
                    "resume with the original data configuration for a "
                    "bit-exact replay (or skip data-state restore to "
                    "accept a different order)")
        if (sd.get("dataset_len") is not None
                and int(sd["dataset_len"]) != len(self.dataset)):
            from megatron_tpu.utils.logging import print_rank_0
            print_rank_0(
                f"warning: data-iterator state was written over "
                f"{sd['dataset_len']} samples, this dataset has "
                f"{len(self.dataset)} — epoch boundaries moved, the "
                "resumed order may not be bit-exact")
        self._epoch = int(sd.get("epoch", 0))
        self.samples_yielded = int(sd["samples_yielded"])
        self.sampler = self._make_sampler(0)
        self.sampler.load_state_dict(sd["sampler"])
        self._it = iter(self.sampler)

    def __iter__(self):
        return self

    def _next_indices(self):
        """One micro-batch of sample indices, wrapping epochs."""
        try:
            idxs = next(self._it)
        except StopIteration:
            if self._dataloader_type == "cyclic":
                # the random sampler's consumed_samples advanced during
                # iteration; re-iterating it starts the NEXT epoch with a
                # fresh seed+epoch permutation (ref: data_samplers.py:
                # 119-166)
                self._it = iter(self.sampler)
            else:
                # sequential wrap: restart from sample 0, NOT from the
                # resume offset — otherwise samples [0, consumed) would
                # be excluded from every later epoch
                self._epoch += 1
                self.sampler = self._make_sampler(0)
                self._it = iter(self.sampler)
            idxs = next(self._it)
        self.samples_yielded += len(idxs)
        return idxs

    def __next__(self) -> dict:
        micro = []
        full_rows = self._sampler_args[0] * self._sampler_args[1]
        all_full = True  # every microbatch this call was full-size
        for _ in range(self.num_microbatches):
            idxs = self._next_indices()
            rows = self.host_rows
            if len(idxs) != full_rows:
                # partial tail batch (drop_last=False). The tail must still
                # divide dp or make_global_batch's P(None,'dp') lift fails
                # downstream with an inscrutable sharding error; fail here
                # with an actionable message instead (single- AND multi-host).
                dp = self._sampler_args[1]
                if len(idxs) % dp != 0:
                    raise ValueError(
                        f"drop_last=False tail batch of {len(idxs)} rows is "
                        f"not divisible by dp={dp}; either use drop_last="
                        "True or pad the dataset to a multiple of "
                        "micro_batch_size*dp")
                if rows is not None:
                    # multi-host: the dp sharding of the SMALLER array maps
                    # hosts to different rows than the precomputed range —
                    # materialize everything rather than risk feeding zero
                    # rows to a device
                    rows = None
                    all_full = False
            if rows is not None:
                lo, hi = rows
                if self._zero_row is None:
                    self._zero_row = np.zeros_like(
                        np.asarray(self.dataset[idxs[0]]["text"]))
                micro.append(np.stack(
                    [np.asarray(self.dataset[i]["text"])
                     if lo <= r < hi else self._zero_row
                     for r, i in enumerate(idxs)]))
            else:
                micro.append(np.stack(
                    [np.asarray(self.dataset[i]["text"]) for i in idxs]))
        tokens = np.stack(micro).astype(np.int32)  # [n_micro, b, seq+1]
        batch = {"tokens": tokens}
        n_micro, b, sp1 = tokens.shape
        # owned row range for mask work: zero-filled rows are never read
        # by this host's devices, and running the EOD scan on them is
        # waste (pathological when eod_token==0 — every position matches)
        lo, hi = self.host_rows if (self.host_rows is not None
                                    and all_full) else (0, b)
        if ((self.reset_position_ids or self.reset_attention_mask or
             self.eod_mask_loss) and self.eod_token is not None):
            # helper runs on the INPUT tokens (tokens[:-1]); its loss_mask
            # zeroes positions whose input is EOD — i.e. it suppresses
            # predicting the next document's first token FROM the EOD,
            # matching ref: megatron/utils.py:137-194
            flat = tokens[:, lo:hi, :-1].reshape(n_micro * (hi - lo),
                                                 sp1 - 1)
            loss_mask, pos, seg = get_ltor_masks_and_position_ids(
                flat, self.eod_token,
                reset_position_ids=self.reset_position_ids,
                reset_attention_mask=self.reset_attention_mask,
                eod_mask_loss=self.eod_mask_loss)

            def expand(x, fill):
                if (lo, hi) == (0, b):  # single-host: zero-copy reshape
                    return x.reshape(n_micro, b, sp1 - 1)
                out = np.full((n_micro, b, sp1 - 1), fill, x.dtype)
                out[:, lo:hi] = x.reshape(n_micro, hi - lo, sp1 - 1)
                return out

            batch["loss_mask"] = expand(loss_mask, 0)
            if self.reset_position_ids:
                batch["position_ids"] = expand(pos, 0)
            if self.reset_attention_mask:
                batch["segment_ids"] = expand(seg, 0)
        else:
            batch["loss_mask"] = np.ones(tokens[..., 1:].shape, np.float32)
        return batch


class DictBatchIterator:
    """Assemble [n_micro, micro_bs*dp, ...] batches from ANY map-style
    dataset yielding dict samples (BERT pairs, T5 spans, ICT query/context)
    — the generic counterpart of BatchIterator for non-GPT losses
    (ref: megatron/data/data_samplers.py build_pretraining_data_loader used
    by pretrain_bert/t5/ict)."""

    def __init__(self, dataset, micro_batch_size: int, data_parallel: int,
                 num_microbatches: int, consumed_samples: int = 0,
                 dataloader_type: str = "single", seed: int = 1234,
                 drop_last: bool = True):
        self.dataset = dataset
        self.num_microbatches = num_microbatches
        if not drop_last and num_microbatches > 1:
            # same rectangularity constraint as BatchIterator: a partial
            # tail microbatch cannot stack with full wrapped-epoch ones
            raise ValueError(
                "drop_last=False requires num_microbatches == 1 "
                f"(got {num_microbatches})")
        self._sampler_args = (micro_batch_size, data_parallel, seed,
                              drop_last)
        self._dataloader_type = dataloader_type
        # shared with BatchIterator: sequential resume derives
        # (epoch, within-epoch cursor) from the monotonic count — one
        # drop_last epoch emits only the batch-aligned prefix, so the
        # modulus is that epoch length; the random sampler takes the
        # GLOBAL count (its epoch arithmetic is internal)
        self._position(consumed_samples)

    _make_sampler = BatchIterator._make_sampler
    _epoch_len = BatchIterator._epoch_len
    _position = BatchIterator._position
    _next_indices = BatchIterator._next_indices
    state_dict = BatchIterator.state_dict
    load_state_dict = BatchIterator.load_state_dict

    def __iter__(self):
        return self

    def __next__(self) -> dict:
        micro = []
        for _ in range(self.num_microbatches):
            idxs = self._next_indices()
            items = [self.dataset[i] for i in idxs]
            micro.append({k: np.stack([it[k] for it in items])
                          for k in items[0]})
        return {k: np.stack([m[k] for m in micro]) for k in micro[0]}


def restore_data_state(it, data_state) -> bool:
    """Position an iterator at a checkpoint's exact data state
    (`load_state_dict`). A mismatched state — different seed/geometry
    because the user changed the data config on purpose — degrades,
    loudly, to the consumed-samples fast-forward the iterator was
    already built with. Returns True only on an exact restore."""
    from megatron_tpu.utils.logging import print_rank_0
    if it is None or not data_state:
        return False
    try:
        it.load_state_dict(data_state)
        return True
    except (ValueError, KeyError) as e:
        print_rank_0(f"warning: checkpoint data state not restored "
                     f"({e}); falling back to consumed-samples "
                     "fast-forward — the resumed batch order may "
                     "differ from the interrupted run")
        return False


def get_ltor_masks_and_position_ids(
    tokens: np.ndarray, eod_token: int,
    reset_position_ids: bool = False,
    reset_attention_mask: bool = False,
    eod_mask_loss: bool = False,
):
    """Loss mask / position ids with optional EOD resets
    (ref: megatron/utils.py:137-194 — the attention mask itself is built
    inside the attention op on TPU, so only its EOD-reset boundaries are
    returned here as segment ids for a block-diagonal mask)."""
    b, s = tokens.shape
    loss_mask = np.ones((b, s), np.float32)
    if eod_mask_loss:
        loss_mask[tokens == eod_token] = 0.0
    position_ids = np.tile(np.arange(s, dtype=np.int32), (b, 1))
    segment_ids = np.zeros((b, s), np.int32)
    if reset_position_ids or reset_attention_mask:
        for bi in range(b):
            eods = np.where(tokens[bi] == eod_token)[0]
            prev = 0
            for si, e in enumerate(eods):
                if reset_position_ids:
                    position_ids[bi, e + 1:] -= (e + 1 - prev)
                if reset_attention_mask:
                    segment_ids[bi, e + 1:] = si + 1
                prev = e + 1
    return loss_mask, position_ids, segment_ids


class PrefetchIterator:
    """Background-thread batch prefetch: host-side sample assembly
    (tokenization, masks, index walks) overlaps device compute instead of
    sitting on the training step's critical path — the reference gets the
    same overlap from torch DataLoader worker processes
    (ref: data_samplers.py num_workers). Order-preserving; exceptions from
    the source iterator re-raise at the consuming call site; exhaustion
    keeps raising (the sentinel is re-armed). Call `close()` when done —
    the train loop does in its finally block — or the producer thread
    stays parked holding `depth` buffered batches.

    Batches stay HOST arrays here: running jax.device_put from the
    producer thread races the main thread's dispatch and aborts inside
    XLA on CPU jax 0.4.x, so the train loop does its device-side input
    double-buffering on the MAIN thread instead (loop.py
    "prefetch_ahead" — batch N+1 is lifted right after step N's async
    dispatch, overlapping step N's device time).

    NOT safe under batch-size rampup: buffered batches lag a
    num_microbatches change by up to `depth` steps, skewing the
    consumed-samples accounting, so loop.py only wraps when rampup is
    off (num_microbatches is then constant and the forwarding setter is
    a benign same-value write).

    Exact-resume state: the producer runs AHEAD of the consumer by up
    to `depth` batches, so the source iterator's live `state_dict()`
    over-counts what training has actually seen. The producer therefore
    snapshots the source state after pulling each batch and ships the
    pair through the queue; `state_dict()` returns the snapshot of the
    last batch DELIVERED to the consumer — checkpointing it resumes
    exactly at the next undelivered batch, never `depth` batches late.
    The producer thread starts lazily on the first `__next__`, so
    `load_state_dict()` before consumption is race-free."""

    _STOP = object()

    def __init__(self, it, depth: int = 2):
        import queue
        import threading
        self._queue_mod = queue
        self._threading_mod = threading
        self._it = it
        self.depth = max(depth, 1)
        self._q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._err = None
        self._closed = threading.Event()
        self._thread = None  # started on first __next__
        self._last_state = None  # source state at the last delivered batch

    @property
    def num_microbatches(self):
        return self._it.num_microbatches

    @num_microbatches.setter
    def num_microbatches(self, v):
        self._it.num_microbatches = v

    def state_dict(self):
        """Source iterator state at the CONSUMER's position (None when
        the source has no state protocol), tagged with the prefetch
        depth."""
        sd = self._last_state
        if sd is None:
            get_state = getattr(self._it, "state_dict", None)
            if get_state is None:
                return None
            sd = get_state()
        return {**sd, "prefetch_depth": int(self.depth)}

    def load_state_dict(self, sd) -> None:
        """Delegate to the source. Only legal before the producer has
        started (i.e. before the first `__next__`) — once batches are
        buffered, repositioning the source would splice two streams."""
        if self._thread is not None:
            raise RuntimeError(
                "load_state_dict on a running PrefetchIterator — "
                "restore the source iterator before wrapping it "
                "(or before consuming the first batch)")
        self._it.load_state_dict(sd)

    def _ensure_started(self):
        if self._thread is None and not self._closed.is_set():
            self._thread = self._threading_mod.Thread(
                target=self._run, daemon=True)
            self._thread.start()

    def _run(self):
        try:
            get_state = getattr(self._it, "state_dict", None)
            for batch in self._it:
                # snapshot AFTER the pull: the state a consumer resuming
                # past this batch needs (single-threaded producer — no
                # later pull can race the snapshot)
                state = get_state() if get_state is not None else None
                while not self._closed.is_set():
                    try:
                        self._q.put((batch, state), timeout=0.2)
                        break
                    except self._queue_mod.Full:
                        continue
                if self._closed.is_set():
                    return
        except BaseException as e:  # re-raised on the consumer side
            self._err = e
        finally:
            # the sentinel MUST land (a lost sentinel deadlocks the
            # consumer); keep trying unless close() is draining anyway
            while not self._closed.is_set():
                try:
                    self._q.put(self._STOP, timeout=0.2)
                    break
                except self._queue_mod.Full:
                    continue

    def close(self):
        """Stop the producer and release buffered batches."""
        self._closed.set()
        while True:  # drain so a blocked put wakes and sees the flag
            try:
                self._q.get_nowait()
            except self._queue_mod.Empty:
                break
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def __iter__(self):
        return self

    def __next__(self):
        self._ensure_started()
        item = self._q.get()
        if item is self._STOP:
            self._q.put(self._STOP)  # re-arm: every later call raises too
            if self._err is not None:
                raise self._err
            raise StopIteration
        batch, state = item
        if state is not None:
            self._last_state = state
        return batch
