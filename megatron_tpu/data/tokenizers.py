"""Tokenizer factory with Megatron vocab-padding semantics.

TPU-native port of megatron/tokenizer/tokenizer.py (:12-62 factory +
padded-vocab derivation, :254 GPT-2 BPE, :288 Falcon/HF, :326-499
SentencePiece with special-token injection). The abstract contract —
`tokenize/detokenize/vocab_size/eod` plus optional cls/sep/pad/bos/eos ids —
is preserved; implementations are backed by HF `transformers` (baked into
this image) or a self-contained GPT-2 byte-pair encoder, rather than the
reference's vendored gpt2 code + sentencepiece package.

Vocab padding: `padded_vocab_size(vocab, multiple)` rounds up so the
embedding shards cleanly (ref: tokenizer.py:42-62 pads to
make-vocab-size-divisible-by * tp; we pad tp-independently — see
ModelConfig.padded_vocab_size — so checkpoints are layout-free).
"""
from __future__ import annotations

import json
import os
from typing import Optional, Sequence


def padded_vocab_size(orig_vocab_size: int, multiple: int) -> int:
    after = orig_vocab_size
    while after % multiple != 0:
        after += 1
    return after


class AbstractTokenizer:
    name = "abstract"

    @property
    def vocab_size(self) -> int:
        raise NotImplementedError

    def tokenize(self, text: str) -> list[int]:
        raise NotImplementedError

    def detokenize(self, ids: Sequence[int]) -> str:
        raise NotImplementedError

    @property
    def eod(self) -> int:
        raise NotImplementedError

    @property
    def eos(self) -> Optional[int]:
        return None

    @property
    def bos(self) -> Optional[int]:
        return None

    @property
    def pad(self) -> Optional[int]:
        return None


class HFTokenizer(AbstractTokenizer):
    """Any HuggingFace tokenizer — covers the reference's FalconTokenizer
    (ref: tokenizer.py:288-325, AutoTokenizer('tiiuae/falcon-40b')) and
    arbitrary `--tokenizer_type HuggingFaceTokenizer` setups."""

    name = "HFTokenizer"

    def __init__(self, path: str, **kwargs):
        from transformers import AutoTokenizer
        self._t = AutoTokenizer.from_pretrained(path, **kwargs)

    @property
    def vocab_size(self) -> int:
        return len(self._t)

    def tokenize(self, text: str) -> list[int]:
        return self._t.encode(text, add_special_tokens=False)

    def detokenize(self, ids) -> str:
        return self._t.decode(ids)

    @property
    def eod(self) -> int:
        t = self._t
        return t.eos_token_id if t.eos_token_id is not None else t.pad_token_id

    @property
    def eos(self):
        return self._t.eos_token_id

    @property
    def bos(self):
        return self._t.bos_token_id

    @property
    def pad(self):
        return self._t.pad_token_id


class SentencePieceTokenizer(AbstractTokenizer):
    """SentencePiece model with Megatron special-token injection
    (ref: tokenizer.py:326-499 _SentencePieceTokenizer: registers
    <CLS>/<SEP>/<EOD>/<MASK>/<PAD> plus `vocab_extra_ids_list` entries on top
    of the base model, tracking an _extra_id map). Backed by HF
    LlamaTokenizer(Fast) when the `sentencepiece` package is absent."""

    name = "SentencePieceTokenizer"
    SPECIAL = ("<CLS>", "<SEP>", "<EOD>", "<MASK>", "<PAD>")

    def __init__(self, model_file: str, vocab_extra_ids: int = 0,
                 vocab_extra_ids_list: Optional[str] = None,
                 new_tokens: bool = True):
        self._sp = None
        try:
            import sentencepiece as spm
            self._sp = spm.SentencePieceProcessor(model_file=model_file)
            base_vocab = self._sp.get_piece_size()
            self._bos_id = self._sp.bos_id()
            self._eos_id = self._sp.eos_id()
        except ImportError:
            # no sentencepiece package in this image: load the surrounding HF
            # tokenizer directory (tokenizer.model usually ships with one)
            from transformers import AutoTokenizer
            self._hf = AutoTokenizer.from_pretrained(
                os.path.dirname(model_file) or ".", use_fast=True)
            base_vocab = len(self._hf)
            self._bos_id = self._hf.bos_token_id
            self._eos_id = self._hf.eos_token_id
        self._special: dict[str, int] = {}
        self._vocab_size = base_vocab
        if new_tokens:
            for tok in self.SPECIAL:
                self._special[tok] = self._vocab_size
                self._vocab_size += 1
            extra = []
            if vocab_extra_ids_list:
                extra += [t.strip() for t in vocab_extra_ids_list.split(",")]
            extra += [f"<extra_id_{i}>" for i in range(vocab_extra_ids)]
            for tok in extra:
                if tok not in self._special:
                    self._special[tok] = self._vocab_size
                    self._vocab_size += 1

    @property
    def vocab_size(self) -> int:
        return self._vocab_size

    def tokenize(self, text: str) -> list[int]:
        if self._sp is not None:
            return self._sp.encode(text)
        return self._hf.encode(text, add_special_tokens=False)

    def detokenize(self, ids) -> str:
        ids = [i for i in ids if i < self._vocab_size - len(self._special)]
        if self._sp is not None:
            return self._sp.decode(ids)
        return self._hf.decode(ids)

    @property
    def eod(self) -> int:
        if "<EOD>" in self._special:
            return self._special["<EOD>"]
        return self._eos_id

    @property
    def eos(self):
        return self._eos_id

    @property
    def bos(self):
        return self._bos_id

    @property
    def pad(self):
        return self._special.get("<PAD>")


class GPT2BPETokenizer(AbstractTokenizer):
    """Self-contained GPT-2 byte-level BPE from vocab.json + merges.txt
    (ref: tokenizer.py:254-287 _GPT2BPETokenizer over the vendored
    megatron/tokenizer/gpt2_tokenization.py). The byte-level BPE algorithm is
    public (GPT-2 paper / tiktoken); implemented here directly."""

    name = "GPT2BPETokenizer"

    def __init__(self, vocab_file: str, merge_file: str):
        with open(vocab_file, encoding="utf-8") as f:
            self.encoder = json.load(f)
        self.decoder = {v: k for k, v in self.encoder.items()}
        with open(merge_file, encoding="utf-8") as f:
            lines = f.read().split("\n")
        merges = [tuple(l.split()) for l in lines
                  if l and not l.startswith("#version") and len(l.split()) == 2]
        self.bpe_ranks = {m: i for i, m in enumerate(merges)}
        self._bpe_cache: dict[str, tuple[str, ...]] = {}
        self.byte_encoder = _bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        # GPT-2's exact pre-tokenizer: separate letter / number / punct
        # classes (underscore is punct, digits split from letters) — token
        # ids must interchange with reference-tokenized corpora.
        try:
            import regex
            self.pat = regex.compile(
                r"'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+"
                r"| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+")
        except ImportError:
            import re
            # \p-free approximation: [^\W\d_] = unicode letters
            self.pat = re.compile(
                r"'s|'t|'re|'ve|'m|'ll|'d| ?[^\W\d_]+| ?\d+"
                r"| ?(?:[^\s\w]|_)+|\s+(?!\S)|\s+", re.UNICODE)

    def _bpe(self, token: str) -> tuple[str, ...]:
        # per-instance cache (an lru_cache on the method would pin every
        # tokenizer instance in a process-global cache forever)
        cached = self._bpe_cache.get(token)
        if cached is not None:
            return cached
        word = tuple(token)
        while len(word) > 1:
            pairs = {(word[i], word[i + 1]) for i in range(len(word) - 1)}
            best = min(pairs, key=lambda p: self.bpe_ranks.get(p, 1 << 30))
            if best not in self.bpe_ranks:
                break
            a, b = best
            out = []
            i = 0
            while i < len(word):
                if i < len(word) - 1 and word[i] == a and word[i + 1] == b:
                    out.append(a + b)
                    i += 2
                else:
                    out.append(word[i])
                    i += 1
            word = tuple(out)
        if len(self._bpe_cache) < 65536:
            self._bpe_cache[token] = word
        return word

    @property
    def vocab_size(self) -> int:
        return len(self.encoder)

    def tokenize(self, text: str) -> list[int]:
        ids = []
        for tok in self.pat.findall(text):
            mapped = "".join(self.byte_encoder[b]
                             for b in tok.encode("utf-8"))
            ids.extend(self.encoder[p] for p in self._bpe(mapped))
        return ids

    def detokenize(self, ids) -> str:
        text = "".join(self.decoder[i] for i in ids)
        return bytearray(self.byte_decoder[c] for c in text).decode(
            "utf-8", errors="replace")

    @property
    def eod(self) -> int:
        return self.encoder["<|endoftext|>"]


def _bytes_to_unicode():
    """GPT-2's reversible byte<->printable-unicode map (public algorithm)."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("\xa1"), ord("\xac") + 1))
          + list(range(ord("\xae"), ord("\xff") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, (chr(c) for c in cs)))


class BertWordPieceTokenizer(AbstractTokenizer):
    """Self-contained BERT WordPiece tokenizer
    (ref: megatron/tokenizer/tokenizer.py:123-253 _BertWordPieceTokenizer
    wrapping the original Google FullTokenizer). Pipeline: clean + optional
    lowercase -> whitespace/punctuation basic tokenization -> greedy
    longest-match-first wordpiece with '##' continuation prefix.

    vocab_file: one token per line (standard BERT vocab.txt)."""

    name = "BertWordPiece"

    def __init__(self, vocab_file: str, lower_case: bool = True,
                 vocab_extra_ids: int = 0):
        self.lower_case = lower_case
        with open(vocab_file, encoding="utf-8") as f:
            tokens = [line.rstrip("\n") for line in f if line.rstrip("\n")]
        self._vocab = {t: i for i, t in enumerate(tokens)}
        # T5-style extra ids appended on top (ref: tokenizer.py:246-253)
        for i in range(vocab_extra_ids):
            self._add_token(f"<extra_id_{i}>")
        self._inv = {i: t for t, i in self._vocab.items()}
        for tok in ("[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"):
            assert tok in self._vocab, f"vocab missing {tok}"

    def _add_token(self, tok: str):
        if tok not in self._vocab:
            self._vocab[tok] = len(self._vocab)

    # -- basic tokenization ------------------------------------------------
    @staticmethod
    def _is_punct(ch: str) -> bool:
        import unicodedata
        cp = ord(ch)
        if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
                or 123 <= cp <= 126):
            return True
        return unicodedata.category(ch).startswith("P")

    @staticmethod
    def _is_cjk(ch: str) -> bool:
        # the CJK Unified Ideograph blocks the original BERT BasicTokenizer
        # splits per-character (standard BERT vocabs carry individual chars)
        cp = ord(ch)
        return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
                or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F
                or 0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF
                or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)

    @staticmethod
    def _is_control(ch: str) -> bool:
        import unicodedata
        if ch in ("\t", "\n", "\r"):
            return False
        return unicodedata.category(ch).startswith("C")

    def _basic_tokenize(self, text: str) -> list[str]:
        import unicodedata
        # clean: drop control chars and the replacement char, normalize
        # whitespace (the original BasicTokenizer's _clean_text)
        text = "".join(" " if ch.isspace() else ch for ch in text
                       if ord(ch) != 0 and ord(ch) != 0xFFFD
                       and not self._is_control(ch))
        if self.lower_case:
            text = text.lower()
            text = "".join(c for c in unicodedata.normalize("NFD", text)
                           if unicodedata.category(c) != "Mn")
        out: list[str] = []
        word: list[str] = []

        def flush():
            if word:
                out.append("".join(word))
                word.clear()

        for ch in text:
            if ch.isspace():
                flush()
            elif self._is_punct(ch) or self._is_cjk(ch):
                flush()
                out.append(ch)
            else:
                word.append(ch)
        flush()
        return out

    def _wordpiece(self, word: str) -> list[str]:
        """Greedy longest-match-first (the published WordPiece algorithm)."""
        if len(word) > 200:
            return ["[UNK]"]
        pieces: list[str] = []
        start = 0
        while start < len(word):
            end = len(word)
            piece = None
            while start < end:
                sub = word[start:end]
                if start > 0:
                    sub = "##" + sub
                if sub in self._vocab:
                    piece = sub
                    break
                end -= 1
            if piece is None:
                return ["[UNK]"]
            pieces.append(piece)
            start = end
        return pieces

    # -- AbstractTokenizer surface ------------------------------------------
    @property
    def vocab_size(self) -> int:
        return len(self._vocab)

    @property
    def vocab(self):
        return self._vocab

    @property
    def inv_vocab(self):
        return self._inv

    def tokenize(self, text: str) -> list[int]:
        ids = []
        for word in self._basic_tokenize(text):
            for piece in self._wordpiece(word):
                ids.append(self._vocab[piece])
        return ids

    def detokenize(self, ids: Sequence[int]) -> str:
        toks = [self._inv[int(i)] for i in ids]
        out = []
        for t in toks:
            if t.startswith("##") and out:
                out[-1] = out[-1] + t[2:]
            else:
                out.append(t)
        return " ".join(out)

    @property
    def cls(self) -> int:
        return self._vocab["[CLS]"]

    @property
    def sep(self) -> int:
        return self._vocab["[SEP]"]

    @property
    def mask(self) -> int:
        return self._vocab["[MASK]"]

    @property
    def pad(self) -> int:
        return self._vocab["[PAD]"]

    @property
    def eod(self) -> int:
        return self._vocab["[SEP]"]  # (ref: tokenizer.py eod == sep)


def build_tokenizer(tokenizer_type: str, *, vocab_file=None, merge_file=None,
                    tokenizer_model=None, vocab_extra_ids=0,
                    vocab_extra_ids_list=None, new_tokens=True,
                    **kwargs) -> AbstractTokenizer:
    """Factory (ref: tokenizer.py:12-41 build_tokenizer)."""
    t = tokenizer_type
    if t in ("GPT2BPETokenizer",):
        assert vocab_file and merge_file
        return GPT2BPETokenizer(vocab_file, merge_file)
    if t in ("BertWordPieceTokenizer", "BertWordPieceLowerCase",
             "BertWordPieceCase"):
        assert vocab_file
        return BertWordPieceTokenizer(
            vocab_file, lower_case=t != "BertWordPieceCase",
            vocab_extra_ids=vocab_extra_ids)
    if t in ("SentencePieceTokenizer",):
        assert tokenizer_model
        return SentencePieceTokenizer(
            tokenizer_model, vocab_extra_ids=vocab_extra_ids,
            vocab_extra_ids_list=vocab_extra_ids_list, new_tokens=new_tokens)
    if t in ("FalconTokenizer", "HuggingFaceTokenizer", "HFTokenizer"):
        path = tokenizer_model or vocab_file or "tiiuae/falcon-40b"
        return HFTokenizer(path, **kwargs)
    raise ValueError(f"unknown tokenizer_type {tokenizer_type!r}")
