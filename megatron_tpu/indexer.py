"""Evidence-index builder: one pass of the context tower over a corpus.

TPU-native equivalent of the reference's IndexBuilder
(ref: megatron/indexer.py:17-123): embed every evidence block with the
biencoder's context model and persist {row_id: embedding} shards that merge
into an OpenRetrievalDataStore. The reference distributes the pass over dp
ranks with one process per GPU; here one process owns the whole pass and
`shard`/`num_shards` slice the corpus for multi-host runs (merge with
OpenRetrievalDataStore.merge_shards_and_save).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from megatron_tpu.config import ModelConfig
from megatron_tpu.data.orqa_dataset import OpenRetrievalEvidenceDataset
from megatron_tpu.data.realm_index import OpenRetrievalDataStore


class IndexBuilder:
    """Embed evidence blocks and fill a datastore
    (ref: megatron/indexer.py:17-123 IndexBuilder.build_and_save_index)."""

    def __init__(self, params, cfg: ModelConfig, dataset:
                 OpenRetrievalEvidenceDataset, *, embedding_path: str,
                 batch_size: int = 128, shard: int = 0, num_shards: int = 1,
                 log_interval: int = 10):
        from megatron_tpu.models.biencoder import _towers, embed_text
        self.params = params
        self.cfg = cfg
        self.dataset = dataset
        self.batch_size = batch_size
        self.shard, self.num_shards = shard, num_shards
        self.log_interval = log_interval
        self.store = OpenRetrievalDataStore(
            embedding_path, load_from_path=False, rank=shard)

        _, context_tower = _towers(params)

        def embed(tokens, types, pad_mask):
            return embed_text(context_tower, tokens, cfg,
                              padding_mask=pad_mask, tokentype_ids=types,
                              deterministic=True)

        self._embed = jax.jit(embed)

    def build_and_save_index(self, save: bool = True) -> \
            OpenRetrievalDataStore:
        """(ref: indexer.py:77-123): batched embedding pass; each batch's
        embeddings land in the datastore keyed by evidence row id."""
        total = 0
        for it, batch in enumerate(self.dataset.batches(
                self.batch_size, shard=self.shard,
                num_shards=self.num_shards)):
            embeds = self._embed(jnp.asarray(batch["context"]),
                                 jnp.asarray(batch["context_types"]),
                                 jnp.asarray(batch["context_pad_mask"]))
            n = batch["n_real"]
            self.store.add_block_data(batch["row_id"][:n],
                                      np.asarray(embeds)[:n])
            total += n
            if self.log_interval and (it + 1) % self.log_interval == 0:
                print(f"indexer: embedded {total} blocks", flush=True)
        if save:
            if self.num_shards > 1:
                self.store.save_shard()
            else:
                self.store.save()
        return self.store
