from megatron_tpu.inference.generation import (  # noqa: F401
    Generator, SamplingParams, beam_search, init_kv_caches)
from megatron_tpu.inference.sampling import sample  # noqa: F401
from megatron_tpu.inference.api import (  # noqa: F401
    beam_search_and_post_process, generate_and_post_process)
