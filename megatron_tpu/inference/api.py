"""High-level text generation API: tokenize -> generate -> detokenize.

TPU-native port of the reference's api/tokenization layer
(ref: megatron/text_generation/api.py:19-186 generate_and_post_process /
beam_search_and_post_process, tokenization.py:13-118). The rank-0
tokenize-and-broadcast machinery dissolves in a single-controller program;
what remains is prompt batching/padding and segment detokenization.
"""
from __future__ import annotations

from typing import Optional, Sequence

from megatron_tpu.inference.generation import (Generator, SamplingParams,
                                               beam_search)


def generate_and_post_process(
    generator: Generator,
    tokenizer,
    prompts: Sequence[str],
    tokens_to_generate: int = 64,
    temperature: float = 1.0,
    top_k: int = 0,
    top_p: float = 0.0,
    add_BOS: bool = False,
    return_output_log_probs: bool = False,
    seed: int = 0,
    prompt_ids: Optional[Sequence[Sequence[int]]] = None,
):
    """(ref: api.py:19-102). Returns (texts, tokens, logprobs|None).

    `prompt_ids`: pre-tokenized prompts (with BOS already applied) — the
    server's preflight validation tokenizes anyway, so passing them here
    avoids tokenizing every prompt twice."""
    if prompt_ids is None:
        prompt_ids = []
        for p in prompts:
            ids = tokenizer.tokenize(p)
            if add_BOS and tokenizer.bos is not None:
                ids = [tokenizer.bos] + ids
            prompt_ids.append(ids)
    sp = SamplingParams(temperature=temperature, top_k=top_k, top_p=top_p)
    tokens, lengths, logprobs = generator.generate(
        prompt_ids, tokens_to_generate, sampling=sp, seed=seed)
    texts = [tokenizer.detokenize(tokens[i, :lengths[i]].tolist())
             for i in range(len(prompts))]
    out_tokens = [tokens[i, :lengths[i]].tolist() for i in range(len(prompts))]
    if return_output_log_probs:
        lps = [logprobs[i, :lengths[i]].tolist() for i in range(len(prompts))]
        return texts, out_tokens, lps
    return texts, out_tokens, None


def beam_search_and_post_process(
    generator: Generator,
    tokenizer,
    prompt: str,
    tokens_to_generate: int = 64,
    beam_size: int = 4,
    length_penalty: float = 1.0,
    add_BOS: bool = False,
    prompt_ids: Optional[Sequence[int]] = None,
):
    """(ref: api.py:106-186). `prompt_ids`: pre-tokenized prompt (BOS
    applied) so preflight-validating callers don't tokenize twice."""
    if prompt_ids is not None:
        ids = list(prompt_ids)
    else:
        ids = tokenizer.tokenize(prompt)
        if add_BOS and tokenizer.bos is not None:
            ids = [tokenizer.bos] + ids
    tokens, lengths, scores = beam_search(
        generator, ids, beam_size, tokens_to_generate,
        length_penalty=length_penalty)
    texts = [tokenizer.detokenize(tokens[i, :lengths[i]].tolist())
             for i in range(len(tokens))]
    return texts, scores.tolist()
