"""Autoregressive generation engine with KV cache.

TPU-native equivalent of the reference's generation stack
(ref: megatron/text_generation/generation.py:89-285
`generate_tokens_probs_and_return_on_first_stage`, forward_step.py:17-204
InferenceParams/ForwardStep, beam_utils.py). Structural mapping:

- *InferenceParams KV dict* -> the functional `KVCache` pytree
  (models/attention.py) stacked over layers, threaded through `lax.scan`.
- *Incremental context growth* (the reference re-runs the model on
  tokens[prev:cur] per step) -> one PREFILL pass over the padded prompts,
  then a jitted per-token decode loop. Shapes are static (max_len fixed at
  trace time): no recompilation per request length bucket.
- *Early termination* (done-flag broadcast, generation.py:260-263) -> the
  loop still runs to max_len under jit (static bound) but finished rows keep
  emitting pad via the done mask — same outputs, no host sync per token.
- *Per-step last-stage sample + broadcast to first stage*
  (generation.py:179-263, communication.py:111) -> nothing: single program,
  GSPMD owns placement.
- *Scoring path* (generation.py:20-86) -> `score_tokens` returning per-token
  logprobs.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from megatron_tpu.config import ModelConfig
from megatron_tpu.inference.sampling import sample
from megatron_tpu.models import language_model as lm
from megatron_tpu.models.attention import KVCache


class SamplingParams(NamedTuple):
    """(ref: api.py:70-102 broadcast_float_list of sampling knobs)"""
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 0.0


KV_CACHE_AXES = ("layers", None, None, "kv_heads", None)

# Generator.generate rounds the prefill length DOWN to this multiple
# (jit-cache bucketing); the serving engine's seeded-determinism burn
# (serving/engine.py _initial_rng) counts the serial path's in-prompt
# RNG splits from the SAME constant — change it in one place only.
PREFILL_BUCKET = 16


def kv_region_cap(cfg: ModelConfig, max_len: int,
                  prefill_len=None) -> int:
    """Token capacity of one sequence's KV region — THE single source
    of the rolling-cap decision. `init_kv_caches` allocates this many
    positions per row, and `serving.kv_pool.slot_nbytes` sizes pools
    from the same number, so the two can never disagree.

    With cfg.sliding_window < max_len the region rolls (holds only the
    last W positions) when the prefill can land in the W-slot buffer:
    the flash impl computes prefill outputs from the raw k/v, and a
    dot-impl prefill that FITS the window overwrites nothing. A
    dot-impl prompt longer than the window keeps the full-length
    region (correct, just not memory-bounded)."""
    if cfg.sliding_window is not None and (
            cfg.attention_impl == "flash"
            or (prefill_len is not None
                and prefill_len <= cfg.sliding_window)):
        return min(max_len, cfg.sliding_window)
    return max_len


def init_kv_caches(cfg: ModelConfig, batch: int, max_len: int,
                   dtype=jnp.bfloat16, prefill_len=None,
                   per_slot_offsets: bool = False) -> KVCache:
    """Stacked-over-layers KV cache [L, b, max_len, nkv, hd].

    Under a mesh context the cache is sharded over 'tp' on the kv-head dim
    (and 'pp' on layers) — the TP-sharded serving layout the reference
    reaches with per-rank InferenceParams dicts
    (ref: text_generation_server.py + forward_step.py:17-42). Batch stays
    replicated like the reference's broadcast-to-all-ranks tokens.

    dtype=jnp.int8: quantized cache with per-(token, head) scales — decode
    streams the whole cache every step, so this halves the dominant HBM
    stream at long context AND the residency (a 7B 32k bf16 cache alone
    outgrows a v5e).

    With cfg.sliding_window < max_len the cache is a ROLLING buffer of
    exactly `sliding_window` slots (Mistral's rolling-buffer serving):
    banded attention never reads past the window, so memory is O(W)
    regardless of stream length — attention_apply writes position % W
    and masks by the slot->position map.

    per_slot_offsets=True allocates PER-ROW offsets [L, batch] instead of
    the shared per-layer scalar [L]: the continuous-batching engine's
    slot-grid layout (serving/kv_pool.py), where every batch row is an
    independent request at its own sequence position."""
    from megatron_tpu.parallel.sharding import constrain
    L = cfg.num_layers
    # rolling-cap decision single-sourced in kv_region_cap (the serving
    # pool's slot_nbytes sizes from the same helper)
    max_len = kv_region_cap(cfg, max_len, prefill_len)
    shape = (L, batch, max_len, cfg.num_kv_heads, cfg.kv_channels)
    # jnp.dtype normalization: "int8" (cfg-style spelling) must behave
    # exactly like jnp.int8 — see KVCache.create
    quant = jnp.dtype(dtype) == jnp.dtype(jnp.int8)
    sshape = shape[:4] + (1,)
    return KVCache(
        k=constrain(jnp.zeros(shape, dtype), KV_CACHE_AXES),
        v=constrain(jnp.zeros(shape, dtype), KV_CACHE_AXES),
        offset=jnp.zeros((L, batch) if per_slot_offsets else (L,),
                         jnp.int32),
        k_scale=(constrain(jnp.ones(sshape, jnp.float32), KV_CACHE_AXES)
                 if quant else None),
        v_scale=(constrain(jnp.ones(sshape, jnp.float32), KV_CACHE_AXES)
                 if quant else None),
    )


def prefill_chunk(params, tokens, caches, cfg: ModelConfig, *, rope,
                  last_idx, next_offset, adapters=None):
    """Forward one [1, s] prompt chunk through a batch-1 cache at the
    cache's CURRENT offset and return (caches, last_logits_row).

    Offset 0 is the classic whole-prompt prefill; offset > 0 is the
    continuation form the serving engine's prefix cache and chunked
    prefill rely on — a multi-token append whose causal mask starts at
    the cache offset (models/attention.py generalizes the decode
    masking to q-len > 1; the flash impl routes offset > 0 through the
    cached dot path via its lax.cond). `last_idx` (traced) picks the
    logits row of the chunk's last REAL token.

    `next_offset` (traced) is the REAL token count after this chunk:
    the attention write advances the offset by the full padded chunk
    length, so a bucket-padded chunk would leave the cache pointing
    past its pad garbage and the NEXT chunk would append at the wrong
    positions. Resetting to the real count makes the next chunk's
    write start right after the real tokens, overwriting the pads
    write-before-read — the same invariant bucketed prefill +
    insert_prefill already rely on for the final pads."""
    logits, caches = lm.model_forward(params, tokens, cfg,
                                      kv_caches=caches, rope=rope,
                                      logits_dtype=jnp.float32,
                                      adapters=adapters)
    last = jax.lax.dynamic_slice_in_dim(logits[0], last_idx, 1,
                                        axis=0)[0]
    caches = caches._replace(offset=jnp.full_like(
        caches.offset, jnp.asarray(next_offset, jnp.int32)))
    return caches, last


def verify_tokens(params, tokens, caches, cfg: ModelConfig, *, rope,
                  lengths, max_len: int, adapters=None):
    """Forward a [slots, w]-token window through the slot-grid cache at
    per-row offsets `lengths` and return (logits [slots, w, Vp], caches).

    The speculative-decode verify primitive (serving/engine.py
    `--speculative_k`): `prefill_chunk`'s continuation form generalized
    from batch-1/scalar-offset to the whole grid with vector offsets —
    row i's w tokens append at positions lengths[i]..lengths[i]+w-1,
    each query causally masked from its row's own offset
    (models/attention.py grid-batched multi-token append). Rows parked
    at the capacity clamp write nothing past max_len-1 (the scatter
    drops out-of-region indices) and their rope positions clamp to the
    table — garbage logits for garbage rows, discarded by the caller's
    accept mask, never an OOB read/write. The caller owns the offset
    bookkeeping: committed length after acceptance is a REWIND of the
    window (lengths + accepted + 1 <= lengths + w), and rejected
    positions' KV is overwritten write-before-read by the next
    dispatch, the same invariant bucket-padded prefill relies on.

    `caches` may be the contiguous slot-grid KVCache (the classic
    view) OR a block-native BlockKVCache (models/attention.py —
    serving's `--block_native_attn`): the offset broadcast and the
    per-row positions below are layout-agnostic, and attention_apply
    dispatches the window through the Pallas block-map kernel in the
    latter case — speculative verify keeps ONE trace either way."""
    w = tokens.shape[1]
    L = caches.offset.shape[0]
    caches = caches._replace(offset=jnp.broadcast_to(
        lengths[None, :], (L, lengths.shape[0])).astype(jnp.int32))
    positions = jnp.minimum(lengths[:, None] + jnp.arange(w)[None, :],
                            jnp.int32(max_len - 1))
    logits, caches = lm.model_forward(params, tokens, cfg,
                                      kv_caches=caches,
                                      position_ids=positions, rope=rope,
                                      logits_dtype=jnp.float32,
                                      adapters=adapters)
    return logits, caches


def _decode_fn(params, tokens, lengths, rng, *, cfg: ModelConfig,
               max_len: int, min_prompt: int, sp: SamplingParams,
               eos_id: int, pad_id: int, rope, kv_dtype=jnp.bfloat16):
    """tokens: [b, max_len] prompts right-padded; lengths: [b] prompt lens.
    `min_prompt` is static (host-computed): the prefill length.
    Returns (tokens [b, max_len], logprobs [b, max_len])."""
    b = tokens.shape[0]

    caches = init_kv_caches(cfg, b, max_len, dtype=kv_dtype,
                            prefill_len=min_prompt)

    # PREFILL on the common prefix [0, min_prompt) — mirrors the reference
    # starting generation at the min prompt length and re-using prompt tokens
    # for the longer rows (ref: generation.py:179-199)
    prefill = tokens[:, :min_prompt]
    logits, caches = lm.model_forward(params, prefill, cfg, kv_caches=caches,
                                      rope=rope, logits_dtype=jnp.float32)

    def step(carry, pos):
        tokens, caches, last_logits, rng, done = carry
        rng, r = jax.random.split(rng)
        sampled = sample(r, last_logits, top_k=sp.top_k, top_p=sp.top_p,
                         temperature=sp.temperature,
                         vocab_size=cfg.vocab_size)
        # rows still inside their prompt keep their prompt token
        # (ref: generation.py:210-214 "context tokens are kept")
        in_prompt = pos < lengths
        prompt_tok = jax.lax.dynamic_index_in_dim(tokens, pos, axis=1,
                                                  keepdims=False)
        cur = jnp.where(in_prompt, prompt_tok, sampled)
        cur = jnp.where(done, pad_id, cur)
        tokens = jax.lax.dynamic_update_index_in_dim(tokens, cur, pos, axis=1)
        logprob = jax.nn.log_softmax(last_logits, axis=-1)
        lp = jnp.take_along_axis(logprob, cur[:, None], axis=-1)[:, 0]
        done = done | ((cur == eos_id) & ~in_prompt)
        logits, caches = lm.model_forward(
            params, cur[:, None], cfg, kv_caches=caches, rope=rope,
            logits_dtype=jnp.float32)
        return (tokens, caches, logits[:, 0], rng, done), lp

    done0 = jnp.zeros((b,), bool)
    (tokens, _, _, _, done), lps = jax.lax.scan(
        step, (tokens, caches, logits[:, -1], rng, done0),
        min_prompt + jnp.arange(max_len - min_prompt))
    logprobs = jnp.zeros((b, max_len), jnp.float32)
    logprobs = jax.lax.dynamic_update_slice_in_dim(
        logprobs, lps.T, min_prompt, axis=1)
    return tokens, logprobs


class Generator:
    """Jit-cached generation engine. One compile per (batch, max_len) bucket
    (the reference instead pays a fresh CUDA graph per request shape).

    `mesh`: serve a sharded model in place — params consume their
    tp/pp-sharded layout via in_shardings (no re-layout on every call), the
    KV cache shards over 'tp' on kv-heads, logits shard over 'tp' on vocab.
    The reference's equivalent is the 8-GPU TP text_generation_server with
    broadcast tokens (ref: megatron/text_generation_server.py)."""

    def __init__(self, params, cfg: ModelConfig, eos_id: int,
                 pad_id: Optional[int] = None, mesh=None,
                 kv_cache_dtype=jnp.bfloat16, expert_axis: str = "tp"):
        self.params = params
        self.cfg = cfg
        self.eos_id = eos_id
        self.pad_id = pad_id if pad_id is not None else eos_id
        self.rope = lm.make_rope(cfg, max_len=cfg.max_position_embeddings)
        self.mesh = mesh
        # jnp.int8: quantized KV cache (see init_kv_caches) — halves the
        # decode-dominant cache stream and residency at ~0.4% k/v error
        self.kv_cache_dtype = kv_cache_dtype
        self._decode = {}
        self._rules = None
        self._param_sh = None
        if mesh is not None:
            from megatron_tpu.ops.quantized import quantize_axes
            from megatron_tpu.parallel import sharding as shd
            # expert_axis mirrors ParallelConfig.expert_axis: a model
            # trained with dp-sharded expert banks must serve with the
            # same 'experts' mapping or the bank gets resharded
            self._rules = shd.make_logical_rules(False,
                                                 expert_axis=expert_axis)
            # int8-quantized weights (ops/quantized.quantize_weights)
            # restructure the params tree — align the axes tree with it
            # so in_shardings still match leaf-for-leaf
            self._param_sh = shd.tree_logical_to_sharding(
                mesh, quantize_axes(lm.model_axes(cfg), params),
                self._rules)

        def _score_fn(params, tokens):
            logits, _ = lm.model_forward(params, tokens, self.cfg,
                                         rope=self.rope,
                                         logits_dtype=jnp.float32)
            lp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
            return jnp.take_along_axis(
                lp, tokens[:, 1:, None], axis=-1)[..., 0]

        # one cached jit; retraces only on new (batch, len) shapes
        self._score_fn = self._jit(_score_fn, n_array_args=1)

    def _jit(self, fn, n_array_args: int, donate_argnums=()):
        """jit with the mesh treatment: params consumed in their sharded
        layout, activation ctx active during trace. The `None` in_shardings
        entries mean 'inherit the argument's own sharding' (host numpy
        inputs land replicated, which is the broadcast-tokens serving
        layout; a pre-sharded array would be consumed as-is).

        `donate_argnums`: buffer donation for persistently-resident state
        (the serving engine's KV pool — without donation every decode
        step would copy the whole pool; ignored on backends without
        aliasing support, e.g. CPU)."""
        if self.mesh is None:
            return jax.jit(fn, donate_argnums=donate_argnums)
        from megatron_tpu.parallel import sharding as shd
        mesh, rules = self.mesh, self._rules

        def fn_ctx(*args, **kwargs):
            with shd.activation_shardings(mesh, rules):
                return fn(*args, **kwargs)

        return jax.jit(fn_ctx,
                       in_shardings=(self._param_sh,) + (None,) * n_array_args,
                       donate_argnums=donate_argnums)

    def _get_decode(self, max_len: int, min_prompt: int,
                    sp: SamplingParams):
        key = (max_len, min_prompt, sp)
        if key not in self._decode:
            self._decode[key] = self._jit(functools.partial(
                _decode_fn, cfg=self.cfg, max_len=max_len,
                min_prompt=min_prompt, sp=sp,
                eos_id=self.eos_id, pad_id=self.pad_id, rope=self.rope,
                kv_dtype=self.kv_cache_dtype),
                n_array_args=3)
        return self._decode[key]

    def generate(self, prompts: list[list[int]], max_new_tokens: int,
                 sampling: SamplingParams = SamplingParams(),
                 seed: int = 0):
        """prompts: list of token id lists. Returns (tokens, lengths,
        logprobs) as numpy, one row per prompt
        (ref: generation.py:89-285)."""
        b = len(prompts)
        lengths = np.array([len(p) for p in prompts], np.int32)
        max_len = int(lengths.max()) + max_new_tokens
        max_pos = self.cfg.max_position_embeddings
        if max_len > max_pos:
            raise ValueError(
                f"prompt ({int(lengths.max())}) + max_new_tokens "
                f"({max_new_tokens}) = {max_len} exceeds "
                f"max_position_embeddings={max_pos}; positions past the RoPE "
                "table would silently clamp")
        # bucket shapes so the jit cache actually hits across request sizes:
        # max_len rounds UP to 64, prefill length DOWN to PREFILL_BUCKET
        max_len = min(-(-max_len // 64) * 64, max_pos)
        min_prompt = max(
            (int(lengths.min()) // PREFILL_BUCKET) * PREFILL_BUCKET, 1)
        toks = np.full((b, max_len), self.pad_id, np.int32)
        for i, p in enumerate(prompts):
            toks[i, :len(p)] = p
        fn = self._get_decode(max_len, min_prompt, sampling)
        tokens, logprobs = fn(self.params, jnp.asarray(toks),
                              jnp.asarray(lengths),
                              jax.random.PRNGKey(seed))
        tokens = np.asarray(tokens)
        logprobs = np.asarray(logprobs)
        out_lens = []
        for i in range(b):
            # the decode ran to the BUCKETED max_len; the caller asked for at
            # most lengths[i] + max_new_tokens
            requested = int(lengths[i]) + max_new_tokens
            row = tokens[i, lengths[i]:requested]
            hits = np.where(row == self.eos_id)[0]
            end = int(lengths[i]) + (int(hits[0]) + 1 if len(hits)
                                     else requested - int(lengths[i]))
            out_lens.append(end)
        return tokens, np.asarray(out_lens, np.int32), logprobs

    def score(self, token_rows: list[list[int]]):
        """Per-token logprobs of given sequences (ref: generation.py:20-86
        score_and_return_on_first_stage)."""
        b = len(token_rows)
        lengths = np.array([len(t) for t in token_rows], np.int32)
        max_len = int(lengths.max())
        toks = np.full((b, max_len), self.pad_id, np.int32)
        for i, t in enumerate(token_rows):
            toks[i, :len(t)] = t
        return np.asarray(self._score_fn(self.params, jnp.asarray(toks)))


def beam_search(generator: Generator, prompt: list[int], beam_width: int,
                max_new_tokens: int, length_penalty: float = 1.0):
    """Beam search decode (ref: generation.py:288-415 + beam_utils.py:19-64).

    Jit-friendly formulation: all `beam_width` hypotheses run as one batch;
    each step expands to beam_width^2 candidates and keeps the top
    beam_width by cumulative logprob (length-penalized at finalization,
    matching the reference's scoring)."""
    cfg = generator.cfg
    eos = generator.eos_id
    params = generator.params
    rope = generator.rope
    prompt_len = len(prompt)
    max_len = prompt_len + max_new_tokens
    bw = beam_width

    toks = np.full((bw, max_len), generator.pad_id, np.int32)
    toks[:, :prompt_len] = prompt

    def prefill(params, tokens):
        caches = init_kv_caches(cfg, bw, max_len,
                                dtype=generator.kv_cache_dtype,
                                prefill_len=prompt_len)
        logits, caches = lm.model_forward(
            params, tokens[:, :prompt_len], cfg, kv_caches=caches, rope=rope,
            logits_dtype=jnp.float32)
        return logits[:, -1], caches

    def step(params, tokens, caches, scores, done, pos, last_logits):
        lp = jax.nn.log_softmax(last_logits, axis=-1)  # [bw, V]
        V = lp.shape[-1]
        iota = jnp.arange(V)
        lp = jnp.where(iota[None, :] < cfg.vocab_size, lp, -jnp.inf)
        # finished beams only extend with pad at no cost
        cand = jnp.where(done[:, None], -jnp.inf, lp) + scores[:, None]
        cand = cand.reshape(-1)
        # keep finished beams alive as single candidates
        keep_done = jnp.where(done, scores, -jnp.inf)
        all_scores = jnp.concatenate([cand, keep_done])
        top = jax.lax.top_k(all_scores, bw)[1]
        is_kept_done = top >= bw * V
        parent = jnp.where(is_kept_done, top - bw * V, top // V)
        token = jnp.where(is_kept_done, generator.pad_id, top % V)
        scores = all_scores[top]
        tokens = tokens[parent]
        caches = KVCache(
            k=caches.k[:, parent], v=caches.v[:, parent],
            offset=caches.offset,
            k_scale=(None if caches.k_scale is None
                     else caches.k_scale[:, parent]),
            v_scale=(None if caches.v_scale is None
                     else caches.v_scale[:, parent]))
        tokens = jax.lax.dynamic_update_index_in_dim(
            tokens, token.astype(jnp.int32), pos, axis=1)
        done = done[parent] | (token == eos)
        logits, caches = lm.model_forward(
            params, tokens[:, pos][:, None], cfg, kv_caches=caches,
            rope=rope, logits_dtype=jnp.float32)
        return tokens, caches, scores, done, logits[:, 0]

    # route through the generator's mesh-aware jit so TP-sharded serving
    # applies to beam decode too (same treatment as generate/score)
    prefill = generator._jit(prefill, n_array_args=1)
    step = generator._jit(step, n_array_args=6)

    last_logits, caches = prefill(params, jnp.asarray(toks))
    tokens = jnp.asarray(toks)
    scores = jnp.asarray([0.0] + [-1e9] * (bw - 1), jnp.float32)
    done = jnp.zeros((bw,), bool)
    for pos in range(prompt_len, max_len):
        tokens, caches, scores, done, last_logits = step(
            params, tokens, caches, scores, done, pos, last_logits)
        if bool(done.all()):
            break
    # length-penalized final ranking (ref: beam_utils.py:19-64)
    tokens = np.asarray(tokens)
    out_len = np.full((bw,), max_len)
    for i in range(bw):
        hits = np.where(tokens[i, prompt_len:] == eos)[0]
        if len(hits):
            out_len[i] = prompt_len + hits[0] + 1
    gen_len = np.maximum(out_len - prompt_len, 1)
    final = np.asarray(scores) / (gen_len ** length_penalty)
    order = np.argsort(-final)
    return tokens[order], out_len[order], final[order]
