"""Token sampling: temperature / top-k / top-p.

TPU-native port of the reference's sampler
(ref: megatron/text_generation/sampling.py:14-93 `modify_logits_for_top_k/p_
filtering` + `sample`): greedy when top_k==0 and top_p==0 and temperature==0;
otherwise temperature-scaled logits filtered by top-k then top-p. In-place
masking becomes functional `jnp.where`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def top_k_filter(logits, k: int):
    """Keep the k largest logits per row (ref: sampling.py:14-23)."""
    if k <= 0:
        return logits
    kth = jnp.sort(logits, axis=-1)[..., -k][..., None]
    return jnp.where(logits < kth, -jnp.inf, logits)


def top_p_filter(logits, p: float):
    """Nucleus filtering (ref: sampling.py:26-42): drop the tail whose
    cumulative probability exceeds 1-p (keeping at least the top token)."""
    if p <= 0.0 or p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # a sorted position is kept while the cumulative mass BEFORE it is < p
    keep_sorted = (cum - probs) < p
    min_kept = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                       axis=-1, keepdims=True)
    return jnp.where(logits < min_kept, -jnp.inf, logits)


def sample(rng, logits, *, top_k: int = 0, top_p: float = 0.0,
           temperature: float = 1.0, vocab_size: int | None = None):
    """One sampling step over [batch, vocab] logits
    (ref: sampling.py:45-93). Returns int32 [batch]."""
    logits = logits.astype(jnp.float32)
    if vocab_size is not None and vocab_size < logits.shape[-1]:
        iota = jnp.arange(logits.shape[-1])
        logits = jnp.where(iota < vocab_size, logits, -jnp.inf)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if temperature == 0.0 or (top_k == 1):
        return greedy
    logits = logits / max(temperature, 1e-6)
    logits = top_k_filter(logits, top_k)
    logits = top_p_filter(logits, top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def _top_k_filter_rows(logits, k):
    """top_k_filter with a PER-ROW traced k [b] (0 disables the row)."""
    V = logits.shape[-1]
    srt = jnp.sort(logits, axis=-1)  # ascending
    # sorted[V - k] == sorted[-k], the serial filter's threshold
    idx = jnp.clip(V - jnp.maximum(k, 1), 0, V - 1).astype(jnp.int32)
    kth = jnp.take_along_axis(srt, idx[:, None], axis=-1)
    filtered = jnp.where(logits < kth, -jnp.inf, logits)
    return jnp.where((k > 0)[:, None], filtered, logits)


def _top_p_filter_rows(logits, p):
    """top_p_filter with a PER-ROW traced p [b] (<=0 or >=1 disables)."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (cum - probs) < p[:, None]
    min_kept = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                       axis=-1, keepdims=True)
    filtered = jnp.where(logits < min_kept, -jnp.inf, logits)
    return jnp.where(((p > 0.0) & (p < 1.0))[:, None], filtered, logits)


def sample_batched(rngs, logits, *, temperature, top_k, top_p,
                   vocab_size: int | None = None, banned=None,
                   mask=None):
    """One sampling step with PER-ROW keys and sampling params — the
    continuous-batching engine's path (serving/engine.py), where one
    compiled decode step serves slots carrying different requests.

    rngs: [b, 2] uint32 (one PRNG key per row); logits: [b, vocab];
    temperature/top_p: float32 [b]; top_k: int32 [b]. Returns int32 [b].

    Row-for-row it reproduces `sample(rngs[i], logits[i:i+1], ...)`
    bit-exactly: the filters are the same row-wise math with traced
    instead of static knobs, and a vmapped `categorical` over a [V] row
    draws the same threefry bits as the serial [1, V] call (the counter
    stream depends only on the key and the element count).

    `banned` (int32 [b], < 0 disables a row): mask ONE token per row
    out of the PROCESSED distribution — i.e. AFTER temperature/top-k/
    top-p, so the result is exactly the renormalized residual
    norm(max(p - q, 0)) of point-mass rejection sampling against draft
    q = delta(banned) (speculative decoding, serving engine). Applied
    post-filter on purpose: masking before top-k would admit a
    replacement token the original distribution filtered out. Greedy
    rows ignore the ban — a greedy rejection already implies
    banned != argmax, so the residual of the argmax point mass IS the
    unchanged argmax. Rows with banned < 0 are bit-identical to the
    banned=None call (the categorical consumes the same key bits).

    `mask` (bool [b, vocab], True = allowed): the SET generalization
    of `banned` — grammar-constrained decoding's per-slot legal-token
    bitmask (serving/structured.py). Applied at the same post-filter
    seam and composing with `banned` (an accepted residual carry must
    also be grammar-legal). Unlike `banned`, greedy rows OBEY the
    mask: the constrained greedy answer is the argmax over legal
    tokens, not the unconstrained argmax. A row whose mask admits NO
    candidate returns the sentinel -1 (for greedy AND stochastic rows)
    instead of sampling from a renormalized-empty distribution — the
    engine fails that request typed (GrammarDeadEndError -> 422). An
    all-True mask row is bit-identical to mask=None (the masking
    `where` is the identity and the categorical consumes the same key
    bits), so free rows ride the same trace unchanged."""
    logits = logits.astype(jnp.float32)
    if vocab_size is not None and vocab_size < logits.shape[-1]:
        iota = jnp.arange(logits.shape[-1])
        logits = jnp.where(iota < vocab_size, logits, -jnp.inf)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    greedy_rows = (temperature == 0.0) | (top_k == 1)
    x = logits / jnp.maximum(temperature, 1e-6)[:, None]
    x = _top_k_filter_rows(x, top_k)
    x = _top_p_filter_rows(x, top_p)
    if banned is not None:
        iota = jnp.arange(x.shape[-1])
        x = jnp.where((banned >= 0)[:, None]
                      & (iota[None, :] == banned[:, None]), -jnp.inf, x)
    if mask is not None:
        greedy = jnp.argmax(jnp.where(mask, logits, -jnp.inf),
                            axis=-1).astype(jnp.int32)
        x = jnp.where(mask, x, -jnp.inf)
    sampled = jax.vmap(
        lambda r, row: jax.random.categorical(r, row, axis=-1))(rngs, x)
    out = jnp.where(greedy_rows, greedy, sampled).astype(jnp.int32)
    if mask is not None:
        dead = ~jnp.any(mask, axis=-1)
        out = jnp.where(dead, jnp.int32(-1), out)
    return out


def verify_draft_probs(logits, drafts, *, temperature, top_k, top_p,
                       vocab_size: int | None = None, mask=None):
    """Per-(row, position) acceptance inputs for speculative decoding.

    logits: [b, w, vocab] — the verify forward's outputs, position j
    holding the model's distribution for the token draft[:, j] claims;
    drafts: [b, w] int32; temperature/top_p: float32 [b]; top_k:
    int32 [b] (per-ROW knobs, shared across the row's positions).

    Returns (probs [b, w] float32, greedy_targets [b, w] int32):
    `probs[i, j]` is the PROCESSED probability of drafts[i, j] — the
    same temperature/top-k/top-p pipeline `sample_batched` draws from,
    which is what point-mass rejection sampling must accept against
    (accept with probability min(1, p(d)/q(d)) = p(d) for q = delta(d));
    `greedy_targets` is the plain argmax (greedy rows accept by exact
    match). The [b, w] grid folds to [b*w] rows with each row's knobs
    repeated, so the filters are bit-identical to a serial
    one-position-at-a-time verify of the same logits.

    `mask` (bool [b, w, vocab], True = allowed): grammar-constrained
    rows' per-POSITION legal-token masks (the host steps the FSM along
    the draft chain — serving/structured.py). Masked positions accept
    against the masked renormalized distribution: an illegal draft's
    processed probability is exactly 0 (never accepted, since the
    acceptance uniform lives in [0, 1)), and greedy targets become
    the masked argmax (-1 on a dead position, which never equals a
    real draft). All-True positions are bit-identical to mask=None —
    free rows share the trace unchanged."""
    b, w, V = logits.shape
    x = logits.astype(jnp.float32).reshape(b * w, V)
    if vocab_size is not None and vocab_size < V:
        iota = jnp.arange(V)
        x = jnp.where(iota < vocab_size, x, -jnp.inf)
    if mask is not None:
        m = mask.reshape(b * w, V)
        greedy_targets = jnp.argmax(jnp.where(m, x, -jnp.inf),
                                    axis=-1).astype(jnp.int32)
        greedy_targets = jnp.where(jnp.any(m, axis=-1), greedy_targets,
                                   jnp.int32(-1))
    else:
        greedy_targets = jnp.argmax(x, axis=-1).astype(jnp.int32)
    temp = jnp.repeat(temperature, w)
    x = x / jnp.maximum(temp, 1e-6)[:, None]
    x = _top_k_filter_rows(x, jnp.repeat(top_k, w))
    x = _top_p_filter_rows(x, jnp.repeat(top_p, w))
    if mask is not None:
        x = jnp.where(mask.reshape(b * w, V), x, -jnp.inf)
    p = jax.nn.softmax(x, axis=-1)
    probs = jnp.take_along_axis(
        p, drafts.reshape(b * w, 1).astype(jnp.int32), axis=-1)[:, 0]
    return probs.reshape(b, w), greedy_targets.reshape(b, w)
