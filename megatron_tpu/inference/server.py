"""REST text-generation server.

TPU-native port of the reference's Flask server
(ref: megatron/text_generation_server.py:17-241 + tools/
run_text_generation_server.py:60-84): same `/api` PUT contract —
{"prompts": [...], "tokens_to_generate": N, "temperature": ..,
 "top_k": .., "top_p": .., "logprobs": bool, "beam_width": int|absent} ->
{"text": [...], "segments"/"logprobs": ...}.

Beyond the reference, `/api` routes through the continuous-batching
engine (megatron_tpu/serving): each prompt becomes an independent
request that joins the persistent decode batch at token granularity, so
a long request no longer blocks every other caller. The reference's
serial one-lock path is kept behind `ServingConfig(serial_fallback=
True)` (and always serves beam search, which stays whole-batch). Proper
HTTP statuses on BOTH transport backends: 400 for invalid payloads
(shared validator), 429 when the bounded admission queue overflows, the
engine sheds on overload, or the engine is draining for shutdown, 503
for queued work dropped by a drain and for an unhealthy engine (the
supervisor's crash-loop circuit breaker tripped), 504 when a request
outlives its effective deadline, 500 for internal errors. 429/503
responses carry a `Retry-After` header and the current queue depth in
the JSON body, so clients and load balancers can back off instead of
hammering a saturated replica. `GET /metrics` exposes the
ServingMetrics snapshot; `GET /healthz` is the separate liveness/
readiness probe (engine loop alive, circuit-breaker state, slot
occupancy, queue depth) — host-state reads only, so a wedged decode
cannot wedge the probe. Payloads may carry `priority` (higher wins
admission ordering and, with ServingConfig.preemption, may preempt
running slots) and `deadline_s` (per-request SLO overriding
request_deadline_s). SIGTERM drains gracefully: stop admitting, finish
in-flight slots, then exit.

Front door (docs/serving.md "Front door"): `ServingConfig(
num_replicas=N)` puts N full engine replicas behind the in-process
prefix-affinity router (serving/router.py) — health-driven failover,
token-exact retry on survivors, degraded-vs-down /healthz. Payloads
with `stream: true` (single prompt) switch the response to SSE
(`text/event-stream`) on BOTH transports: one `token` event per
committed token with `id:` = its monotonic index, a terminal `done` or
typed `error` event, and reconnect-resume via `stream_id` +
`Last-Event-ID` (the engine holds committed tokens per request, so
resume replays the tail — no duplicated or missing tokens).

The reference needs a rank-0 Flask thread that broadcasts a GENERATE/BEAM
signal to all other ranks sitting in a receive loop
(ref: text_generation_server.py:22-31); single-controller JAX needs none of
that — one process serves and drives all chips. Flask is used when
available, else the stdlib http.server (this image has no flask).
"""
from __future__ import annotations

import itertools
import json
import math
import secrets
import threading
from typing import Optional, Tuple

from megatron_tpu.inference.api import (beam_search_and_post_process,
                                        generate_and_post_process)
from megatron_tpu.inference.generation import Generator
from megatron_tpu.utils.logging import print_rank_0

MAX_PROMPTS = 128


class _StreamEntry:
    """Registry row for one SSE stream: the live request handle (its
    `generated` list IS the resume buffer) plus the TTL bookkeeping."""

    __slots__ = ("sid", "req", "created", "done_t")

    def __init__(self, sid: str, req):
        import time as _time
        self.sid = sid
        self.req = req
        self.created = _time.monotonic()
        self.done_t = None  # set when first observed done; TTL runs


def _is_stream_body(body) -> bool:
    import types as _types
    return isinstance(body, _types.GeneratorType)


def validate_generate_payload(payload) -> Optional[str]:
    """Shared request validator for both transport backends: returns an
    error message (→ HTTP 400) or None. Mirrors the reference's checks
    (ref: text_generation_server.py:31-228), which it answered with
    200 + {"message": ...} under flask."""
    if not isinstance(payload, dict):
        return "request body must be a JSON object"
    has_text = "prompts" in payload
    has_tokens = "prompt_tokens" in payload
    if has_text and has_tokens:
        return "prompts and prompt_tokens are mutually exclusive"
    if not has_text and not has_tokens:
        return "prompts argument required"
    if has_tokens:
        # replica-mode wire format (serving/remote.py): the front tier
        # already tokenized, so rows of token ids skip this replica's
        # tokenizer — the stream stays token-exact across the process
        # hop and across a failover resubmission
        rows = payload["prompt_tokens"]
        if not isinstance(rows, list) or not rows:
            return "prompt_tokens must be a non-empty list"
        if len(rows) > MAX_PROMPTS:
            return f"Maximum number of prompts is {MAX_PROMPTS}"
        for r in rows:
            if not isinstance(r, list) or not r or not all(
                    isinstance(t, int) and not isinstance(t, bool)
                    for t in r):
                return ("prompt_tokens rows must be non-empty lists "
                        "of integer token ids")
        n_prompts = len(rows)
    else:
        prompts = payload["prompts"]
        if not isinstance(prompts, list) or not prompts:
            return "prompts must be a non-empty list"
        if len(prompts) > MAX_PROMPTS:
            return f"Maximum number of prompts is {MAX_PROMPTS}"
        if not all(isinstance(p, str) and p for p in prompts):
            return "prompts must be non-empty strings"
        n_prompts = len(prompts)
    try:
        n = int(payload.get("tokens_to_generate", 64))
    except (TypeError, ValueError):
        return "tokens_to_generate must be an integer"
    if n < 0:
        return "tokens_to_generate must be >= 0"
    # sampling + SLO knobs must coerce cleanly — a list/dict/None here
    # would otherwise surface as a 500 from deep inside the handler
    for field, conv in (("temperature", float), ("top_k", int),
                        ("top_p", float), ("length_penalty", float),
                        ("beam_width", int), ("random_seed", int),
                        ("priority", int), ("deadline_s", float),
                        ("arrival_id", int)):
        v = payload.get(field)
        if v is None:
            continue
        try:
            conv(v)
        except (TypeError, ValueError):
            return f"{field} must be a number"
    if payload.get("deadline_s") is not None:
        # json.loads happily parses NaN/Infinity; a NaN deadline would
        # make every expiry comparison False (an unreapable request)
        # AND poison the scheduler's sort key, scrambling EDF order
        # for OTHER requests — reject at the boundary
        import math as _math
        d = float(payload["deadline_s"])
        if not _math.isfinite(d) or d <= 0.0:
            return "deadline_s must be a finite number > 0"
    if payload.get("beam_width") and n_prompts > 1:
        # (ref: beam-search rejects multi-prompt requests)
        return "With beam_search only one prompt is allowed"
    if has_tokens and payload.get("beam_width"):
        # beam search runs the serial path, which needs text prompts
        return "prompt_tokens requires the serving-engine path; beam " \
               "search is text-prompt only"
    aid = payload.get("adapter_id")
    if aid is not None and not isinstance(aid, (str, int)):
        # multi-tenant LoRA serving: the id is an opaque registry key
        # (unknown ids 400 at submit via UnknownAdapterError)
        return "adapter_id must be a string or integer"
    if aid is not None and payload.get("beam_width"):
        return "beam search runs the serial path; adapters require " \
               "the serving engine"
    rf = payload.get("response_format")
    if rf is not None:
        # structured output (docs/serving.md "Structured output &
        # n-best"): shape-validate HERE so a malformed grammar 400s
        # identically on both transports; whether the pattern COMPILES
        # is the engine's admission check (also a 400)
        from megatron_tpu.serving.structured import \
            validate_response_format
        msg = validate_response_format(rf)
        if msg is not None:
            return f"response_format: {msg}"
    for field in ("n", "best_of"):
        v = payload.get(field)
        if v is None:
            continue
        # bool is an int subclass; `"n": true` must not mean 1
        if isinstance(v, bool) or not isinstance(v, int):
            return f"{field} must be an integer"
        if v < 1:
            return f"{field} must be >= 1"
    n_samples = payload.get("n")
    best_of = payload.get("best_of")
    if n_samples is not None and best_of is not None \
            and n_samples > best_of:
        return f"n ({n_samples}) must be <= best_of ({best_of})"
    if (best_of or n_samples or 1) > 1 and payload.get("beam_width"):
        return "beam search does not compose with n/best_of parallel " \
               "sampling"
    return None


class MegatronServer:
    """(ref: text_generation_server.py:229-241 MegatronServer)"""

    def __init__(self, generator: Generator, tokenizer, serving=None,
                 request_timeout: float = 600.0, weight_version=None):
        from megatron_tpu.config import ServingConfig
        self.generator = generator
        self.tokenizer = tokenizer
        # a fleet front tier (serving.fleet) holds NO weights — the
        # replica processes do — so generator may be None there; every
        # route that forwards locally (serial, beam) guards on it
        self.serving = (serving if serving is not None
                        else ServingConfig()).validate(
            generator.cfg if generator is not None else None)
        self._lock = threading.Lock()  # serial paths: one at a time (ref: :37)
        self._request_counter = itertools.count()
        self._timeout = request_timeout
        # SSE stream registry: stream_id -> live request handle, so a
        # dropped connection resumes via Last-Event-ID (the engine
        # already holds every committed token on the request — resume
        # is a replay of the tail, not recomputation)
        self._streams: dict = {}
        self._streams_lock = threading.Lock()
        self.engine = None
        if self.serving.fleet:
            # fleet front tier (docs/serving.md "Front door"): the SAME
            # EngineRouter, but each replica is a RemoteReplica client
            # over a standalone --replica_mode server process — health
            # polling, typed transport faults, token-exact failover,
            # and rolling upgrades all run over TCP. The shared
            # ServingMetrics registry is BOTH the router's overlay
            # registry and the transport-fault counter sink, so one
            # /metrics scrape shows fleet counters next to the summed
            # per-replica ones.
            from megatron_tpu.serving import EngineRouter
            from megatron_tpu.serving.metrics import ServingMetrics
            from megatron_tpu.serving.remote import RemoteReplica
            counters = ServingMetrics()
            replicas = [
                RemoteReplica(
                    addr.strip(), counters=counters,
                    connect_timeout_s=self.serving
                    .remote_connect_timeout_s,
                    read_timeout_s=self.serving.remote_read_timeout_s,
                    max_retries=self.serving.remote_max_retries,
                    digest_interval_s=self.serving
                    .remote_digest_interval_s)
                for addr in self.serving.fleet.split(",")
                if addr.strip()]
            self.engine = EngineRouter(
                replicas, metrics=counters,
                max_retries=self.serving.router_max_retries,
                heartbeat_timeout_s=self.serving
                .router_heartbeat_timeout_s)
        elif not self.serving.serial_fallback:
            from megatron_tpu.serving import ServingEngine
            from megatron_tpu.serving.topology import devices_per_engine
            # serving-mesh topology (docs/serving.md "Sharded &
            # disaggregated serving" / "Per-phase topology &
            # placement"): each replica occupies its own window of the
            # device list — decode_tp chips for the decode group plus
            # prefill_tp more for the prefill group when disaggregated
            # (each phase its own width; both default to serving_tp),
            # or exactly placement_budget chips when the placement
            # optimizer holds the split — so an EngineRouter replica is
            # a (prefill-group, decode-group) PAIR and killing either
            # half fails over like any replica death. per == 1 passes
            # devices=None (the topology-free engine, bit-identical).
            per = devices_per_engine(self.serving)
            slices = [None] * self.serving.num_replicas
            if per > 1:
                import jax
                devs = jax.devices()
                need = per * self.serving.num_replicas
                assert len(devs) >= need, (
                    f"serving topology needs {need} devices "
                    f"({self.serving.num_replicas} replicas x {per}) "
                    f"but the backend has {len(devs)}")
                slices = [devs[i * per:(i + 1) * per]
                          for i in range(self.serving.num_replicas)]
            if self.serving.num_replicas > 1:
                # N full engine replicas (own KV pool / queue /
                # supervisor each, same weights) behind the in-process
                # prefix-affinity router. num_replicas=1 builds NO
                # router at all — the bare engine, bit-identical to
                # the single-replica server (test-pinned).
                from megatron_tpu.serving import EngineRouter
                engines = [ServingEngine(generator, self.serving,
                                         devices=sl,
                                         weight_version=weight_version)
                           for sl in slices]
                self.engine = EngineRouter(
                    engines,
                    max_retries=self.serving.router_max_retries,
                    heartbeat_timeout_s=
                    self.serving.router_heartbeat_timeout_s)
            else:
                self.engine = ServingEngine(generator, self.serving,
                                            devices=slices[0],
                                            weight_version=weight_version)
        # live-weight serving (docs/serving.md "Live weights & rolling
        # upgrade"): watch the training tracker and drive the engine /
        # fleet to every newly published checkpoint — the
        # zero-operator-action half of the training->serving loop
        self._watcher = None
        if self.engine is not None and \
                getattr(self.serving, "watch_checkpoints", None):
            from megatron_tpu.serving.weights import CheckpointWatcher
            initial_tag = None
            if weight_version is not None:
                # staged at boot from this very root: the CURRENT
                # tracker tag (whatever its spelling — "release"
                # included) is what the fleet already serves; seeding
                # with it stops the first poll from redundantly
                # re-swapping the boot checkpoint through a full
                # drain->swap->canary walk
                try:
                    import os as _os

                    from megatron_tpu.serving.weights import \
                        manifest_digest
                    from megatron_tpu.training.checkpointing import \
                        read_tracker
                    tag = read_tracker(self.serving.watch_checkpoints)
                    # only when the tracker still names what we STAGED
                    # — a publish that landed between staging and here
                    # must NOT be skipped. Iteration tags compare by
                    # number; a "release" tag compares by manifest
                    # digest (the iteration alone can't distinguish
                    # "we staged the release dir" from "release
                    # published after we staged iter_N").
                    if tag == str(weight_version.iteration):
                        initial_tag = tag
                    elif tag == "release" and manifest_digest(
                            _os.path.join(
                                self.serving.watch_checkpoints,
                                "release")) == weight_version.digest:
                        initial_tag = tag
                except Exception:  # noqa: BLE001 — racing a publish
                    initial_tag = str(weight_version.iteration)
            self._watcher = CheckpointWatcher(
                self.engine, self.serving.watch_checkpoints,
                interval_s=self.serving.watch_interval_s,
                initial_tag=initial_tag).start()

    def close(self):
        if self._watcher is not None:
            self._watcher.close()
        if self.engine is not None:
            self.engine.close()

    def drain(self, timeout: Optional[float] = 120.0) -> bool:
        """Graceful shutdown: stop admitting, finish in-flight slots,
        then stop the engine. Serial mode has no queue to drain — the
        one-lock path finishes its current batch when the process
        exits."""
        if self.engine is None:
            return True
        drained = self.engine.drain(timeout)
        if not drained:
            self.engine.close()  # stragglers fail hard rather than hang
        return drained

    def install_sigterm_drain(self, shutdown_cb=None) -> bool:
        """SIGTERM -> drain + stop serving (the k8s/rolling-restart
        contract: the pod gets its grace period to finish in-flight
        work). `shutdown_cb` stops the HTTP front end once the drain
        completes. Returns False outside the main thread (signal
        handlers can only install there — tests drive `drain()`
        directly)."""
        import signal as _signal

        def _on_sigterm(signum, frame):
            print_rank_0("SIGTERM: draining serving engine "
                         "(no new admissions; finishing in-flight)")
            t = threading.Thread(target=self._drain_and_shutdown,
                                 args=(shutdown_cb,), daemon=True,
                                 name="sigterm-drain")
            t.start()

        try:
            _signal.signal(_signal.SIGTERM, _on_sigterm)
            return True
        except ValueError:  # not the main thread
            return False

    def _drain_and_shutdown(self, shutdown_cb):
        self.drain()
        if shutdown_cb is not None:
            shutdown_cb()

    def _seed_for(self, payload) -> int:
        """Explicit random_seed stays deterministic; unseeded requests
        mix real entropy with a per-process counter so traffic differs
        run-to-run AND request-to-request (the old counter-only fallback
        restarted at 0 every process start, making 'unseeded' traffic
        identical across restarts)."""
        if payload.get("random_seed") is not None:
            return int(payload["random_seed"])
        return (secrets.randbits(31)
                ^ (next(self._request_counter) & 0x7FFFFFFF))

    def handle(self, payload: dict,
               headers: Optional[dict] = None) -> Tuple[int, object]:
        """(ref: text_generation_server.py:31-228 MegatronGenerate.put).
        Returns (http_status, body) — body is a JSON-able dict, or a
        GENERATOR of SSE-formatted strings when the payload asked for
        `stream: true` (both transports detect that and switch to
        `text/event-stream`). `headers` carries the request headers
        (Last-Event-ID for stream resume)."""
        from megatron_tpu.serving import (AdmissionError,
                                          DeadlineExceededError,
                                          EngineUnhealthyError,
                                          GrammarDeadEndError,
                                          QueueFullError,
                                          ServiceUnavailableError)
        try:
            if isinstance(payload, dict) \
                    and payload.get("prompt_tokens") is not None \
                    and not self.serving.replica_mode:
                # the pre-tokenized wire format is the FRONT TIER's
                # protocol to a replica process; a public server keeps
                # speaking text prompts (its tokenizer is the contract)
                return 400, {"message":
                             "prompt_tokens is the replica-mode wire "
                             "format (run the server with "
                             "--replica_mode); send text prompts"}
            if isinstance(payload, dict) and payload.get("cancel"):
                # remote cancel (serving/remote.py RemoteReplica
                # .cancel): best-effort eviction of a stream the front
                # tier abandoned — frees the slot instead of decoding
                # tokens nobody will read
                return self._handle_cancel(payload)
            if isinstance(payload, dict) and payload.get("stream"):
                # streaming validates inside (a RESUME payload carries
                # only stream_id — no prompts to validate)
                return self._handle_stream(payload, headers or {})
            err = validate_generate_payload(payload)
            if err is not None:
                return 400, {"message": err}
            if payload.get("beam_width"):
                if self.generator is None:
                    return 400, {"message":
                                 "beam search forwards locally; a "
                                 "fleet front tier holds no weights"}
                err = self._stale_fallback_error("beam search")
                if err is not None:
                    return 409, {"message": err}
                return 200, self._handle_beam(payload)
            if payload.get("serial") and self.generator is None:
                return 400, {"message":
                             "the serial route forwards locally; a "
                             "fleet front tier holds no weights"}
            if payload.get("prompt_tokens") is not None \
                    and (self.engine is None or payload.get("serial")):
                return 400, {"message":
                             "prompt_tokens requires the serving-"
                             "engine path (drop 'serial': true / "
                             "serial_fallback)"}
            if self.engine is not None and not payload.get("serial"):
                return 200, self._handle_engine(payload)
            if self.engine is not None:
                err = self._stale_fallback_error("the serial route")
                if err is not None:
                    return 409, {"message": err}
            if payload.get("adapter_id") is not None:
                # the serial path threads no adapter bank — silently
                # decoding the BASE model would be wrong output, not a
                # degraded mode
                return 400, {"message":
                             "adapter_id requires the serving-engine "
                             "path (drop 'serial': true / "
                             "serial_fallback)"}
            if payload.get("response_format") is not None or \
                    (payload.get("best_of") or payload.get("n") or 1) > 1:
                # same reasoning: the serial path has no FSM masking
                # and no slot grid to fan out on — unconstrained /
                # single-sample output would be wrong, not degraded
                return 400, {"message":
                             "response_format and n/best_of require "
                             "the serving-engine path (drop 'serial': "
                             "true / serial_fallback)"}
            return 200, self._handle_serial(payload)
        except EngineUnhealthyError as e:
            # crash-loop circuit breaker open: this replica cannot
            # serve — 503 so the client/LB retries against another one
            return 503, self._backoff_body(str(e), retry_after=30)
        except QueueFullError as e:
            # bounded-queue overflow, early load shedding
            # (OverloadShedError subclasses this), or a draining
            # engine — all retryable, all carry the backoff hint
            return 429, self._backoff_body(
                str(e), retry_after=getattr(e, "retry_after", None),
                queue_depth=getattr(e, "queue_depth", None))
        except DeadlineExceededError as e:
            # per-request deadline expiry (payload deadline_s /
            # ServingConfig.request_deadline_s): the engine evicted the
            # request — gateway-timeout semantics, retryable
            return 504, {"message": str(e)}
        except ServiceUnavailableError as e:
            # queued work dropped by a graceful drain: retry elsewhere
            return 503, self._backoff_body(str(e), retry_after=5)
        except AdmissionError as e:
            # only explicit admission failures are client errors; a bare
            # ValueError from inside the model stack stays a 500 (it is
            # a server fault, not a fixable request)
            return 400, {"message": str(e)}
        except GrammarDeadEndError as e:
            # constrained generation reached a state with NO legal
            # token: the request was well-formed (not a 400) and the
            # server is healthy (not a 500) — the generation itself is
            # unprocessable, which is exactly what 422 means. Not
            # retryable as-is: the same grammar + budget + seed dead-
            # ends again; the client should loosen one of them.
            return 422, {"message": str(e)}
        except Exception as e:  # noqa: BLE001 — 500 with message, both paths
            return 500, {"message": str(e)}

    def _stale_fallback_error(self, what: str) -> Optional[str]:
        """The serial/beam fallback routes forward through the
        Generator's ORIGINAL params, which a live-weight hot swap
        deliberately never touches (sibling replicas share one
        Generator). Once any engine replica has swapped, those routes
        would silently serve the OLD weights under a fleet reporting
        the new version — a correctness lie, so they answer 409 typed
        instead. Serial-only servers (engine=None) never swap and are
        unaffected."""
        if self.engine is None:
            return None
        try:
            snap = (self.engine.aggregate_snapshot()
                    if hasattr(self.engine, "aggregate_snapshot")
                    else self.engine.metrics.snapshot())
            swapped = snap.get("weight_swaps", 0) > 0
        except Exception:  # noqa: BLE001 — can't tell: let it through
            swapped = False
        if not swapped:
            return None
        return (f"{what} is unavailable after a live-weight hot swap: "
                "it forwards through the server's original startup "
                "weights, not the engine's current version — restart "
                "the server on the new checkpoint to use it")

    def _backoff_body(self, message: str,
                      retry_after: Optional[int] = None,
                      queue_depth: Optional[int] = None) -> dict:
        """JSON body for 429/503: the message plus the machine-readable
        backoff hint (`retry_after`, seconds — also emitted as the
        Retry-After header by both transports) and the current queue
        depth, so clients can back off proportionally to the backlog
        instead of hammering a saturated replica."""
        if queue_depth is None:
            queue_depth = (self.engine.queue_depth()
                           if self.engine is not None else 0)
        # ceil-clamp to >= 1s: a remote replica's hint arrives as a
        # FLOAT, and int(0.5) == 0 would emit Retry-After: 0 — telling
        # every shed client to retry immediately, a synchronized herd
        # at the worst possible moment (and response_headers would
        # drop the falsy header entirely). Sub-second estimates round
        # UP; absent hints default to 1.
        hint = (1 if retry_after is None
                else max(1, int(math.ceil(float(retry_after)))))
        return {"message": message,
                "retry_after": hint,
                "queue_depth": int(queue_depth)}

    @staticmethod
    def response_headers(body: dict) -> dict:
        """Extra HTTP headers for a response body (shared by both
        transports): a `retry_after` hint in the body becomes the
        standard Retry-After header."""
        if isinstance(body, dict) and body.get("retry_after"):
            return {"Retry-After": str(int(body["retry_after"]))}
        return {}

    def healthz(self) -> Tuple[int, dict]:
        """Liveness/readiness for `/healthz` — separate from `/metrics`
        (a scrape-schema document) so probes get a stable, tiny,
        host-state-only answer: 200 only while the engine ACCEPTS new
        work; 503 once the crash-loop circuit breaker is open, the
        loop is wedged/dead, or a drain started (a draining replica
        rejects every new request — the probe must pull it out of
        rotation, that is the whole point of a readiness signal).
        Serial mode has no engine loop to probe."""
        if self.engine is None:
            return 200, {"healthy": True, "serving": "serial"}
        self._gc_streams()  # probes double as the registry's sweeper
        h = self.engine.health()
        # `accepting` is the readiness verdict both the engine and the
        # router compute (a DEGRADED router — some replicas down, at
        # least one serving — stays ready: pulling the whole front
        # door would turn a partial failure into a total one)
        ok = bool(h.get("accepting",
                        h.get("healthy") and h.get("state") == "running"
                        and h.get("loop_alive")))
        return (200 if ok else 503), h

    def _handle_beam(self, payload: dict) -> dict:
        prompts = payload["prompts"]
        # same length admission as the other routes: positions past the
        # RoPE table would silently clamp, not error
        prompt_ids = self._preflight_lengths(
            payload, self.generator.cfg.max_position_embeddings,
            "max_position_embeddings")
        with self._lock:
            texts, scores = beam_search_and_post_process(
                self.generator, self.tokenizer, prompts[0],
                tokens_to_generate=int(payload.get("tokens_to_generate",
                                                   64)),
                beam_size=int(payload["beam_width"]),
                length_penalty=float(payload.get("length_penalty", 1.0)),
                add_BOS=bool(payload.get("add_BOS", False)),
                prompt_ids=prompt_ids[0])
            return {"text": texts, "score": scores}

    def _preflight_lengths(self, payload: dict, max_total: int,
                           what: str):
        """Tokenize-and-check before generating, so oversize/empty
        prompts 400 as AdmissionError on EVERY route (a bare ValueError
        escaping the model stack stays a 500 — it is a server fault).
        Returns the token ids (BOS applied) so no route tokenizes
        twice."""
        from megatron_tpu.serving import AdmissionError
        n = int(payload.get("tokens_to_generate", 64))
        if payload.get("prompt_tokens") is not None:
            # replica-mode wire format: rows are ALREADY token ids (the
            # front tier tokenized; add_BOS was applied there too) —
            # only the length admission runs here, so an oversize row
            # still 400s identically to a text prompt
            prompt_ids = []
            for i, row in enumerate(payload["prompt_tokens"]):
                ids = [int(t) for t in row]
                if len(ids) + n > max_total:
                    raise AdmissionError(
                        f"prompt {i} ({len(ids)} tokens) + tokens_to_"
                        f"generate ({n}) exceeds {what}={max_total}")
                prompt_ids.append(ids)
            return prompt_ids
        add_bos = bool(payload.get("add_BOS", False))
        prompt_ids = []
        for i, p in enumerate(payload["prompts"]):
            ids = self.tokenizer.tokenize(p)
            if add_bos and self.tokenizer.bos is not None:
                ids = [self.tokenizer.bos] + ids
            if not ids:
                raise AdmissionError(
                    f"prompt {i} tokenized to zero tokens")
            if len(ids) + n > max_total:
                raise AdmissionError(
                    f"prompt {i} ({len(ids)} tokens) + tokens_to_"
                    f"generate ({n}) exceeds {what}={max_total}")
            prompt_ids.append(ids)
        return prompt_ids

    def _handle_serial(self, payload: dict) -> dict:
        """The reference's whole-batch path: one generation at a time."""
        prompt_ids = self._preflight_lengths(
            payload, self.generator.cfg.max_position_embeddings,
            "max_position_embeddings")
        with self._lock:
            texts, tokens, logprobs = generate_and_post_process(
                self.generator, self.tokenizer, payload["prompts"],
                tokens_to_generate=int(payload.get("tokens_to_generate",
                                                   64)),
                temperature=float(payload.get("temperature", 1.0)),
                top_k=int(payload.get("top_k", 0)),
                top_p=float(payload.get("top_p", 0.0)),
                add_BOS=bool(payload.get("add_BOS", False)),
                return_output_log_probs=bool(payload.get("logprobs",
                                                         False)),
                seed=self._seed_for(payload),
                prompt_ids=prompt_ids)
        out = {"text": texts, "segments": tokens}
        if logprobs is not None:
            out["logprobs"] = logprobs
        return out

    def _handle_engine(self, payload: dict) -> dict:
        """Continuous-batching path: each prompt is an independent
        request interleaved with all other traffic. Prompt i of a
        multi-prompt payload uses seed+i (a single seeded prompt
        reproduces the serial path token-for-token; multi-prompt
        payloads sample independently per row instead of sharing the
        serial path's one batch-wide key).

        With `n`/`best_of` each prompt fans out into best_of
        independently seeded samples (seed+i, seed+i+1, ... would
        collide across prompts, so prompt i's fan-out seeds from
        seed + i*best_of) and the response's text/segments/logprobs
        entries for that prompt become LISTS of the n best
        completions. `response_format` rides through to the engine's
        grammar-constrained decoding (docs/serving.md)."""
        from megatron_tpu.serving import (OverloadShedError,
                                          QueueFullError, SamplingOptions)
        n = int(payload.get("tokens_to_generate", 64))
        sampling = SamplingOptions(
            temperature=float(payload.get("temperature", 1.0)),
            top_k=int(payload.get("top_k", 0)),
            top_p=float(payload.get("top_p", 0.0)))
        want_lp = bool(payload.get("logprobs", False))
        seed = self._seed_for(payload)
        rf = payload.get("response_format")
        n_samples = int(payload.get("n", 1) or 1)
        best_of = int(payload.get("best_of", n_samples) or n_samples)
        fanout = best_of > 1
        # SLO fields: priority orders admission (and may preempt, with
        # ServingConfig.preemption); deadline_s overrides the engine
        # default for THIS request (validated numeric above)
        priority = int(payload.get("priority", 0) or 0)
        deadline_s = payload.get("deadline_s")
        deadline_s = None if deadline_s is None else float(deadline_s)
        # replica mode: a resubmitted failover request carries its
        # ORIGINAL arrival position across the wire, so it re-enters
        # this replica's EDF queue where its first attempt stood
        # (prompt i offsets by i to keep multi-prompt rows distinct)
        aid0 = payload.get("arrival_id")
        aid0 = None if aid0 is None else int(aid0)
        # tokenize + validate EVERY prompt before submitting ANY, so a
        # bad prompt 400s without leaving earlier rows decoding for a
        # response that will never be read
        prompt_ids = self._preflight_lengths(payload, self.engine.max_len,
                                             "max_len")
        # Submit in waves: a payload with more prompts than the queue
        # bound (the reference's contract allows up to MAX_PROMPTS=128)
        # drains its OWN completed rows to make room instead of failing.
        # 429 fires only when the queue is full of OTHER traffic before
        # this payload served a single row.
        import time as _time
        deadline = _time.monotonic() + self._timeout
        reqs: dict = {}
        results: dict = {}
        pending: list = []
        try:
            for i, ids in enumerate(prompt_ids):
                while True:
                    try:
                        reqs[i] = self.engine.submit(
                            ids, n, sampling,
                            seed=seed + i * best_of,
                            priority=priority, deadline_s=deadline_s,
                            arrival_id=(None if aid0 is None
                                        else aid0 + i),
                            adapter_id=payload.get("adapter_id"),
                            response_format=rf, n=n_samples,
                            best_of=best_of)
                        pending.append(i)
                        break
                    except OverloadShedError:
                        # early shedding says this row can no longer
                        # make its deadline — retrying in the wave
                        # would only burn a worker thread toward a
                        # slow 500; fail the payload FAST with the
                        # retryable 429 the feature exists to produce
                        # (already-submitted siblings are cancelled by
                        # the outer handler)
                        raise
                    except QueueFullError:
                        if pending:
                            # make room by draining our oldest row
                            j = pending.pop(0)
                            results[j] = reqs[j].result(
                                timeout=self._timeout)
                        elif results:
                            # our rows are all done; OTHER traffic holds
                            # the queue — wait for room, bounded. On
                            # deadline this is a timeout (500), NOT a
                            # 429: retrying would redo work already
                            # spent on the served rows
                            if _time.monotonic() > deadline:
                                raise RuntimeError(
                                    "timed out waiting for queue space "
                                    f"after serving {len(results)} of "
                                    f"{len(prompt_ids)} prompts")
                            _time.sleep(0.05)
                        else:
                            raise  # genuine backpressure: nothing served
            for j in pending:
                results[j] = reqs[j].result(timeout=self._timeout)
        except Exception:
            # rejection/timeout dooms the whole payload: cancel every
            # sibling still in flight so the slot grid is not kept busy
            # decoding output nobody will read
            for r in reqs.values():
                self.engine.cancel(r)
            raise
        texts, tokens, logprobs = [], [], []
        for i in range(len(prompt_ids)):
            plen = len(reqs[i].prompt)
            if fanout:
                # FanoutRequest.result(): the n best samples, each a
                # (tokens, logprobs) pair — per-prompt entries become
                # lists of n completions
                toks_list, lps_list = results[i]
                texts.append([self.tokenizer.detokenize(t)
                              for t in toks_list])
                tokens.append(toks_list)
                logprobs.append([[0.0] * plen + lp for lp in lps_list])
                continue
            toks, gen_lps = results[i]
            texts.append(self.tokenizer.detokenize(toks))
            tokens.append(toks)
            # serial-contract shape: one value per OUTPUT token; prompt
            # positions are zero (the serial path fills some in-prompt
            # positions with scoring values — an artifact of its
            # bucketed prefill, not part of the contract)
            logprobs.append([0.0] * plen + gen_lps)
        out = {"text": texts, "segments": tokens}
        if want_lp:
            out["logprobs"] = logprobs
        return out

    # ------------------------------------------------------------------
    # SSE streaming (docs/serving.md "Front door": streaming protocol)
    # ------------------------------------------------------------------
    @staticmethod
    def _sse(data: dict, event: Optional[str] = None,
             event_id: Optional[int] = None) -> str:
        """One SSE frame. Token events carry `id:` = the MONOTONIC
        token index, which is what makes `Last-Event-ID` resume exact:
        the client replays nothing and misses nothing."""
        lines = []
        if event_id is not None:
            lines.append(f"id: {event_id}")
        if event:
            lines.append(f"event: {event}")
        lines.append("data: " + json.dumps(data))
        return "\n".join(lines) + "\n\n"

    def _req_weight_version(self, req) -> str:
        """Weight-version label of the replica serving `req` right now:
        router-backed requests read their CURRENT attempt's replica (a
        failed-over stream reports the survivor's version), bare-engine
        requests read the engine."""
        rep = getattr(req, "replica", None)
        eng = rep.engine if rep is not None else self.engine
        v = getattr(eng, "weight_version", None)
        return v.label if v is not None else "unversioned"

    def _count_metric(self, name: str):
        m = getattr(self.engine, "metrics", None)
        if m is not None:
            m.count(name)

    def _gc_streams(self):
        """Sweep the stream registry. Runs on every stream request AND
        on the /metrics + /healthz scrape paths — a monitored server
        sweeps periodically even when no new stream ever arrives, so
        finished/abandoned entries (each pinning a live request and
        its token lists) cannot outlive their TTL indefinitely."""
        import time as _time
        with self._streams_lock:
            self._gc_streams_locked(_time.monotonic())

    def _gc_streams_locked(self, now: float):
        ttl = float(self.serving.stream_ttl_s)
        for sid in list(self._streams):
            e = self._streams[sid]
            if e.done_t is None and e.req.done():
                e.done_t = now
            if e.done_t is not None and now - e.done_t > ttl:
                del self._streams[sid]
            elif e.done_t is None and now - e.created > ttl + self._timeout:
                # a router-backed request's done() only settles when a
                # caller pumps it — an abandoned stream (client gone,
                # nobody waiting) would otherwise sit here forever.
                # Past the request timeout + resume TTL nobody can
                # legitimately resume it: cancel and drop.
                try:
                    self.engine.cancel(e.req)
                except Exception:  # noqa: BLE001 — GC is best-effort
                    pass
                del self._streams[sid]

    def _handle_stream(self, payload: dict, headers) -> Tuple[int, object]:
        """`stream: true` payloads: fresh streams submit one request
        and return an SSE generator; resume payloads (`stream_id` set)
        re-attach to the live request and replay its committed tail
        from `Last-Event-ID` + 1 — the engine holds every committed
        token on the request, so resume is a replay, not a recompute."""
        import time as _time
        if self.engine is None:
            return 400, {"message": "streaming requires the continuous-"
                                    "batching engine (serial_fallback "
                                    "serves whole completions only)"}
        last = headers.get("Last-Event-ID") if headers else None
        if last is None:
            last = payload.get("last_event_id")
        try:
            last = int(last) if last is not None else -1
        except (TypeError, ValueError):
            return 400, {"message": "Last-Event-ID must be an integer "
                                    "token index"}
        sid = payload.get("stream_id")
        if sid is not None:
            with self._streams_lock:
                self._gc_streams_locked(_time.monotonic())
                entry = self._streams.get(sid)
            if entry is None:
                return 404, {"message": f"unknown or expired stream_id "
                                        f"{sid!r}; start a new stream"}
            self._count_metric("stream_reconnects")
            if getattr(entry.req, "children", None):
                return 200, self._stream_events_fanout(entry,
                                                       start=last + 1,
                                                       resumed=True)
            return 200, self._stream_events(entry, start=last + 1,
                                            resumed=True)
        err = validate_generate_payload(payload)
        if err is not None:
            return 400, {"message": err}
        if payload.get("beam_width"):
            return 400, {"message": "beam search is whole-batch; it "
                                    "does not stream"}
        n_rows = len(payload.get("prompts")
                     or payload.get("prompt_tokens") or ())
        if n_rows != 1:
            return 400, {"message": "streaming supports exactly one "
                                    "prompt per request"}
        n_samples = int(payload.get("n", 1) or 1)
        best_of = int(payload.get("best_of", n_samples) or n_samples)
        if best_of > 1 and n_samples != best_of:
            # n-best selection needs every sample finished before any
            # can be ranked — incompatible with streaming tokens as
            # they commit. Fan-out streams deliver ALL samples.
            return 400, {"message": "streaming requires n == best_of "
                                    "(n-best selection cannot stream; "
                                    "drop best_of or stream all "
                                    "samples)"}
        from megatron_tpu.serving import SamplingOptions
        prompt_ids = self._preflight_lengths(payload, self.engine.max_len,
                                             "max_len")
        sampling = SamplingOptions(
            temperature=float(payload.get("temperature", 1.0)),
            top_k=int(payload.get("top_k", 0)),
            top_p=float(payload.get("top_p", 0.0)))
        deadline_s = payload.get("deadline_s")
        aid = payload.get("arrival_id")
        req = self.engine.submit(
            prompt_ids[0], int(payload.get("tokens_to_generate", 64)),
            sampling, seed=self._seed_for(payload),
            priority=int(payload.get("priority", 0) or 0),
            deadline_s=None if deadline_s is None else float(deadline_s),
            arrival_id=None if aid is None else int(aid),
            adapter_id=payload.get("adapter_id"),
            response_format=payload.get("response_format"),
            n=n_samples, best_of=best_of)
        sid = secrets.token_hex(8)
        entry = _StreamEntry(sid, req)
        with self._streams_lock:
            self._gc_streams_locked(_time.monotonic())
            self._streams[sid] = entry
        if getattr(req, "children", None):
            return 200, self._stream_events_fanout(entry, start=0,
                                                   resumed=False)
        return 200, self._stream_events(entry, start=0, resumed=False)

    def _stream_events(self, entry: "_StreamEntry", start: int,
                       resumed: bool):
        """The SSE event generator: `start` frame (stream_id for later
        resumes), one `token` frame per committed token with `id:` =
        its monotonic index, then exactly one terminal frame — `done`
        with the full text, or `error` with the typed HTTP status a
        non-streaming caller would have seen (a mid-stream replica
        crash lands here as a clean terminal event, never a silent
        hang; a retryable one invites reconnect-or-resubmit)."""
        from megatron_tpu.serving import (DeadlineExceededError,
                                          EngineUnhealthyError,
                                          GrammarDeadEndError,
                                          QueueFullError,
                                          ServiceUnavailableError)
        import time as _time
        req = entry.req
        yield self._sse({"stream_id": entry.sid, "resumed": resumed,
                         "next_index": max(start, 0),
                         # the weight version of the replica actually
                         # serving this stream — every start frame, so
                         # a mixed-version fleet (mid-rolling-upgrade)
                         # is observable per stream, resumes included
                         "weight_version": self._req_weight_version(req)},
                        event="start")
        i = max(start, 0)
        # same overall budget the non-streaming path enforces via
        # result(timeout): a stuck request must end in a terminal
        # frame, not an open connection that never emits again
        stream_deadline = _time.monotonic() + self._timeout
        while True:
            gen = req.generated
            if i < len(gen):
                lps = req.gen_logprobs
                data = {"index": i, "token": int(gen[i]),
                        "text": self.tokenizer.detokenize([int(gen[i])])}
                if i < len(lps):
                    data["logprob"] = float(lps[i])
                yield self._sse(data, event="token", event_id=i)
                i += 1
                continue
            if req.done():
                break
            if _time.monotonic() > stream_deadline:
                # buffered tokens above were all delivered; the
                # request itself is stuck — terminal frame, not an
                # open connection that never emits again
                yield self._sse(
                    {"message": f"stream timed out after "
                                f"{self._timeout:.0f}s waiting for "
                                "tokens", "status": 500,
                     "retryable": True,
                     "committed": len(req.generated)}, event="error")
                return
            # wait_token drives the router's retry pump too, so a
            # failed-over request keeps streaming from a survivor
            req.wait_token(i, timeout=0.25)
        try:
            toks, _ = req.result(timeout=self._timeout)
        except Exception as e:  # noqa: BLE001 — typed terminal frame
            if isinstance(e, DeadlineExceededError):
                status = 504
            elif isinstance(e, (ServiceUnavailableError,
                                EngineUnhealthyError)):
                status = 503
            elif isinstance(e, QueueFullError):
                status = 429
            elif isinstance(e, GrammarDeadEndError):
                status = 422  # constrained generation got stuck —
                # deterministic for this (grammar, prompt, seed), so
                # never retryable
            else:
                status = 500
            yield self._sse({"message": str(e), "status": status,
                             "retryable": status in (429, 503),
                             "committed": len(req.generated)},
                            event="error")
            return
        yield self._sse({"text": self.tokenizer.detokenize(toks),
                         "segments": toks,
                         "generated": len(req.generated)}, event="done")

    def _stream_events_fanout(self, entry: "_StreamEntry", start: int,
                              resumed: bool):
        """SSE generator for n>1 fan-out streams (docs/api.md
        "Parallel sampling"). Frames are SAMPLE-MAJOR: sample 0 streams
        to completion, then sample 1, ... — a single GLOBAL monotonic
        frame id spans all samples, so `Last-Event-ID` resume is as
        exact as the single-sample protocol (walk the children in
        order, skip frames below `start`). Each token frame carries
        `sample` (which child) alongside its per-sample `index`. A
        child's typed failure emits an `error` frame tagged with its
        sample and the stream CONTINUES to the remaining samples; the
        terminal `done` frame reports every completed text."""
        from megatron_tpu.serving import (DeadlineExceededError,
                                          EngineUnhealthyError,
                                          GrammarDeadEndError,
                                          QueueFullError,
                                          ServiceUnavailableError)
        import time as _time
        agg = entry.req
        yield self._sse(
            {"stream_id": entry.sid, "resumed": resumed,
             "next_index": max(start, 0), "n": agg.n,
             "weight_version": self._req_weight_version(agg.children[0])},
            event="start")
        gid = 0  # global frame counter across ALL samples
        start = max(start, 0)
        stream_deadline = _time.monotonic() + self._timeout
        texts, errors = [], []
        for k, req in enumerate(agg.children):
            i = 0
            while True:
                gen = req.generated
                if i < len(gen):
                    if gid >= start:
                        lps = req.gen_logprobs
                        data = {"sample": k, "index": i,
                                "token": int(gen[i]),
                                "text": self.tokenizer.detokenize(
                                    [int(gen[i])])}
                        if i < len(lps):
                            data["logprob"] = float(lps[i])
                        yield self._sse(data, event="token",
                                        event_id=gid)
                    gid += 1
                    i += 1
                    continue
                if req.done():
                    break
                if _time.monotonic() > stream_deadline:
                    yield self._sse(
                        {"message": f"stream timed out after "
                                    f"{self._timeout:.0f}s waiting "
                                    "for tokens", "status": 500,
                         "retryable": True, "sample": k,
                         "committed": len(req.generated)},
                        event="error")
                    return
                req.wait_token(i, timeout=0.25)
            try:
                toks, _ = req.result(timeout=self._timeout)
                texts.append(self.tokenizer.detokenize(toks))
            except Exception as e:  # noqa: BLE001 — typed per-sample frame
                if isinstance(e, DeadlineExceededError):
                    status = 504
                elif isinstance(e, (ServiceUnavailableError,
                                    EngineUnhealthyError)):
                    status = 503
                elif isinstance(e, QueueFullError):
                    status = 429
                elif isinstance(e, GrammarDeadEndError):
                    status = 422
                else:
                    status = 500
                errors.append({"sample": k, "status": status})
                yield self._sse({"message": str(e), "status": status,
                                 "retryable": status in (429, 503),
                                 "sample": k,
                                 "committed": len(req.generated)},
                                event="error")
        yield self._sse({"text": texts, "n": agg.n,
                         "completed": len(texts),
                         "failed": errors}, event="done")

    def metrics_snapshot(self) -> dict:
        if self.engine is None:
            return {"serving": "serial"}
        self._gc_streams()  # scrapes double as the registry's sweeper
        if hasattr(self.engine, "aggregate_snapshot"):
            # router: base counters summed across replicas + the
            # router-level failover/retry/stream counters overlaid
            return self.engine.aggregate_snapshot()
        return self.engine.metrics.snapshot()

    # ------------------------------------------------------------------
    # replica/fleet control plane (serving/remote.py speaks these)
    # ------------------------------------------------------------------
    def _handle_cancel(self, payload: dict) -> Tuple[int, dict]:
        """`{"stream_id": ..., "cancel": true}`: evict a live stream —
        the front tier's best-effort cleanup when a client vanished or
        a request failed over to a survivor, so this replica's slot
        stops decoding tokens nobody will read."""
        import time as _time
        if self.engine is None:
            return 400, {"message": "cancel requires the serving engine"}
        sid = payload.get("stream_id")
        if not isinstance(sid, str) or not sid:
            return 400, {"message": "cancel requires a stream_id"}
        with self._streams_lock:
            self._gc_streams_locked(_time.monotonic())
            entry = self._streams.get(sid)
        if entry is None:
            # idempotent: an already-collected stream is as cancelled
            # as it gets — the front tier's retry must not 4xx-loop
            return 200, {"cancelled": False, "stream_id": sid,
                         "message": "unknown or already-expired stream"}
        self.engine.cancel(entry.req)
        return 200, {"cancelled": True, "stream_id": sid}

    def handle_admin(self, payload: dict) -> Tuple[int, dict]:
        """`PUT /admin` (replica/fleet processes): the control-plane
        ops a remote front tier drives over the wire — swap_weights
        (each replica stages itself from shared storage; a router-
        fronted process runs its own rolling_upgrade), register_adapter
        (path-only: factors cannot cross the process boundary), drain.
        Refusals stay typed: 409 for a rejected swap (the process
        keeps serving its old weights), 400 for bad requests."""
        if self.engine is None:
            return 400, {"message": "admin ops require the serving "
                                    "engine (serial_fallback has no "
                                    "control plane)"}
        if not isinstance(payload, dict):
            return 400, {"message": "request body must be a JSON object"}
        op = payload.get("op")
        if op == "swap_weights":
            ckpt = payload.get("ckpt_dir")
            if not ckpt:
                return 400, {"message": "swap_weights requires ckpt_dir"}
            timeout = payload.get("timeout")
            timeout = float(timeout) if timeout is not None else 120.0
            from megatron_tpu.serving.router import RollingUpgradeError
            from megatron_tpu.serving.weights import WeightSwapError
            try:
                if hasattr(self.engine, "rolling_upgrade"):
                    version = self.engine.rolling_upgrade(
                        str(ckpt), swap_timeout_s=timeout)
                else:
                    version = self.engine.swap_weights(str(ckpt),
                                                       timeout=timeout)
            except (WeightSwapError, RollingUpgradeError) as e:
                # refused swap: the old weights still serve — conflict
                # with current state, not a server fault
                return 409, {"message": str(e)}
            return 200, {"label": version.label,
                         "iteration": int(getattr(version, "iteration",
                                                  0) or 0)}
        if op == "register_adapter":
            aid = payload.get("adapter_id")
            if aid is None:
                return 400, {"message": "register_adapter requires "
                                        "adapter_id"}
            from megatron_tpu.serving import AdmissionError
            try:
                rank = payload.get("rank")
                self.engine.register_adapter(
                    aid, path=payload.get("path"),
                    rank=None if rank is None else int(rank),
                    alpha=float(payload.get("alpha", 1.0)))
            except AdmissionError as e:
                return 400, {"message": str(e)}
            return 200, {"registered": aid}
        if op == "drain":
            timeout = payload.get("timeout")
            drained = self.engine.drain(
                float(timeout) if timeout is not None else 120.0)
            return 200, {"drained": bool(drained)}
        return 400, {"message": f"unknown admin op {op!r} (swap_weights"
                                " | register_adapter | drain)"}

    def invariant_report(self, strict: bool = False) -> dict:
        """`GET /invariants`: this process runs its OWN sweep
        (serving/invariants.py) on its live engines — KV accounting
        and in-flight walks need the real objects, which cannot cross
        the wire — and serves the verdict. The fleet's `check_all`
        folds each replica's report into the fleet-wide sweep. Default
        strict=False: a live replica is rarely quiesced; the caller
        opts into the strict accounting sweep once traffic stops."""
        from megatron_tpu.serving.invariants import (
            _Sweep, _check_remote_engine, check_engine,
            check_router_health, check_schema)
        if self.engine is None:
            return {"engines": 0, "laws_checked": [], "violations": [],
                    "ok": True}
        sweep = _Sweep()
        unreachable = []
        engines = getattr(self.engine, "engines", None)
        is_router = engines is not None
        if not is_router:
            engines = [self.engine]
        for e in engines:
            try:
                if hasattr(e, "invariant_report"):
                    # fleet mode: a RemoteReplica client — the replica
                    # process runs its OWN sweep and ships the report;
                    # an unreachable (killed/ejected) replica is
                    # recorded, not convicted — the router-level laws
                    # below must still show degraded-not-down
                    res = _check_remote_engine(e, strict, sweep)
                    if "unreachable" in res:
                        unreachable.append(res["remote"])
                else:
                    check_engine(e, strict=strict, sweep=sweep)
            except Exception as ex:  # noqa: BLE001 — a sweep crash is
                # itself a reportable violation, not a 500
                sweep.violations.append(
                    ("sweep", f"check_engine raised {type(ex).__name__}:"
                              f" {ex}"))
        if is_router:
            try:
                check_router_health(self.engine.health(), sweep=sweep)
                check_schema(self.engine.aggregate_snapshot(),
                             router=True, sweep=sweep)
            except Exception as ex:  # noqa: BLE001
                sweep.violations.append(
                    ("sweep", f"router sweep raised "
                              f"{type(ex).__name__}: {ex}"))
        report = {"engines": len(engines),
                  "laws_checked": list(sweep.checked),
                  "violations": [[law, detail]
                                 for law, detail in sweep.violations],
                  "ok": not sweep.violations}
        if unreachable:
            report["unreachable"] = unreachable
        return report

    def affinity_digest(self) -> dict:
        """`GET /affinity` (replica mode): the compact routing digest a
        remote front tier peeks instead of calling prefix_peek over
        the wire per request — per-namespace cumulative-CRC32 block
        chains plus adapter residency (engine.affinity_digest). A
        router-fronted process merges its replicas' digests (union of
        chains, max residency): affinity is a hint, so over-claiming
        a hit costs a suboptimal pick, never a wrong token."""
        if self.engine is None:
            return {"granularity": 0, "namespaces": {}, "adapters": {}}
        engines = getattr(self.engine, "engines", None)
        if engines is None:
            return self.engine.affinity_digest()
        merged: dict = {"granularity": 0, "namespaces": {},
                        "adapters": {}}
        for e in engines:
            try:
                d = e.affinity_digest()
            except Exception:  # noqa: BLE001 — a dead replica has none
                continue
            merged["granularity"] = merged["granularity"] or \
                int(d.get("granularity", 0))
            for label, chain in d.get("namespaces", {}).items():
                bucket = merged["namespaces"].setdefault(label, set())
                bucket.update(chain)
            for aid, lvl in d.get("adapters", {}).items():
                merged["adapters"][aid] = max(
                    merged["adapters"].get(aid, 0), int(lvl))
        merged["namespaces"] = {label: sorted(v) for label, v
                                in merged["namespaces"].items()}
        return merged

    def run(self, host: str = "0.0.0.0", port: int = 5000):
        try:
            self._run_flask(host, port)
        except ImportError:
            self._run_stdlib(host, port)

    def _run_flask(self, host, port):
        from flask import Flask, jsonify, request
        app = Flask(__name__)
        server = self

        @app.route("/api", methods=["PUT"])
        def api():
            status, body = server.handle(request.get_json(silent=True),
                                         headers=request.headers)
            if _is_stream_body(body):
                from flask import Response
                return Response(body, status=status,
                                mimetype="text/event-stream",
                                headers={"Cache-Control": "no-cache",
                                         "X-Accel-Buffering": "no"})
            return (jsonify(body), status,
                    server.response_headers(body))

        @app.route("/admin", methods=["PUT"])
        def admin():
            status, body = server.handle_admin(
                request.get_json(silent=True))
            return jsonify(body), status

        @app.route("/metrics", methods=["GET"])
        def metrics():
            return jsonify(server.metrics_snapshot()), 200

        @app.route("/healthz", methods=["GET"])
        def healthz():
            status, body = server.healthz()
            return jsonify(body), status

        @app.route("/invariants", methods=["GET"])
        def invariants():
            strict = request.args.get("strict", "0") \
                not in ("0", "", "false")
            return jsonify(server.invariant_report(strict=strict)), 200

        @app.route("/affinity", methods=["GET"])
        def affinity():
            return jsonify(server.affinity_digest()), 200

        print_rank_0(f"serving (flask) on {host}:{port}/api")
        # flask's dev server has no programmatic shutdown, and the
        # drain callback runs on a worker thread where signal.signal()
        # would raise — once the engine is drained there is nothing
        # left to clean up, so exit the process directly
        import os as _os
        self.install_sigterm_drain(shutdown_cb=lambda: _os._exit(0))
        app.run(host=host, port=port, threaded=True)

    def _run_stdlib(self, host, port):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        server = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, status: int, body: dict):
                data = json.dumps(body).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in server.response_headers(body).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def _send_stream(self, status: int, gen):
                """SSE response: no Content-Length, one flushed write
                per event. A dropped client (BrokenPipe) stops the
                WRITER only — the request keeps decoding server-side,
                and a reconnect with Last-Event-ID resumes the tail."""
                self.send_response(status)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()
                try:
                    for chunk in gen:
                        self.wfile.write(chunk.encode())
                        self.wfile.flush()
                except (ConnectionError, OSError):
                    pass  # client gone; stream resumable via registry
                finally:
                    gen.close()

            def do_PUT(self):
                from urllib.parse import urlsplit
                path = urlsplit(self.path).path.rstrip("/")
                if path not in ("/api", "/admin"):
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    payload = json.loads(self.rfile.read(length) or b"{}")
                except json.JSONDecodeError as e:
                    self._send(400, {"message": f"invalid JSON: {e}"})
                    return
                try:
                    if path == "/admin":
                        status, body = server.handle_admin(payload)
                    else:
                        status, body = server.handle(payload,
                                                     headers=self.headers)
                except Exception as e:  # pragma: no cover — handle()
                    status, body = 500, {"message": str(e)}
                if _is_stream_body(body):
                    self._send_stream(status, body)
                else:
                    self._send(status, body)

            def do_GET(self):
                from urllib.parse import parse_qs, urlsplit
                parts = urlsplit(self.path)
                path = parts.path.rstrip("/")
                if path == "/metrics":
                    self._send(200, server.metrics_snapshot())
                elif path == "/healthz":
                    status, body = server.healthz()
                    self._send(status, body)
                elif path == "/invariants":
                    qs = parse_qs(parts.query)
                    strict = (qs.get("strict", ["0"])[0]
                              not in ("0", "", "false"))
                    try:
                        self._send(200,
                                   server.invariant_report(strict=strict))
                    except Exception as e:  # noqa: BLE001 — report, not 500
                        self._send(500, {"message": str(e)})
                elif path == "/affinity":
                    try:
                        self._send(200, server.affinity_digest())
                    except Exception as e:  # noqa: BLE001
                        self._send(500, {"message": str(e)})
                else:
                    self.send_error(404)

            def log_message(self, fmt, *a):
                pass

        print_rank_0(f"serving (http.server) on {host}:{port}/api")
        httpd = ThreadingHTTPServer((host, port), Handler)
        # SIGTERM drains in-flight work, then shutdown() unblocks
        # serve_forever for a clean exit (rolling-restart contract)
        self.install_sigterm_drain(shutdown_cb=httpd.shutdown)
        httpd.serve_forever()
        httpd.server_close()
