"""REST text-generation server.

TPU-native port of the reference's Flask server
(ref: megatron/text_generation_server.py:17-241 + tools/
run_text_generation_server.py:60-84): same `/api` PUT contract —
{"prompts": [...], "tokens_to_generate": N, "temperature": ..,
 "top_k": .., "top_p": .., "logprobs": bool, "beam_width": int|absent} ->
{"text": [...], "segments"/"logprobs": ...}.

The reference needs a rank-0 Flask thread that broadcasts a GENERATE/BEAM
signal to all other ranks sitting in a receive loop
(ref: text_generation_server.py:22-31); single-controller JAX needs none of
that — one process serves and drives all chips. Flask is used when
available, else the stdlib http.server (this image has no flask).
"""
from __future__ import annotations

import itertools
import json
import threading

from megatron_tpu.inference.api import (beam_search_and_post_process,
                                        generate_and_post_process)
from megatron_tpu.inference.generation import Generator
from megatron_tpu.utils.logging import print_rank_0


class MegatronServer:
    """(ref: text_generation_server.py:229-241 MegatronServer)"""

    def __init__(self, generator: Generator, tokenizer):
        self.generator = generator
        self.tokenizer = tokenizer
        self._lock = threading.Lock()  # one generation at a time (ref: :37)
        self._request_counter = itertools.count()

    def handle(self, payload: dict) -> dict:
        """(ref: text_generation_server.py:31-228 MegatronGenerate.put)"""
        if "prompts" not in payload:
            return {"message": "prompts argument required"}
        prompts = payload["prompts"]
        if not isinstance(prompts, list) or not prompts:
            return {"message": "prompts must be a non-empty list"}
        if len(prompts) > 128:
            return {"message": "Maximum number of prompts is 128"}
        n = int(payload.get("tokens_to_generate", 64))
        if n < 0:
            return {"message": "tokens_to_generate must be >= 0"}
        with self._lock:
            if payload.get("beam_width"):
                if len(prompts) > 1:
                    # (ref: text_generation_server.py beam-search rejects
                    # multi-prompt requests)
                    return {"message":
                            "With beam_search only one prompt is allowed"}
                texts, scores = beam_search_and_post_process(
                    self.generator, self.tokenizer, prompts[0],
                    tokens_to_generate=n,
                    beam_size=int(payload["beam_width"]),
                    length_penalty=float(payload.get("length_penalty", 1.0)),
                    add_BOS=bool(payload.get("add_BOS", False)))
                return {"text": texts, "score": scores}
            texts, tokens, logprobs = generate_and_post_process(
                self.generator, self.tokenizer, prompts,
                tokens_to_generate=n,
                temperature=float(payload.get("temperature", 1.0)),
                top_k=int(payload.get("top_k", 0)),
                top_p=float(payload.get("top_p", 0.0)),
                add_BOS=bool(payload.get("add_BOS", False)),
                return_output_log_probs=bool(payload.get("logprobs", False)),
                # unseeded requests must differ run-to-run (the reference
                # leaves sampling unseeded unless random_seed is given)
                seed=int(payload.get("random_seed",
                                     next(self._request_counter))))
            out = {"text": texts, "segments": tokens}
            if logprobs is not None:
                out["logprobs"] = logprobs
            return out

    def run(self, host: str = "0.0.0.0", port: int = 5000):
        try:
            self._run_flask(host, port)
        except ImportError:
            self._run_stdlib(host, port)

    def _run_flask(self, host, port):
        from flask import Flask, jsonify, request
        app = Flask(__name__)
        server = self

        @app.route("/api", methods=["PUT"])
        def api():
            return jsonify(server.handle(request.get_json()))

        print_rank_0(f"serving (flask) on {host}:{port}/api")
        app.run(host=host, port=port, threaded=True)

    def _run_stdlib(self, host, port):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_PUT(self):
                if self.path.rstrip("/") != "/api":
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    result = server.handle(payload)
                    body = json.dumps(result).encode()
                    self.send_response(200)
                except Exception as e:  # mirror flask's 500-with-message
                    body = json.dumps({"message": str(e)}).encode()
                    self.send_response(500)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *a):
                pass

        print_rank_0(f"serving (http.server) on {host}:{port}/api")
        httpd = ThreadingHTTPServer((host, port), Handler)
        httpd.serve_forever()
