"""Multi-head / grouped-query / multi-query attention.

TPU-native equivalent of the reference's ParallelAttention + CoreAttention
(ref: megatron/model/transformer.py:280-529 and :144-277). Differences by
design, not omission:

- The reference fuses Q,K,V into one column-parallel matmul with a grouped
  [s,b,groups,q_per_group+2,hd] layout (ref: transformer.py:313-333,440-455)
  because NCCL-sharded checkpoints need contiguous per-rank slices. Under
  GSPMD the parameter layout is decoupled from device layout, so we keep a
  Q projection and a fused KV projection: Q shards over 'heads'→tp and KV over
  'kv_heads'→tp (replicated when kv_heads < tp, the MQA case), which is the
  clean mesh formulation of the reference's GQA broadcast
  (ref: transformer.py:448-455).
- The unfused CoreAttention path (baddbmm into a global memory buffer + fused
  scale-mask-softmax CUDA kernel, ref: transformer.py:191-277 and
  fused_kernels K1-K3) is a single einsum chain here — XLA fuses
  scale+mask+softmax on TPU without a custom kernel. The flash path
  (ref: transformer.py:514-522 flash_attn_func) maps to our Pallas flash
  kernel in megatron_tpu/ops/flash_attention.py.
- KV-cache (`InferenceParams`, ref: megatron/text_generation/forward_step.py:
  17-42, used at transformer.py:402-409,482-495) becomes an explicit
  functional cache pytree updated with lax.dynamic_update_slice.
"""
from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from megatron_tpu.config import ModelConfig
from megatron_tpu.models.rope import apply_rotary
from megatron_tpu.ops.dropout import dropout
from megatron_tpu.ops.quantized import qdense, wcast


class KVCache(NamedTuple):
    """Functional KV cache (ref: InferenceParams, forward_step.py:17-42).

    dtype=jnp.int8 stores k/v int8 with per-(batch, token, head) fp32
    scales (k_scale/v_scale, amax over head_dim) — decode streams the
    whole cache every step, so int8 halves the bandwidth-bound cache
    read AND the residency: a 7B 32k-context cache (~17 GB bf16) does
    not fit a 16 GB v5e at all until quantized. Entries are quantized at
    write time and dequantized at read — including the current decode
    token's own k/v (one round-trip, same ~0.4% error as the rest of
    the cache); only the offset-0 flash-prefill branch bypasses the
    cache entirely (it reads the raw projections)."""
    k: jax.Array  # [batch, max_seq, n_kv_heads, head_dim]
    v: jax.Array
    # tokens already in cache: scalar int32, or PER-ROW [batch] int32 for
    # the serving engine's slot grid (each row decodes at its own length;
    # vector offsets support s == 1 steps only — see attention_apply)
    offset: jax.Array
    k_scale: Optional[jax.Array] = None  # [batch, max_seq, n_kv, 1] fp32
    v_scale: Optional[jax.Array] = None

    @staticmethod
    def create(batch: int, max_seq: int, n_kv: int, head_dim: int,
               dtype=jnp.bfloat16):
        shape = (batch, max_seq, n_kv, head_dim)
        # normalize: accept "int8" the way cfg dtypes are spelled — the
        # raw `dtype == jnp.int8` would be False for the string while
        # jnp.zeros still allocated int8, leaving scales None (crash at
        # the first cache write)
        quant = jnp.dtype(dtype) == jnp.dtype(jnp.int8)
        return KVCache(
            k=jnp.zeros(shape, dtype=dtype),
            v=jnp.zeros(shape, dtype=dtype),
            offset=jnp.zeros((), dtype=jnp.int32),
            k_scale=jnp.ones(shape[:3] + (1,), jnp.float32) if quant else None,
            v_scale=jnp.ones(shape[:3] + (1,), jnp.float32) if quant else None,
        )


class LoraAdapter(NamedTuple):
    """Batched low-rank (LoRA) adapter factors for the q/k/v/o
    projections — the model-facing half of multi-tenant adapter serving
    (serving/adapters.py AdapterBank; S-LoRA / Punica, PAPERS.md).

    Two shapes flow through the same type:
      - STACKED (what the bank holds and stack_apply scans): every leaf
        carries a leading 'layers' dim — [L, n, h, r] for the A factors,
        [L, n, r, out] for the B factors — so the stack scan slices one
        layer's [n, ...] bank per step exactly like it slices the KV
        caches;
      - PER-LAYER (what attention_apply consumes inside the scan):
        [n, h, r] / [n, r, out].

    `n` is the bank capacity (adapter slots + 1); ROW 0 IS THE IDENTITY
    adapter (all-zero factors), so base-model requests ride the same
    batched gather + matmul trace with a zero delta — adapter indices
    are DATA, like the KV block map, and the decode/verify/prefill
    programs keep one compile each. Scaling (alpha / rank) is folded
    into the B factors at load time, so apply-time math is just
    x @ A[idx] @ B[idx] added to the base projection."""
    aq: jax.Array  # [.., n, h, r]
    bq: jax.Array  # [.., n, r, nq*hd]
    ak: jax.Array  # [.., n, h, r]
    bk: jax.Array  # [.., n, r, nkv*hd]
    av: jax.Array  # [.., n, h, r]
    bv: jax.Array  # [.., n, r, nkv*hd]
    ao: jax.Array  # [.., n, nq*hd, r]
    bo: jax.Array  # [.., n, r, h]


class BlockKVCache(NamedTuple):
    """Block-NATIVE serving cache: the flat block arena plus the
    per-slot block map, consumed directly by the Pallas block-native
    decode-attention kernel (ops/block_attention_pallas.py) — no
    contiguous [S, cap, ...] view is ever materialized (the
    resolve_view/scatter_view bracket in serving/kv_pool.py is exactly
    what this type exists to delete from the decode hot path).

    Shapes are per LAYER once inside the stack scan (stack_apply scans
    the leading layers dim off every leaf, the map included — it is
    broadcast over layers by serving/kv_pool.block_native_cache):

      k/v:     [total_blocks, B, nkv, hd]   flat arena (int8 for
                                            quantized pools)
      offset:  [num_slots] int32            per-slot live lengths
      map:     [num_slots, cap/B] int32     logical -> physical block
      k_scale/v_scale: [total_blocks, B, nkv, 1] fp32 (int8 pools)

    attention_apply recognizes this type and takes the block-native
    path: the step's k/v scatter ONLY into the touched arena blocks
    (O(slots * tokens) bytes, not O(pool)), and the attention read
    walks each slot's block chain through the map inside the kernel.
    Causal self-attention with per-slot vector offsets only; ROLLING
    (ring) layouts are excluded — the engine keeps the view bracket
    for those."""
    k: jax.Array
    v: jax.Array
    offset: jax.Array
    map: jax.Array
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None


def _block_native_update_attend(q, k, v, cache: BlockKVCache, *,
                                scale: float, dtype):
    """Block-native KV append + kernel attention for one layer.

    Append: row i's s tokens land at positions offset[i]..offset[i]+s-1
    — physical block map[i, pos // B], in-block slot pos % B — as ONE
    scatter touching only the written blocks (`mode="drop"` vanishes
    writes past the region for rows parked at the capacity clamp, the
    same contract as the contiguous per-slot scatter). Idle rows
    (map parked on the shared TRASH block) write their garbage there,
    exactly where scatter_view used to land it.

    Read: the Pallas kernel walks the map — q attends each slot's
    block-chained K/V causally from its own offset, dequantizing int8
    in kernel. Write-before-read holds like the dot path: the kernel
    consumes the post-append arena."""
    from megatron_tpu.ops.block_attention_pallas import \
        block_native_attention
    S, s, nq, hd = q.shape
    T, B, nkv, _ = cache.k.shape
    nb = cache.map.shape[1]
    cap = nb * B
    offset = cache.offset
    pos = offset[:, None] + jnp.arange(s)[None, :]          # [S, s]
    blk_log = jnp.minimum(pos // B, nb - 1)
    phys = jnp.take_along_axis(cache.map, blk_log, axis=1)  # [S, s]
    # out-of-region writes (idle rows at the clamp with s > 1) index
    # past the arena and are DROPPED — never wrap, never collide
    phys = jnp.where(pos >= cap, jnp.int32(T), phys)
    inblk = pos % B

    def wr(arena, val):
        return arena.at[phys, inblk].set(val.astype(arena.dtype),
                                         mode="drop")

    if cache.k.dtype == jnp.int8:
        from megatron_tpu.ops.quantized import quantize_rows
        ki, ks = quantize_rows(k)  # per (slot, token, head) scales
        vi, vs = quantize_rows(v)
        cache = cache._replace(
            k=wr(cache.k, ki), v=wr(cache.v, vi),
            k_scale=wr(cache.k_scale, ks),
            v_scale=wr(cache.v_scale, vs),
            offset=offset + s)
    else:
        cache = cache._replace(k=wr(cache.k, k), v=wr(cache.v, v),
                               offset=offset + s)
    # TP-sharded serving (serving/topology.py): XLA cannot partition a
    # custom call, so with a tp mesh active the kernel runs under an
    # explicit shard_map on the head-sharded arena — each tp shard
    # walks its OWN nkv/tp kv heads' block chains (the GQA head loop
    # shrinks per shard; attention is per-head independent, so no
    # collective inside). Single-device traces (mesh None) lower the
    # bare call, bit-identical to before.
    from megatron_tpu.parallel.sharding import active_tp_mesh
    mesh = active_tp_mesh()
    if mesh is None:
        out = block_native_attention(
            q, cache.k, cache.v, cache.map, offset, scale=scale,
            block_size=B, k_scale=cache.k_scale, v_scale=cache.v_scale)
    else:
        from jax.sharding import PartitionSpec as P
        from megatron_tpu.parallel.mesh import TENSOR_AXIS
        tp = mesh.shape[TENSOR_AXIS]
        assert nq % tp == 0 and nkv % tp == 0, (
            f"block_native_attn under serving_tp={tp} needs query "
            f"({nq}) and kv ({nkv}) head counts divisible by tp — "
            "serve with the resolve/scatter bracket instead "
            "(ServingConfig.validate rejects this combination)")
        h_spec = P(None, None, TENSOR_AXIS, None)
        quant = cache.k_scale is not None

        def _kern(q_, k_, v_, m_, off_, *sc):
            ks_, vs_ = sc if quant else (None, None)
            return block_native_attention(
                q_, k_, v_, m_, off_, scale=scale, block_size=B,
                k_scale=ks_, v_scale=vs_)

        args = [q, cache.k, cache.v, cache.map, offset]
        in_specs = [h_spec, h_spec, h_spec, P(), P()]
        if quant:
            args += [cache.k_scale, cache.v_scale]
            in_specs += [h_spec, h_spec]
        out = jax.shard_map(_kern, mesh=mesh,
                            in_specs=tuple(in_specs),
                            out_specs=h_spec, check_vma=False)(*args)
    return out.astype(dtype), cache


def attention_init(rng, cfg: ModelConfig, dtype=jnp.float32):
    """Params: wq [h, nq*hd], wkv [h, 2*nkv*hd], wo [nq*hd, h]."""
    h = cfg.hidden_size
    hd = cfg.kv_channels
    nq = cfg.num_attention_heads
    nkv = cfg.num_kv_heads
    k1, k2, k3 = jax.random.split(rng, 3)
    std = cfg.init_method_std
    out_std = std / math.sqrt(2.0 * cfg.num_layers) if cfg.use_scaled_init else std
    params = {
        "wq": jax.random.normal(k1, (h, nq * hd), dtype) * std,
        "wkv": jax.random.normal(k2, (h, 2 * nkv * hd), dtype) * std,
        "wo": jax.random.normal(k3, (nq * hd, h), dtype) * out_std,
    }
    if cfg.use_bias:
        params["bq"] = jnp.zeros((nq * hd,), dtype)
        params["bkv"] = jnp.zeros((2 * nkv * hd,), dtype)
        params["bo"] = jnp.zeros((h,), dtype)
    return params


def attention_axes(cfg: ModelConfig):
    axes = {
        "wq": ("embed", "heads"),
        "wkv": ("embed", "kv_heads"),
        "wo": ("heads", "embed"),
    }
    if cfg.use_bias:
        axes.update({"bq": ("heads",), "bkv": ("kv_heads",), "bo": ("embed",)})
    return axes


def _dot_attention(q, k, v, *, causal: bool, softmax_fp32: bool,
                   scale: float, q_offset=None, dropout_rate: float = 0.0,
                   dropout_rng=None, segment_ids=None,
                   sliding_window=None, kv_positions=None):
    """Unfused attention: einsum QK^T -> mask -> softmax -> einsum AV.

    q: [b, s, nq, hd]; k, v: [b, t, nkv, hd]. GQA handled by reshaping q into
    [b, s, nkv, q_per_kv, hd] (equivalent of the reference's kv broadcast at
    transformer.py:448-455, but without materializing the broadcast).
    `q_offset` (scalar) shifts the causal mask for incremental decoding.
    `segment_ids` [b, s] makes the mask block-diagonal across EOD-separated
    documents (ref: --reset_attention_mask, megatron/utils.py:137-194)."""
    b, s, nq, hd = q.shape
    t, nkv = k.shape[1], k.shape[2]
    g = nq // nkv
    qg = q.reshape(b, s, nkv, g, hd)
    scores = jnp.einsum("bsngd,btnd->bngst", qg, k) * scale
    if softmax_fp32:
        scores = scores.astype(jnp.float32)
    # sliding_window is a refinement OF the causal mask; non-causal
    # callers must not set it (attention_apply asserts), so the gate
    # stays causal-or-segments
    if causal or segment_ids is not None:
        if causal:
            q_pos = jnp.arange(s)[None, :]  # [1, s]
            if q_offset is not None:
                # scalar offset (one sequence position for the whole
                # batch) or PER-ROW [b] offsets (the serving engine's
                # slot grid, where every row decodes at its own length)
                off = (q_offset[:, None] if jnp.ndim(q_offset) == 1
                       else q_offset)
                q_pos = q_pos + off  # [b|1, s]
            # kv_positions: the ROLLING cache's slot->position map (slot
            # order is not time order), [t] shared or [b, t] per-row;
            # default is the contiguous layout
            if kv_positions is not None:
                kv_pos = (kv_positions if kv_positions.ndim == 2
                          else kv_positions[None, :])
            else:
                kv_pos = jnp.arange(t)[None, :]  # [1, t]
            win = (q_pos[:, :, None] >= kv_pos[:, None, :])  # [b|1, s, t]
            if sliding_window is not None:
                # banded causal: attend at most the previous W positions
                win = win & (q_pos[:, :, None] - kv_pos[:, None, :]
                             < sliding_window)
            mask = jnp.broadcast_to(win, (b, s, t))
        else:
            mask = jnp.ones((b, s, t), bool)
        if segment_ids is not None:
            assert s == t, "segment masking requires full (non-cached) attn"
            mask = mask & (segment_ids[:, :, None] == segment_ids[:, None, :])
        scores = jnp.where(mask[:, None, None], scores, jnp.finfo(scores.dtype).min)
        # fully-masked rows (e.g. pad queries in their own segment... none
        # here since a pad attends itself) would softmax to NaN; segments
        # always include self so every row keeps >=1 valid entry
    probs = jax.nn.softmax(scores, axis=-1)
    probs = probs.astype(v.dtype)
    if dropout_rate > 0.0 and dropout_rng is not None:
        probs = dropout(dropout_rng, probs, dropout_rate)
    out = jnp.einsum("bngst,btnd->bsngd", probs, v)
    return out.reshape(b, s, nq, hd)


def attention_apply(
    params,
    x,
    cfg: ModelConfig,
    *,
    rope_cos=None,
    rope_sin=None,
    position_ids=None,
    kv_cache: Optional[KVCache] = None,
    layer_number: int = 1,
    dropout_rng=None,
    deterministic: bool = True,
    segment_ids=None,
    causal: bool = True,
    kv_input=None,
    cp_pre_zigzag: bool = False,
    adapters=None,
):
    """Forward pass. x: [b, s, h]. Returns (out [b, s, h], new_kv_cache).

    `causal=False` gives a bidirectional encoder (BERT/T5-encoder,
    ref: megatron/model/transformer.py AttnMaskType.padding).
    `kv_input` switches to CROSS-attention: keys/values projected from the
    encoder output, no rotary on k (ref: transformer.py:664-683 decoder
    cross-attention).

    `adapters`: optional (LoraAdapter per-layer bank, adapter_idx [b])
    pair — the multi-tenant LoRA path (serving/adapters.py). Each batch
    row gathers its own adapter's A/B factors from the bank (one take
    per factor) and adds the low-rank delta x @ A[idx] @ B[idx] to the
    q/k/v/o projections — the Punica batched-gather-grouped-matmul
    shape, with row 0 the identity (zero) adapter so base rows ride the
    same trace. Indices are data: adapters on keeps one compile per
    program; adapters=None compiles to exactly today's graph."""
    b, s, h = x.shape
    hd = cfg.kv_channels
    nq = cfg.num_attention_heads
    nkv = cfg.num_kv_heads
    dtype = x.dtype
    cross = kv_input is not None

    lw = aidx = None
    if adapters is not None:
        lw, aidx = adapters
        assert not cross, (
            "LoRA adapters apply to causal self-attention projections "
            "only (the serving slot grid); cross-attention has no "
            "adapter path")

    def _lora(inp, a, bmat):
        """Per-row low-rank delta: inp [b, s, d_in] -> [b, s, d_out]
        through each row's gathered [d_in, r] / [r, d_out] factors.
        Scaling (alpha/r) is pre-folded into bmat at bank-load time."""
        at = jnp.take(a, aidx, axis=0).astype(dtype)      # [b, d_in, r]
        bt = jnp.take(bmat, aidx, axis=0).astype(dtype)   # [b, r, d_out]
        t = jnp.einsum("bsd,bdr->bsr", inp.astype(dtype), at)
        return jnp.einsum("bsr,brd->bsd", t, bt)

    q = qdense(x, wcast(params["wq"], dtype), cfg.quantized_gemm)
    kv = qdense(kv_input if cross else x, wcast(params["wkv"], dtype),
                cfg.quantized_gemm)
    if cfg.use_bias:
        q = q + params["bq"].astype(dtype)
        kv = kv + params["bkv"].astype(dtype)
    if lw is not None:
        # deltas join BEFORE the head reshape (and therefore before
        # rope): (W + A·B) @ x semantics, the merged-weights oracle the
        # exactness tests pin against
        q = q + _lora(x, lw.aq, lw.bq)
    q = q.reshape(b, s, nq, hd)
    kv = kv.reshape(b, kv.shape[1], 2, nkv, hd)
    k, v = kv[:, :, 0], kv[:, :, 1]
    if lw is not None:
        t = kv.shape[1]
        k = k + _lora(x, lw.ak, lw.bk).reshape(b, t, nkv, hd)
        v = v + _lora(x, lw.av, lw.bv).reshape(b, t, nkv, hd)

    q_offset = None
    per_slot = False
    if kv_cache is not None:
        q_offset = kv_cache.offset
        # PER-SLOT offsets (vector [b]): every batch row sits at its own
        # sequence position — the continuous-batching engine's slot grid
        # (serving/engine.py). s == 1 is the classic decode step; s > 1
        # is the GRID-BATCHED multi-token append (the speculative-decode
        # verify window, serving engine `--speculative_k`): row i writes
        # its s tokens at positions offset[i]..offset[i]+s-1 and the
        # causal mask starts at each row's own offset — prefill_chunk's
        # continuation form generalized from batch-1/scalar-offset to
        # the whole grid with vector offsets.
        per_slot = jnp.ndim(q_offset) == 1
        if per_slot:
            assert not cross, (
                "per-slot (vector) KV-cache offsets support only "
                "self-attention")
        if position_ids is None:
            if per_slot:
                position_ids = q_offset[:, None] + jnp.arange(s)[None, :]
            else:
                position_ids = kv_cache.offset + jnp.arange(s)[None, :]
                position_ids = jnp.broadcast_to(position_ids, (b, s))

    if cfg.use_rotary_emb and not cross:
        assert rope_cos is not None and rope_sin is not None, (
            "cfg.use_rotary_emb=True requires rope_cos/rope_sin tables "
            "(build them with models.language_model.make_rope)")
        q = apply_rotary(q, rope_cos, rope_sin, position_ids)
        k = apply_rotary(k, rope_cos, rope_sin, position_ids)

    # Active attention dropout runs on the dot path AND the flash
    # blockwise path (per-block inverted-dropout masks); the cp rings
    # and the cached prefill exclude it (see the dispatch below).
    # sliding_window refines the CAUSAL mask; a bidirectional caller
    # (BERT/T5-encoder, cross-attention) setting it would be silently
    # ignored by every implementation — fail at trace time instead
    assert cfg.sliding_window is None or (causal and not cross), (
        "sliding_window requires causal self-attention")
    dropout_active = not deterministic and cfg.attention_dropout > 0.0
    if isinstance(kv_cache, BlockKVCache):
        # block-NATIVE serving path (--block_native_attn): append this
        # step's k/v into the touched arena blocks only and read the
        # chain through the map inside the Pallas kernel — the
        # contiguous view (and its resolve/scatter bracket) never
        # exists. Decode (s == 1) and the speculative verify window
        # (s > 1, causal within the window from each row's offset)
        # share this one path.
        assert causal and not cross and segment_ids is None, (
            "block-native attention serves causal self-attention only")
        assert cfg.sliding_window is None, (
            "block-native attention excludes ROLLING (sliding-window) "
            "layouts — the ring's slot->position map breaks the "
            "kernel's contiguous position arithmetic; the engine keeps "
            "the resolve/scatter bracket there (ServingConfig.validate)")
        assert not dropout_active, "no dropout on the serving path"
        out, kv_cache = _block_native_update_attend(
            q, k, v, kv_cache, scale=1.0 / math.sqrt(hd), dtype=dtype)
        out = out.reshape(b, s, nq * hd)
        proj = qdense(out, wcast(params["wo"], dtype), cfg.quantized_gemm)
        if lw is not None:
            proj = proj + _lora(out, lw.ao, lw.bo)
        out = proj
        if cfg.use_bias:
            out = out + params["bo"].astype(dtype)
        return out, kv_cache
    # A cached forward with s > 1 is either an offset-0 prefill
    # (generation.py's whole-prompt pass) or a CONTINUATION chunk at
    # offset > 0 (generation.py prefill_chunk — the serving engine's
    # prefix-cache suffix / chunked prefill): the decode masking
    # generalized to q-len > 1, queries at positions offset..offset+s
    # attending the cache's live region. At offset 0 causal attention
    # over the cache equals plain causal attention over the fresh k/v,
    # so that case can take the flash path on the raw (un-cache-rounded)
    # tensors instead of paying O(s^2) score materialization on the dot
    # path — the reference's prefill pays full unfused attention. The
    # offset-0 condition is ENFORCED below with a lax.cond: an
    # offset > 0 chunk gets the correct cached dot path, not silently
    # wrong flash over the fresh chunk only.
    # per_slot excluded: a grid-batched s > 1 append (speculative
    # verify) has VECTOR offsets — never all-zero (active rows sit at
    # len >= 1), so the offset-0 flash shortcut can't apply and the
    # lax.cond predicate below wouldn't even be a scalar; it takes the
    # cached dot path, the same path the s == 1 grid decode uses.
    # QUANTIZED caches also skip the shortcut (except rolling buffers,
    # which need it for prompts longer than the window): flash-over-raw
    # reads different values than the dequantized int8 cache an
    # offset>0 continuation (prefix suffix, chunk, preemption replay,
    # speculative verify) reads, which is exactly the token-exactness
    # hole the old flash-int8 serving exclusions papered over. Routing
    # the int8 prefill through the cached dot path makes EVERY cached
    # forward read the same dequantized values through the same
    # algorithm — the exclusions are erased structurally, at the cost
    # of O(s^2) score materialization for int8-flash prefills.
    cache_rolling = (kv_cache is not None and cfg.sliding_window is not None
                     and kv_cache.k.shape[1] == cfg.sliding_window)
    cache_quant = kv_cache is not None and kv_cache.k.dtype == jnp.int8
    prefill_flash = (cfg.attention_impl == "flash" and kv_cache is not None
                     and s > 1 and segment_ids is None and causal
                     and not cross and not dropout_active and not per_slot
                     and (not cache_quant or cache_rolling))
    k_raw, v_raw = k, v

    kv_positions = None
    if kv_cache is not None:
        cap = kv_cache.k.shape[1]
        # ROLLING mode: the cache holds only the last `sliding_window`
        # positions (capacity == window). Writes land at position % W and
        # reads mask by the slot->position map below — O(W) serving
        # memory for unbounded streams. Created by init_kv_caches when
        # cfg.sliding_window < max_len.
        rolling = (cfg.sliding_window is not None
                   and cap == cfg.sliding_window)
        quant = kv_cache.k.dtype == jnp.int8
        if quant:
            from megatron_tpu.ops.quantized import quantize_rows
            ki, ks = quantize_rows(k)  # per (b, token, head) over head_dim
            vi, vs = quantize_rows(v)
            if prefill_flash:
                # ROLLING int8 prefill keeps the flash shortcut (a
                # prompt longer than W cannot take the cached dot
                # path), but reads the quantize->dequantize ROUND-TRIP
                # of the fresh k/v, i.e. exactly the values the cache
                # now holds — so continuation steps (which read the
                # dequantized ring) see the same numbers the prefill
                # attended, and a retained rolling prefix clone stays
                # token-consistent with the cache-off path.
                k_raw = ki.astype(dtype) * ks.astype(dtype)
                v_raw = vi.astype(dtype) * vs.astype(dtype)
        if per_slot:
            # serving slot grid: row i writes its s tokens' k/v at its
            # own offset[i]..offset[i]+s-1 (one scatter, [b, s] index
            # grids) — through the ring (position % W) when the buffer
            # is rolling. s > 1 is the speculative-verify window; its
            # rewind invariant (rejected-position KV overwritten
            # write-before-read) cannot hold on a rolling ring, so the
            # engine excludes that combination (ServingConfig.validate).
            assert s == 1 or not rolling, (
                "per-slot multi-token appends (speculative verify) are "
                "undefined on ROLLING caches: a rejected draft's ring "
                "write already evicted history — see "
                "ServingConfig.validate")
            rows = jnp.arange(b)[:, None]
            slots = kv_cache.offset[:, None] + jnp.arange(s)[None, :]
            if rolling:
                slots = slots % cap
            # mode="drop": a row parked at the capacity clamp
            # (serving/engine.py keeps device lengths <= max_len-1)
            # would index past the region with s > 1 — those writes are
            # garbage for garbage rows and must vanish, not wrap or
            # collide nondeterministically at cap-1
            def wr(buf, val):
                return buf.at[rows, slots].set(val.astype(buf.dtype),
                                               mode="drop")

            if quant:
                kv_cache = KVCache(wr(kv_cache.k, ki), wr(kv_cache.v, vi),
                                   kv_cache.offset + s,
                                   wr(kv_cache.k_scale, ks),
                                   wr(kv_cache.v_scale, vs))
            else:
                kv_cache = KVCache(wr(kv_cache.k, k), wr(kv_cache.v, v),
                                   kv_cache.offset + s)
            if rolling:
                # per-row map: slot j holds the largest p <= t_last[row]
                # with p % W == j (sentinel for never-written slots)
                t_last = kv_cache.offset[:, None] - 1  # [b, 1]
                j = jnp.arange(cap)[None, :]
                p = t_last - ((t_last - j) % cap)
                kv_positions = jnp.where(p >= 0, p, jnp.int32(2 ** 30))
        elif rolling:
            # tokens beyond the window never survive a chunked write:
            # keep only the last min(s, W) and scatter to their slots
            # (unique by construction). Multi-token chunks are CORRECT
            # when (a) routed through the offset-0 flash prefill (outputs
            # come from the raw k/v; the cache just ends in the right
            # state) or (b) s <= W at offset 0 on the dot path (nothing
            # is overwritten). Mid-stream s > 1 chunks would need history
            # this buffer already dropped — generation.py only prefills
            # at offset 0, which is the caller contract here.
            assert s == 1 or prefill_flash or s <= cap, (
                "rolling KV cache: multi-token steps need the flash "
                "prefill or s <= sliding_window (decode steps are s == 1)")
            n_keep = min(s, cap)  # static: plain slices, no gather
            slots = (kv_cache.offset + (s - n_keep)
                     + jnp.arange(n_keep)) % cap

            def wr(buf, val):
                return buf.at[:, slots].set(
                    val[:, s - n_keep:].astype(buf.dtype))

            if quant:
                kv_cache = KVCache(wr(kv_cache.k, ki), wr(kv_cache.v, vi),
                                   kv_cache.offset + s,
                                   wr(kv_cache.k_scale, ks),
                                   wr(kv_cache.v_scale, vs))
            else:
                kv_cache = KVCache(wr(kv_cache.k, k), wr(kv_cache.v, v),
                                   kv_cache.offset + s)
            # slot j holds the largest position p <= t_last with
            # p % W == j; never-written slots (p < 0) map to a sentinel
            # the causal mask rejects
            t_last = kv_cache.offset - 1
            j = jnp.arange(cap)
            p = t_last - ((t_last - j) % cap)
            kv_positions = jnp.where(p >= 0, p, jnp.int32(2 ** 30))
        else:
            dus = jax.lax.dynamic_update_slice_in_dim
            if quant:
                kv_cache = KVCache(
                    dus(kv_cache.k, ki, kv_cache.offset, axis=1),
                    dus(kv_cache.v, vi, kv_cache.offset, axis=1),
                    kv_cache.offset + s,
                    dus(kv_cache.k_scale, ks, kv_cache.offset, axis=1),
                    dus(kv_cache.v_scale, vs, kv_cache.offset, axis=1))
            else:
                kv_cache = KVCache(
                    dus(kv_cache.k, k.astype(kv_cache.k.dtype),
                        kv_cache.offset, axis=1),
                    dus(kv_cache.v, v.astype(kv_cache.v.dtype),
                        kv_cache.offset, axis=1),
                    kv_cache.offset + s)
        if quant:
            # dequant at read; XLA fuses convert*scale into the attention
            # dot's operand load, so HBM streams the int8 payload
            k = kv_cache.k.astype(dtype) * kv_cache.k_scale.astype(dtype)
            v = kv_cache.v.astype(dtype) * kv_cache.v_scale.astype(dtype)
        else:
            k, v = kv_cache.k.astype(dtype), kv_cache.v.astype(dtype)

    scale = 1.0 / math.sqrt(hd)
    # Note on apply_query_key_layer_scaling: in the reference it divides QK^T
    # by layer_number and the fused softmax multiplies it straight back
    # (ref: transformer.py:172-184, fused_softmax.py:193-196) — a net-no-op
    # fp16 overflow trick. Our softmax always runs in fp32
    # (attention_softmax_in_fp32), so the trick is unnecessary and the flag
    # intentionally has no numerical effect.

    # dropout_active (defined above, with the prefill gate): the cp
    # rings have no dropout plumbing, so a training trace with
    # attention_dropout > 0 routes them to the dot path (validate warns);
    # the flash branch below carries dropout natively. Eval traces
    # (deterministic=True) keep every fused path.
    ring_branch = (cfg.attention_impl in ("ring", "ulysses")
                   and kv_cache is None and segment_ids is None and causal
                   and cfg.sliding_window is None and not dropout_active)
    # a pre-permuted batch MUST reach the ring path: any gating drift
    # between data_zigzag_cp (which told the loss to permute) and this
    # dispatch would apply causal masks to the wrong rows and silently
    # diverge — fail at trace time instead
    assert not cp_pre_zigzag or (ring_branch
                                 and cfg.attention_impl == "ring"), (
        "cp_pre_zigzag=True but the ring-attention path is not taken "
        "(data_zigzag_cp and attention_apply gating drifted)")
    if ring_branch:
        # context-parallel attention over the 'cp' mesh axis (absent in
        # the reference — SURVEY.md §2.8): K/V-rotation ring
        # (parallel/ring_attention.py) or all-to-all head-parallel Ulysses
        # (parallel/ulysses.py)
        mesh = jax.sharding.get_abstract_mesh()  # jit-safe ambient mesh
        if "cp" in mesh.axis_names and not mesh.empty:
            if cfg.attention_impl == "ulysses":
                from megatron_tpu.parallel.ulysses import ulysses_attention
                out = ulysses_attention(q, k, v, mesh, causal=True,
                                        scale=scale)
            else:
                from megatron_tpu.parallel.ring_attention import \
                    ring_attention
                # cp_pre_zigzag: the loss pre-permuted the batch into
                # zigzag order (data_zigzag_cp), so the ring skips its
                # runtime permute-gathers
                out = ring_attention(
                    q, k, v, mesh, causal=True, scale=scale,
                    layout="pre_zigzag" if cp_pre_zigzag else "auto")
        else:
            assert not cp_pre_zigzag, (
                "cp_pre_zigzag=True but no 'cp' mesh is ambient — the "
                "batch was permuted for a ring that will not run")
            from megatron_tpu.ops.flash_attention import flash_attention
            out = flash_attention(q, k, v, causal=True, scale=scale)
    elif cfg.attention_impl == "flash" and kv_cache is None:
        from megatron_tpu.ops.flash_attention import flash_attention
        # segment_ids ride into the kernel (EOD-reset block-diagonal
        # masking, ref: --reset_attention_mask) — O(s) memory where the
        # dot path would materialize the [s, s] scores; sliding_window
        # additionally skips whole blocks outside the band. Active
        # attention dropout stays on this path too (the reference's
        # FA2 dropout_p, ref: transformer.py:514-522): the blockwise
        # impl draws per-block inverted-dropout masks — no O(s^2)
        # demotion when training GPT/Falcon presets with dropout
        out = flash_attention(
            q, k, v, causal=causal, scale=scale, segment_ids=segment_ids,
            sliding_window=cfg.sliding_window,
            dropout_rate=(cfg.attention_dropout
                          if dropout_active and dropout_rng is not None
                          else 0.0),
            dropout_rng=dropout_rng if dropout_active else None)
    elif prefill_flash:
        from megatron_tpu.ops.flash_attention import flash_attention

        if kv_positions is not None:
            # ROLLING cache: the dot fallback below would be silently
            # wrong for an offset>0 chunk (the chunk's own writes already
            # evicted history its early queries need), so a multi-token
            # step is defined ONLY at offset 0 — take flash directly on
            # the raw k/v, and poison the output with NaN for any
            # offset>0 chunked prefill so a contract violation fails
            # loudly at the first logit instead of decoding garbage
            out = flash_attention(
                q, k_raw, v_raw, causal=True, scale=scale,
                sliding_window=cfg.sliding_window)
            out = jnp.where(q_offset == 0, out, jnp.nan)
        else:
            # both branches trace (compile-time cost only); runtime
            # executes one, and only offset 0 gets the flash shortcut
            out = jax.lax.cond(
                q_offset == 0,
                lambda: flash_attention(
                    q, k_raw, v_raw, causal=True, scale=scale,
                    sliding_window=cfg.sliding_window).astype(jnp.float32),
                lambda: _dot_attention(
                    q, k, v, causal=causal,
                    softmax_fp32=cfg.attention_softmax_in_fp32,
                    scale=scale, q_offset=q_offset,
                    segment_ids=segment_ids,
                    sliding_window=cfg.sliding_window,
                    kv_positions=kv_positions).astype(jnp.float32),
            ).astype(dtype)
    else:
        rate = 0.0 if deterministic else cfg.attention_dropout
        out = _dot_attention(
            q, k, v, causal=causal,
            softmax_fp32=cfg.attention_softmax_in_fp32,
            scale=scale, q_offset=q_offset, dropout_rate=rate,
            dropout_rng=dropout_rng, segment_ids=segment_ids,
            sliding_window=cfg.sliding_window,
            kv_positions=kv_positions)

    out = out.reshape(b, s, nq * hd)
    proj = qdense(out, wcast(params["wo"], dtype), cfg.quantized_gemm)
    if lw is not None:
        proj = proj + _lora(out, lw.ao, lw.bo)
    out = proj
    if cfg.use_bias:
        out = out + params["bo"].astype(dtype)
    return out, kv_cache
