"""BERT: bidirectional encoder with MLM + NSP heads.

TPU-native equivalent of the reference's BertModel
(ref: megatron/model/bert_model.py — BertLMHead :55-91, bert_position_ids,
post_language_model_processing :94-121, BertModel :124-242) over the shared
transformer stack. Structure:

- embeddings: word + learned position + tokentype (ref: language_model.py:
  133-326 Embedding with num_tokentypes=2)
- encoder: post-LN bidirectional transformer (causal=False)
- pooler: dense+tanh over [CLS] (ref: language_model.py Pooler)
- MLM head: dense+gelu+LN then decode against the (tied) embedding matrix
  (ref: bert_model.py:55-91)
- NSP head: binary dense over the pooled output (ref: bert_model.py:171-176)
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from megatron_tpu.config import ModelConfig
from megatron_tpu.models import transformer as tfm
from megatron_tpu.models.norms import apply_norm, norm_axes, norm_init
from megatron_tpu.ops.cross_entropy import cross_entropy_loss


def bert_config(**overrides) -> ModelConfig:
    """bert-base-ish defaults (ref: examples/pretrain_bert.sh flags)."""
    base = dict(
        num_layers=12, hidden_size=768, num_attention_heads=12,
        vocab_size=30522, seq_length=512, use_rotary_emb=False,
        use_position_embedding=True, norm_type="layernorm",
        activation="gelu", use_bias=True, use_post_ln=True,
        tie_embed_logits=True,
    )
    base.update(overrides)
    return ModelConfig(**base).derived()


def bert_init(rng, cfg: ModelConfig, num_tokentypes: int = 2,
              dtype=jnp.float32):
    ks = jax.random.split(rng, 7)
    h = cfg.hidden_size
    v = cfg.padded_vocab_size
    std = cfg.init_method_std
    params = {
        "embedding": {
            "word_embeddings": jax.random.normal(ks[0], (v, h), dtype) * std,
            "position_embeddings": jax.random.normal(
                ks[1], (cfg.max_position_embeddings, h), dtype) * std,
            "tokentype_embeddings": jax.random.normal(
                ks[2], (num_tokentypes, h), dtype) * std,
        },
        # BERT is post-LN but still normalizes the embeddings
        # (ref: language_model.py embedding dropout + encoder's initial LN
        # in the post-LN arrangement)
        "embedding_norm": norm_init(cfg.norm_type, h, dtype),
        "transformer": tfm.stack_init(ks[3], cfg, dtype=dtype),
        "pooler": {"w": jax.random.normal(ks[4], (h, h), dtype) * std,
                   "b": jnp.zeros((h,), dtype)},
        "lm_head": {  # transform before tied decode (ref: bert_model.py:55-91)
            "dense": {"w": jax.random.normal(ks[5], (h, h), dtype) * std,
                      "b": jnp.zeros((h,), dtype)},
            "norm": norm_init(cfg.norm_type, h, dtype),
            "bias": jnp.zeros((v,), dtype),
        },
        "binary_head": {"w": jax.random.normal(ks[6], (h, 2), dtype) * std,
                        "b": jnp.zeros((2,), dtype)},
    }
    return params


def bert_axes(cfg: ModelConfig):
    return {
        "embedding": {
            "word_embeddings": ("vocab", "embed"),
            "position_embeddings": (None, "embed"),
            "tokentype_embeddings": (None, "embed"),
        },
        "embedding_norm": norm_axes(cfg.norm_type),
        "transformer": tfm.stack_axes(cfg),
        "pooler": {"w": ("embed", "embed"), "b": ("embed",)},
        "lm_head": {
            "dense": {"w": ("embed", "embed"), "b": ("embed",)},
            "norm": norm_axes(cfg.norm_type),
            "bias": ("vocab",),
        },
        "binary_head": {"w": ("embed", None), "b": (None,)},
    }


def strip_pretraining_heads(tree):
    """Drop the MLM/NSP heads, keeping the encoder+pooler — the base for
    classification / multiple-choice / biencoder towers
    (ref: bert_model.py add_lm_head/add_binary_head toggles)."""
    return {k: v for k, v in tree.items()
            if k not in ("lm_head", "binary_head")}


def bert_encode(params, tokens, cfg: ModelConfig, *, tokentype_ids=None,
                padding_mask=None, rng=None, deterministic: bool = True):
    """Shared encoder: tokens [b, s] -> (hidden [b, s, h], pooled [b, h]).
    The building block for the MLM model, classification / multiple-choice
    heads, and the ICT biencoder towers (ref: bert_model.py:124-242 with
    add_binary_head/add_lm_head toggles)."""
    from megatron_tpu.config import as_dtype
    compute_dtype = as_dtype(cfg.compute_dtype)
    b, s = tokens.shape
    emb = params["embedding"]
    x = emb["word_embeddings"][tokens]
    x = x + emb["position_embeddings"][jnp.arange(s)][None]
    if tokentype_ids is not None:
        x = x + emb["tokentype_embeddings"][tokentype_ids]
    x = x.astype(compute_dtype)
    x = apply_norm(cfg.norm_type, params["embedding_norm"], x,
                   cfg.norm_epsilon)
    if rng is not None and not deterministic and cfg.hidden_dropout > 0.0:
        # embedding-output dropout (ref: language_model.py:226-258
        # Embedding.forward embedding_dropout) — same placement as the
        # pipelined intake in bert_1f1b_fns so pp=1 and pp>1 train
        # identically
        from megatron_tpu.ops.dropout import dropout as _drop
        rng, r_emb = jax.random.split(rng)
        x = _drop(r_emb, x, cfg.hidden_dropout)
    seg = None
    if padding_mask is not None:
        seg = bert_pad_segments(padding_mask)
    assert cfg.num_experts == 1, (
        "MoE aux-loss accumulation is only wired into the GPT loss path")
    x, _, _ = tfm.stack_apply(params["transformer"], x, cfg, causal=False,
                           segment_ids=seg, rng=rng,
                           deterministic=deterministic)
    return x, bert_pool(params, x, compute_dtype)


def bert_pool(params, x, compute_dtype):
    """dense+tanh over [CLS] (ref: language_model.py Pooler)."""
    return jnp.tanh(x[:, 0] @ params["pooler"]["w"].astype(compute_dtype)
                    + params["pooler"]["b"].astype(compute_dtype))


def bert_lm_logits(params, x, cfg: ModelConfig, compute_dtype):
    """MLM head: dense+gelu+LN then tied decode + bias
    (ref: bert_model.py:55-91). Shared by the sequential forward and the
    pipelined per-microbatch head so pp=1 and pp>1 run the same math."""
    lh = params["lm_head"]
    y = x @ lh["dense"]["w"].astype(compute_dtype) + \
        lh["dense"]["b"].astype(compute_dtype)
    y = jax.nn.gelu(y, approximate=False)
    y = apply_norm(cfg.norm_type, lh["norm"], y, cfg.norm_epsilon)
    w_out = params["embedding"]["word_embeddings"].T.astype(compute_dtype)
    return (y @ w_out).astype(jnp.float32) + lh["bias"].astype(jnp.float32)


def bert_nsp_logits(params, pooled, compute_dtype):
    """NSP binary head over the pooled output (ref: bert_model.py:171-176)."""
    return (pooled @ params["binary_head"]["w"].astype(compute_dtype)
            + params["binary_head"]["b"].astype(compute_dtype)
            ).astype(jnp.float32)


def bert_forward(params, tokens, cfg: ModelConfig, *, tokentype_ids=None,
                 padding_mask=None, rng=None, deterministic: bool = True):
    """tokens [b, s] -> (lm_logits [b, s, V], nsp_logits [b, 2]).

    `padding_mask` [b, s] 1=real: padded positions are excluded from
    attention via segment isolation (pad gets its own segment)."""
    from megatron_tpu.config import as_dtype
    compute_dtype = as_dtype(cfg.compute_dtype)
    x, pooled = bert_encode(params, tokens, cfg, tokentype_ids=tokentype_ids,
                            padding_mask=padding_mask, rng=rng,
                            deterministic=deterministic)
    return (bert_lm_logits(params, x, cfg, compute_dtype),
            bert_nsp_logits(params, pooled, compute_dtype))


def bert_pad_segments(padding_mask):
    """padding_mask [.., s] 1=real -> segment ids isolating each pad
    position (real tokens segment 0)."""
    s = padding_mask.shape[-1]
    return jnp.where(padding_mask > 0, 0,
                     2 + jnp.arange(s)).astype(jnp.int32)


def bert_1f1b_fns(cfg: ModelConfig, deterministic: bool = True):
    """(intake_fn, chunk_fn, head_loss_fn) pipelining BERT over 'pp' via
    parallel/pipeline.py's generic 1F1B core — the custom-loss pipelining
    the reference reaches through its forward_step_func plug into the 1F1B
    schedule (ref: megatron/schedules.py:606-722 + pretrain_bert.py
    forward_step). Streams come from bert_1f1b_streams."""
    from megatron_tpu.config import as_dtype
    from megatron_tpu.ops.dropout import dropout as _drop
    # the BERT chunk fn returns bare h (no MoE router-aux threading);
    # _chunk_ret would read aux==0 and silently drop the balance loss
    assert cfg.num_experts == 1, (
        "BERT pipeline spec has no MoE router-aux threading")
    compute_dtype = as_dtype(cfg.compute_dtype)

    def intake(shared_p, sl, rng_mb):
        emb = shared_p["embedding"]
        tok = sl["tokens"]
        s = tok.shape[-1]
        x = emb["word_embeddings"][tok]
        x = x + emb["position_embeddings"][jnp.arange(s)][None]
        if "tokentype_ids" in sl:
            x = x + emb["tokentype_embeddings"][sl["tokentype_ids"]]
        x = x.astype(compute_dtype)
        x = apply_norm(cfg.norm_type, shared_p["embedding_norm"], x,
                       cfg.norm_epsilon)
        if rng_mb is not None and not deterministic and \
                cfg.hidden_dropout > 0.0:
            x = _drop(jax.random.fold_in(rng_mb, 0), x, cfg.hidden_dropout)
        return x

    def chunk(cp, h, sl, offset, rng_mb):
        layer_rng = (jax.random.fold_in(rng_mb, 1)
                     if rng_mb is not None and not deterministic else None)
        seg = bert_pad_segments(sl["padding_mask"]) \
            if "padding_mask" in sl else None
        return tfm.stack_apply(cp, h, cfg, causal=False, segment_ids=seg,
                               rng=layer_rng, deterministic=deterministic,
                               layer_offset=offset)[0]

    def head_loss(shared_p, h, sl, rng_mb):
        # the per-microbatch tail of bert_forward/bert_loss, via the SAME
        # head helpers the sequential path uses (no drift between pp=1
        # and pp>1)
        lm_logits = bert_lm_logits(shared_p, h, cfg, compute_dtype)
        losses = cross_entropy_loss(lm_logits, sl["labels"],
                                    vocab_size=cfg.vocab_size)
        mask = sl["loss_mask"].astype(jnp.float32)
        total = jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        if "is_random" in sl:
            nsp_logits = bert_nsp_logits(
                shared_p, bert_pool(shared_p, h, compute_dtype),
                compute_dtype)
            total = total + jnp.mean(
                cross_entropy_loss(nsp_logits, sl["is_random"]))
        return total

    return intake, chunk, head_loss


def bert_loss(params, batch, cfg: ModelConfig, *, rng=None,
              deterministic: bool = True):
    """MLM + NSP loss (ref: bert_model.py post_language_model_processing +
    pretrain_bert.py forward_step). batch: {tokens, labels, loss_mask,
    tokentype_ids?, padding_mask?, is_random?}."""
    lm_logits, nsp_logits = bert_forward(
        params, batch["tokens"], cfg,
        tokentype_ids=batch.get("tokentype_ids"),
        padding_mask=batch.get("padding_mask"),
        rng=rng, deterministic=deterministic)
    losses = cross_entropy_loss(lm_logits, batch["labels"],
                                vocab_size=cfg.vocab_size)
    mask = batch["loss_mask"].astype(jnp.float32)
    lm_loss = jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    total = lm_loss
    if "is_random" in batch:
        nsp = cross_entropy_loss(nsp_logits, batch["is_random"])
        total = total + jnp.mean(nsp)
    return total
