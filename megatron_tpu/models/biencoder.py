"""ICT / REALM biencoder: dual BERT towers for retrieval pretraining.

TPU-native equivalent of the reference's retriever stack
(ref: megatron/model/biencoder_model.py:71-370 BiEncoderModel,
megatron/data/realm_index.py:17-224 OpenRetreivalDataStore/FaissMIPSIndex,
pretrain_ict.py). Structure:

- query tower + context tower: each a BERT encoder + pooler
  (bert_encode), optionally SHARED (`shared=True` ==
  biencoder_shared_query_context_model, ref: biencoder_model.py:94-115).
- optional projection to `ict_head_size` when the retrieval embedding is
  smaller than hidden (ref: biencoder_model.py:289-312 projection_enabled).
- in-batch retrieval loss: scores = q_emb @ c_emb^T / sqrt(d) with the
  diagonal as positives — the ICT training objective
  (ref: pretrain_ict.py forward_step's softmax over the batch).
- MIPSIndex: exact top-k inner-product search over block embeddings as one
  jitted matmul — on TPU the MXU makes brute-force exact search the
  idiomatic replacement for the reference's FaissMIPSIndex (which is
  approximate by default and CPU/GPU-library bound).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from megatron_tpu.config import ModelConfig
from megatron_tpu.models.bert import (bert_axes, bert_encode, bert_init,
                                      strip_pretraining_heads)


def _tower_init(rng, cfg, ict_head_size, dtype):
    k_bert, k_proj = jax.random.split(rng)
    tower = strip_pretraining_heads(bert_init(k_bert, cfg, dtype=dtype))
    if ict_head_size is not None:
        tower["ict_head"] = {
            "w": jax.random.normal(k_proj, (cfg.hidden_size, ict_head_size),
                                   dtype) * cfg.init_method_std,
            "b": jnp.zeros((ict_head_size,), dtype),
        }
    return tower


def biencoder_init(rng, cfg: ModelConfig, *,
                   ict_head_size: Optional[int] = None,
                   shared: bool = False, dtype=jnp.float32):
    """(ref: biencoder_model.py:94-115: separate or shared towers)."""
    kq, kc = jax.random.split(rng)
    if shared:
        return {"shared_model": _tower_init(kq, cfg, ict_head_size, dtype)}
    return {"query_model": _tower_init(kq, cfg, ict_head_size, dtype),
            "context_model": _tower_init(kc, cfg, ict_head_size, dtype)}


def biencoder_axes(cfg: ModelConfig, *, ict_head_size=None,
                   shared: bool = False):
    tower = strip_pretraining_heads(bert_axes(cfg))
    if ict_head_size is not None:
        tower = dict(tower, ict_head={"w": ("embed", None), "b": (None,)})
    if shared:
        return {"shared_model": tower}
    return {"query_model": tower, "context_model": tower}


def embed_text(tower, tokens, cfg: ModelConfig, *, padding_mask=None,
               tokentype_ids=None, rng=None, deterministic: bool = True):
    """One tower: tokens [b, s] -> retrieval embedding [b, d]
    (ref: biencoder_model.py:145-151 embed_text)."""
    from megatron_tpu.config import as_dtype
    compute_dtype = as_dtype(cfg.compute_dtype)
    _, pooled = bert_encode(tower, tokens, cfg, tokentype_ids=tokentype_ids,
                            padding_mask=padding_mask, rng=rng,
                            deterministic=deterministic)
    if "ict_head" in tower:
        head = tower["ict_head"]
        pooled = pooled @ head["w"].astype(compute_dtype) + \
            head["b"].astype(compute_dtype)
    return pooled.astype(jnp.float32)


def _towers(params):
    if "shared_model" in params:
        return params["shared_model"], params["shared_model"]
    return params["query_model"], params["context_model"]


def biencoder_forward(params, query_tokens, context_tokens,
                      cfg: ModelConfig, *, query_pad_mask=None,
                      context_pad_mask=None, rng=None,
                      deterministic: bool = True):
    """-> (query_emb [b, d], context_emb [b, d])
    (ref: biencoder_model.py:123-143 forward)."""
    rq = rc = None
    if rng is not None and not deterministic:
        rq, rc = jax.random.split(rng)
    q_tower, c_tower = _towers(params)
    q = embed_text(q_tower, query_tokens, cfg, padding_mask=query_pad_mask,
                   rng=rq, deterministic=deterministic)
    c = embed_text(c_tower, context_tokens, cfg,
                   padding_mask=context_pad_mask, rng=rc,
                   deterministic=deterministic)
    return q, c


def retrieval_loss(params, batch, cfg: ModelConfig, *, rng=None,
                   deterministic: bool = True):
    """In-batch softmax retrieval loss: row i's positive is context i
    (ref: pretrain_ict.py forward_step). batch: {query_tokens,
    context_tokens, query_pad_mask?, context_pad_mask?}. Returns
    (loss, accuracy)."""
    q, c = biencoder_forward(
        params, batch["query_tokens"], batch["context_tokens"], cfg,
        query_pad_mask=batch.get("query_pad_mask"),
        context_pad_mask=batch.get("context_pad_mask"),
        rng=rng, deterministic=deterministic)
    scores = q @ c.T / jnp.sqrt(jnp.float32(q.shape[-1]))
    logprobs = jax.nn.log_softmax(scores, axis=-1)
    b = scores.shape[0]
    loss = -jnp.mean(jnp.diagonal(logprobs))
    acc = jnp.mean(jnp.argmax(scores, axis=-1) == jnp.arange(b))
    return loss, acc


class MIPSIndex:
    """Exact max-inner-product index over block embeddings
    (ref: megatron/data/realm_index.py:118-224 FaissMIPSIndex +
    OpenRetreivalDataStore). One jitted matmul + top_k: exact, MXU-bound."""

    def __init__(self, embed_dim: int):
        self.embed_dim = embed_dim
        self._ids: list[int] = []
        self._embeds: list[np.ndarray] = []
        self._matrix = None

        def _search(matrix, queries, k):
            scores = queries @ matrix.T
            top_s, top_i = jax.lax.top_k(scores, k)
            return top_s, top_i

        self._search = jax.jit(_search, static_argnames=("k",))

    def add_block_data(self, row_ids, block_embeds):
        """(ref: realm_index.py:61-73)"""
        block_embeds = np.asarray(block_embeds, np.float32)
        assert block_embeds.shape[-1] == self.embed_dim
        self._ids.extend(int(i) for i in np.asarray(row_ids).ravel())
        self._embeds.append(block_embeds.reshape(-1, self.embed_dim))
        self._matrix = None  # rebuilt lazily

    def __len__(self):
        return len(self._ids)

    def search_mips_index(self, query_embeds, top_k: int):
        """-> (scores [b, k], block_ids [b, k])
        (ref: realm_index.py:199-224 search_mips_index)."""
        if self._matrix is None:
            self._matrix = jnp.asarray(np.concatenate(self._embeds, axis=0))
        q = jnp.asarray(np.asarray(query_embeds, np.float32))
        k = min(top_k, len(self._ids))
        scores, idx = self._search(self._matrix, q, k)
        ids = np.asarray(self._ids)[np.asarray(idx)]
        return np.asarray(scores), ids
