"""Classification and multiple-choice heads over the BERT encoder.

TPU-native equivalents of the reference's finetuning heads
(ref: megatron/model/classification.py:1-107 Classification,
megatron/model/multiple_choice.py:1-120 MultipleChoice). Both are the BERT
encoder + pooler with a dropout + dense head over the pooled output; the
multiple-choice variant flattens [b, num_choices, s] to a batch of
[b*num_choices, s], scores each choice with a 1-unit head, and reshapes
back to [b, num_choices] (ref: multiple_choice.py:84-113).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from megatron_tpu.config import ModelConfig
from megatron_tpu.models.bert import (bert_axes, bert_encode, bert_init,
                                      strip_pretraining_heads as
                                      _strip_lm_heads)
from megatron_tpu.ops.cross_entropy import cross_entropy_loss
from megatron_tpu.ops.dropout import dropout


def classification_init(rng, cfg: ModelConfig, num_classes: int,
                        dtype=jnp.float32):
    """(ref: classification.py:33-45 — encoder + classification_head)."""
    k_bert, k_head = jax.random.split(rng)
    params = _strip_lm_heads(bert_init(k_bert, cfg, dtype=dtype))
    params["classification_head"] = {
        "w": jax.random.normal(k_head, (cfg.hidden_size, num_classes),
                               dtype) * cfg.init_method_std,
        "b": jnp.zeros((num_classes,), dtype),
    }
    return params


def classification_axes(cfg: ModelConfig):
    axes = _strip_lm_heads(bert_axes(cfg))
    axes["classification_head"] = {"w": ("embed", None), "b": (None,)}
    return axes


def classification_forward(params, tokens, cfg: ModelConfig, *,
                           tokentype_ids=None, padding_mask=None, rng=None,
                           deterministic: bool = True):
    """tokens [b, s] -> logits [b, num_classes]
    (ref: classification.py:62-88: pooled -> dropout -> dense)."""
    from megatron_tpu.config import as_dtype
    compute_dtype = as_dtype(cfg.compute_dtype)
    r_enc = r_drop = None
    if rng is not None and not deterministic:
        r_enc, r_drop = jax.random.split(rng)
    _, pooled = bert_encode(params, tokens, cfg, tokentype_ids=tokentype_ids,
                            padding_mask=padding_mask, rng=r_enc,
                            deterministic=deterministic)
    if not deterministic and cfg.hidden_dropout > 0.0:
        pooled = dropout(r_drop, pooled, cfg.hidden_dropout)
    head = params["classification_head"]
    logits = pooled @ head["w"].astype(compute_dtype) + \
        head["b"].astype(compute_dtype)
    return logits.astype(jnp.float32)


def classification_loss(params, batch, cfg: ModelConfig, *, rng=None,
                        deterministic: bool = True):
    """batch: {tokens, label, tokentype_ids?, padding_mask?}
    (ref: tasks/finetune_utils.py cross-entropy over class logits)."""
    logits = classification_forward(
        params, batch["tokens"], cfg,
        tokentype_ids=batch.get("tokentype_ids"),
        padding_mask=batch.get("padding_mask"),
        rng=rng, deterministic=deterministic)
    return jnp.mean(cross_entropy_loss(logits, batch["label"]))


def multiple_choice_init(rng, cfg: ModelConfig, dtype=jnp.float32):
    """(ref: multiple_choice.py:37-48 — 1-unit head over pooled output)."""
    k_bert, k_head = jax.random.split(rng)
    params = _strip_lm_heads(bert_init(k_bert, cfg, dtype=dtype))
    params["multichoice_head"] = {
        "w": jax.random.normal(k_head, (cfg.hidden_size, 1),
                               dtype) * cfg.init_method_std,
        "b": jnp.zeros((1,), dtype),
    }
    return params


def multiple_choice_axes(cfg: ModelConfig):
    axes = _strip_lm_heads(bert_axes(cfg))
    axes["multichoice_head"] = {"w": ("embed", None), "b": (None,)}
    return axes


def multiple_choice_forward(params, tokens, cfg: ModelConfig, *,
                            tokentype_ids=None, padding_mask=None, rng=None,
                            deterministic: bool = True):
    """tokens [b, num_choices, s] -> logits [b, num_choices]
    (ref: multiple_choice.py:84-113 flatten/score/reshape)."""
    from megatron_tpu.config import as_dtype
    compute_dtype = as_dtype(cfg.compute_dtype)
    b, c, s = tokens.shape
    flat = lambda x: None if x is None else x.reshape(b * c, s)  # noqa: E731
    r_enc = r_drop = None
    if rng is not None and not deterministic:
        r_enc, r_drop = jax.random.split(rng)
    _, pooled = bert_encode(params, flat(tokens), cfg,
                            tokentype_ids=flat(tokentype_ids),
                            padding_mask=flat(padding_mask), rng=r_enc,
                            deterministic=deterministic)
    if not deterministic and cfg.hidden_dropout > 0.0:
        pooled = dropout(r_drop, pooled, cfg.hidden_dropout)
    head = params["multichoice_head"]
    scores = pooled @ head["w"].astype(compute_dtype) + \
        head["b"].astype(compute_dtype)
    return scores.reshape(b, c).astype(jnp.float32)


def multiple_choice_loss(params, batch, cfg: ModelConfig, *, rng=None,
                         deterministic: bool = True):
    """batch: {tokens [b,c,s], label [b], ...}."""
    logits = multiple_choice_forward(
        params, batch["tokens"], cfg,
        tokentype_ids=batch.get("tokentype_ids"),
        padding_mask=batch.get("padding_mask"),
        rng=rng, deterministic=deterministic)
    return jnp.mean(cross_entropy_loss(logits, batch["label"]))
