"""Embedding, LM head, and the full causal language model.

TPU-native equivalent of TransformerLanguageModel / Embedding /
parallel_lm_logits / GPTModel (ref: megatron/model/language_model.py:329-638,
:133-326, :24-53; megatron/model/gpt_model.py:18-100).

- VocabParallelEmbedding's mask-ids-outside-shard + all-reduce
  (ref: core/tensor_parallel/layers.py:187-210) is a plain gather whose table
  carries 'vocab'-axis sharding; GSPMD emits the same collective.
- Untied lm_head (`not tie_embed_logits`) is a separate ('embed','vocab')
  parameter (ref: language_model.py:436-457); tied mode reuses the embedding
  table like parallel_lm_logits (ref: language_model.py:24-53).
- The vocab-parallel cross-entropy with its three TP all-reduces
  (ref: core/tensor_parallel/cross_entropy.py:14-143) is a
  shard-friendly log-softmax cross-entropy in megatron_tpu/ops/cross_entropy.py.
- Activations are [batch, seq, hidden] (batch-major): the reference's
  [s, b, h] transpose (ref: language_model.py:248) existed for NCCL-contiguity
  of sequence-parallel scatters, which GSPMD makes unnecessary.
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from megatron_tpu.config import ModelConfig
from megatron_tpu.models import transformer as tfm
from megatron_tpu.models.norms import apply_norm, norm_axes, norm_init
from megatron_tpu.models.rope import precompute_freqs
from megatron_tpu.ops.cross_entropy import cross_entropy_loss
from megatron_tpu.ops.dropout import dropout
from megatron_tpu.parallel.sharding import constrain


def model_init(rng, cfg: ModelConfig, dtype=None):
    """Full-model parameter tree."""
    from megatron_tpu.config import as_dtype
    dtype = dtype or as_dtype(cfg.params_dtype)
    k_emb, k_stack, k_head, k_pos = jax.random.split(rng, 4)
    v = cfg.padded_vocab_size
    h = cfg.hidden_size
    params = {
        "embedding": {
            "word_embeddings": jax.random.normal(k_emb, (v, h), dtype) * cfg.init_method_std,
        },
        "transformer": tfm.stack_init(k_stack, cfg, dtype=dtype),
        "final_norm": norm_init(cfg.norm_type, h, dtype),
    }
    if cfg.use_position_embedding:
        params["embedding"]["position_embeddings"] = (
            jax.random.normal(k_pos, (cfg.max_position_embeddings, h), dtype)
            * cfg.init_method_std)
    if not cfg.tie_embed_logits:
        params["lm_head"] = jax.random.normal(k_head, (h, v), dtype) * cfg.init_method_std
    return params


def model_axes(cfg: ModelConfig):
    axes = {
        "embedding": {"word_embeddings": ("vocab", "embed")},
        "transformer": tfm.stack_axes(cfg),
        "final_norm": norm_axes(cfg.norm_type),
    }
    if cfg.use_position_embedding:
        axes["embedding"]["position_embeddings"] = (None, "embed")
    if not cfg.tie_embed_logits:
        axes["lm_head"] = ("embed", "vocab")
    return axes


class RopeTables(NamedTuple):
    cos: jax.Array
    sin: jax.Array


def make_rope(cfg: ModelConfig, max_len: Optional[int] = None) -> Optional[RopeTables]:
    if not cfg.use_rotary_emb:
        return None
    max_len = max_len or cfg.max_position_embeddings
    cos, sin = precompute_freqs(
        cfg.kv_channels, max_len, theta=cfg.rope_theta,
        scaling_factor=cfg.rope_scaling_factor)
    return RopeTables(cos, sin)


def model_forward(
    params,
    tokens,  # [b, s] int32
    cfg: ModelConfig,
    *,
    position_ids=None,
    kv_caches=None,
    rope: Optional[RopeTables] = None,
    rng=None,
    deterministic: bool = True,
    logits_dtype=jnp.float32,
    segment_ids=None,
    cp_pre_zigzag: bool = False,
    return_aux: bool = False,
    adapters=None,
):
    """Forward to logits [b, s, padded_vocab]. Returns (logits, kv_caches),
    or (logits, kv_caches, moe_aux) with `return_aux=True` (loss_fn uses
    it to add the MoE router's load-balancing loss).

    `cp_pre_zigzag`: the caller pre-permuted tokens/positions into the
    ring-cp zigzag order (see loss_fn / parallel/ring_attention.py
    data_zigzag_cp) — logits come back in the SAME permuted order.

    `adapters`: (stacked LoraAdapter bank, adapter_idx [b]) — per-row
    low-rank deltas on the attention projections (multi-tenant LoRA
    serving / LoRA finetuning; models/attention.py)."""
    from megatron_tpu.config import as_dtype
    compute_dtype = as_dtype(cfg.compute_dtype)
    emb = params["embedding"]["word_embeddings"]
    x = emb[tokens].astype(compute_dtype)
    if cfg.use_position_embedding:
        if position_ids is None:
            pos = jnp.arange(tokens.shape[1])[None, :]
            if kv_caches is not None:
                # incremental decode: positions continue from the cache offset
                # (all layers share one offset; ref: InferenceParams keeps a
                # single sequence_len_offset, forward_step.py:17-42)
                off = kv_caches.offset[0]
                # per-slot serving pools carry [batch] offsets per layer
                pos = pos + (off[:, None] if jnp.ndim(off) == 1 else off)
        else:
            pos = position_ids
        x = x + params["embedding"]["position_embeddings"][pos].astype(compute_dtype)
    if rope is None:
        rope = make_rope(cfg)
    if rng is not None and not deterministic and cfg.hidden_dropout > 0.0:
        rng, r_emb = jax.random.split(rng)
        x = dropout(r_emb, x, cfg.hidden_dropout)
    # SP: scatter the embedding output along seq (ref: language_model.py:
    # 255-258 scatter_to_sequence_parallel_region); no-op without a mesh ctx
    x = constrain(x, tfm.RESIDUAL_AXES)

    x, kv_caches, aux = tfm.stack_apply(
        params["transformer"], x, cfg,
        rope_cos=rope.cos if rope else None,
        rope_sin=rope.sin if rope else None,
        position_ids=position_ids, kv_caches=kv_caches,
        rng=rng, deterministic=deterministic, segment_ids=segment_ids,
        cp_pre_zigzag=cp_pre_zigzag, adapters=adapters)

    # final norm + SP gather + vocab-parallel head: ONE implementation
    # shared with both pp schedules (head_logits below)
    logits = head_logits(params, x, cfg, logits_dtype=logits_dtype)
    if return_aux:
        return logits, kv_caches, aux
    return logits, kv_caches


def head_logits(params, x, cfg: ModelConfig, *, mb_axis: bool = False,
                logits_dtype=jnp.float32):
    """Final norm + (tied/untied) LM head with SP-aware sharding hints —
    the single implementation behind the sequential forward AND both
    pipelined tails (the lockstep pipeline's post-shard_map head and the
    1F1B per-microbatch head), so execution schedules cannot drift.
    `mb_axis` adds the leading 'microbatch' logical axis used when the
    head work is spread over 'pp'. The seq constrain is the SP gather the
    reference places before parallel_lm_logits (ref: language_model.py:
    24-53 + mappings.py:191-230): logits shard vocab over 'tp', so the
    seq dim must come off it."""
    from megatron_tpu.config import as_dtype
    compute_dtype = as_dtype(cfg.compute_dtype)
    pre = ("microbatch",) if mb_axis else ()
    x = constrain(x, pre + ("batch", "seq_sp", "act_embed"))
    x = apply_norm(cfg.norm_type, params["final_norm"], x, cfg.norm_epsilon)
    x = constrain(x, pre + ("batch", "seq", "act_embed"))
    if cfg.tie_embed_logits:
        w_out = params["embedding"]["word_embeddings"].T
    else:
        w_out = params["lm_head"]
    logits = (x @ w_out.astype(compute_dtype)).astype(logits_dtype)
    return constrain(logits, pre + ("batch", "seq", "vocab"))


def loss_fn(
    params,
    tokens,  # [b, s+1] or (inputs [b,s], labels [b,s])
    cfg: ModelConfig,
    *,
    loss_mask=None,
    rope=None,
    rng=None,
    deterministic: bool = True,
    position_ids=None,
    segment_ids=None,
    adapters=None,
):
    """Causal LM loss: mean CE over unmasked positions
    (ref: finetune.py:83 loss_func — masked mean).

    `adapters` threads a LoRA factor bank + per-row index into the
    forward (training/lora.py differentiates wrt the factors with the
    base frozen — the train-side of multi-tenant adapter serving)."""
    if isinstance(tokens, tuple):
        inputs, labels = tokens
    else:
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
        if loss_mask is not None and loss_mask.shape[1] == tokens.shape[1]:
            loss_mask = loss_mask[:, 1:]

    # ring-cp zigzag: permute the batch ONCE here (ints + mask — cheap)
    # so ring attention skips its per-call q/k/v/out permute-gathers. The
    # masked-mean loss is permutation-invariant because labels and mask
    # ride the same permutation; RoPE/positions stay correct because the
    # permuted position_ids carry the original positions.
    from megatron_tpu.parallel.ring_attention import (data_zigzag_cp,
                                                      zigzag_permutation)
    cp = data_zigzag_cp(cfg, inputs.shape[1], segment_ids=segment_ids)
    pre_zigzag = cp > 0
    if pre_zigzag:
        perm, _ = zigzag_permutation(inputs.shape[1], cp)
        if position_ids is None:
            position_ids = jnp.broadcast_to(
                jnp.arange(inputs.shape[1], dtype=jnp.int32),
                inputs.shape)
        inputs = inputs[:, perm]
        labels = labels[:, perm]
        position_ids = position_ids[:, perm]
        if loss_mask is not None:
            loss_mask = loss_mask[:, perm]

    logits, _, aux = model_forward(params, inputs, cfg, rope=rope, rng=rng,
                                   deterministic=deterministic,
                                   position_ids=position_ids,
                                   segment_ids=segment_ids,
                                   cp_pre_zigzag=pre_zigzag,
                                   return_aux=True, adapters=adapters)
    losses = cross_entropy_loss(logits, labels, vocab_size=cfg.vocab_size)
    # MoE router load-balancing loss (0 for dense stacks)
    aux_term = cfg.moe_aux_loss_coeff * aux if cfg.num_experts > 1 else 0.0
    if loss_mask is None:
        return jnp.mean(losses) + aux_term
    loss_mask = loss_mask.astype(losses.dtype)
    return (jnp.sum(losses * loss_mask)
            / jnp.maximum(jnp.sum(loss_mask), 1.0)) + aux_term
