"""Feed-forward / GLU-family MLP.

TPU-native equivalent of the reference's ParallelMLP
(ref: megatron/model/transformer.py:77-141) and its GLU activation family
liglu/geglu/reglu/swiglu (ref: megatron/model/glu_activations.py:13-55).
The reference's column-parallel h→4h (doubled for GLU) + row-parallel 4h→h
pair becomes two matmuls whose parameters carry 'mlp'-axis sharding; XLA
inserts the row-parallel all-reduce. The jit-fused bias-gelu kernel
(ref: megatron/model/fused_bias_gelu.py, warmed up at initialize.py:208-275)
is unnecessary — XLA fuses bias+activation into the GEMM epilogue.

Sharding note for GLU: the reference doubles one column-parallel projection
so every TP rank holds matching gate/value slices (ref: transformer.py:86-95).
We get the same alignment by shaping w1 as [h, 2, ffn] with the 'mlp' axis on
the ffn dim — the gate/value split is then a leading-index, never crossing a
shard boundary.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from megatron_tpu.config import ModelConfig
from megatron_tpu.ops.quantized import qdense, wcast


def activation_fn(name: str, a, b=None):
    """Activation dispatch (ref: transformer.py:99-124, glu_activations.py:13-55).

    GLU variants take the (gate, value) pair: act(a) * b."""
    if name == "gelu":
        return jax.nn.gelu(a, approximate=False)
    if name == "relu":
        return jax.nn.relu(a)
    if name == "squared_relu":
        r = jax.nn.relu(a)
        return r * r
    if name == "swiglu":
        return jax.nn.silu(a) * b
    if name == "geglu":
        return jax.nn.gelu(a, approximate=False) * b
    if name == "reglu":
        return jax.nn.relu(a) * b
    if name == "liglu":
        return a * b
    raise ValueError(f"unknown activation {name}")


def mlp_init(rng, cfg: ModelConfig, dtype=jnp.float32):
    h = cfg.hidden_size
    ffn = cfg.ffn_hidden_size
    k1, k2 = jax.random.split(rng)
    std = cfg.init_method_std
    out_std = std / math.sqrt(2.0 * cfg.num_layers) if cfg.use_scaled_init else std
    if cfg.is_glu:
        w1 = jax.random.normal(k1, (h, 2, ffn), dtype) * std
        b1_shape = (2, ffn)
    else:
        w1 = jax.random.normal(k1, (h, ffn), dtype) * std
        b1_shape = (ffn,)
    params = {
        "w1": w1,
        "w2": jax.random.normal(k2, (ffn, h), dtype) * out_std,
    }
    if cfg.use_bias:
        params["b1"] = jnp.zeros(b1_shape, dtype)
        params["b2"] = jnp.zeros((h,), dtype)
    return params


def mlp_axes(cfg: ModelConfig):
    if cfg.is_glu:
        axes = {"w1": ("embed", None, "mlp"), "w2": ("mlp", "embed")}
        b1_axes = (None, "mlp")
    else:
        axes = {"w1": ("embed", "mlp"), "w2": ("mlp", "embed")}
        b1_axes = ("mlp",)
    if cfg.use_bias:
        axes.update({"b1": b1_axes, "b2": ("embed",)})
    return axes


def mlp_apply(params, x, cfg: ModelConfig):
    """x: [b, s, h] -> [b, s, h]."""
    dtype = x.dtype
    # GLU: single h -> 2*ffn GEMM, gate/value as leading index of the output
    y = qdense(x, wcast(params["w1"], dtype), cfg.quantized_gemm)
    if cfg.use_bias:
        y = y + params["b1"].astype(dtype)
    if cfg.is_glu:
        y = activation_fn(cfg.activation, y[:, :, 0], y[:, :, 1])
    else:
        y = activation_fn(cfg.activation, y)
    y = qdense(y, wcast(params["w2"], dtype), cfg.quantized_gemm)
    if cfg.use_bias:
        y = y + params["b2"].astype(dtype)
    return y
