"""Mixture-of-Experts MLP with expert parallelism.

ABSENT in the reference (SURVEY.md §2.8: "Expert parallelism (MoE) —
absent") — provided here the TPU-native way, like the ring/Ulysses
context parallelism: experts are one more sharded parameter dimension,
not a process group. The GShard/Switch dense-dispatch formulation keeps
every shape static for XLA:

- router: logits = x @ wr, softmax in fp32, top-k gates renormalized;
- capacity C = ceil(top_k * s * capacity_factor / E) per expert; each
  token takes the next free slot of its chosen experts (cumsum position,
  k=0 round gets priority, overflow tokens drop — the standard Switch
  semantics);
- dispatch/combine are einsums against a [b, s, E, C] one-hot tensor, so
  expert parallelism is purely the 'experts'-axis sharding on the expert
  weight bank [E, ...] — GSPMD inserts the all-to-alls;
- load-balancing aux loss (Switch eq. 4): E * sum_e f_e * P_e, where f_e
  is the top-1 dispatch fraction and P_e the mean router probability.
  loss_fn adds cfg.moe_aux_loss_coeff * aux.

Two dispatch implementations share the same routing semantics (capacity
fills k=0 choices first, then k=1, ...; within a round, earlier sequence
positions win; overflow drops):

- "sort" (default): the (token, k) choices are sorted by expert id
  (stable sort keeps the priority order), the slot index inside each
  expert is rank-minus-segment-start, and tokens move through ONE
  scatter-add into the [E, C, h] expert blocks and one gather back.
  Memory is O(s * top_k * h) — linear in sequence length — so MoE
  composes with long context. The sort itself is O(sK log sK) int32 work
  per layer, noise beside the expert GEMMs.
- "dense": the original GShard einsum against a [b, s, E, C] one-hot
  dispatch tensor — O(s^2 * top_k * capacity_factor) elements. Kept as
  the semantic oracle (sort-vs-dense equality is tested) and for
  explicit A/B on chip.

Expert parallelism is the 'experts'-axis sharding on the weight bank and
the [b, E, C, h] blocks in both paths; GSPMD partitions the dense
einsums directly and the sort path's scatter/gather by resharding the
(small, [b, sK]) index vectors.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from megatron_tpu.config import ModelConfig
from megatron_tpu.models.mlp import activation_fn


def moe_capacity(cfg: ModelConfig, seq: int) -> int:
    return int(math.ceil(cfg.moe_top_k * seq * cfg.moe_capacity_factor
                         / cfg.num_experts))


def moe_init(rng, cfg: ModelConfig, dtype=jnp.float32):
    E = cfg.num_experts
    h = cfg.hidden_size
    ffn = cfg.ffn_hidden_size
    kr, k1, k2 = jax.random.split(rng, 3)
    std = cfg.init_method_std
    out_std = (std / math.sqrt(2.0 * cfg.num_layers)
               if cfg.use_scaled_init else std)
    if cfg.is_glu:
        w1 = jax.random.normal(k1, (E, h, 2, ffn), dtype) * std
    else:
        w1 = jax.random.normal(k1, (E, h, ffn), dtype) * std
    params = {
        "router": jax.random.normal(kr, (h, E), dtype) * std,
        "w1": w1,
        "w2": jax.random.normal(k2, (E, ffn, h), dtype) * out_std,
    }
    if cfg.use_bias:
        b1_shape = (E, 2, ffn) if cfg.is_glu else (E, ffn)
        params["b1"] = jnp.zeros(b1_shape, dtype)
        params["b2"] = jnp.zeros((E, h), dtype)
    return params


def moe_axes(cfg: ModelConfig):
    # experts shard over 'tp' (expert parallelism); the ffn dim stays
    # unsharded — one expert's GEMM runs whole on its device
    w1_axes = (("experts", "embed", None, None) if cfg.is_glu
               else ("experts", "embed", None))
    axes = {
        "router": ("embed", None),
        "w1": w1_axes,
        "w2": ("experts", None, "embed"),
    }
    if cfg.use_bias:
        axes["b1"] = (("experts", None, None) if cfg.is_glu
                      else ("experts", None))
        axes["b2"] = ("experts", None)
    return axes


def moe_dispatch(idx, gates, E: int, C: int):
    """Build the dispatch/combine tensors [b, s, E, C] from top-k routing.

    Capacity slots fill k=0 choices first, then k=1, ... (Switch
    priority); each (token, k) choice takes the next free slot of its
    expert via a sequence cumsum offset by the earlier rounds' running
    per-expert counts. Tokens past capacity drop (dispatch row all-zero).
    Invariants (tested in tests/test_moe.py): each filled slot holds
    exactly one token; with ample capacity every token occupies exactly
    its top-k slots and its combine weights sum to 1."""
    dispatch = 0.0
    combine = 0.0
    count = 0.0
    for k in range(idx.shape[-1]):
        onek = jax.nn.one_hot(idx[..., k], E, dtype=jnp.float32)
        pos = (jnp.cumsum(onek, axis=1) - onek) + count
        keep = (pos < C) * onek                              # [b, s, E]
        slot = jax.nn.one_hot(pos.astype(jnp.int32), C,
                              dtype=jnp.float32) * keep[..., None]
        dispatch = dispatch + slot
        combine = combine + slot * gates[..., k][:, :, None, None]
        count = count + jnp.sum(onek, axis=1)[:, None, :]
    return dispatch, combine


def _sort_route(idx, gates, E: int, C: int):
    """Per-batch-row routing by stable sort (vmapped over b).

    idx/gates: [s, K] -> entry arrays [K*s] in k-major order (all k=0
    choices first — the Switch priority; within a k, sequence order):
    (expert, token, gate, slot, keep). Slot = the entry's rank among
    same-expert entries; computed as sorted-rank minus the expert's
    segment start, then scattered back to entry order. Exactly the
    bookkeeping moe_dispatch materializes as [s, E, C] one-hots, in
    O(sK) memory."""
    s, K = idx.shape
    e = idx.T.reshape(-1)                        # [K*s], k-major
    g = gates.T.reshape(-1)
    tok = jnp.tile(jnp.arange(s), K)
    order = jnp.argsort(e)                       # stable in jax
    e_sorted = e[order]
    counts = jnp.zeros((E,), jnp.int32).at[e_sorted].add(1)
    seg_start = jnp.cumsum(counts) - counts      # exclusive cumsum [E]
    pos_sorted = jnp.arange(K * s) - seg_start[e_sorted]
    n = K * s
    pos = jnp.zeros((n,), jnp.int32).at[order].set(pos_sorted)
    keep = pos < C
    return e, tok, g, pos, keep


def moe_apply(params, x, cfg: ModelConfig):
    """x: [b, s, h] -> (y [b, s, h], aux_loss scalar f32)."""
    b, s, h = x.shape
    E = cfg.num_experts
    K = cfg.moe_top_k
    C = moe_capacity(cfg, s)
    dtype = x.dtype

    logits = x @ params["router"].astype(dtype)             # [b, s, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    gates, idx = jax.lax.top_k(probs, K)                    # [b, s, K]
    gates = gates / jnp.maximum(
        jnp.sum(gates, axis=-1, keepdims=True), 1e-9)

    # Switch aux loss on the top-1 assignment (before capacity drops)
    top1 = jax.nn.one_hot(idx[..., 0], E, dtype=jnp.float32)
    f_e = jnp.mean(top1, axis=(0, 1))                       # [E]
    p_e = jnp.mean(probs, axis=(0, 1))
    aux = E * jnp.sum(f_e * p_e)

    if cfg.moe_dispatch == "dense":
        dispatch, combine = moe_dispatch(idx, gates, E, C)
        # dispatch -> per-expert token blocks [b, E, C, h]
        xin = jnp.einsum("bsec,bsh->bech", dispatch.astype(dtype), x)
    else:
        e, tok, g, pos, keep = jax.vmap(
            lambda i, ga: _sort_route(i, ga, E, C))(idx, gates)
        pos_c = jnp.minimum(pos, C - 1)      # dropped entries write 0s
        brow = jnp.arange(b)[:, None]
        contrib = x[brow, tok] * keep[..., None].astype(dtype)  # [b,KS,h]
        xin = jnp.zeros((b, E, C, h), dtype).at[brow, e, pos_c].add(contrib)
    w1 = params["w1"].astype(dtype)
    w2 = params["w2"].astype(dtype)
    E_, h_ = w1.shape[0], w1.shape[1]

    def bank_gemm(xb, wb):
        # expert GEMMs honor --quantized_gemm like the dense MLP does
        # (wb flattened to [E, K, N]; the GLU split stays a leading
        # index of the flattened output)
        if cfg.quantized_gemm == "int8":
            from megatron_tpu.ops.quantized import int8_expert_matmul
            return int8_expert_matmul(xb, wb)
        return jnp.einsum("beck,ekn->becn", xb, wb)

    # the float path einsums the weight banks UNRESHAPED: under the 1F1B
    # store-activations stash, reshaped banks would stop being identity-
    # passthrough vjp leaves and a full bank copy would ride every stash
    # slot (the _assert_dedup_passthrough guard fires). The int8 path
    # reshapes (its quantization re-materializes weights anyway) — pair
    # it with the recompute stash mode.
    if cfg.is_glu:
        if cfg.quantized_gemm == "int8":
            y1 = bank_gemm(xin, w1.reshape(E_, h_, -1))
            y1 = y1.reshape(*y1.shape[:-1], 2, cfg.ffn_hidden_size)
        else:
            y1 = jnp.einsum("bech,ehgf->becgf", xin, w1)
        if cfg.use_bias:
            y1 = y1 + params["b1"].astype(dtype)[None, :, None]
        act = activation_fn(cfg.activation, y1[..., 0, :], y1[..., 1, :])
    else:
        y1 = bank_gemm(xin, w1)
        if cfg.use_bias:
            y1 = y1 + params["b1"].astype(dtype)[None, :, None]
        act = activation_fn(cfg.activation, y1)
    y2 = bank_gemm(act, w2)
    if cfg.use_bias:
        # per-expert output bias; dropped (not duplicated) tokens simply
        # never see it, matching the dispatch semantics
        y2 = y2 + params["b2"].astype(dtype)[None, :, None]
    if cfg.moe_dispatch == "dense":
        y = jnp.einsum("bech,bsec->bsh", y2, combine.astype(dtype))
    else:
        out = y2[brow, e, pos_c]                         # [b, KS, h]
        w = (g * keep).astype(dtype)
        y = (out * w[..., None]).reshape(b, K, s, h).sum(axis=1)
    return y, aux
