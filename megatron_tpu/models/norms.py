"""RMSNorm / LayerNorm.

TPU-native replacement for the reference's fused CUDA mixed-precision
LayerNorm (ref: megatron/fused_kernels/layer_norm_cuda_kernel.cu, wrapped by
megatron/model/fused_layer_norm.py:64-122) and its plain-torch RMSNorm
(ref: fused_layer_norm.py:125-139). On TPU, XLA fuses the normalization
chain into neighboring ops, so the "fused kernel" is simply the jnp
expression; stats are computed in fp32 regardless of input dtype, matching
the reference's mixed-precision contract (fp16/bf16 in, fp32 stats).

A Pallas implementation lives in megatron_tpu/ops/fused_norms.py for cases
where we want explicit control; this module is the canonical reference
implementation.
"""
from __future__ import annotations

import jax.lax as lax
import jax.numpy as jnp


def rmsnorm_init(hidden_size: int, dtype=jnp.float32):
    return {"scale": jnp.ones((hidden_size,), dtype=dtype)}


def rmsnorm_axes():
    return {"scale": ("embed",)}


def rmsnorm(params, x, eps: float = 1e-5):
    """RMSNorm with fp32 statistics (ref: fused_layer_norm.py:132-139 computes
    in fp32 then casts back)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    xf = xf * lax.rsqrt(var + eps)
    return xf.astype(dtype) * params["scale"].astype(dtype)


def layernorm_init(hidden_size: int, dtype=jnp.float32):
    return {
        "scale": jnp.ones((hidden_size,), dtype=dtype),
        "bias": jnp.zeros((hidden_size,), dtype=dtype),
    }


def layernorm_axes():
    return {"scale": ("embed",), "bias": ("embed",)}


def layernorm(params, x, eps: float = 1e-5):
    """Affine LayerNorm, fp32 stats (ref: layer_norm_cuda.cpp forward_affine)."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    xf = (xf - mean) * lax.rsqrt(var + eps)
    return xf.astype(dtype) * params["scale"].astype(dtype) + params["bias"].astype(dtype)


def norm_init(norm_type: str, hidden_size: int, dtype=jnp.float32):
    if norm_type == "rmsnorm":
        return rmsnorm_init(hidden_size, dtype)
    elif norm_type == "layernorm":
        return layernorm_init(hidden_size, dtype)
    raise ValueError(norm_type)


def norm_axes(norm_type: str):
    return rmsnorm_axes() if norm_type == "rmsnorm" else layernorm_axes()


def apply_norm(norm_type: str, params, x, eps: float = 1e-5):
    if norm_type == "rmsnorm":
        return rmsnorm(params, x, eps)
    elif norm_type == "layernorm":
        return layernorm(params, x, eps)
    raise ValueError(norm_type)
