"""Rotary position embeddings (RoPE) with linear position-interpolation scaling.

TPU-native equivalent of the reference's complex-multiplication RoPE
(ref: megatron/model/positional_embeddings.py:7-51 `precompute_freqs_cis` /
`apply_rotary_emb`, applied at megatron/model/transformer.py:373-379,500-501).

Convention: the *interleaved-pair* (Meta/Llama) layout — head-dim elements
(2i, 2i+1) form the complex pair. The reference keeps the same convention and
permutes HF checkpoints into it during conversion
(ref: weights2megatron/permute_qkv.py:12-81); our converter does the same, so
numerics line up with the reference end-to-end.

Instead of complex arithmetic (poorly supported on the TPU vector unit) we use
the equivalent real-valued rotation on the de-interleaved halves, which XLA
fuses into the surrounding attention ops.
"""
from __future__ import annotations

import jax.numpy as jnp


def precompute_freqs(
    head_dim: int,
    max_seq_len: int,
    theta: float = 10000.0,
    scaling_factor: float = 1.0,
    dtype=jnp.float32,
):
    """cos/sin tables of shape [max_seq_len, head_dim // 2].

    `scaling_factor` implements linear position interpolation: positions are
    divided by the factor so a model trained at 4k attends coherently at
    4k * factor (ref: positional_embeddings.py:10-12, --rope_scaling_factor
    arguments.py:460-461)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq_len, dtype=jnp.float32) / scaling_factor
    freqs = jnp.outer(t, inv_freq)  # [s, hd/2]
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rotary(x, cos, sin, position_ids=None):
    """Rotate [batch, seq, heads, head_dim] by position.

    Supports non-monotonic `position_ids` [batch, seq] the same way the
    reference indexes freqs_cis by position_ids
    (ref: positional_embeddings.py:34-43)."""
    b, s, n, d = x.shape
    if position_ids is None:
        c = cos[:s][None, :, None, :]  # [1, s, 1, d/2]
        sn = sin[:s][None, :, None, :]
    else:
        c = cos[position_ids][:, :, None, :]  # [b, s, 1, d/2]
        sn = sin[position_ids][:, :, None, :]
    # interleaved pairs: (x0, x1), (x2, x3), ...
    xr = x.astype(jnp.float32).reshape(b, s, n, d // 2, 2)
    x0, x1 = xr[..., 0], xr[..., 1]
    out0 = x0 * c - x1 * sn
    out1 = x1 * c + x0 * sn
    out = jnp.stack([out0, out1], axis=-1).reshape(b, s, n, d)
    return out.astype(x.dtype)
