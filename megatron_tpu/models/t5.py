"""T5: encoder-decoder transformer with cross-attention.

TPU-native equivalent of the reference's T5Model
(ref: megatron/model/t5_model.py — t5_extended_attention_mask,
T5LMHead :36-60, T5Model :63-198) over the shared transformer stack:
bidirectional encoder, causal decoder with per-layer cross-attention
(models/transformer.py `encoder_output=`), shared embedding, tied LM head.
The reference realizes the encoder/decoder split through
ModelType.encoder_and_decoder + pipeline split-rank machinery
(ref: core/parallel_state.py split_rank); here both stacks are plain
parameter subtrees — the mesh lays them out.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from megatron_tpu.config import ModelConfig
from megatron_tpu.models import transformer as tfm
from megatron_tpu.models.norms import apply_norm, norm_axes, norm_init
from megatron_tpu.ops.cross_entropy import cross_entropy_loss


def t5_config(**overrides) -> ModelConfig:
    """t5-base-ish defaults (ref: examples/pretrain_t5 flags)."""
    base = dict(
        num_layers=12, hidden_size=768, num_attention_heads=12,
        vocab_size=32128, seq_length=512, use_rotary_emb=False,
        use_position_embedding=True, norm_type="layernorm",
        activation="gelu", use_bias=True, use_post_ln=False,
        tie_embed_logits=True,
    )
    base.update(overrides)
    return ModelConfig(**base).derived()


def t5_init(rng, cfg: ModelConfig, decoder_layers: Optional[int] = None,
            dtype=jnp.float32):
    ks = jax.random.split(rng, 5)
    h = cfg.hidden_size
    v = cfg.padded_vocab_size
    std = cfg.init_method_std
    return {
        "embedding": {
            "word_embeddings": jax.random.normal(ks[0], (v, h), dtype) * std,
            "position_embeddings": jax.random.normal(
                ks[1], (cfg.max_position_embeddings, h), dtype) * std,
        },
        "encoder": tfm.stack_init(ks[2], cfg, dtype=dtype),
        "encoder_norm": norm_init(cfg.norm_type, h, dtype),
        "decoder": tfm.stack_init(ks[3], cfg,
                                  num_layers=decoder_layers or cfg.num_layers,
                                  dtype=dtype, cross_attn=True),
        "decoder_norm": norm_init(cfg.norm_type, h, dtype),
        # T5LMHead bias (tied decode weight, ref: t5_model.py:36-60)
        "lm_head_bias": jnp.zeros((v,), dtype),
    }


def t5_axes(cfg: ModelConfig):
    return {
        "embedding": {"word_embeddings": ("vocab", "embed"),
                      "position_embeddings": (None, "embed")},
        "encoder": tfm.stack_axes(cfg),
        "encoder_norm": norm_axes(cfg.norm_type),
        "decoder": tfm.stack_axes(cfg, cross_attn=True),
        "decoder_norm": norm_axes(cfg.norm_type),
        "lm_head_bias": ("vocab",),
    }


def _embed(params, tokens, cfg, compute_dtype):
    emb = params["embedding"]
    s = tokens.shape[1]
    x = emb["word_embeddings"][tokens] + \
        emb["position_embeddings"][jnp.arange(s)][None]
    return x.astype(compute_dtype)


def t5_forward(params, enc_tokens, dec_tokens, cfg: ModelConfig, *,
               enc_padding_mask=None, rng=None, deterministic: bool = True):
    """-> lm_logits [b, s_dec, V] (ref: t5_model.py:117-170 forward)."""
    from megatron_tpu.config import as_dtype
    compute_dtype = as_dtype(cfg.compute_dtype)

    x = _embed(params, enc_tokens, cfg, compute_dtype)
    seg = None
    if enc_padding_mask is not None:
        from megatron_tpu.models.bert import bert_pad_segments
        seg = bert_pad_segments(enc_padding_mask)
    assert cfg.num_experts == 1, (
        "MoE aux-loss accumulation is only wired into the GPT loss path")
    enc, _, _ = tfm.stack_apply(params["encoder"], x, cfg, causal=False,
                             segment_ids=seg, rng=rng,
                             deterministic=deterministic)
    enc = apply_norm(cfg.norm_type, params["encoder_norm"], enc,
                     cfg.norm_epsilon)

    y = _embed(params, dec_tokens, cfg, compute_dtype)
    dec, _, _ = tfm.stack_apply(params["decoder"], y, cfg, causal=True,
                             encoder_output=enc, rng=rng,
                             deterministic=deterministic)
    return t5_lm_logits(params, dec, cfg, compute_dtype)


def t5_lm_logits(params, dec, cfg: ModelConfig, compute_dtype):
    """Decoder-final norm + tied decode + bias (ref: t5_model.py:36-60
    T5LMHead) — shared by the sequential and pipelined tails."""
    dec = apply_norm(cfg.norm_type, params["decoder_norm"], dec,
                     cfg.norm_epsilon)
    w_out = params["embedding"]["word_embeddings"].T.astype(compute_dtype)
    return (dec @ w_out).astype(jnp.float32) + \
        params["lm_head_bias"].astype(jnp.float32)


def t5_loss(params, batch, cfg: ModelConfig, *, rng=None,
            deterministic: bool = True):
    """(ref: pretrain_t5.py forward_step): batch {text_enc, text_dec,
    labels, loss_mask, enc_mask?}."""
    logits = t5_forward(params, batch["text_enc"], batch["text_dec"], cfg,
                        enc_padding_mask=batch.get("enc_mask"),
                        rng=rng, deterministic=deterministic)
    losses = cross_entropy_loss(logits, batch["labels"],
                                vocab_size=cfg.vocab_size)
    mask = batch["loss_mask"].astype(jnp.float32)
    return jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def t5_pipeline_loss_fn(params, batch, cfg: ModelConfig, mesh, *,
                        vpp: int = 1, rng=None, deterministic: bool = True):
    """T5 loss with BOTH stacks pipelined over 'pp'.

    The reference pipelines encoder-decoder models by assigning encoder
    ranks and decoder ranks around a split point and forwarding the encoder
    output alongside decoder activations
    (ref: megatron/schedules.py:505-535 + core/parallel_state.py
    split_rank). Here the same capability is two `pipeline_apply` passes
    over the SAME 'pp' axis — every stage holds an encoder chunk AND a
    decoder chunk (layers/(2*pp) each side), the encoder's normed output
    re-enters the second pass as a per-microbatch stream feeding every
    decoder chunk's cross-attention, and the backward through both passes
    is derived by jax.grad. batch leaves are [n_micro, b, ...].
    """
    from megatron_tpu.config import as_dtype
    from megatron_tpu.parallel.pipeline import pipeline_apply
    from megatron_tpu.parallel.sharding import constrain
    # this path discards pipeline_apply's aux return (the enc/dec chunk
    # fns drop stack_apply's aux too) — with MoE it would silently train
    # routers unregularized, like the sequential t5_forward guard above
    assert cfg.num_experts == 1, (
        "T5 pipeline path has no MoE router-aux threading")
    compute_dtype = as_dtype(cfg.compute_dtype)

    enc_tokens = batch["text_enc"]   # [n_micro, b, s_enc]
    dec_tokens = batch["text_dec"]   # [n_micro, b, s_dec]
    n_micro, n_b, s_enc = enc_tokens.shape
    s_dec = dec_tokens.shape[-1]

    def embed_intake(shared_p, sl, rng_mb):
        return _embed({"embedding": shared_p}, sl["tokens"], cfg,
                      compute_dtype)

    def enc_chunk(cp, h, sl, offset, rng_mb):
        layer_rng = (jax.random.fold_in(rng_mb, 1)
                     if rng_mb is not None and not deterministic else None)
        return tfm.stack_apply(cp, h, cfg, causal=False,
                               segment_ids=sl.get("seg"), rng=layer_rng,
                               deterministic=deterministic,
                               layer_offset=offset)[0]

    enc_streams = {"tokens": enc_tokens}
    if batch.get("enc_mask") is not None:
        from megatron_tpu.models.bert import bert_pad_segments
        enc_streams["seg"] = bert_pad_segments(batch["enc_mask"])

    enc, _ = pipeline_apply(
        params["encoder"], params["embedding"], enc_streams, cfg, mesh,
        intake_fn=embed_intake, chunk_fn=enc_chunk,
        batch_shape=(n_b, s_enc), vpp=vpp, rng=rng)

    # encoder-final norm with the microbatch dim spread over the pipeline
    # stages (they are idle between the two passes)
    enc = constrain(enc, ("microbatch", "batch", "seq", "act_embed"))
    enc = apply_norm(cfg.norm_type, params["encoder_norm"], enc,
                     cfg.norm_epsilon)

    def dec_chunk(cp, h, sl, offset, rng_mb):
        layer_rng = (jax.random.fold_in(rng_mb, 2)
                     if rng_mb is not None and not deterministic else None)
        return tfm.stack_apply(cp, h, cfg, causal=True,
                               encoder_output=sl["enc"].astype(h.dtype),
                               rng=layer_rng,
                               deterministic=deterministic,
                               layer_offset=offset)[0]

    # the enc stream crosses the shard_map boundary replicated over 'pp';
    # its derived cotangent is psum'd there — same CPU-partitioner bf16
    # constraint as pipeline_apply's ring boundary, same f32 workaround
    boundary_dtype = (jnp.float32 if jax.default_backend() == "cpu"
                      else enc.dtype)
    dec_streams = {"tokens": dec_tokens, "enc": enc.astype(boundary_dtype)}
    dec, _ = pipeline_apply(
        params["decoder"], params["embedding"], dec_streams, cfg, mesh,
        intake_fn=embed_intake, chunk_fn=dec_chunk,
        batch_shape=(n_b, s_dec), vpp=vpp, rng=rng)

    dec = constrain(dec, ("microbatch", "batch", "seq", "act_embed"))
    logits = t5_lm_logits(params, dec, cfg, compute_dtype)
    logits = constrain(logits, ("microbatch", "batch", "seq", "vocab"))
    losses = cross_entropy_loss(logits, batch["labels"],
                                vocab_size=cfg.vocab_size)
    mask = batch["loss_mask"].astype(losses.dtype)
    # per-microbatch masked mean, then mean over microbatches (== train_step)
    per_mb = (jnp.sum(losses * mask, axis=(1, 2))
              / jnp.maximum(jnp.sum(mask, axis=(1, 2)), 1.0))
    return jnp.mean(per_mb)
