"""Transformer layer and scan-stacked transformer.

TPU-native equivalent of ParallelTransformerLayer / ParallelTransformer
(ref: megatron/model/transformer.py:581-815 and :896-1251). Structural
features carried over:

- pre-LN (default) vs post-LN (`use_post_ln`, ref: transformer.py:629-633)
- Falcon-style parallel attention+MLP sharing one input norm, with no
  attention residual-dropout (`parallel_attn`, ref: transformer.py:647,773-805)
- dedicated MLP layernorm for Falcon-40B (`parallel_layernorm`,
  ref: transformer.py:604,612-628,770-771)
- LIMA per-layer dropout ramp p_l = l/L * p (ref: transformer.py:963-970)
- activation recompute: 'full' remats each layer, 'selective' saves GEMM
  outputs but recomputes the attention softmax — the jax.checkpoint
  formulation of the reference's tensor_parallel.checkpoint machinery
  (ref: megatron/core/tensor_parallel/random.py:175-252, transformer.py:357,
  1079-1145). No RNG save/restore is needed: jax.random keys are pure.

TPU-first design choices: all layers share one set of stacked parameters
(leading 'layers' dim) applied via `lax.scan` — one compiled layer body
regardless of depth, which keeps compile time flat for 80-layer models and
gives the pipeline partitioner a natural chunking axis.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from megatron_tpu.config import ModelConfig
from megatron_tpu.models.attention import attention_apply, attention_axes, attention_init
from megatron_tpu.models.mlp import mlp_apply, mlp_axes, mlp_init
from megatron_tpu.models.norms import apply_norm, norm_axes, norm_init
from megatron_tpu.ops.dropout import drop_path as _drop_path
from megatron_tpu.ops.dropout import dropout as _dropout
from megatron_tpu.parallel.sharding import constrain

# Residual-stream activations between TP blocks live seq-sharded when
# sequence parallelism is on (ref: layers.py:225-296 — the SP all-gather/
# reduce-scatter pair); `constrain` is a no-op outside a mesh context.
RESIDUAL_AXES = ("batch", "seq_sp", "act_embed")


# ---------------------------------------------------------------------------
# single layer
# ---------------------------------------------------------------------------

def layer_init(rng, cfg: ModelConfig, dtype=jnp.float32,
               cross_attn: bool = False):
    """Norm layout mirrors ref: transformer.py:606-633 —
    pre-LN: input_layernorm + post_attention_layernorm (output_layernorm=Id);
    post-LN: input_layernorm=Id, post_attention_layernorm + output_layernorm;
    parallel_attn drops post_attention_layernorm; parallel_layernorm adds a
    dedicated mlp norm."""
    k_attn, k_mlp, k_inter = jax.random.split(rng, 3)
    if cfg.num_experts > 1:
        from megatron_tpu.models.moe import moe_init
        mlp_params = moe_init(k_mlp, cfg, dtype)
    else:
        mlp_params = mlp_init(k_mlp, cfg, dtype)
    params = {
        "attention": attention_init(k_attn, cfg, dtype),
        "mlp": mlp_params,
    }
    if cross_attn:
        # decoder cross-attention + its input norm
        # (ref: transformer.py:664-683,782-794)
        params["inter_attention"] = attention_init(k_inter, cfg, dtype)
        params["post_inter_norm"] = norm_init(cfg.norm_type,
                                              cfg.hidden_size, dtype)
    if not cfg.use_post_ln:
        params["input_norm"] = norm_init(cfg.norm_type, cfg.hidden_size, dtype)
    else:
        params["output_norm"] = norm_init(cfg.norm_type, cfg.hidden_size, dtype)
    if not cfg.parallel_attn:
        params["post_attn_norm"] = norm_init(cfg.norm_type, cfg.hidden_size, dtype)
    if cfg.parallel_layernorm:
        params["mlp_norm"] = norm_init(cfg.norm_type, cfg.hidden_size, dtype)
    return params


def layer_axes(cfg: ModelConfig, cross_attn: bool = False):
    if cfg.num_experts > 1:
        from megatron_tpu.models.moe import moe_axes
        mlp_ax = moe_axes(cfg)
    else:
        mlp_ax = mlp_axes(cfg)
    axes = {
        "attention": attention_axes(cfg),
        "mlp": mlp_ax,
    }
    if cross_attn:
        axes["inter_attention"] = attention_axes(cfg)
        axes["post_inter_norm"] = norm_axes(cfg.norm_type)
    if not cfg.use_post_ln:
        axes["input_norm"] = norm_axes(cfg.norm_type)
    else:
        axes["output_norm"] = norm_axes(cfg.norm_type)
    if not cfg.parallel_attn:
        axes["post_attn_norm"] = norm_axes(cfg.norm_type)
    if cfg.parallel_layernorm:
        axes["mlp_norm"] = norm_axes(cfg.norm_type)
    return axes


def layer_apply(
    params,
    x,
    cfg: ModelConfig,
    *,
    rope_cos=None,
    rope_sin=None,
    position_ids=None,
    kv_cache=None,
    layer_number: int = 1,
    hidden_dropout: Optional[float] = None,
    drop_path_rate=None,
    rng=None,
    deterministic: bool = True,
    segment_ids=None,
    causal: bool = True,
    encoder_output=None,
    cp_pre_zigzag: bool = False,
    adapters=None,
):
    """One transformer layer. x: [b, s, h]. Returns (x, kv_cache, aux) —
    `aux` is the MoE router's load-balancing loss (0.0 for dense MLPs).

    `adapters`: (per-layer LoraAdapter bank, adapter_idx [b]) for the
    SELF-attention projections only (multi-tenant LoRA serving —
    models/attention.py; cross-attention has no adapter path).

    `encoder_output` enables the decoder cross-attention sublayer between
    self-attention and the MLP (ref: transformer.py:782-794).

    Residual structure follows ref: transformer.py:754-815 exactly:
      ln_out = input_norm(x)            (Identity when post-LN)
      attn   = attention(ln_out)
      parallel_attn:  out = output-ish residual handled below
      else:  ln_in  = x + drop(attn)
             ln_out = post_attn_norm(ln_in)
             mlp    = mlp(ln_out)
             out    = ln_in + drop(mlp)
      out = output_norm(out)            (Identity when pre-LN)
    """
    eps = cfg.norm_epsilon
    p_drop = cfg.hidden_dropout if hidden_dropout is None else hidden_dropout
    if deterministic:
        rng = None
    r_attn = r_mlp = r_score = r_inter = r_dp1 = r_dp2 = None
    if rng is not None:
        (r_attn, r_mlp, r_score, r_inter,
         r_dp1, r_dp2) = jax.random.split(rng, 6)

    def _branch(r_dp, branch):
        # residual + drop_path(dropout(branch)) when stochastic depth is
        # on (ref: transformer.py:723-730); drop_path_rate may be a
        # traced per-layer scalar from the scanned linspace ramp
        if drop_path_rate is None or r_dp is None:
            return branch
        return _drop_path(r_dp, branch, drop_path_rate)

    def _mlp_branch(inp):
        """Dense MLP or the MoE expert bank: (out, aux_loss)."""
        if cfg.num_experts > 1:
            from megatron_tpu.models.moe import moe_apply
            return moe_apply(params["mlp"], inp, cfg)
        return mlp_apply(params["mlp"], inp, cfg), jnp.zeros((), jnp.float32)

    residual = x
    if cfg.use_post_ln:
        ln_out = x  # input_layernorm = Identity (ref: transformer.py:630-631)
    else:
        ln_out = apply_norm(cfg.norm_type, params["input_norm"], x, eps)

    attn_out, kv_cache = attention_apply(
        params["attention"], ln_out, cfg,
        rope_cos=rope_cos, rope_sin=rope_sin, position_ids=position_ids,
        kv_cache=kv_cache, layer_number=layer_number,
        dropout_rng=r_score, deterministic=deterministic,
        segment_ids=segment_ids, causal=causal,
        cp_pre_zigzag=cp_pre_zigzag, adapters=adapters)

    if cfg.parallel_attn:
        # Falcon block: no dropout-add after attention
        # (ref: transformer.py:781-782 layernorm_input = attention_output);
        # mlp input is mlp_norm(x) (Falcon-40B) or the shared input norm
        # (ref: transformer.py:770-771, 796-801)
        if cfg.parallel_layernorm:
            mlp_in = apply_norm(cfg.norm_type, params["mlp_norm"], residual, eps)
        else:
            mlp_in = ln_out
        mlp_out, aux = _mlp_branch(mlp_in)
        out = residual + _branch(r_dp1,
                                 _dropout(r_mlp, mlp_out + attn_out, p_drop))
    else:
        ln_in = constrain(
            residual + _branch(r_dp1, _dropout(r_attn, attn_out, p_drop)),
            RESIDUAL_AXES)
        if encoder_output is not None and "inter_attention" in params:
            # decoder cross-attention sublayer (ref: transformer.py:782-794)
            ln_x = apply_norm(cfg.norm_type, params["post_inter_norm"],
                              ln_in, eps)
            inter_out, _ = attention_apply(
                params["inter_attention"], ln_x, cfg,
                deterministic=deterministic, causal=False,
                kv_input=encoder_output)
            ln_in = ln_in + _dropout(r_inter, inter_out, p_drop)
        ln2 = apply_norm(cfg.norm_type, params["post_attn_norm"], ln_in, eps)
        mlp_out, aux = _mlp_branch(ln2)
        out = ln_in + _branch(r_dp2, _dropout(r_mlp, mlp_out, p_drop))

    if cfg.use_post_ln:
        out = apply_norm(cfg.norm_type, params["output_norm"], out, eps)
    return constrain(out, RESIDUAL_AXES), kv_cache, aux


# ---------------------------------------------------------------------------
# stacked transformer (scan over layers)
# ---------------------------------------------------------------------------

def stack_init(rng, cfg: ModelConfig, num_layers: Optional[int] = None,
               dtype=jnp.float32, cross_attn: bool = False):
    """Stacked params with leading 'layers' dim via vmap over per-layer init."""
    n = num_layers if num_layers is not None else cfg.num_layers
    keys = jax.random.split(rng, n)
    return jax.vmap(lambda k: layer_init(k, cfg, dtype,
                                         cross_attn=cross_attn))(keys)


def stack_axes(cfg: ModelConfig, cross_attn: bool = False):
    """Logical axes for stacked params: prepend 'layers'."""
    per_layer = layer_axes(cfg, cross_attn=cross_attn)
    return jax.tree.map(lambda ax: ("layers",) + ax, per_layer,
                        is_leaf=lambda x: isinstance(x, tuple))


def lima_dropout_rates(cfg: ModelConfig, num_layers: int):
    """LIMA ramp: linspace(0, p_hidden, L) — first layer exactly 0.0
    (ref: transformer.py:963-970 torch.linspace(0, hidden_dropout, L))."""
    if not cfg.lima_dropout:
        return jnp.full((num_layers,), cfg.hidden_dropout, jnp.float32)
    return jnp.linspace(0.0, cfg.hidden_dropout, num_layers, dtype=jnp.float32)


def drop_path_rates(cfg: ModelConfig, num_layers: int):
    """Stochastic-depth ramp: linspace(0, drop_path_rate, L)
    (ref: transformer.py:961 drop_path_rates)."""
    return jnp.linspace(0.0, cfg.drop_path_rate, num_layers,
                        dtype=jnp.float32)


def stack_apply(
    stacked_params,
    x,
    cfg: ModelConfig,
    *,
    rope_cos=None,
    rope_sin=None,
    position_ids=None,
    kv_caches=None,  # stacked KVCache with leading layers dim, or None
    rng=None,
    deterministic: bool = True,
    layer_offset: int = 0,
    segment_ids=None,
    causal: bool = True,
    encoder_output=None,
    cp_pre_zigzag: bool = False,
    adapters=None,
):
    """Apply all (or a pipeline stage's worth of) layers via lax.scan.

    Returns (x, kv_caches, aux) — `aux` sums the layers' MoE router
    load-balancing losses (0.0 for dense stacks; loss_fn weighs it by
    cfg.moe_aux_loss_coeff).

    `layer_offset` preserves layer_number-dependent behavior across pipeline
    stages (ref: transformer.py:1014-1044 layer offsets for vpp).

    `adapters`: (STACKED LoraAdapter with a leading 'layers' dim,
    adapter_idx [b]) — the factor bank rides the scan like the KV
    caches (each step slices one layer's [n, ...] bank), the per-row
    index is layer-invariant and closes over the body. None compiles to
    exactly today's graph (multi-tenant LoRA serving,
    models/attention.py)."""
    num_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    drop_rates = lima_dropout_rates(cfg, cfg.num_layers)
    drop_rates = jax.lax.dynamic_slice_in_dim(drop_rates, layer_offset, num_layers)
    dp_rates = jax.lax.dynamic_slice_in_dim(
        drop_path_rates(cfg, cfg.num_layers), layer_offset, num_layers)
    use_drop_path = cfg.drop_path_rate > 0.0
    layer_ids = layer_offset + jnp.arange(num_layers)
    # the stacked factor bank scans with the params/caches; the per-row
    # adapter index is the same for every layer and closes over the body
    lora_stack, adapter_idx = (adapters if adapters is not None
                               else (None, None))

    def body(carry, scanned):
        h, aux_sum = carry
        p, rate, dp_rate, lid, cache, lw = scanned
        layer_rng = None
        if rng is not None and not deterministic:
            layer_rng = jax.random.fold_in(rng, lid)
        h, new_cache, aux = layer_apply(
            p, h, cfg, rope_cos=rope_cos, rope_sin=rope_sin,
            position_ids=position_ids, kv_cache=cache,
            layer_number=lid + 1, hidden_dropout=rate,
            drop_path_rate=dp_rate if use_drop_path else None,
            rng=layer_rng,
            deterministic=deterministic, segment_ids=segment_ids,
            causal=causal, encoder_output=encoder_output,
            cp_pre_zigzag=cp_pre_zigzag,
            adapters=(lw, adapter_idx) if lw is not None else None)
        return (h, aux_sum + aux), new_cache

    if cfg.recompute_granularity == "full":
        body = jax.checkpoint(body, prevent_cse=False)
    elif cfg.recompute_granularity == "selective":
        # save GEMM outputs, recompute the attention softmax — the analogue of
        # the reference's selective core-attention recompute (transformer.py:357)
        body = jax.checkpoint(
            body, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            prevent_cse=False)

    aux0 = jnp.zeros((), jnp.float32)
    # None entries are empty pytrees: scan passes them through untouched
    # (the no-adapters / no-cache cases scan the same body shape)
    xs = (stacked_params, drop_rates, dp_rates, layer_ids, kv_caches,
          lora_stack)
    if kv_caches is None:
        def body_nocache(carry, scanned):
            p, rate, dp_rate, lid, lw = scanned
            c, _ = body(carry, (p, rate, dp_rate, lid, None, lw))
            return c, None
        (x, aux), _ = jax.lax.scan(body_nocache, (x, aux0),
                                   (stacked_params, drop_rates, dp_rates,
                                    layer_ids, lora_stack))
        return x, None, aux
    (x, aux), new_caches = jax.lax.scan(body, (x, aux0), xs)
    return x, new_caches, aux
