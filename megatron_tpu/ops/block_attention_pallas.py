"""Pallas TPU block-native decode attention: read the KV arena through
the block map, never materialize the contiguous view.

With the block-granular pool (`--kv_block_size`, serving/kv_pool.py)
every decode / verify dispatch used to bracket its body with
`resolve_view`/`scatter_view` — a full [L, S, cap, nkv, hd] gather of
every slot's blocks into a contiguous view and a scatter back, PER
STEP: O(pool bytes) of HBM traffic spent relocating KV the attention
dot then streams *again*. vLLM's PagedAttention showed the fix — the
attention kernel consumes the block map directly. We rejected paging
on TPU when it meant XLA-level gather indirection; this hand-written
kernel indexes the flat arena by physical block id instead, which
sidesteps exactly that objection:

- grid (slot, kv_block): the kv axis is innermost, so TPU's sequential
  grid execution lets VMEM scratch carry the FlashAttention-2
  online-softmax state (m, l, acc) across a slot's block CHAIN — the
  same (m, l, acc) pattern as ops/flash_attention_pallas.py, walking a
  block map instead of a contiguous sequence.
- the per-slot block map and lengths ride as SCALAR PREFETCH
  (pltpu.PrefetchScalarGridSpec): the k/v BlockSpec index_map reads
  map[slot, j] to pick which physical arena block to DMA — block
  indices are data, so one compile serves every block assignment, and
  each block is DMA'd HBM->VMEM exactly once per slot regardless of
  head count (all kv heads ride in one block fetch; the head loop is
  static).
- blocks past a slot's live length are SKIPPED: compute via `pl.when`,
  and the DMA via the index-revisit trick (a dead step's index_map
  returns the previous live block, and Pallas skips re-fetching an
  unchanged block) — a 3-block slot in a 64-block region pays 3 block
  reads, not 64.
- queries per slot w >= 1: w == 1 is plain decode; w == k+1 is the
  speculative-decode verify window (causal within the window, each
  query masked from its own position `length + j`) — ONE kernel serves
  both, so decode and verify keep one trace each.
- GQA: a static loop over kv heads computes that head's g query rows
  against the block's k/v slice — MQA/GQA never materialize the
  broadcast (the kv-head slice is a static lane offset into the
  nkv*hd-folded block).
- int8 pools dequantize IN KERNEL: per-(token, head) fp32 scales are
  fetched alongside k/v (same index_map) and multiply the int8 payload
  after the cast — HBM streams the int8 bytes, exactly like the
  XLA-fused dot path.
- the partial tail block is masked by lane iota against the slot's
  length (causal: query at position len+j attends kv positions <=
  len+j), and idle rows (length 0, map parked on the TRASH block) read
  one garbage position — finite garbage in, garbage out, discarded by
  the engine like every idle-row compute.

Like flash_attention_pallas.py, the kernel body uses only ops the
interpret path supports (no pltpu-only primitives), so the SAME kernel
runs under `interpret=True` on CPU — that is the tier-1 test path and
the serving engine's CPU fallback; on-chip shapes/timings live in the
`slow` tier and tools/bench_block_attn.py.

Layout: q [S, w, nq, hd] at the API boundary; arena k/v
[total_blocks, B, nkv, hd] (the serving pool's per-layer arena slice),
scales [total_blocks, B, nkv, 1]; map [S, nb] int32; lengths [S] int32
(each slot's first query position). The kernel runs group-major
[S, nkv*g*w, hd] internally.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
# exp clamp for rows fully masked within one live block (a verify
# window's earliest query sees nothing in a block the window's LAST
# query made live) — same trick as flash_attention_pallas.MASK_CLAMP
MASK_CLAMP = -1e20
# per-row online-softmax stats carry a small trailing lanes dim so the
# VMEM scratch tiles on TPU (same trick, same constant rationale, as
# flash_attention_pallas.STAT_LANES)
STAT_LANES = 8


def _bn_kernel(map_ref, len_ref, q_ref, k_ref, v_ref, *refs, scale,
               block_size, nb, nkv, g, w, hd, quant):
    # refs: [ks_ref, vs_ref]? o_ref, m_ref, l_ref, acc_ref — the int8
    # scale blocks are inputs only when the pool is quantized, so the
    # bf16 path pays zero extra DMA
    refs = list(refs)
    ks_ref = vs_ref = None
    if quant:
        ks_ref, vs_ref = refs[0], refs[1]
        refs = refs[2:]
    o_ref, m_ref, l_ref, acc_ref = refs
    si = pl.program_id(0)
    j = pl.program_id(1)
    B = block_size
    G = nkv * g * w
    length = len_ref[si]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # a block is live when ANY query can see it: the slot's last query
    # sits at position length + w - 1, so blocks starting past it hold
    # nothing this dispatch may read (their content is other slots' KV
    # or free-list garbage)
    live = j * B <= length + w - 1

    @pl.when(live)
    def _body():
        # q positions per group row r: the query index is r % w (rows
        # are (kv_head, group, query)-major), so row r's query sits at
        # position length + (r % w) — decode (w == 1) degenerates to
        # every row at `length`
        row_q = jax.lax.broadcasted_iota(jnp.int32, (G, B), 0)
        q_pos = length + jax.lax.rem(row_q, w)
        kv_pos = j * B + jax.lax.broadcasted_iota(jnp.int32, (G, B), 1)
        keep = q_pos >= kv_pos  # causal incl. the partial tail block
        s_full = jnp.zeros((G, B), jnp.float32)
        for h in range(nkv):  # static GQA loop: nkv is a trace constant
            qh = q_ref[0, h * g * w:(h + 1) * g * w, :] \
                .astype(jnp.float32) * scale                  # [g*w, hd]
            kh = k_ref[0][:, h * hd:(h + 1) * hd] \
                .astype(jnp.float32)                          # [B, hd]
            if quant:
                kh = kh * ks_ref[0][:, h:h + 1].astype(jnp.float32)
            sh = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)           # [g*w, B]
            s_full = jax.lax.dynamic_update_slice(
                s_full, sh, (h * g * w, 0))
        s_full = jnp.where(keep, s_full, NEG_INF)

        m_prev = m_ref[:, :1]                                 # [G, 1]
        m_cur = jnp.max(s_full, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # MASK_CLAMP: a verify window's earliest query can be fully
        # masked in a block only its later queries made live —
        # exp(NEG_INF - NEG_INF) == 1 would attend those masked keys
        p = jnp.exp(s_full - jnp.maximum(m_new, MASK_CLAMP))
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1,
                                              keepdims=True)
        pacc = jnp.zeros((G, hd), jnp.float32)
        for h in range(nkv):
            vh = v_ref[0][:, h * hd:(h + 1) * hd] \
                .astype(jnp.float32)                          # [B, hd]
            if quant:
                vh = vh * vs_ref[0][:, h:h + 1].astype(jnp.float32)
            ph = jax.lax.dynamic_slice(p, (h * g * w, 0), (g * w, B))
            oh = jax.lax.dot_general(
                ph, vh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)           # [g*w, hd]
            pacc = jax.lax.dynamic_update_slice(pacc, oh,
                                                (h * g * w, 0))
        acc_ref[:] = acc_ref[:] * alpha + pacc
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == nb - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l > 0.0, l, 1.0)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "block_size", "interpret"))
def block_native_attention(q, k_arena, v_arena, block_map, lengths, *,
                           scale: float, block_size: int,
                           k_scale=None, v_scale=None,
                           interpret: bool | None = None):
    """Per-slot q against block-chained K/V, straight out of the arena.

    q:          [S, w, nq, hd]  (post-rope queries; w == 1 for decode,
                                 w == k+1 for the speculative verify
                                 window — causal within the window)
    k_arena/v_arena: [total_blocks, B, nkv, hd]  flat arena (one
                                 layer's slice of the serving pool;
                                 int8 for quantized pools)
    block_map:  [S, nb] int32    logical -> physical block per slot
    lengths:    [S] int32        first query's position per slot (the
                                 slot's pre-append token count); the
                                 slot's own k/v for the window must
                                 already be WRITTEN into the arena
                                 (write-before-read, like the dot path)
    k_scale/v_scale: [total_blocks, B, nkv, 1] fp32 — int8 pools only;
                                 dequant happens in kernel.

    Returns [S, w, nq, hd] in q's dtype. Rolling (ring) layouts are
    NOT supported — their slot->position map breaks the contiguous
    position arithmetic; the engine keeps the resolve/scatter bracket
    for those (serving/engine.py)."""
    S, w, nq, hd = q.shape
    T, B, nkv, _ = k_arena.shape
    nb = block_map.shape[1]
    assert B == block_size, (B, block_size)
    assert nq % nkv == 0, (nq, nkv)
    g = nq // nkv
    quant = k_scale is not None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    G = nq * w

    # group-major query rows [S, nkv*g*w, hd]: row r = (kv_head, group,
    # query)-major, so the kernel's static head loop slices contiguous
    # row ranges (same h -> h // g mapping as _dot_attention's reshape)
    qg = q.reshape(S, w, nkv, g, hd).transpose(0, 2, 3, 1, 4) \
        .reshape(S, G, hd)
    # fold (nkv, hd) into lanes: free reshape (row-major contiguous),
    # and it keeps the block's trailing dims TPU-tileable
    # ([B, nkv*hd] instead of [B, nkv, hd] with a sub-8 middle dim)
    kf = k_arena.reshape(T, B, nkv * hd)
    vf = v_arena.reshape(T, B, nkv * hd)
    flat_map = block_map.reshape(-1).astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)

    def _phys(si, j, map_ref, len_ref):
        # index-revisit DMA skip: steps past the slot's last live block
        # re-address that same live block, so Pallas skips the fetch
        # (pl.when skips the compute) — dead blocks cost nothing
        last = jnp.maximum(len_ref[si] + w - 1, 0) // B
        j_eff = jnp.minimum(j, jnp.minimum(last, nb - 1))
        return (map_ref[si * nb + j_eff], 0, 0)

    kv_spec = pl.BlockSpec((1, B, nkv * hd), _phys)
    in_specs = [
        pl.BlockSpec((1, G, hd), lambda si, j, m, ln: (si, 0, 0)),
        kv_spec, kv_spec,
    ]
    inputs = [qg, kf, vf]
    if quant:
        ksf = k_scale.reshape(T, B, nkv)
        vsf = v_scale.reshape(T, B, nkv)
        sc_spec = pl.BlockSpec((1, B, nkv), _phys)
        in_specs += [sc_spec, sc_spec]
        inputs += [ksf, vsf]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, G, hd),
                               lambda si, j, m, ln: (si, 0, 0)),
        scratch_shapes=[pltpu.VMEM((G, STAT_LANES), jnp.float32),  # m
                        pltpu.VMEM((G, STAT_LANES), jnp.float32),  # l
                        pltpu.VMEM((G, hd), jnp.float32)],         # acc
    )
    out = pl.pallas_call(
        functools.partial(_bn_kernel, scale=scale, block_size=B,
                          nb=nb, nkv=nkv, g=g, w=w, hd=hd,
                          quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, G, hd), q.dtype),
        interpret=interpret,
    )(flat_map, lengths, *inputs)
    # [S, nkv*g*w, hd] group-major -> [S, w, nq, hd]
    return out.reshape(S, nkv, g, w, hd).transpose(0, 3, 1, 2, 4) \
        .reshape(S, w, nq, hd)
