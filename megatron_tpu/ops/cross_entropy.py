"""Sharded-logit cross-entropy.

TPU-native equivalent of vocab_parallel_cross_entropy
(ref: megatron/core/tensor_parallel/cross_entropy.py:14-143). The reference
keeps logits sharded over the vocab dim and hand-codes three TP all-reduces
(max, predicted-logit, sum-exp) plus a custom backward. Under GSPMD the same
dataflow is a numerically-stable log-softmax over a 'vocab'-sharded axis —
XLA lowers the reductions to the identical collectives, and autodiff supplies
the backward.

Handles the padded vocab: logits for ids >= true vocab_size are excluded from
the partition function, matching the reference's masking of the padded region
(vocab padding: ref megatron/tokenizer/tokenizer.py:42-62).
Supports label smoothing (ref: cross_entropy.py:88-110).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_loss(
    logits,  # [..., padded_vocab] (any float dtype; promoted to fp32)
    labels,  # [...] int
    vocab_size: int | None = None,
    label_smoothing: float = 0.0,
):
    """Per-token CE loss, fp32. Masks padded vocab entries if vocab_size given."""
    logits = logits.astype(jnp.float32)
    padded_vocab = logits.shape[-1]
    if vocab_size is not None and vocab_size < padded_vocab:
        iota = jnp.arange(padded_vocab)
        logits = jnp.where(iota < vocab_size, logits, -1e30)
    # stable log-softmax
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    shifted = logits - m
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1))
    # predicted-logit extraction as a masked REDUCTION, not a gather: under
    # a 'vocab'-sharded logits layout this lowers to the reference's
    # predicted-logit all-reduce (ref: cross_entropy.py:54-63) — and the
    # XLA SPMD partitioner handles sharded reductions everywhere, incl.
    # inside partial-manual (shard_map) regions where sharded gathers
    # CHECK-fail on the CPU backend. XLA fuses the select+sum, so the
    # one-hot is never materialized.
    iota_v = jnp.arange(padded_vocab)
    label_logit = jnp.sum(
        jnp.where(iota_v == labels[..., None], shifted, 0.0), axis=-1)
    loss = lse - label_logit
    if label_smoothing > 0.0:
        # smoothed loss mixes in mean log-prob over the (true) vocab
        # (ref: cross_entropy.py:88-110)
        n = vocab_size if vocab_size is not None else padded_vocab
        eps = label_smoothing
        mean_logit = jnp.sum(
            jnp.where(jnp.arange(padded_vocab) < n, shifted, 0.0), axis=-1) / n
        smooth_loss = lse - mean_logit
        loss = (1.0 - eps) * loss + eps * smooth_loss
    return loss
