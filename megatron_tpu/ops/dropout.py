"""Shared dropout primitive (single definition for all call sites)."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def dropout(rng, x, rate):
    """Inverted dropout. `rate` may be a traced scalar (LIMA ramp is scanned).

    rng=None means deterministic/eval mode: identity (the functional analogue
    of the reference's `self.training` switch)."""
    if rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))
