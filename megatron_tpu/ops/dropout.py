"""Shared dropout primitive (single definition for all call sites)."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def dropout(rng, x, rate):
    """Inverted dropout. `rate` may be a traced scalar (LIMA ramp is scanned).

    rng=None means deterministic/eval mode: identity (the functional analogue
    of the reference's `self.training` switch)."""
    if rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))


def drop_path(rng, x, rate):
    """Stochastic depth: zero the whole residual branch PER SAMPLE, scaled
    by 1/keep (ref: megatron/model/transformer.py:43-63 DropPath). x is
    [b, ...]; the keep mask broadcasts over everything but batch. `rate`
    may be a traced per-layer scalar (linspace ramp is scanned)."""
    if rng is None:
        return x
    shape = (x.shape[0],) + (1,) * (x.ndim - 1)
    keep = jax.random.bernoulli(rng, 1.0 - rate, shape)
    return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))
