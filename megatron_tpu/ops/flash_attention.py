"""Flash attention: blockwise online-softmax attention, O(seq) memory.

TPU-native replacement for the reference's FlashAttention-2 integration
(ref: megatron/model/transformer.py:514-522 `flash_attn_func` from the
external CUDA `flash_attn` package) and, transitively, for the fused
scaled-masked-softmax CUDA kernels it superseded (ref: megatron/fused_kernels/
scaled_*_softmax*.cu, K1-K3 in SURVEY.md §2.2).

This module provides the flash *algorithm* (tiled K/V loop with online
softmax renormalization) expressed in XLA ops via `lax.scan` — it runs on any
backend and is the numerics reference. The hand-tuned Pallas TPU kernel
(`megatron_tpu.ops.flash_attention_pallas`) overrides it on TPU when
available; both share this module's interface:

    flash_attention(q, k, v, *, causal, scale, segment_ids) -> out
      q: [b, sq, nq, d], k/v: [b, skv, nkv, d], GQA by nq % nkv == 0;
      segment_ids [b, s] masks attention block-diagonally across
      EOD-separated documents (ref: --reset_attention_mask).
"""
from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp

DEFAULT_BLOCK_KV = 512
logger = logging.getLogger(__name__)
_warned_shapes = set()


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_kv",
                                             "use_pallas", "sliding_window",
                                             "dropout_rate"))
def flash_attention(q, k, v, *, causal: bool = True, scale: float | None = None,
                    block_kv: int = DEFAULT_BLOCK_KV, use_pallas: bool | None = None,
                    segment_ids=None, sliding_window: int | None = None,
                    dropout_rate: float = 0.0, dropout_rng=None):
    """Blockwise attention with online softmax. Returns [b, sq, nq, d].

    `segment_ids` [b, s] (shared q/k length) masks attention across
    EOD-separated documents (ref: --reset_attention_mask) — the flash
    formulation of the reference's block-diagonal mask, O(s) memory
    instead of the dot path's O(s^2) scores.

    `dropout_rate > 0` applies attention dropout INSIDE the tiled loop
    (the reference's FlashAttention-2 `dropout_p`,
    ref: megatron/model/transformer.py:514-522): the inverted-dropout
    mask multiplies each block's post-softmax weights in the value
    accumulation while the softmax normalizer keeps the undropped sum —
    exactly softmax-then-dropout like the dot path, O(block) mask
    memory, unbiased (E[out] == no-dropout out). Mask bits are drawn
    per kv-block from `dropout_rng` folded with the block index, so
    the backward (jax AD through the scan) sees identical masks."""
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas and (q.shape[1] % 128 != 0 or k.shape[1] % 128 != 0):
        # kernel blocks need 128-divisible sequence lengths; odd shapes take
        # the XLA blockwise path. Warn once per shape — this is a perf cliff,
        # not a correctness issue.
        key = (q.shape, k.shape)
        if key not in _warned_shapes:
            _warned_shapes.add(key)
            logger.warning(
                "flash_attention: seq lengths %s/%s not 128-divisible; "
                "falling back to the (slower) XLA blockwise path",
                q.shape[1], k.shape[1])
        use_pallas = False
    if dropout_rate > 0.0:
        assert dropout_rng is not None, (
            "flash_attention: dropout_rate > 0 needs dropout_rng")
    if use_pallas:
        try:
            from megatron_tpu.ops.flash_attention_pallas import pallas_flash_attention
            # positional: custom_vjp functions reject keyword arguments;
            # ids go in as floats so every diff arg is float
            from megatron_tpu.ops.flash_attention_pallas import (
                DEFAULT_BLOCK_KV as PBKV, DEFAULT_BLOCK_Q as PBQ,
                STAT_LANES)
            seg = (segment_ids.astype(jnp.float32)
                   if segment_ids is not None else None)
            seed = None
            if dropout_rate > 0.0:
                # the kernel's counter-based hash takes one integer seed
                # (<= 2^24 so the f32 plumbing is exact); per-block
                # streams come from hashing it with the block coords
                seed = jax.random.randint(
                    dropout_rng, (1, STAT_LANES), 0,
                    1 << 23).astype(jnp.float32)
            return pallas_flash_attention(
                q, k, v, causal, scale, PBQ, PBKV, False, seg, seg,
                sliding_window, dropout_rate, seed)
        except ImportError:
            pass
    return _blockwise_attention(q, k, v, causal=causal, scale=scale,
                                block_kv=block_kv, segment_ids=segment_ids,
                                sliding_window=sliding_window,
                                dropout_rate=dropout_rate,
                                dropout_rng=dropout_rng)


def _blockwise_attention(q, k, v, *, causal, scale, block_kv,
                         segment_ids=None, sliding_window=None,
                         dropout_rate=0.0, dropout_rng=None):
    b, sq, nq, d = q.shape
    skv, nkv = k.shape[1], k.shape[2]
    if scale is None:
        scale = d ** -0.5
    g = nq // nkv
    block_kv = min(block_kv, skv)
    # pad kv to a multiple of block_kv
    n_blocks = -(-skv // block_kv)
    pad = n_blocks * block_kv - skv
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    k_seg_blocks = None
    if segment_ids is not None:
        k_seg = segment_ids
        if pad:  # pad with -1: matches no real document id
            k_seg = jnp.pad(k_seg, ((0, 0), (0, pad)), constant_values=-1)
        k_seg_blocks = k_seg.reshape(b, n_blocks, block_kv)

    qg = (q.astype(jnp.float32) * scale).reshape(b, sq, nkv, g, d)
    kb = k.astype(jnp.float32).reshape(b, n_blocks, block_kv, nkv, d)
    vb = v.astype(jnp.float32).reshape(b, n_blocks, block_kv, nkv, d)
    q_pos = jnp.arange(sq)

    def body(carry, blk):
        acc, m, l = carry  # acc [b,sq,nkv,g,d], m/l [b,sq,nkv,g]
        kj, vj, j = blk    # kj/vj [b,block_kv,nkv,d]
        s = jnp.einsum("bsngd,btnd->bsngt", qg, kj)  # [b,sq,nkv,g,block_kv]
        kv_pos = j * block_kv + jnp.arange(block_kv)
        valid = kv_pos < skv
        if causal:
            win = q_pos[:, None] >= kv_pos[None, :]
            if sliding_window is not None:
                win = win & (q_pos[:, None] - kv_pos[None, :]
                             < sliding_window)
            valid = valid[None, :] & win
            valid = jnp.broadcast_to(valid[None], (b, sq, block_kv))
        else:
            valid = jnp.broadcast_to(valid[None, None], (b, sq, block_kv))
        if segment_ids is not None:
            # block-diagonal across documents (--reset_attention_mask)
            ksj = jax.lax.dynamic_index_in_dim(k_seg_blocks, j, axis=1,
                                               keepdims=False)
            valid = valid & (segment_ids[:, :, None] == ksj[:, None, :])
        s = jnp.where(valid[:, :, None, None, :], s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # guard fully-masked rows (m_new = -inf): exp(-inf - -inf) -> use 0
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        # l accumulates the UNdropped sum (dropout scales softmax output,
        # it does not renormalize it — same as the dot path's
        # softmax-then-dropout); only the value accumulation sees the
        # inverted-dropout mask
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pz = p
        if dropout_rate > 0.0:
            keep = jax.random.bernoulli(
                jax.random.fold_in(dropout_rng, j), 1.0 - dropout_rate,
                p.shape)
            pz = p * keep.astype(p.dtype) / (1.0 - dropout_rate)
        acc_new = acc * alpha[..., None] + jnp.einsum("bsngt,btnd->bsngd",
                                                      pz, vj)
        return (acc_new, m_new, l_new), None

    acc0 = jnp.zeros((b, sq, nkv, g, d), jnp.float32)
    m0 = jnp.full((b, sq, nkv, g), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, sq, nkv, g), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(
        body, (acc0, m0, l0),
        (jnp.moveaxis(kb, 1, 0), jnp.moveaxis(vb, 1, 0), jnp.arange(n_blocks)))
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.reshape(b, sq, nq, d).astype(q.dtype)
