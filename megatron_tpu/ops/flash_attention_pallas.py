"""Pallas TPU flash attention: causal + GQA + segment masks, fwd and bwd.

TPU-native replacement for the reference's CUDA attention kernels — the
external FlashAttention-2 package (ref: megatron/model/transformer.py:514-522
`flash_attn_func`) and the fused scaled-masked-softmax kernels it superseded
(ref: megatron/fused_kernels/scaled_*_softmax*.cu — K1-K3 in SURVEY.md §2.2).

Kernel shape (FlashAttention-2 algorithm on the TPU memory hierarchy):
- grid (batch, q_heads, q_blocks, kv_blocks); the kv axis is innermost, so
  TPU's sequential grid execution lets a VMEM scratch accumulator carry the
  online-softmax state (m, l, acc) across kv steps — the analogue of the
  CUDA kernel's per-CTA registers.
- Q/K/V blocks are DMA'd HBM->VMEM by BlockSpec; the MXU does the two GEMMs
  per tile; softmax renormalization runs on the VPU in fp32.
- Causality skips whole kv blocks past the diagonal (`pl.when`), the partial
  diagonal block is masked by lane iota.
- GQA: the kv-head BlockSpec index maps q-head h -> kv-head h // group, so
  MQA/GQA never materialize broadcast K/V (the reference materializes the
  broadcast at transformer.py:448-455 in the unfused path).
- Backward is a custom VJP with the standard flash recomputation: saved
  per-row logsumexp + delta = rowsum(dO*O), one kernel for dQ (grid over q
  blocks) and one for dK/dV (grid over kv blocks).

Layout: [b, s, n, d] at the API boundary (matching models/attention.py);
kernels run head-major [b, n, s, d].
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_KV = 512
NEG_INF = -1e30
# exp clamp for rows whose every score in a block is masked (possible with
# segment masking: a document's rows see zero keys in a foreign-document
# block). exp(s - max(m, CLAMP)) = exp(NEG_INF + 1e20) == 0 for masked
# entries even when the running max itself is still NEG_INF; real scores
# always exceed the clamp so normal rows are untouched.
MASK_CLAMP = -1e20
# Per-row stats (lse, delta) carry a trailing lanes dim: TPU lowering requires
# the last two block dims be (8k, 128k) or equal to the array dims, so a
# rank-3 [b, n, s] stat with block (1, 1, bq) cannot lower. Stats are stored
# [b, n, s, STAT_LANES] with the row value broadcast across lanes (the
# official jax TPU flash kernel does the same with 128 lanes; 8 == one f32
# sublane keeps the HBM footprint 16x smaller, which matters at 32k seq).
STAT_LANES = 8

# murmur3 fmix32 constants as wrapping int32 (0x85ebca6b, 0xc2b2ae35) —
# the in-kernel counter-based dropout RNG below uses plain int32 ops
# (wrapping multiply + LOGICAL shifts), so it runs identically under
# interpret mode on CPU and compiled on TPU; pltpu.prng_random_bits has
# no CPU lowering, which would leave the dropout path untestable here
_FMIX_M1 = -2048144789
_FMIX_M2 = -1028477387


def _fmix32(x):
    """murmur3 finalizer: full avalanche on int32 (wrapping arithmetic).

    Constants stay PYTHON ints (signed-int32 values): a jnp constant
    would be captured as a pallas_call closure array, which the
    interpret path refuses ('Cannot lower a pallas_call with
    constants'); python scalars promote weakly onto the traced int32."""
    srl = jax.lax.shift_right_logical
    x = x ^ srl(x, 16)
    x = x * _FMIX_M1
    x = x ^ srl(x, 13)
    x = x * _FMIX_M2
    x = x ^ srl(x, 16)
    return x


def _dropout_keep(seed_i32, bh, qi, ki, block_q, block_kv, rate):
    """Deterministic [block_q, block_kv] keep mask for (batch*head, q
    block, kv block): two fmix rounds over (seed ^ head-row, kv column).
    The SAME function runs in the forward and BOTH backward kernels, so
    the mask regenerates bit-exactly without ever being stored."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 0)
    kv_pos = ki * block_kv + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 1)
    # golden-ratio constants as wrapping int32 (0x9E3779B1 == -1640531535
    # signed); python ints, not jnp constants — see _fmix32
    row = _fmix32(seed_i32 ^ (bh * (-1640531535))
                  ^ (q_pos * 0x61C88647))
    u = _fmix32(row ^ kv_pos)
    # 31 uniform bits vs a compile-time threshold
    u31 = jax.lax.shift_right_logical(u, 1)
    thresh = int(rate * float(2 ** 31))
    return u31 >= thresh


def _fwd_kernel(q_ref, k_ref, v_ref, *refs, scale, causal, block_q,
                block_kv, num_kv, has_segs=False, window=None,
                dropout_rate=0.0):
    # refs: [qs_ref, ks_ref]? [seed_ref]? o_ref, lse_ref, acc_ref, m_ref,
    # l_ref — segment-id blocks / the dropout seed are inputs only when
    # the feature is on, so the plain path pays zero extra DMA
    refs = list(refs)
    qs_ref = ks_ref = seed_ref = None
    if has_segs:
        qs_ref, ks_ref = refs[0], refs[1]
        refs = refs[2:]
    if dropout_rate > 0.0:
        seed_ref = refs[0]
        refs = refs[1:]
    o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    drop_z = None
    if dropout_rate > 0.0:
        # computed at kernel top level: program_id inside a pl.when body
        # would be captured as a cond-closure constant, which the
        # interpret path refuses
        bh = pl.program_id(0) * pl.num_programs(1) + pl.program_id(1)
        dkeep = _dropout_keep(seed_ref[0, 0].astype(jnp.int32), bh, qi,
                              ki, block_q, block_kv, dropout_rate)
        drop_z = dkeep.astype(jnp.float32) / (1.0 - dropout_rate)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # whole block beyond the diagonal -> skip (causal); with a sliding
    # window also skip blocks entirely BEHIND the band
    run = True
    if causal:
        run = ki * block_kv <= qi * block_q + block_q - 1
        if window is not None:
            run = run & (ki * block_kv + block_kv - 1
                         > qi * block_q - window)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32) * scale      # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)              # [bkv, d]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            kv_pos = ki * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            keep = q_pos >= kv_pos
            if window is not None:
                keep = keep & (q_pos - kv_pos < window)
            s = jnp.where(keep, s, NEG_INF)
        if has_segs:
            # block-diagonal across documents (ref: --reset_attention_mask,
            # megatron/utils.py:137-194); ids ride as f32 lanes, equality
            # on small ints is exact
            q_seg = qs_ref[0][:, :1]                     # [bq, 1]
            k_seg = ks_ref[0][:, 0][None, :]             # [1, bkv]
            s = jnp.where(q_seg == k_seg, s, NEG_INF)

        m_prev = m_ref[:, :1]                            # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # MASK_CLAMP: a row can be fully masked in this block (foreign
        # document) — without the clamp exp(NEG_INF - NEG_INF) == 1 would
        # attend uniformly to the masked keys
        p = jnp.exp(s - jnp.maximum(m_new, MASK_CLAMP))
        alpha = jnp.exp(m_prev - m_new)                  # [bq, 1]
        # softmax-then-dropout: l keeps the UNdropped sum (dropout scales
        # the normalized probs, it does not renormalize them); only the
        # value accumulation sees the inverted-dropout mask
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pz = p if drop_z is None else p * drop_z
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            pz, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l > 0.0, l, 1.0)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(
            m_ref[:, :1] + jnp.log(l_safe), lse_ref.shape[2:])


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   *refs, scale, causal, block_q, block_kv, num_kv,
                   has_dlse=False, has_segs=False, window=None,
                   dropout_rate=0.0):
    # refs: [qs_ref, ks_ref]? [dlse_ref]? [seed_ref]? dq_ref, dq_acc —
    # segment blocks / dlse / the dropout seed are inputs only when the
    # respective feature is on (the plain path skips the DMAs)
    refs = list(refs)
    qs_ref = ks_ref = dlse_ref = seed_ref = None
    if has_segs:
        qs_ref, ks_ref = refs[0], refs[1]
        refs = refs[2:]
    if has_dlse:
        dlse_ref = refs[0]
        refs = refs[1:]
    if dropout_rate > 0.0:
        seed_ref = refs[0]
        refs = refs[1:]
    dq_ref, dq_acc = refs
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    drop_z = None
    if dropout_rate > 0.0:
        bh = pl.program_id(0) * pl.num_programs(1) + pl.program_id(1)
        dkeep = _dropout_keep(seed_ref[0, 0].astype(jnp.int32), bh, qi,
                              ki, block_q, block_kv, dropout_rate)
        drop_z = dkeep.astype(jnp.float32) / (1.0 - dropout_rate)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = True
    if causal:
        run = ki * block_kv <= qi * block_q + block_q - 1
        if window is not None:
            run = run & (ki * block_kv + block_kv - 1
                         > qi * block_q - window)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32) * scale
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        # clamp like the forward: a fully-masked row's lse is NEG_INF and
        # exp(NEG_INF - NEG_INF) would resurrect its masked entries
        lse = jnp.maximum(lse_ref[0, 0][:, :1], MASK_CLAMP)  # [bq, 1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            kv_pos = ki * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            keep = q_pos >= kv_pos
            if window is not None:
                keep = keep & (q_pos - kv_pos < window)
            s = jnp.where(keep, s, NEG_INF)
        if has_segs:
            q_seg = qs_ref[0][:, :1]
            k_seg = ks_ref[0][:, 0][None, :]
            s = jnp.where(q_seg == k_seg, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if drop_z is not None:
            # the forward's regenerated mask; with O = (P∘Z)V/l the
            # chain rule gives dS = P ∘ (Z∘dP_raw - delta): delta =
            # rowsum(dO∘O) already absorbs the dropped entries
            dp = dp * drop_z
        # dlse term: d(lse)/d(s) = p, so an lse cotangent adds p*dlse
        # (used by ring attention's online merge weights)
        rest = dp - delta
        if has_dlse:
            rest = rest + dlse_ref[0, 0][:, :1]
        ds = p * rest
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(ki == num_kv - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    *refs, scale, causal, block_q, block_kv, num_q,
                    has_dlse=False, has_segs=False, window=None,
                    dropout_rate=0.0):
    refs = list(refs)
    qs_ref = ks_ref = dlse_ref = seed_ref = None
    if has_segs:
        qs_ref, ks_ref = refs[0], refs[1]
        refs = refs[2:]
    if has_dlse:
        dlse_ref = refs[0]
        refs = refs[1:]
    if dropout_rate > 0.0:
        seed_ref = refs[0]
        refs = refs[1:]
    dk_ref, dv_ref, dk_acc, dv_acc = refs
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    drop_z = None
    if dropout_rate > 0.0:
        # same (bh, qi, ki) stream as the forward — this kernel's grid
        # swaps the block axes, but the mask is indexed by the block
        # COORDINATES, not the grid order
        bh = pl.program_id(0) * pl.num_programs(1) + pl.program_id(1)
        dkeep = _dropout_keep(seed_ref[0, 0].astype(jnp.int32), bh, qi,
                              ki, block_q, block_kv, dropout_rate)
        drop_z = dkeep.astype(jnp.float32) / (1.0 - dropout_rate)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        # q block entirely above the diagonal contributes nothing; with a
        # sliding window, neither does one entirely past the band
        run = qi * block_q + block_q - 1 >= ki * block_kv
        if window is not None:
            run = run & (qi * block_q
                         < ki * block_kv + block_kv - 1 + window)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32) * scale
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = jnp.maximum(lse_ref[0, 0][:, :1], MASK_CLAMP)  # [bq, 1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            kv_pos = ki * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            keep = q_pos >= kv_pos
            if window is not None:
                keep = keep & (q_pos - kv_pos < window)
            s = jnp.where(keep, s, NEG_INF)
        if has_segs:
            q_seg = qs_ref[0][:, :1]
            k_seg = ks_ref[0][:, 0][None, :]
            s = jnp.where(q_seg == k_seg, s, NEG_INF)
        p = jnp.exp(s - lse)                             # [bq, bkv]
        pz = p
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if drop_z is not None:
            pz = p * drop_z  # dV sees the dropped weights: dV = (P∘Z)ᵀdO
            dp = dp * drop_z  # dS = P ∘ (Z∘dP_raw - delta)
        dv_acc[:] += jax.lax.dot_general(
            pz, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        rest = dp - delta
        if has_dlse:
            rest = rest + dlse_ref[0, 0][:, :1]
        ds = p * rest
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _pick_block(s: int, bmax: int) -> int:
    """Largest block <= bmax that tiles s: the requested block if it divides
    s exactly, else the largest 128-multiple divisor of s. Handles
    128-divisible-but-not-512-divisible lengths like 640/768/1280 by
    shrinking instead of asserting."""
    bmax = min(bmax, s)
    if s % bmax == 0:
        return bmax
    for b in range(bmax - bmax % 128, 0, -128):
        if s % b == 0:
            return b
    raise ValueError(
        f"sequence length {s} has no 128-multiple block divisor <= {bmax}; "
        "pad the sequence to a multiple of 128 or use the XLA fallback path")


def _pick_blocks(sq, sk, block_q, block_kv):
    return _pick_block(sq, block_q), _pick_block(sk, block_kv)


def _seg_lanes(seg, lanes=STAT_LANES):
    """[b, s] f32 segment ids -> [b, s, lanes] broadcast (same trick as
    the lse/delta stats: the trailing lanes dim satisfies TPU tiling)."""
    return jnp.broadcast_to(seg.astype(jnp.float32)[..., None],
                            seg.shape + (lanes,))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 10, 11))
def pallas_flash_attention(q, k, v, causal=True, scale=None,
                           block_q=DEFAULT_BLOCK_Q, block_kv=DEFAULT_BLOCK_KV,
                           interpret=False, q_seg=None, k_seg=None,
                           sliding_window=None, dropout_rate=0.0,
                           dropout_seed=None):
    """q [b, sq, nq, d], k/v [b, sk, nkv, d] -> [b, sq, nq, d].

    `q_seg`/`k_seg` [b, s] FLOAT segment ids (cast outside so the vjp's
    cotangent plumbing stays all-float): scores are masked where ids
    differ — block-diagonal attention across EOD-separated documents
    (ref: --reset_attention_mask, megatron/utils.py:137-194).

    `dropout_rate` (static) + `dropout_seed` ([1, STAT_LANES] f32 array
    holding one integer <= 2^24, a zero-cotangent diff arg like the seg
    ids): attention dropout INSIDE the kernel — the reference's FA2
    `dropout_p` (ref: transformer.py:514-522). Masks are regenerated
    from (seed, head, block coords) by a counter-based hash in forward
    AND both backward kernels; nothing is stored."""
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_kv, interpret,
                        q_seg, k_seg, sliding_window, dropout_rate,
                        dropout_seed)
    return out


def _flash_fwd(q, k, v, causal, scale, block_q, block_kv, interpret,
               q_seg=None, k_seg=None, sliding_window=None,
               dropout_rate=0.0, dropout_seed=None):
    b, sq, nq, d = q.shape
    sk, nkv = k.shape[1], k.shape[2]
    g = nq // nkv
    if scale is None:
        scale = d ** -0.5
    bq, bkv = _pick_blocks(sq, sk, block_q, block_kv)
    num_q, num_kv = sq // bq, sk // bkv
    has_segs = q_seg is not None
    assert has_segs == (k_seg is not None), "q_seg/k_seg must come together"
    has_drop = dropout_rate > 0.0
    assert not has_drop or dropout_seed is not None, (
        "dropout_rate > 0 needs dropout_seed")

    qT = q.transpose(0, 2, 1, 3)  # [b, nq, sq, d]
    kT = k.transpose(0, 2, 1, 3)  # [b, nkv, sk, d]
    vT = v.transpose(0, 2, 1, 3)

    grid = (b, nq, num_q, num_kv)
    q_spec = pl.BlockSpec((1, 1, bq, d), lambda bi, h, qi, ki: (bi, h, qi, 0))
    kv_spec = pl.BlockSpec((1, 1, bkv, d),
                           lambda bi, h, qi, ki: (bi, h // g, ki, 0))
    o_spec = pl.BlockSpec((1, 1, bq, d), lambda bi, h, qi, ki: (bi, h, qi, 0))
    lse_spec = pl.BlockSpec((1, 1, bq, STAT_LANES),
                            lambda bi, h, qi, ki: (bi, h, qi, 0))
    seg_inputs, seg_specs = [], []
    if has_segs:
        seg_inputs = [_seg_lanes(q_seg), _seg_lanes(k_seg)]
        seg_specs = [
            pl.BlockSpec((1, bq, STAT_LANES),
                         lambda bi, h, qi, ki: (bi, qi, 0)),
            pl.BlockSpec((1, bkv, STAT_LANES),
                         lambda bi, h, qi, ki: (bi, ki, 0)),
        ]
    drop_inputs, drop_specs = [], []
    if has_drop:
        drop_inputs = [jnp.broadcast_to(
            jnp.asarray(dropout_seed, jnp.float32).reshape(1, -1)[:, :1],
            (1, STAT_LANES))]
        drop_specs = [pl.BlockSpec((1, STAT_LANES),
                                   lambda bi, h, qi, ki: (0, 0))]

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=bq, block_kv=bkv, num_kv=num_kv,
                          has_segs=has_segs, window=sliding_window,
                          dropout_rate=dropout_rate),
        grid=grid,
        in_specs=[q_spec, kv_spec, kv_spec] + seg_specs + drop_specs,
        out_specs=[o_spec, lse_spec],
        out_shape=[jax.ShapeDtypeStruct((b, nq, sq, d), q.dtype),
                   jax.ShapeDtypeStruct((b, nq, sq, STAT_LANES), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32),
                        pltpu.VMEM((bq, STAT_LANES), jnp.float32),
                        pltpu.VMEM((bq, STAT_LANES), jnp.float32)],
        interpret=interpret,
    )(qT, kT, vT, *seg_inputs, *drop_inputs)
    out = out.transpose(0, 2, 1, 3)
    return out, (q, k, v, out, lse, q_seg, k_seg, dropout_seed)


def _flash_bwd_core(causal, scale, block_q, block_kv, interpret, res, dout,
                    dlse=None, sliding_window=None, dropout_rate=0.0):
    """Shared backward. `dlse` [b, sq, nq] is the cotangent of the exposed
    logsumexp (ring attention's merge weights use it); None means zero."""
    q, k, v, out, lse, q_seg, k_seg, dropout_seed = res
    b, sq, nq, d = q.shape
    sk, nkv = k.shape[1], k.shape[2]
    g = nq // nkv
    if scale is None:
        scale = d ** -0.5
    bq, bkv = _pick_blocks(sq, sk, block_q, block_kv)
    num_q, num_kv = sq // bq, sk // bkv
    has_segs = q_seg is not None
    has_drop = dropout_rate > 0.0

    qT = q.transpose(0, 2, 1, 3)
    kT = k.transpose(0, 2, 1, 3)
    vT = v.transpose(0, 2, 1, 3)
    doT = dout.transpose(0, 2, 1, 3)
    # delta = rowsum(dO * O) [b, nq, sq] (flash-2 backward precomputation),
    # broadcast to STAT_LANES like the lse residual
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).transpose(0, 2, 1)
    delta = jnp.broadcast_to(delta[..., None], (b, nq, sq, STAT_LANES))
    has_dlse = dlse is not None
    seg_inputs = ([_seg_lanes(q_seg), _seg_lanes(k_seg)] if has_segs else [])
    extra = []
    if has_dlse:
        extra = [jnp.broadcast_to(
            dlse.astype(jnp.float32).transpose(0, 2, 1)[..., None],
            (b, nq, sq, STAT_LANES))]
    drop_inputs = []
    if has_drop:
        drop_inputs = [jnp.broadcast_to(
            jnp.asarray(dropout_seed, jnp.float32).reshape(1, -1)[:, :1],
            (1, STAT_LANES))]

    q_spec = pl.BlockSpec((1, 1, bq, d), lambda bi, h, qi, ki: (bi, h, qi, 0))
    kv_spec = pl.BlockSpec((1, 1, bkv, d),
                           lambda bi, h, qi, ki: (bi, h // g, ki, 0))
    row_spec = pl.BlockSpec((1, 1, bq, STAT_LANES),
                            lambda bi, h, qi, ki: (bi, h, qi, 0))
    seg_specs = ([
        pl.BlockSpec((1, bq, STAT_LANES), lambda bi, h, qi, ki: (bi, qi, 0)),
        pl.BlockSpec((1, bkv, STAT_LANES), lambda bi, h, qi, ki: (bi, ki, 0)),
    ] if has_segs else [])

    seed_spec = [pl.BlockSpec((1, STAT_LANES),
                              lambda bi, h, qi, ki: (0, 0))] * has_drop

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_kv=bkv, num_kv=num_kv,
                          has_dlse=has_dlse, has_segs=has_segs,
                          window=sliding_window,
                          dropout_rate=dropout_rate),
        grid=(b, nq, num_q, num_kv),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec]
        + seg_specs + [row_spec] * has_dlse + seed_spec,
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda bi, h, qi, ki: (bi, h, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, nq, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qT, kT, vT, doT, lse, delta, *seg_inputs, *extra, *drop_inputs)

    # dk/dv: grid swaps the roles — kv blocks outer, q blocks inner; every
    # q-head contributes to its kv-head, so run per Q-HEAD and sum groups
    # after (keeps the kernel free of cross-head reductions)
    q_spec2 = pl.BlockSpec((1, 1, bq, d),
                           lambda bi, h, ki, qi: (bi, h, qi, 0))
    kv_spec2 = pl.BlockSpec((1, 1, bkv, d),
                            lambda bi, h, ki, qi: (bi, h // g, ki, 0))
    row_spec2 = pl.BlockSpec((1, 1, bq, STAT_LANES),
                             lambda bi, h, ki, qi: (bi, h, qi, 0))
    dk_spec = pl.BlockSpec((1, 1, bkv, d),
                           lambda bi, h, ki, qi: (bi, h, ki, 0))
    seg_specs2 = ([
        pl.BlockSpec((1, bq, STAT_LANES), lambda bi, h, ki, qi: (bi, qi, 0)),
        pl.BlockSpec((1, bkv, STAT_LANES), lambda bi, h, ki, qi: (bi, ki, 0)),
    ] if has_segs else [])

    seed_spec2 = [pl.BlockSpec((1, STAT_LANES),
                               lambda bi, h, ki, qi: (0, 0))] * has_drop

    dk_per_head, dv_per_head = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_kv=bkv, num_q=num_q,
                          has_dlse=has_dlse, has_segs=has_segs,
                          window=sliding_window,
                          dropout_rate=dropout_rate),
        grid=(b, nq, num_kv, num_q),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2]
        + seg_specs2 + [row_spec2] * has_dlse + seed_spec2,
        out_specs=[dk_spec, dk_spec],
        out_shape=[jax.ShapeDtypeStruct((b, nq, sk, d), jnp.float32),
                   jax.ShapeDtypeStruct((b, nq, sk, d), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bkv, d), jnp.float32),
                        pltpu.VMEM((bkv, d), jnp.float32)],
        interpret=interpret,
    )(qT, kT, vT, doT, lse, delta, *seg_inputs, *extra, *drop_inputs)

    # GQA: sum the per-q-head dk/dv into kv heads
    dk = dk_per_head.reshape(b, nkv, g, sk, d).sum(axis=2)
    dv = dv_per_head.reshape(b, nkv, g, sk, d).sum(axis=2)

    grads = (dq.transpose(0, 2, 1, 3),
             dk.transpose(0, 2, 1, 3).astype(k.dtype),
             dv.transpose(0, 2, 1, 3).astype(v.dtype))
    # float segment ids / the dropout seed are diff args purely for
    # plumbing: zero cotangent
    seg_grads = (jnp.zeros_like(q_seg) if has_segs else None,
                 jnp.zeros_like(k_seg) if has_segs else None,
                 jnp.zeros_like(dropout_seed) if has_drop else None)
    return grads, seg_grads


def _flash_bwd(causal, scale, block_q, block_kv, interpret,
               sliding_window, dropout_rate, res, dout):
    # sliding_window/dropout_rate arrive as NONDIFF args (static Python
    # values), never via the residuals — a traced scalar could not close
    # over the kernels
    (dq, dk, dv), (dqs, dks, dseed) = _flash_bwd_core(
        causal, scale, block_q, block_kv, interpret, res, dout,
        sliding_window=sliding_window, dropout_rate=dropout_rate)
    return dq, dk, dv, dqs, dks, dseed


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_kv, interpret,
                    q_seg=None, k_seg=None, sliding_window=None,
                    dropout_rate=0.0, dropout_seed=None):
    out, res = _flash_fwd(q, k, v, causal, scale, block_q, block_kv,
                          interpret, q_seg, k_seg, sliding_window,
                          dropout_rate, dropout_seed)
    return out, res


pallas_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def pallas_flash_attention_with_lse(q, k, v, causal=True, scale=None,
                                    block_q=DEFAULT_BLOCK_Q,
                                    block_kv=DEFAULT_BLOCK_KV,
                                    interpret=False):
    """Like pallas_flash_attention but also returns the per-row logsumexp
    [b, sq, nq] — differentiable, for online merging across blocks that
    live on different devices (ring attention hops)."""
    (out, lse), _ = _with_lse_fwd(q, k, v, causal, scale, block_q, block_kv,
                                  interpret)
    return out, lse


def _with_lse_fwd(q, k, v, causal, scale, block_q, block_kv, interpret):
    out, res = _flash_fwd(q, k, v, causal, scale, block_q, block_kv,
                          interpret)
    lse4 = res[4]  # [b, nq, sq, STAT_LANES]
    return (out, lse4[..., 0].transpose(0, 2, 1)), res


def _with_lse_bwd(causal, scale, block_q, block_kv, interpret, res, cot):
    dout, dlse = cot
    grads, _ = _flash_bwd_core(causal, scale, block_q, block_kv, interpret,
                               res, dout, dlse)
    return grads


pallas_flash_attention_with_lse.defvjp(_with_lse_fwd, _with_lse_bwd)
