"""Pallas fused RMSNorm / LayerNorm (fwd + bwd).

TPU-native equivalent of the reference's fused mixed-precision LayerNorm
CUDA extension (ref: megatron/fused_kernels/layer_norm_cuda_kernel.cu:1-818,
layer_norm_cuda.cpp forward_affine/backward_affine) and the RMSNorm it
pairs with (ref: megatron/model/fused_layer_norm.py:125-139). Stats are
fp32 regardless of input dtype — the reference kernel's mixed-precision
contract.

One kernel invocation normalizes a [block_rows, h] tile resident in VMEM:
the load, the fp32 moment reduction, the rsqrt, and the affine output are
fused with zero HBM round-trips. The backward recomputes row statistics
from x (cheaper than an HBM round-trip for saved stats at transformer
widths) and emits per-grid-step partial weight grads that are summed
outside — the Pallas formulation of the CUDA kernel's two-stage
gamma/beta reduction (ref: layer_norm_cuda_kernel.cu cuComputePartGradGammaBeta).

`megatron_tpu/models/norms.py` is the canonical jnp implementation; these
kernels exist for explicit fusion control. On-chip A/B numbers live in
PERF_NOTES.md — XLA already fuses the jnp chain well, so the model default
stays jnp unless a profile says otherwise.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _pick_rows(rows: int, h: int, vmem_budget: int = 1 << 21) -> int:
    """Row block: a multiple of 8 (TPU sublane) whose fp32 tile stays under
    ~2 MB of VMEM. Divisibility of `rows` is NOT required — callers zero-pad
    the row dim up to a block multiple (padded rows contribute nothing to
    the weight-grad partials since dy is zero there), so a prime row count
    no longer collapses to a 1-row grid."""
    cap = max(vmem_budget // (4 * h), 1)
    if cap < 8:
        return cap
    return min(cap // 8 * 8, max(-(-rows // 8) * 8, 8))


def _pad_rows(xr, br: int):
    """Zero-pad [rows, h] up to a multiple of the row block."""
    pad = (-xr.shape[0]) % br
    if pad:
        xr = jnp.pad(xr, ((0, pad), (0, 0)))
    return xr


# ---------------------------------------------------------------------------
# RMSNorm
# ---------------------------------------------------------------------------

def _rms_fwd_kernel(x_ref, s_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    o_ref[...] = (x * r * s_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _rms_bwd_kernel(x_ref, s_ref, dy_ref, dx_ref, ds_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    s = s_ref[...].astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    xh = x * r
    g = dy * s
    c = jnp.mean(g * xh, axis=-1, keepdims=True)
    dx_ref[...] = (r * (g - xh * c)).astype(dx_ref.dtype)
    ds_ref[...] = jnp.sum(dy * xh, axis=0, keepdims=True)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def pallas_rmsnorm(x, scale, eps: float = 1e-5, interpret: bool = False):
    """x [..., h] * rsqrt(mean(x², -1) + eps) * scale, fused."""
    out, _ = _rms_fwd(x, scale, eps, interpret)
    return out


def _rms_fwd(x, scale, eps, interpret):
    orig_shape = x.shape
    h = orig_shape[-1]
    xr = x.reshape(-1, h)
    rows = xr.shape[0]
    br = _pick_rows(rows, h)
    xr = _pad_rows(xr, br)
    rows_p = xr.shape[0]
    s2 = scale.reshape(1, h)
    out = pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps),
        grid=(rows_p // br,),
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((1, h), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((br, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows_p, h), x.dtype),
        interpret=interpret,
    )(xr, s2)
    return out[:rows].reshape(orig_shape), (x, scale)


def _rms_bwd(eps, interpret, res, dy):
    x, scale = res
    orig_shape = x.shape
    h = orig_shape[-1]
    xr = x.reshape(-1, h)
    dyr = dy.reshape(-1, h)
    rows = xr.shape[0]
    br = _pick_rows(rows, h)
    xr = _pad_rows(xr, br)
    dyr = _pad_rows(dyr, br)
    rows_p = xr.shape[0]
    grid = rows_p // br
    dx, ds_part = pl.pallas_call(
        functools.partial(_rms_bwd_kernel, eps=eps),
        grid=(grid,),
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((1, h), lambda i: (0, 0)),
                  pl.BlockSpec((br, h), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                   pl.BlockSpec((1, h), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((rows_p, h), x.dtype),
                   jax.ShapeDtypeStruct((grid, h), jnp.float32)],
        interpret=interpret,
    )(xr, scale.reshape(1, h), dyr)
    ds = jnp.sum(ds_part, axis=0).astype(scale.dtype)
    return dx[:rows].reshape(orig_shape), ds


pallas_rmsnorm.defvjp(_rms_fwd, _rms_bwd)


# ---------------------------------------------------------------------------
# LayerNorm
# ---------------------------------------------------------------------------

def _ln_fwd_kernel(x_ref, s_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    r = jax.lax.rsqrt(jnp.mean(xc * xc, axis=-1, keepdims=True) + eps)
    o_ref[...] = (xc * r * s_ref[...].astype(jnp.float32)
                  + b_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _ln_bwd_kernel(x_ref, s_ref, dy_ref, dx_ref, ds_ref, db_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    s = s_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    r = jax.lax.rsqrt(jnp.mean(xc * xc, axis=-1, keepdims=True) + eps)
    xh = xc * r
    g = dy * s
    gm = jnp.mean(g, axis=-1, keepdims=True)
    c = jnp.mean(g * xh, axis=-1, keepdims=True)
    dx_ref[...] = (r * (g - gm - xh * c)).astype(dx_ref.dtype)
    ds_ref[...] = jnp.sum(dy * xh, axis=0, keepdims=True)
    db_ref[...] = jnp.sum(dy, axis=0, keepdims=True)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def pallas_layernorm(x, scale, bias, eps: float = 1e-5,
                     interpret: bool = False):
    """Affine LayerNorm (fp32 stats), fused
    (ref: layer_norm_cuda.cpp forward_affine)."""
    out, _ = _ln_fwd(x, scale, bias, eps, interpret)
    return out


def _ln_fwd(x, scale, bias, eps, interpret):
    orig_shape = x.shape
    h = orig_shape[-1]
    xr = x.reshape(-1, h)
    rows = xr.shape[0]
    br = _pick_rows(rows, h)
    xr = _pad_rows(xr, br)
    rows_p = xr.shape[0]
    out = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(rows_p // br,),
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((1, h), lambda i: (0, 0)),
                  pl.BlockSpec((1, h), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((br, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows_p, h), x.dtype),
        interpret=interpret,
    )(xr, scale.reshape(1, h), bias.reshape(1, h))
    return out[:rows].reshape(orig_shape), (x, scale)


def _ln_bwd(eps, interpret, res, dy):
    x, scale = res
    orig_shape = x.shape
    h = orig_shape[-1]
    xr = x.reshape(-1, h)
    dyr = dy.reshape(-1, h)
    rows = xr.shape[0]
    br = _pick_rows(rows, h)
    xr = _pad_rows(xr, br)
    dyr = _pad_rows(dyr, br)
    rows_p = xr.shape[0]
    grid = rows_p // br
    dx, ds_part, db_part = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, eps=eps),
        grid=(grid,),
        in_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                  pl.BlockSpec((1, h), lambda i: (0, 0)),
                  pl.BlockSpec((br, h), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((br, h), lambda i: (i, 0)),
                   pl.BlockSpec((1, h), lambda i: (i, 0)),
                   pl.BlockSpec((1, h), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((rows_p, h), x.dtype),
                   jax.ShapeDtypeStruct((grid, h), jnp.float32),
                   jax.ShapeDtypeStruct((grid, h), jnp.float32)],
        interpret=interpret,
    )(xr, scale.reshape(1, h), dyr)
    ds = jnp.sum(ds_part, axis=0).astype(scale.dtype)
    db = jnp.sum(db_part, axis=0).astype(scale.dtype)
    return dx[:rows].reshape(orig_shape), ds, db


pallas_layernorm.defvjp(_ln_fwd, _ln_bwd)
