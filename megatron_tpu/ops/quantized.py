"""Int8 quantized GEMM — the TPU-native counterpart of the reference's
Transformer Engine fp8 path (ref: megatron/model/transformer.py:931-950 and
the --fp8_* flag group, megatron/arguments.py:303-313).

The reference reaches low-precision GEMM throughput through TE's fp8
(H100-only; inert on its A100 targets too). TPU v5e/v5p MXUs have no fp8
datapath — the hardware's low-precision lever is **int8**, at ~2x the bf16
MACs/cycle on v5e. This module is the TE recipe rebuilt on that datapath:

- forward GEMMs run int8 x int8 -> int32 on the MXU, with **per-token
  activation scales** and **per-output-channel weight scales** (the
  "current scaling" recipe: amax is taken from the tensor being quantized,
  no cross-step amax history to thread through the train state);
- the backward runs in the compute dtype on the *unquantized* operands
  (straight-through estimate; the hybrid recipe the reference exposes as
  --no_fp8_wgrad, extended to dgrad because e5m2 has no int analogue).

Applied to the attention q/kv/out projections and both MLP GEMMs when
`ModelConfig.quantized_gemm == "int8"`; the embedding and lm head stay in
the compute dtype (TE keeps those out of fp8 for the same accuracy
reasons). Opt in with --quantized_gemm int8.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class W8(NamedTuple):
    """A weight stored int8 with per-output-channel fp32 scales — the
    serving-side (weight-only storage) half of the int8 path: decode is
    HBM-bandwidth-bound, and an int8-resident weight halves its stream.
    Produced by `quantize_weights`; consumed transparently by `qdense`
    (the GEMM runs on the int8 datapath against per-token-quantized
    activations). As a NamedTuple it is a pytree: `lax.scan` slices the
    stacked [L, ...] serving layout per layer, and shardings ride the
    aligned axes from `quantize_axes`."""
    q: jax.Array      # int8, same shape as the source weight
    scale: jax.Array  # fp32, source shape minus the contraction axis


def quantize_rows(x):
    """x [..., K] -> (int8 values, fp32 scale [..., 1]) with per-row amax."""
    ax = jnp.max(jnp.abs(x), axis=-1, keepdims=True).astype(jnp.float32)
    scale = jnp.where(ax > 0, ax / 127.0, 1.0)
    xi = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    return xi.astype(jnp.int8), scale


def _quantize_cols(w):
    """w [K, N] -> (int8 values, fp32 scale [N]) with per-column amax."""
    aw = jnp.max(jnp.abs(w), axis=0).astype(jnp.float32)
    scale = jnp.where(aw > 0, aw / 127.0, 1.0)
    wi = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[None, :]),
                  -127, 127)
    return wi.astype(jnp.int8), scale


def _int8_matmul_impl(x, w):
    xi, sx = quantize_rows(x)
    wi, sw = _quantize_cols(w)
    yi = jax.lax.dot_general(
        xi, wi, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (yi.astype(jnp.float32) * sx * sw).astype(x.dtype)


@jax.custom_vjp
def int8_matmul(x, w):
    """[..., K] @ [K, N] with an int8-MXU forward and a full-precision
    backward. Numerics: per-row/per-column symmetric quantization bounds
    the forward's relative error at ~0.4% rms for well-conditioned
    operands; gradients are exact for the straight-through estimate."""
    return _int8_matmul_impl(x, w)


def _int8_matmul_fwd(x, w):
    return _int8_matmul_impl(x, w), (x, w)


def _int8_matmul_bwd(res, dy):
    x, w = res
    # contract dy's N against w's N for dx; batch dims of x against dy for dw
    dx = jax.lax.dot_general(dy, w, (((dy.ndim - 1,), (1,)), ((), ())))
    lead = tuple(range(x.ndim - 1))
    dw = jnp.tensordot(x, dy, axes=(lead, lead))
    return dx.astype(x.dtype), dw.astype(w.dtype)


int8_matmul.defvjp(_int8_matmul_fwd, _int8_matmul_bwd)


def _w8_matmul(x, w8: W8):
    """[..., K] against a pre-quantized weight: per-token-quantize x,
    int8 dot against the resident int8 weight, dequantize by both scales.
    No custom_vjp — this is the serving path; jnp.round's zero cotangent
    makes accidental differentiation loud (zero grads), not silently
    wrong."""
    xi, sx = quantize_rows(x)
    k = w8.q.shape[0]
    wi = w8.q.reshape(k, -1)
    yi = jax.lax.dot_general(
        xi, wi, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    y = (yi.astype(jnp.float32) * sx
         * w8.scale.reshape(-1).astype(jnp.float32))
    return y.astype(x.dtype).reshape(*x.shape[:-1], *w8.q.shape[1:])


# the contraction axis quantize_weights removes from each STACKED
# transformer weight [L, K, ...]; quantize_axes must drop the same one
_STACKED_CONTRACT_AXIS = 1
_QUANTIZABLE = ("wq", "wkv", "wo", "w1", "w2")


def quantize_weights(params):
    """Serving-time transform: re-store the transformer attention/MLP
    weights (the _QUANTIZABLE names, scan-stacked [L, K, ...]) as int8
    W8 leaves with per-layer per-output-channel scales. Embedding, norms
    and lm head keep their dtype (the TE-style accuracy carve-out).
    Returns a new params tree; pair with `quantize_axes` for sharded
    serving."""
    def walk(name, node):
        if isinstance(node, dict):
            if "router" in node:
                # MoE expert bank: [L, E, K, ...] layout — axis 1 is the
                # EXPERT dim, not the contraction, and _w8_matmul has no
                # banked path; experts stay in the compute dtype
                # (int8_expert_matmul covers the training-side lever)
                return node
            return {k: walk(k, v) for k, v in node.items()}
        if name in _QUANTIZABLE:
            ax = _STACKED_CONTRACT_AXIS
            amax = jnp.max(jnp.abs(node), axis=ax).astype(jnp.float32)
            scale = jnp.where(amax > 0, amax / 127.0, 1.0)
            qv = jnp.clip(jnp.round(node.astype(jnp.float32)
                                    / jnp.expand_dims(scale, ax)),
                          -127, 127).astype(jnp.int8)
            return W8(q=qv, scale=scale)
        return node
    out = dict(params)
    if "transformer" in out:
        out["transformer"] = walk("", params["transformer"])
    return out


def quantize_axes(axes, params):
    """Align a logical-axes tree with a `quantize_weights`-transformed
    params tree: wherever params holds a W8, the tuple axes leaf expands
    to W8(q=<original>, scale=<original minus the contraction axis>)."""
    def fix(ax, p):
        if isinstance(p, W8):
            a = _STACKED_CONTRACT_AXIS
            return W8(q=ax, scale=ax[:a] + ax[a + 1:])
        return ax
    # type(x) is tuple: stop at plain axes tuples, but a W8 ALREADY in
    # the axes tree (double application) would recurse — harmless, fix()
    # only rewraps against params
    return jax.tree.map(fix, axes, params,
                        is_leaf=lambda x: type(x) is tuple)


def has_quantized_weights(params) -> bool:
    return any(isinstance(x, W8) for x in jax.tree.leaves(
        params, is_leaf=lambda x: isinstance(x, W8)))


def wcast(w, dtype):
    """The call-site weight cast: fp weights cast to the compute dtype;
    W8 weights pass through untouched (dequantization is fused into the
    int8 GEMM inside qdense)."""
    if isinstance(w, W8):
        return w
    return w.astype(dtype)


def qdense(x, w, quantized_gemm: str):
    """Dense-layer dispatch shared by the attention/MLP call sites.

    `w` may carry extra trailing structure (the GLU [h, 2, ffn] layout) —
    it is flattened to [K, prod(rest)] for the GEMM and the output is
    reshaped back, so gate/value splits keep their leading-index layout.
    A W8 weight (serving-time int8 storage) takes the int8 datapath
    regardless of the training-mode flag — the resident weight demands
    it."""
    if isinstance(w, W8):
        return _w8_matmul(x, w)
    if quantized_gemm == "none":
        if w.ndim == 2:
            return x @ w
        return jnp.einsum("...h,hcf->...cf", x, w)
    assert quantized_gemm == "int8", quantized_gemm
    if w.ndim == 2:
        return int8_matmul(x, w)
    k = w.shape[0]
    y = int8_matmul(x, w.reshape(k, -1))
    return y.reshape(*y.shape[:-1], *w.shape[1:])


def _int8_bmm_impl(x, w):
    """x [..., E, C, K] against a per-expert bank w [E, K, N] on the int8
    datapath: per-row activation scales, per-(expert, column) weight
    scales, int32 accumulation."""
    xi, sx = quantize_rows(x)
    # one quantization recipe: per-expert vmap of the dense per-column rule
    wi, sw = jax.vmap(_quantize_cols)(w)                      # [E,K,N],[E,N]
    yi = jnp.einsum("...eck,ekn->...ecn", xi, wi,
                    preferred_element_type=jnp.int32)
    y = yi.astype(jnp.float32) * sx * sw[:, None, :]
    return y.astype(x.dtype)


@jax.custom_vjp
def int8_expert_matmul(x, w):
    """Per-expert batched GEMM (MoE banks) with the same int8-forward /
    full-precision-backward recipe as int8_matmul. x [..., E, C, K],
    w [E, K, N] -> [..., E, C, N]."""
    return _int8_bmm_impl(x, w)


def _int8_bmm_fwd(x, w):
    return _int8_bmm_impl(x, w), (x, w)


def _int8_bmm_bwd(res, dy):
    x, w = res
    dx = jnp.einsum("...ecn,ekn->...eck", dy, w)
    dw = jnp.einsum("...eck,...ecn->ekn", x, dy)
    return dx.astype(x.dtype), dw.astype(w.dtype)


int8_expert_matmul.defvjp(_int8_bmm_fwd, _int8_bmm_bwd)
