"""Int8 quantized GEMM — the TPU-native counterpart of the reference's
Transformer Engine fp8 path (ref: megatron/model/transformer.py:931-950 and
the --fp8_* flag group, megatron/arguments.py:303-313).

The reference reaches low-precision GEMM throughput through TE's fp8
(H100-only; inert on its A100 targets too). TPU v5e/v5p MXUs have no fp8
datapath — the hardware's low-precision lever is **int8**, at ~2x the bf16
MACs/cycle on v5e. This module is the TE recipe rebuilt on that datapath:

- forward GEMMs run int8 x int8 -> int32 on the MXU, with **per-token
  activation scales** and **per-output-channel weight scales** (the
  "current scaling" recipe: amax is taken from the tensor being quantized,
  no cross-step amax history to thread through the train state);
- the backward runs in the compute dtype on the *unquantized* operands
  (straight-through estimate; the hybrid recipe the reference exposes as
  --no_fp8_wgrad, extended to dgrad because e5m2 has no int analogue).

Applied to the attention q/kv/out projections and both MLP GEMMs when
`ModelConfig.quantized_gemm == "int8"`; the embedding and lm head stay in
the compute dtype (TE keeps those out of fp8 for the same accuracy
reasons). Opt in with --quantized_gemm int8.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _quantize_rows(x):
    """x [..., K] -> (int8 values, fp32 scale [..., 1]) with per-row amax."""
    ax = jnp.max(jnp.abs(x), axis=-1, keepdims=True).astype(jnp.float32)
    scale = jnp.where(ax > 0, ax / 127.0, 1.0)
    xi = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    return xi.astype(jnp.int8), scale


def _quantize_cols(w):
    """w [K, N] -> (int8 values, fp32 scale [N]) with per-column amax."""
    aw = jnp.max(jnp.abs(w), axis=0).astype(jnp.float32)
    scale = jnp.where(aw > 0, aw / 127.0, 1.0)
    wi = jnp.clip(jnp.round(w.astype(jnp.float32) / scale[None, :]),
                  -127, 127)
    return wi.astype(jnp.int8), scale


def _int8_matmul_impl(x, w):
    xi, sx = _quantize_rows(x)
    wi, sw = _quantize_cols(w)
    yi = jax.lax.dot_general(
        xi, wi, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (yi.astype(jnp.float32) * sx * sw).astype(x.dtype)


@jax.custom_vjp
def int8_matmul(x, w):
    """[..., K] @ [K, N] with an int8-MXU forward and a full-precision
    backward. Numerics: per-row/per-column symmetric quantization bounds
    the forward's relative error at ~0.4% rms for well-conditioned
    operands; gradients are exact for the straight-through estimate."""
    return _int8_matmul_impl(x, w)


def _int8_matmul_fwd(x, w):
    return _int8_matmul_impl(x, w), (x, w)


def _int8_matmul_bwd(res, dy):
    x, w = res
    # contract dy's N against w's N for dx; batch dims of x against dy for dw
    dx = jax.lax.dot_general(dy, w, (((dy.ndim - 1,), (1,)), ((), ())))
    lead = tuple(range(x.ndim - 1))
    dw = jnp.tensordot(x, dy, axes=(lead, lead))
    return dx.astype(x.dtype), dw.astype(w.dtype)


int8_matmul.defvjp(_int8_matmul_fwd, _int8_matmul_bwd)


def qdense(x, w, quantized_gemm: str):
    """Dense-layer dispatch shared by the attention/MLP call sites.

    `w` may carry extra trailing structure (the GLU [h, 2, ffn] layout) —
    it is flattened to [K, prod(rest)] for the GEMM and the output is
    reshaped back, so gate/value splits keep their leading-index layout."""
    if quantized_gemm == "none":
        if w.ndim == 2:
            return x @ w
        return jnp.einsum("...h,hcf->...cf", x, w)
    assert quantized_gemm == "int8", quantized_gemm
    if w.ndim == 2:
        return int8_matmul(x, w)
    k = w.shape[0]
    y = int8_matmul(x, w.reshape(k, -1))
    return y.reshape(*y.shape[:-1], *w.shape[1:])
