"""Device-mesh topology for megatron_tpu.

TPU-native replacement for the reference's process-group factory
(ref: megatron/core/parallel_state.py:51-205 `initialize_model_parallel` and
its group getters :217-481). The reference builds explicit NCCL communicators
for each of dp/tp/pp/model/embedding groups with the rank-order convention
"tp-fastest, then dp, then pp" (ref: core/parallel_state.py:68-82 docstring).

Here the entire grid is a single `jax.sharding.Mesh` with named axes:

    ('dp', 'pp', 'cp', 'tp')

and "groups" are just mesh axes — a TP all-reduce is `psum` over 'tp', the
pipeline send/recv is `ppermute` over 'pp', the embedding-group sync
(ref: optimizer.py:203-229) is a psum over the 'pp' edge ranks expressed in
the pipeline schedule itself. Axis order puts 'tp' innermost so TP collectives
ride the fastest ICI links, matching the reference's tp-fastest rank packing.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from megatron_tpu.config import ParallelConfig

# Canonical mesh axis names, outermost (slowest-varying) first.
DATA_AXIS = "dp"
PIPELINE_AXIS = "pp"
CONTEXT_AXIS = "cp"
TENSOR_AXIS = "tp"
MESH_AXES = (DATA_AXIS, PIPELINE_AXIS, CONTEXT_AXIS, TENSOR_AXIS)


def build_mesh(
    parallel: ParallelConfig,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Create the (dp, pp, cp, tp) mesh.

    Equivalent of `initialize_model_parallel(tp, pp)`
    (ref: core/parallel_state.py:51); dp is derived from the device count the
    same way the reference derives it from world size
    (ref: megatron/arguments.py:86-100).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    tp = parallel.tensor_parallel
    pp = parallel.pipeline_parallel
    cp = parallel.context_parallel
    dp = parallel.data_parallel or parallel.derive_dp(n)
    assert dp * pp * cp * tp == n, (
        f"mesh {dp}x{pp}x{cp}x{tp} != {n} devices")
    dev_array = np.asarray(devices).reshape(dp, pp, cp, tp)
    return Mesh(dev_array, MESH_AXES)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    devices = [device] if device is not None else jax.devices()[:1]
    return Mesh(np.asarray(devices).reshape(1, 1, 1, 1), MESH_AXES)


# ---------------------------------------------------------------------------
# Rank predicates — the reference exposes is_pipeline_{first,last}_stage etc.
# (ref: core/parallel_state.py:304-358). Inside shard_map'ed code the same
# information comes from `jax.lax.axis_index`.
# ---------------------------------------------------------------------------

def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def mesh_info(mesh: Mesh) -> dict:
    return {a: mesh.shape[a] for a in mesh.axis_names}
