"""Multi-host (pod-scale) runtime: process init + global batch assembly.

TPU-native replacement for the reference's multi-node launch machinery
(ref: megatron/initialize.py:124-151 _initialize_distributed via torchrun +
NCCL init_process_group, and the "dataloader on tp-rank-0 then broadcast"
trick at training.py:855-939). On TPU pods every host runs the SAME
single-controller program over one global mesh; what remains host-side is

1. `initialize_distributed()` — jax.distributed.initialize, opted in via
   MEGATRON_TPU_MULTIHOST=1 (TPU-pod auto-detection) or env-driven
   (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID).
2. `make_global_batch()` — lift host-local numpy batches into globally
   sharded jax.Arrays. Every process builds the same global batch order
   (same seed -> same sampler stream), and each host materializes on its
   devices only the dp rows it owns: the callback formulation means no
   host ever holds more device data than its addressable shard.

Single-process runs bypass all of this (the jit transfer path is already
optimal), so the train loop can call `make_global_batch` unconditionally.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np


def initialize_distributed(coordinator: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> int:
    """Bring up the JAX distributed runtime (multi-controller).

    No-ops when already initialized or when nothing indicates a multi-host
    launch (single-host dev loops must not pay a coordinator timeout).
    Returns the process index. (ref: initialize.py:124-151 — the
    torch.distributed.init_process_group equivalent.)"""
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    num_processes = num_processes or _env_int("JAX_NUM_PROCESSES")
    process_id = process_id if process_id is not None \
        else _env_int("JAX_PROCESS_ID")
    # only an EXPLICIT opt-in triggers pod auto-detection:
    # TPU_WORKER_HOSTNAMES alone is unreliable (single-chip tunnels set it)
    on_pod = bool(os.environ.get("MEGATRON_TPU_MULTIHOST"))
    if not coordinator and not on_pod:
        # single-host: return WITHOUT touching jax — backend init must stay
        # where the entry point put it (platform pinning, lazy tunnels)
        return 0
    try:
        if coordinator:
            jax.distributed.initialize(coordinator_address=coordinator,
                                       num_processes=num_processes,
                                       process_id=process_id)
        else:
            jax.distributed.initialize()  # TPU-pod auto-detection
    except RuntimeError as e:
        # already initialized, or a backend was touched first (interactive
        # sessions): proceed with whatever process topology exists
        print(f"initialize_distributed: {e}")
    return jax.process_index()


def _env_int(name: str) -> Optional[int]:
    v = os.environ.get(name)
    return int(v) if v else None


def make_global_batch(batch: dict, mesh, batch_sharding) -> dict:
    """Host-local numpy batch -> globally dp-sharded jax.Arrays.

    `batch` leaves are the FULL global batch in every process (identical
    sampler streams); `batch_sharding` is the NamedSharding the train step
    expects ([n_micro, batch, ...] with batch over 'dp'). Each process
    materializes only its addressable shards. Single-process: returned
    unchanged — jit's implicit transfer is equivalent and avoids an extra
    host copy."""
    if jax.process_count() == 1:
        return batch

    def lift(v):
        arr = np.asarray(v)
        return jax.make_array_from_callback(
            arr.shape, batch_sharding, lambda idx: arr[idx])

    return {k: lift(v) for k, v in batch.items()}


def process_batch_rows(mesh, global_rows: int) -> tuple:
    """(row_lo, row_hi) of the global batch dim owned by THIS process —
    the hook for samplers that skip tokenizing other hosts' rows (the
    per-host sharded-loader optimization the reference approximates with
    its tp-rank-0 broadcast)."""
    if jax.process_count() == 1:
        return 0, global_rows
    dp = mesh.shape.get("dp", 1)
    assert global_rows % dp == 0
    per = global_rows // dp
    # dp coordinate range covered by this process's addressable devices
    # (dp axis located by NAME so a mesh-axis reorder can't silently map
    # hosts to wrong row ranges)
    dp_dim = mesh.axis_names.index("dp")
    coords = sorted({int(np.argwhere(mesh.devices == d)[0][dp_dim])
                     for d in mesh.devices.ravel()
                     if d.process_index == jax.process_index()})
    lo, hi = coords[0], coords[-1]
    assert coords == list(range(lo, hi + 1)), (
        f"process {jax.process_index()} owns non-contiguous dp coords "
        f"{coords}; a row-range slice would cover other hosts' rows — "
        "lay the mesh out with dp contiguous per process")
    return lo * per, (hi + 1) * per
