"""Pipeline parallelism: collective-permute pipeline over the 'pp' mesh axis.

TPU-native equivalent of the reference's pipeline stack — p2p layer
(ref: megatron/p2p_communication.py:101-405), 1F1B schedules
(ref: megatron/schedules.py:213-722), and per-stage model construction
(ref: megatron/model/transformer.py:844-893 _get_num_layers,
megatron/training.py:204-219). Mapping:

- *Stage partitioning*: the scan-stacked layer params are reshaped to
  [pp, layers_per_stage, ...] and sharded over 'pp' on dim 0 — the analogue
  of each pipeline rank owning its contiguous layer slice.
- *P2P send/recv* (batched isend/irecv + shape handshakes) becomes ONE
  `lax.ppermute` per pipeline tick rotating activations stage i -> i+1.
  No shape handshake is ever needed: shapes are static under jit.
- *Schedule*: microbatch j enters stage i at tick t = i + j; the scan runs
  T = n_micro + pp - 1 ticks (fill + steady + drain). The backward pipeline
  is DERIVED by jax.grad — reverse-mode turns the forward ppermute rotation
  into the mirrored backward rotation, giving the fill-drain schedule's
  backward for free. The reference's hand-written warmup/steady/cooldown
  bookkeeping (schedules.py:606-722) and `deallocate_output_tensor` /
  `custom_backward` memory hacks (schedules.py:36-88) have no equivalent:
  remat policy (`jax.checkpoint` on the stage body) bounds live activations
  instead.
- *Bubble*: identical to 1F1B's (pp-1)/(n_micro+pp-1) fill-drain fraction for
  the forward; peak activation memory is bounded by remat, which on TPU
  (HBM-rich, recompute-cheap on MXU) is the idiomatic trade. A true
  interleaved-1F1B (virtual stages, ref: schedules.py:253-502) maps to
  chunked stage params [pp, vpp, layers/(pp*vpp), ...] with a modulo-chunk
  schedule — planned on top of this same primitive.
- *Embedding/LM-head*: computed OUTSIDE the pipelined region, replicated
  over 'pp' (each pp rank redundantly embeds — cheap — instead of the
  reference's embedding-group all-reduce of tied-embedding grads,
  ref: optimizer.py:203-229; with GSPMD the tied-weight grad contributions
  from first/last "stage" meet automatically because it is one parameter).

The shard_map is manual over 'pp' ONLY; 'dp'/'cp'/'tp' stay automatic, so
GSPMD still inserts the TP/SP collectives inside each stage body.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_tpu.config import ModelConfig
from megatron_tpu.models import transformer as tfm


def stage_params_reshape(stacked_params, pp: int):
    """[L, ...] stacked layer params -> [pp, L//pp, ...]."""
    def r(x):
        L = x.shape[0]
        assert L % pp == 0, f"num_layers {L} not divisible by pp {pp}"
        return x.reshape(pp, L // pp, *x.shape[1:])
    return jax.tree.map(r, stacked_params)


def stage_params_flatten(staged_params):
    """Inverse of stage_params_reshape."""
    return jax.tree.map(
        lambda x: x.reshape(x.shape[0] * x.shape[1], *x.shape[2:]),
        staged_params)


def pipeline_apply(
    staged_params,
    x_micro,  # [n_micro, b, s, h] activations after embedding
    cfg: ModelConfig,
    mesh,
    *,
    rope_cos=None,
    rope_sin=None,
    rng=None,
    deterministic: bool = True,
    position_ids=None,  # [n_micro, b, s] or None
    segment_ids=None,   # [n_micro, b, s] or None
):
    """Run the pipelined transformer stack. Returns [n_micro, b, s, h].

    Equivalent of forward_backward_pipelining_without_interleaving's forward
    half (ref: schedules.py:606-722); its backward half is jax.grad of this.
    """
    pp = mesh.shape["pp"]
    n_micro = x_micro.shape[0]
    layers_per_stage = cfg.num_layers // pp
    T = n_micro + pp - 1

    def stage_fn(params_1stage, h, pos, seg, stage_idx, tick_rng):
        """Apply this stage's layer slice (inner scan over its layers)."""
        return tfm.stack_apply(
            params_1stage, h, cfg,
            rope_cos=rope_cos, rope_sin=rope_sin,
            position_ids=pos, segment_ids=seg,
            rng=tick_rng, deterministic=deterministic,
            layer_offset=stage_idx * layers_per_stage)[0]

    compute_dtype = x_micro.dtype
    # Keep the shard_map boundary in f32: the replicated-input cotangent in
    # the derived backward is a psum over 'pp', and XLA's CPU partitioner
    # CHECK-fails on bf16 psum in partial-manual regions (same bug as below).
    x_micro = x_micro.astype(jnp.float32)
    n_b, n_s = x_micro.shape[1], x_micro.shape[2]
    if position_ids is None:
        position_ids = jnp.broadcast_to(
            jnp.arange(n_s, dtype=jnp.int32), (n_micro, n_b, n_s))
    if segment_ids is None:
        segment_ids = jnp.zeros((n_micro, n_b, n_s), jnp.int32)

    def per_stage(params_shard, x_all, pos_all, seg_all):
        # inside shard_map: params_shard [1, layers_per_stage, ...]; x_all is
        # the full microbatch stream (replicated over 'pp')
        x_all = x_all.astype(compute_dtype)
        params_1 = jax.tree.map(lambda p: p[0], params_shard)
        stage = jax.lax.axis_index("pp")
        is_first = stage == 0
        is_last = stage == pp - 1
        perm = [(i, i + 1) for i in range(pp - 1)]

        def tick(carry, t):
            buf, outputs = carry
            # first stage pulls microbatch t from the host stream (clamped;
            # out-of-range ticks do garbage work that is masked at collect)
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            mb_in = jax.lax.dynamic_index_in_dim(x_all, mb_idx, axis=0,
                                                 keepdims=False)
            # pos/seg ids for the microbatch THIS STAGE is processing at
            # tick t: stage s works on microbatch t - s
            my_mb = jnp.clip(t - stage, 0, n_micro - 1)
            pos = jax.lax.dynamic_index_in_dim(pos_all, my_mb, axis=0,
                                               keepdims=False)
            seg = jax.lax.dynamic_index_in_dim(seg_all, my_mb, axis=0,
                                               keepdims=False)
            h = jnp.where(is_first, mb_in, buf)
            tick_rng = (jax.random.fold_in(rng, t)
                        if rng is not None and not deterministic else None)
            out = stage_fn(params_1, h, pos, seg, stage, tick_rng)
            # collect finished microbatch on the last stage
            out_idx = t - (pp - 1)
            valid = is_last & (out_idx >= 0)
            outputs = jax.lax.cond(
                valid,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, out, jnp.clip(out_idx, 0, n_micro - 1), axis=0),
                lambda o: o,
                outputs)
            # rotate activations stage i -> i+1 (the p2p send/recv)
            buf_next = jax.lax.ppermute(out, "pp", perm) if pp > 1 else out
            return (buf_next, outputs), None

        buf0 = jnp.zeros_like(x_all[0])
        outputs0 = jnp.zeros_like(x_all)
        (_, outputs), _ = jax.lax.scan(
            tick, (buf0, outputs0), jnp.arange(T))
        # replicate the last stage's outputs to every pp rank so the
        # (pp-replicated) LM head can consume them. psum in f32: XLA's CPU
        # SPMD partitioner CHECK-fails on bf16 psum inside a partial-manual
        # region ("Invalid binary instruction opcode copy"); f32 psum is also
        # the numerically safer reduction.
        dtype = outputs.dtype
        outputs = jax.lax.psum(
            jnp.where(is_last, outputs,
                      jnp.zeros_like(outputs)).astype(jnp.float32), "pp")
        return outputs.astype(dtype)

    # Partial-manual shard_map: manual over 'pp' only; dp/cp/tp stay
    # automatic (GSPMD). Constraints of this mode (jax 0.9): must run under
    # jit, with the ambient mesh set via `jax.set_mesh(mesh)` OUTSIDE jit —
    # the caller (train loop / tests) owns both.
    shmap = jax.shard_map(
        per_stage,
        in_specs=(P("pp"), P(), P(), P()),
        out_specs=P(),
        check_vma=False,
        axis_names={"pp"},
    )
    return shmap(staged_params, x_micro, position_ids, segment_ids)


def pipeline_loss_fn(
    params,
    tokens,  # [n_micro, b, s+1]
    cfg: ModelConfig,
    mesh,
    *,
    loss_mask=None,  # [n_micro, b, s]
    rope=None,
    rng=None,
    deterministic: bool = True,
    position_ids=None,  # [n_micro, b, s]
    segment_ids=None,   # [n_micro, b, s]
):
    """Full-model loss with the transformer stack pipelined over 'pp'.

    Embedding / final-norm / LM-head / CE run outside the shard_map,
    pp-replicated (see module docstring). Returns scalar mean loss over all
    microbatches — identical semantics to the sequential microbatch scan in
    training/train_step.py, so pp=1 and pp>1 train identically.
    """
    from megatron_tpu.config import as_dtype
    from megatron_tpu.models import language_model as lm
    from megatron_tpu.ops.cross_entropy import cross_entropy_loss

    if rope is None:
        rope = lm.make_rope(cfg)
    compute_dtype = as_dtype(cfg.compute_dtype)
    inputs = tokens[..., :-1]
    labels = tokens[..., 1:]
    if loss_mask is None:
        loss_mask = jnp.ones(labels.shape, jnp.float32)

    from megatron_tpu.parallel.sharding import constrain

    emb = params["embedding"]["word_embeddings"]
    x = emb[inputs].astype(compute_dtype)  # [n_micro, b, s, h]
    if cfg.use_position_embedding:
        pos = (position_ids if position_ids is not None
               else jnp.arange(inputs.shape[-1]))
        x = x + params["embedding"]["position_embeddings"][pos].astype(
            compute_dtype)
    # SP: embedding output seq-scattered, mirroring model_forward
    # (ref: language_model.py:255-258)
    x = constrain(x, (None, "batch", "seq_sp", "act_embed"))

    pp = mesh.shape["pp"]
    staged = stage_params_reshape(params["transformer"], pp)
    x = pipeline_apply(
        staged, x, cfg, mesh,
        rope_cos=rope.cos if rope else None,
        rope_sin=rope.sin if rope else None,
        rng=rng, deterministic=deterministic,
        position_ids=position_ids, segment_ids=segment_ids)

    from megatron_tpu.models.norms import apply_norm
    x = apply_norm(cfg.norm_type, params["final_norm"], x, cfg.norm_epsilon)
    # gather seq off 'tp' before the vocab-parallel LM head, then shard
    # logits on vocab — mirrors model_forward's constraints exactly
    x = constrain(x, (None, "batch", "seq", "act_embed"))
    if cfg.tie_embed_logits:
        w_out = params["embedding"]["word_embeddings"].T
    else:
        w_out = params["lm_head"]
    logits = (x @ w_out.astype(compute_dtype)).astype(jnp.float32)
    logits = constrain(logits, (None, "batch", "seq", "vocab"))
    losses = cross_entropy_loss(logits, labels, vocab_size=cfg.vocab_size)
    loss_mask = loss_mask.astype(losses.dtype)
    return jnp.sum(losses * loss_mask) / jnp.maximum(jnp.sum(loss_mask), 1.0)
