"""Pipeline parallelism: collective-permute pipeline over the 'pp' mesh axis.

TPU-native equivalent of the reference's pipeline stack — p2p layer
(ref: megatron/p2p_communication.py:101-405), 1F1B schedules
(ref: megatron/schedules.py:213-722), virtual-stage interleaving
(ref: megatron/schedules.py:253-502), and per-stage model construction
(ref: megatron/model/transformer.py:844-893,1014-1044 _get_num_layers +
vpp layer offsets; megatron/training.py:204-219). Mapping:

- *Stage partitioning*: the scan-stacked layer params are reshaped to
  [pp, vpp, layers_per_chunk, ...] and sharded over 'pp' on dim 0 — each
  pipeline rank owns vpp interleaved layer chunks; chunk c of stage s covers
  layers [(c*pp + s)*Lc, ...), exactly the reference's interleaved offset
  arithmetic (ref: transformer.py:1014-1044).
- *P2P send/recv* (batched isend/irecv + shape handshakes) becomes ONE
  `lax.ppermute` per pipeline tick rotating all vpp buffers stage i -> i+1
  around a ring; the pp-1 -> 0 wraparound edge promotes a microbatch to the
  next virtual chunk. No shape handshake is ever needed: shapes are static
  under jit.
- *Schedule*: microbatch j enters the ring at tick j; at tick t, stage s
  holds microbatch t - s - c*pp in chunk-c's buffer. The scan runs
  T = n_micro + pp*vpp - 1 ticks (fill + steady + drain). The backward
  pipeline is DERIVED by jax.grad — reverse-mode turns the forward ppermute
  rotation into the mirrored backward rotation. The reference's hand-written
  warmup/steady/cooldown bookkeeping (schedules.py:606-722) and
  `deallocate_output_tensor` / `custom_backward` memory hacks
  (schedules.py:36-88) have no equivalent: remat policy (`jax.checkpoint`
  on the stage body) bounds live activations instead.
- *Memory*: only the int32 token/position/segment streams are replicated
  over 'pp' (tiny); embedding lookup happens inside stage 0's tick, so the
  [n_micro, b, s, h] activation stream is never materialized replicated.
  The last stage's collected outputs leave the shard_map via an out_spec
  P('pp') concatenation (no psum of activations), and the LM head + CE run
  OUTSIDE with the microbatch dim resharded over 'pp' — logits are computed
  once, with the work spread across pipeline stages, instead of redundantly
  per stage (the reference computes them on the last stage only while other
  stages idle in the bubble).
- *Bubble*: fill-drain fraction (pp*vpp - 1)/(n_micro + pp*vpp - 1) in this
  lockstep formulation. NOTE an honest divergence from the reference: in a
  single jitted lockstep schedule, virtual stages do NOT shrink the bubble
  the way async 1F1B interleaving does (every stage already runs all its
  chunks every tick); vpp>1 here provides the reference's interleaved
  layer->stage assignment (checkpoint-layout parity, memory balance) while
  the bubble lever on TPU is n_micro, which remat makes cheap to raise.
- *Embedding/LM-head*: the tied embedding is one parameter used inside the
  shard_map (stage-0 intake) and outside (head); its gradient contributions
  meet automatically under GSPMD — the reference needs an explicit
  embedding-group all-reduce (ref: optimizer.py:203-229).

The shard_map is manual over 'pp' ONLY; 'dp'/'cp'/'tp' stay automatic, so
GSPMD still inserts the TP/SP collectives inside each stage body.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_tpu.config import ModelConfig
from megatron_tpu.models import transformer as tfm


def stage_params_reshape(stacked_params, pp: int):
    """[L, ...] stacked layer params -> [pp, L//pp, ...] (contiguous
    per-stage slices — stage_params_chunked with a single virtual chunk)."""
    return jax.tree.map(lambda x: x[:, 0],
                        stage_params_chunked(stacked_params, pp, 1))


def stage_params_flatten(staged_params):
    """Inverse of stage_params_reshape."""
    return jax.tree.map(
        lambda x: x.reshape(x.shape[0] * x.shape[1], *x.shape[2:]),
        staged_params)


def stage_params_chunked(stacked_params, pp: int, vpp: int):
    """[L, ...] -> [pp, vpp, L/(pp*vpp), ...] with the INTERLEAVED
    assignment: element [s, c] holds layers [(c*pp + s)*Lc, ...) — the
    reference's virtual-stage layer offsets (ref: transformer.py:1014-1044).
    """
    def r(x):
        L = x.shape[0]
        assert L % (pp * vpp) == 0, (
            f"num_layers {L} not divisible by pp*vpp {pp}x{vpp}")
        Lc = L // (pp * vpp)
        # reshape [vpp, pp, Lc, ...]: index [c, s, l] = (c*pp + s)*Lc + l
        return x.reshape(vpp, pp, Lc, *x.shape[1:]).swapaxes(0, 1)
    return jax.tree.map(r, stacked_params)


def _embed(emb_params, tok, cfg: ModelConfig, dtype, pos):
    """Token (+ absolute position) embedding for one microbatch [b, s]."""
    x = emb_params["word_embeddings"][tok].astype(dtype)
    if cfg.use_position_embedding:
        x = x + emb_params["position_embeddings"][pos].astype(dtype)
    return x


def pipeline_transformer(
    params,          # full model param tree (embedding used for intake)
    inputs,          # [n_micro, b, s] int32 token stream
    cfg: ModelConfig,
    mesh,
    *,
    vpp: int = 1,
    rope_cos=None,
    rope_sin=None,
    rng=None,
    deterministic: bool = True,
    position_ids=None,  # [n_micro, b, s] or None
    segment_ids=None,   # [n_micro, b, s] or None
):
    """Embed + run the pipelined transformer stack over 'pp'.

    Returns the last stage's outputs [n_micro, b, s, h] (final norm / head /
    loss are the caller's job). Equivalent of the forward half of the
    reference's pipelined schedules (ref: schedules.py:253-502,606-722);
    the backward half is jax.grad of this.
    """
    pp = mesh.shape["pp"]
    n_micro, n_b, n_s = inputs.shape
    Lc = cfg.num_layers // (pp * vpp)
    T = n_micro + pp * vpp - 1

    from megatron_tpu.config import as_dtype
    compute_dtype = as_dtype(cfg.compute_dtype)
    # The XLA *CPU* SPMD partitioner CHECK-fails on bf16 psum inside
    # partial-manual regions ("Invalid binary instruction opcode copy"),
    # which the derived backward's replicated-param cotangents hit. Pay the
    # f32-boundary cost only there; on TPU the ring runs in compute dtype.
    boundary_dtype = (jnp.float32 if jax.default_backend() == "cpu"
                      else compute_dtype)

    if position_ids is None:
        position_ids = jnp.broadcast_to(
            jnp.arange(n_s, dtype=jnp.int32), (n_micro, n_b, n_s))
    if segment_ids is None:
        segment_ids = jnp.zeros((n_micro, n_b, n_s), jnp.int32)

    chunked = stage_params_chunked(params["transformer"], pp, vpp)
    emb_params = params["embedding"]

    # separate rng streams for embedding dropout (per microbatch) and layer
    # dropout (per tick/chunk) so the folds can't collide
    rng_emb = rng_layers = None
    if rng is not None and not deterministic:
        rng_emb, rng_layers = jax.random.split(rng)

    def per_stage(emb_p, chunk_shard, inp_all, pos_all, seg_all):
        # inside shard_map: chunk_shard [1, vpp, Lc, ...]; token/pos/seg
        # streams are replicated over 'pp' (int32 — tiny)
        chunks = jax.tree.map(lambda p: p[0], chunk_shard)  # [vpp, Lc, ...]
        stage = jax.lax.axis_index("pp")
        is_first = stage == 0
        is_last = stage == pp - 1
        ring = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, t):
            bufs, outputs = carry  # bufs [vpp, b, s, h]; outputs [n, b,s,h]
            # stage-0 chunk-0 intake: embed microbatch t (clamped; garbage
            # ticks are masked at collect)
            mb_in = jnp.clip(t, 0, n_micro - 1)
            tok = jax.lax.dynamic_index_in_dim(inp_all, mb_in, 0, False)
            pos_in = jax.lax.dynamic_index_in_dim(pos_all, mb_in, 0, False)
            x0 = _embed(emb_p, tok, cfg, compute_dtype, pos_in)
            if rng_emb is not None and cfg.hidden_dropout > 0.0:
                # embedding-output dropout, matching the sequential path
                # (model_forward, language_model.py:117-120; ref:
                # language_model.py:255-258 forked-RNG embedding dropout)
                from megatron_tpu.ops.dropout import dropout as _drop
                x0 = _drop(jax.random.fold_in(rng_emb, mb_in), x0,
                           cfg.hidden_dropout)
            ins = bufs.at[0].set(
                jnp.where(is_first, x0.astype(boundary_dtype), bufs[0]))

            def chunk_body(_, xs):
                cp, h_in, c = xs
                # chunk c of stage s processes microbatch t - s - c*pp
                my_mb = jnp.clip(t - stage - c * pp, 0, n_micro - 1)
                pos = jax.lax.dynamic_index_in_dim(pos_all, my_mb, 0, False)
                seg = jax.lax.dynamic_index_in_dim(seg_all, my_mb, 0, False)
                offset = (c * pp + stage) * Lc
                tick_rng = None
                if rng_layers is not None:
                    tick_rng = jax.random.fold_in(rng_layers, t * vpp + c)
                out = tfm.stack_apply(
                    cp, h_in.astype(compute_dtype), cfg,
                    rope_cos=rope_cos, rope_sin=rope_sin,
                    position_ids=pos, segment_ids=seg,
                    rng=tick_rng, deterministic=deterministic,
                    layer_offset=offset)[0]
                return None, out.astype(boundary_dtype)

            _, outs = jax.lax.scan(chunk_body, None,
                                   (chunks, ins, jnp.arange(vpp)))

            # collect the microbatch finishing its last hop (stage pp-1,
            # chunk vpp-1) at this tick
            out_idx = t - (pp * vpp - 1)
            valid = is_last & (out_idx >= 0)
            outputs = jax.lax.cond(
                valid,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, outs[vpp - 1], jnp.clip(out_idx, 0, n_micro - 1), 0),
                lambda o: o,
                outputs)
            # rotate all chunk buffers one stage down the ring; the
            # wraparound edge pp-1 -> 0 carries chunk c into chunk c+1
            # (the roll below); stage 0's buffer 0 is refilled by intake.
            rotated = jax.lax.ppermute(outs, "pp", ring) if pp > 1 else outs
            shifted = jnp.where(is_first, jnp.roll(rotated, 1, axis=0),
                                rotated) if vpp > 1 else rotated
            return (shifted, outputs), None

        bufs0 = jnp.zeros((vpp, n_b, n_s, cfg.hidden_size), boundary_dtype)
        outputs0 = jnp.zeros((n_micro, n_b, n_s, cfg.hidden_size),
                             boundary_dtype)
        (_, outputs), _ = jax.lax.scan(tick, (bufs0, outputs0),
                                       jnp.arange(T))
        # leave via concatenation over 'pp' (NOT a psum of activations):
        # the caller slices out the last stage's block
        return outputs[None]

    # Partial-manual shard_map: manual over 'pp' only; dp/cp/tp stay
    # automatic (GSPMD). Constraints of this mode (jax 0.9): must run under
    # jit, with the ambient mesh set via `jax.set_mesh(mesh)` OUTSIDE jit —
    # the caller (train loop / tests) owns both.
    shmap = jax.shard_map(
        per_stage,
        in_specs=(P(), P("pp"), P(), P(), P()),
        out_specs=P("pp"),
        check_vma=False,
        axis_names={"pp"},
    )
    stacked_out = shmap(emb_params, chunked, inputs, position_ids,
                        segment_ids)  # [pp, n_micro, b, s, h]
    return stacked_out[-1].astype(compute_dtype)


def pipeline_loss_fn(
    params,
    tokens,  # [n_micro, b, s+1]
    cfg: ModelConfig,
    mesh,
    *,
    vpp: int = 1,
    loss_mask=None,  # [n_micro, b, s]
    rope=None,
    rng=None,
    deterministic: bool = True,
    position_ids=None,  # [n_micro, b, s]
    segment_ids=None,   # [n_micro, b, s]
):
    """Full-model loss with the transformer stack pipelined over 'pp'.

    Final-norm / LM-head / CE run OUTSIDE the shard_map with the microbatch
    dim resharded over 'pp' (logits computed once, work spread over stages —
    see module docstring). Loss is the mean over microbatches of each
    microbatch's masked mean, matching the sequential train_step and the
    reference's per-microbatch loss averaging (ref: schedules.py:176-186) —
    so pp=1 and pp>1 train identically even with non-uniform loss masks.
    """
    from megatron_tpu.config import as_dtype
    from megatron_tpu.models import language_model as lm
    from megatron_tpu.models.norms import apply_norm
    from megatron_tpu.ops.cross_entropy import cross_entropy_loss
    from megatron_tpu.parallel.sharding import constrain

    if rope is None:
        rope = lm.make_rope(cfg)
    compute_dtype = as_dtype(cfg.compute_dtype)
    inputs = tokens[..., :-1]
    labels = tokens[..., 1:]
    if loss_mask is None:
        loss_mask = jnp.ones(labels.shape, jnp.float32)

    x = pipeline_transformer(
        params, inputs, cfg, mesh, vpp=vpp,
        rope_cos=rope.cos if rope else None,
        rope_sin=rope.sin if rope else None,
        rng=rng, deterministic=deterministic,
        position_ids=position_ids, segment_ids=segment_ids)

    # head work spread over the idle-in-the-bubble stages: microbatch dim
    # resharded onto 'pp'
    x = constrain(x, ("microbatch", "batch", "seq_sp", "act_embed"))
    x = apply_norm(cfg.norm_type, params["final_norm"], x, cfg.norm_epsilon)
    x = constrain(x, ("microbatch", "batch", "seq", "act_embed"))
    if cfg.tie_embed_logits:
        w_out = params["embedding"]["word_embeddings"].T
    else:
        w_out = params["lm_head"]
    logits = (x @ w_out.astype(compute_dtype)).astype(jnp.float32)
    logits = constrain(logits, ("microbatch", "batch", "seq", "vocab"))
    losses = cross_entropy_loss(logits, labels, vocab_size=cfg.vocab_size)
    loss_mask = loss_mask.astype(losses.dtype)
    # per-microbatch masked mean, then mean over microbatches (== train_step)
    per_mb = (jnp.sum(losses * loss_mask, axis=(1, 2))
              / jnp.maximum(jnp.sum(loss_mask, axis=(1, 2)), 1.0))
    return jnp.mean(per_mb)
