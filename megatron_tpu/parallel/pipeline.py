"""Pipeline parallelism: collective-permute pipeline over the 'pp' mesh axis.

TPU-native equivalent of the reference's pipeline stack — p2p layer
(ref: megatron/p2p_communication.py:101-405), 1F1B schedules
(ref: megatron/schedules.py:213-722), virtual-stage interleaving
(ref: megatron/schedules.py:253-502), and per-stage model construction
(ref: megatron/model/transformer.py:844-893,1014-1044 _get_num_layers +
vpp layer offsets; megatron/training.py:204-219). Mapping:

- *Stage partitioning*: the scan-stacked layer params are reshaped to
  [pp, vpp, layers_per_chunk, ...] and sharded over 'pp' on dim 0 — each
  pipeline rank owns vpp interleaved layer chunks; chunk c of stage s covers
  layers [(c*pp + s)*Lc, ...), exactly the reference's interleaved offset
  arithmetic (ref: transformer.py:1014-1044).
- *P2P send/recv* (batched isend/irecv + shape handshakes) becomes ONE
  `lax.ppermute` per pipeline tick rotating all vpp buffers stage i -> i+1
  around a ring; the pp-1 -> 0 wraparound edge promotes a microbatch to the
  next virtual chunk. No shape handshake is ever needed: shapes are static
  under jit.
- *Schedules*: TWO schedules share the ring machinery.
  (1) `pipeline_train_1f1b` (training default) is a hand-written
  one-forward-one-backward schedule matching the reference's memory bound
  (schedules.py:606-722): each tick runs one forward micro-step AND one
  backward micro-step per stage, cotangents ride a reverse ring, and the
  only cross-tick activation state is a depth-(2pp-1) circular stash of
  chunk inputs — per-stage live memory is FLAT in n_micro (measured: temp
  bytes n_micro 8 -> 32 at pp=4 grow 1.0001x, vs 3.2x for the derived
  schedule). The backward micro-step recomputes its chunk forward from the
  stashed input inside a same-tick jax.vjp (recompute-full under 1F1B).
  (2) The lockstep fill-drain scan below (`pipeline_transformer`) keeps the
  autodiff-DERIVED backward — reverse-mode turns the forward ppermute
  rotation into the mirrored backward rotation — and remains the
  forward/eval path and the opt-in `--pipeline_schedule gpipe` training
  path; its saved boundary activations grow with n_micro. vpp>1 training
  runs the interleaved 1F1B (`_pipeline_train_1f1b_interleaved`), which
  keeps the 1F1B memory bound.
- *Memory*: only the int32 token/position/segment streams are replicated
  over 'pp' (tiny); embedding lookup happens inside stage 0's tick, so the
  [n_micro, b, s, h] activation stream is never materialized replicated.
  The last stage's collected outputs leave the shard_map via an out_spec
  P('pp') concatenation (no psum of activations), and the LM head + CE run
  OUTSIDE with the microbatch dim resharded over 'pp' — logits are computed
  once, with the work spread across pipeline stages, instead of redundantly
  per stage (the reference computes them on the last stage only while other
  stages idle in the bubble).
- *Bubble*: 1F1B runs T = n_micro + 2(pp-1) ticks of (1 fwd + 1 bwd) work
  — bubble fraction 2(pp-1)/T, the reference 1F1B's (schedules.py diagram).
  The lockstep path's fill-drain fraction is (pp*vpp - 1)/(n_micro+pp*vpp-1)
  per pass. NOTE an honest divergence from the reference: interleaved
  virtual stages CANNOT shrink the bubble in any jit-lockstep formulation,
  and the reason is structural, not an implementation gap. The reference's
  interleave win (bubble/vpp, schedules.py:253-502) comes from ASYNC unit
  ordering — during warmup a rank runs forward chunk-units back-to-back,
  unconstrained by backward slots. A single jitted SPMD program must give
  every stage the identical per-tick op sequence (stages taking different
  fwd-vs-bwd branches would execute divergent collective sequences — the
  deadlock class the 1F1B tick body is explicitly branch-free to avoid),
  so every tick carries a uniform fwd-slot + bwd-slot pair; idle masked
  slots take the same wall time, and the warmup's dead bwd slots exactly
  cancel the interleave gain (worked example: pp=2 vpp=2 n_micro=4 gives
  8 idle chunk-slots either way). vpp>1 therefore provides the
  reference's interleaved layer->stage ASSIGNMENT (checkpoint-layout
  parity, memory balance) — under the 1F1B schedule itself since round 4
  (_pipeline_train_1f1b_interleaved, memory flat in n_micro; its T grows
  with vpp, consistent with this argument) — while the bubble lever on
  TPU is n_micro, which the 1F1B memory bound makes cheap to raise (live
  bytes are flat in n_micro, so gbs-1000-style runs at n_micro >> pp are
  the intended operating point, shrinking the bubble fraction
  2(pp-1)/(n_micro+2(pp-1)) arbitrarily).
- *Embedding/LM-head*: the tied embedding is one parameter used inside the
  shard_map (stage-0 intake) and outside (head); its gradient contributions
  meet automatically under GSPMD — the reference needs an explicit
  embedding-group all-reduce (ref: optimizer.py:203-229).

The shard_map is manual over 'pp' ONLY; 'dp'/'cp'/'tp' stay automatic, so
GSPMD still inserts the TP/SP collectives inside each stage body.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_tpu.config import ModelConfig
from megatron_tpu.models import transformer as tfm


def stage_params_reshape(stacked_params, pp: int):
    """[L, ...] stacked layer params -> [pp, L//pp, ...] (contiguous
    per-stage slices — stage_params_chunked with a single virtual chunk)."""
    return jax.tree.map(lambda x: x[:, 0],
                        stage_params_chunked(stacked_params, pp, 1))


def stage_params_flatten(staged_params):
    """Inverse of stage_params_reshape."""
    return jax.tree.map(
        lambda x: x.reshape(x.shape[0] * x.shape[1], *x.shape[2:]),
        staged_params)


def stage_params_chunked(stacked_params, pp: int, vpp: int):
    """[L, ...] -> [pp, vpp, L/(pp*vpp), ...] with the INTERLEAVED
    assignment: element [s, c] holds layers [(c*pp + s)*Lc, ...) — the
    reference's virtual-stage layer offsets (ref: transformer.py:1014-1044).
    """
    def r(x):
        L = x.shape[0]
        assert L % (pp * vpp) == 0, (
            f"num_layers {L} not divisible by pp*vpp {pp}x{vpp}")
        Lc = L // (pp * vpp)
        # reshape [vpp, pp, Lc, ...]: index [c, s, l] = (c*pp + s)*Lc + l
        return x.reshape(vpp, pp, Lc, *x.shape[1:]).swapaxes(0, 1)
    return jax.tree.map(r, stacked_params)


def stage_params_unchunk(chunked_params):
    """Inverse of stage_params_chunked: [pp, vpp, Lc, ...] -> [L, ...]."""
    return jax.tree.map(
        lambda x: x.swapaxes(0, 1).reshape(-1, *x.shape[3:]), chunked_params)


def _embed(emb_params, tok, cfg: ModelConfig, dtype, pos):
    """Token (+ absolute position) embedding for one microbatch [b, s]."""
    x = emb_params["word_embeddings"][tok].astype(dtype)
    if cfg.use_position_embedding:
        x = x + emb_params["position_embeddings"][pos].astype(dtype)
    return x


def pipeline_apply(
    stacked_params,   # [L, ...] stacked layer params (ONE stack)
    shared_params,    # pytree replicated over 'pp' (embedding tables, ...)
    streams,          # pytree of [n_micro, ...] arrays, replicated on 'pp'
    cfg: ModelConfig,
    mesh,
    *,
    intake_fn,        # (shared, mb_slice, mb_rng) -> [b, s, h]
    chunk_fn,         # (chunk_params, h, mb_slice, layer_offset, rng)
                      #   -> h or (h, moe_aux)
    batch_shape,      # (b, s) of one microbatch's activations
    vpp: int = 1,
    rng=None,
):
    """Generic lockstep fill-drain pipeline over 'pp' with an
    autodiff-derived backward.

    Runs `intake_fn` inside stage 0's tick and `chunk_fn` on each stage's
    vpp interleaved layer chunks; returns (outputs [n_micro, b, s, h],
    moe_aux) — outputs are the last stage's, aux sums every stage's
    router load-balancing losses over all real microbatches (0.0 for
    dense chunk fns; final norm / head / loss are the caller's job).
    Equivalent of the forward half of the reference's pipelined schedules
    (ref: schedules.py:253-502,606-722); the backward half is jax.grad of
    this. The GPT wrapper is `pipeline_transformer`; encoder-decoder models
    call this twice (see models/t5.py t5_pipeline_loss_fn) the way the
    reference's split-rank schedule runs both halves
    (ref: schedules.py:505-535).
    """
    pp = mesh.shape["pp"]
    n_micro = jax.tree.leaves(streams)[0].shape[0]
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    Lc = L // (pp * vpp)
    n_b, n_s = batch_shape
    T = n_micro + pp * vpp - 1

    from megatron_tpu.config import as_dtype
    compute_dtype = as_dtype(cfg.compute_dtype)
    # The XLA *CPU* SPMD partitioner CHECK-fails on bf16 psum inside
    # partial-manual regions ("Invalid binary instruction opcode copy"),
    # which the derived backward's replicated-param cotangents hit. Pay the
    # f32-boundary cost only there; on TPU the ring runs in compute dtype.
    boundary_dtype = (jnp.float32 if jax.default_backend() == "cpu"
                      else compute_dtype)

    chunked = stage_params_chunked(stacked_params, pp, vpp)

    def per_stage(shared_p, chunk_shard, streams_all):
        # inside shard_map: chunk_shard [1, vpp, Lc, ...]; streams are
        # replicated over 'pp'
        chunks = jax.tree.map(lambda p: p[0], chunk_shard)  # [vpp, Lc, ...]
        stage = jax.lax.axis_index("pp")
        is_first = stage == 0
        is_last = stage == pp - 1
        ring = [(i, (i + 1) % pp) for i in range(pp)]

        def mb_rng(i):
            return jax.random.fold_in(rng, i) if rng is not None else None

        def tick(carry, t):
            # bufs [vpp, b, s, h]; outputs [n, b,s,h]; aux_sum scalar f32
            bufs, outputs, aux_sum = carry
            # stage-0 chunk-0 intake for microbatch t (clamped; garbage
            # ticks are masked at collect)
            mb_in = jnp.clip(t, 0, n_micro - 1)
            x0 = intake_fn(shared_p, _dyn(streams_all, mb_in), mb_rng(mb_in))
            ins = bufs.at[0].set(
                jnp.where(is_first, x0.astype(boundary_dtype), bufs[0]))

            def chunk_body(acc, xs):
                cp, h_in, c = xs
                # chunk c of stage s processes microbatch t - s - c*pp
                raw_mb = t - stage - c * pp
                my_mb = jnp.clip(raw_mb, 0, n_micro - 1)
                offset = (c * pp + stage) * Lc
                out, aux = _chunk_ret(chunk_fn(
                    cp, h_in.astype(compute_dtype),
                    _dyn(streams_all, my_mb), offset, mb_rng(my_mb)))
                # fill/drain ticks run chunks on clamped garbage
                # microbatches — their router aux must not count
                mb_valid = (raw_mb >= 0) & (raw_mb < n_micro)
                acc = acc + jnp.where(mb_valid, aux, 0.0)
                return acc, out.astype(boundary_dtype)

            aux_sum, outs = jax.lax.scan(chunk_body, aux_sum,
                                         (chunks, ins, jnp.arange(vpp)))

            # collect the microbatch finishing its last hop (stage pp-1,
            # chunk vpp-1) at this tick
            out_idx = t - (pp * vpp - 1)
            valid = is_last & (out_idx >= 0)
            outputs = jax.lax.cond(
                valid,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, outs[vpp - 1], jnp.clip(out_idx, 0, n_micro - 1), 0),
                lambda o: o,
                outputs)
            # rotate all chunk buffers one stage down the ring; the
            # wraparound edge pp-1 -> 0 carries chunk c into chunk c+1
            # (the roll below); stage 0's buffer 0 is refilled by intake.
            rotated = jax.lax.ppermute(outs, "pp", ring) if pp > 1 else outs
            shifted = jnp.where(is_first, jnp.roll(rotated, 1, axis=0),
                                rotated) if vpp > 1 else rotated
            return (shifted, outputs, aux_sum), None

        bufs0 = jnp.zeros((vpp, n_b, n_s, cfg.hidden_size), boundary_dtype)
        outputs0 = jnp.zeros((n_micro, n_b, n_s, cfg.hidden_size),
                             boundary_dtype)
        (_, outputs, aux_sum), _ = jax.lax.scan(
            tick, (bufs0, outputs0, jnp.zeros((), jnp.float32)),
            jnp.arange(T))
        # leave via concatenation over 'pp' (NOT a psum of activations):
        # the caller slices out the last stage's block; aux sums across
        # stages (each stage owns its own layers' routers)
        return outputs[None], jax.lax.psum(aux_sum, "pp")

    # Partial-manual shard_map: manual over 'pp' only; dp/cp/tp stay
    # automatic (GSPMD). Constraints of this mode (jax 0.9): must run under
    # jit, with the ambient mesh set via `jax.set_mesh(mesh)` OUTSIDE jit —
    # the caller (train loop / tests) owns both.
    shmap = jax.shard_map(
        per_stage,
        in_specs=(P(), P("pp"), P()),
        out_specs=(P("pp"), P()),
        check_vma=False,
        axis_names={"pp"},
    )
    stacked_out, aux = shmap(shared_params, chunked,
                             streams)  # [pp, n_micro, b, s, h], scalar
    return stacked_out[-1].astype(compute_dtype), aux


def pipeline_transformer(
    params,          # full model param tree (embedding used for intake)
    inputs,          # [n_micro, b, s] int32 token stream
    cfg: ModelConfig,
    mesh,
    *,
    vpp: int = 1,
    rope_cos=None,
    rope_sin=None,
    rng=None,
    deterministic: bool = True,
    position_ids=None,  # [n_micro, b, s] or None
    segment_ids=None,   # [n_micro, b, s] or None
    cp_pre_zigzag: bool = False,
):
    """GPT wrapper over `pipeline_apply`: embed intake + causal stack."""
    n_micro, n_b, n_s = inputs.shape
    from megatron_tpu.config import as_dtype
    compute_dtype = as_dtype(cfg.compute_dtype)

    if position_ids is None:
        position_ids = jnp.broadcast_to(
            jnp.arange(n_s, dtype=jnp.int32), (n_micro, n_b, n_s))
    # segment_ids stay None when absent (None is an empty pytree subtree,
    # so the stream dict is scan-safe): materializing zeros here would
    # push every chunk's attention off the flash/ring branches, which
    # require segment_ids is None (models/attention.py ring_branch)
    streams = {"inputs": inputs, "position_ids": position_ids,
               "segment_ids": segment_ids}

    def intake(shared_p, sl, rng_mb):
        # embedding-output dropout matches the sequential path
        # (model_forward, language_model.py:117-120; ref:
        # language_model.py:255-258 forked-RNG embedding dropout)
        x = _embed(shared_p, sl["inputs"], cfg, compute_dtype,
                   sl["position_ids"])
        if rng_mb is not None and not deterministic and \
                cfg.hidden_dropout > 0.0:
            from megatron_tpu.ops.dropout import dropout as _drop
            x = _drop(jax.random.fold_in(rng_mb, 0), x, cfg.hidden_dropout)
        return x

    def chunk(cp, h, sl, offset, rng_mb):
        layer_rng = (jax.random.fold_in(rng_mb, 1)
                     if rng_mb is not None and not deterministic else None)
        x, _, aux = tfm.stack_apply(
            cp, h, cfg, rope_cos=rope_cos, rope_sin=rope_sin,
            position_ids=sl["position_ids"], segment_ids=sl["segment_ids"],
            rng=layer_rng, deterministic=deterministic,
            layer_offset=offset, cp_pre_zigzag=cp_pre_zigzag)
        return x, aux

    return pipeline_apply(
        params["transformer"], params["embedding"], streams, cfg, mesh,
        intake_fn=intake, chunk_fn=chunk, batch_shape=(n_b, n_s), vpp=vpp,
        rng=rng)


# ---------------------------------------------------------------------------
# 1F1B: hand-scheduled forward+backward pipeline with pp-bounded memory
# ---------------------------------------------------------------------------

def _dyn(tree, i):
    """Index every [n_micro, ...] stream leaf at microbatch i (traced)."""
    return jax.tree.map(
        lambda a: jax.lax.dynamic_index_in_dim(a, i, 0, False), tree)


def _chunk_ret(ret):
    """Normalize a chunk_fn return: `h` or `(h, aux)` -> (h, aux).

    `aux` is the chunk's MoE router load-balancing loss (scalar f32);
    dense chunk fns (BERT/T5 specs, pre-MoE callers) keep returning the
    bare hidden state and read as aux == 0."""
    if isinstance(ret, tuple):
        h, aux = ret
        return h, aux.astype(jnp.float32)
    return ret, jnp.zeros((), jnp.float32)


def _assert_dedup_passthrough(closure_leaves, chunk_params_v, label=""):
    """Store-mode dedup-regression guard, shared by both 1F1B schedules.

    The id() dedup leans on jax.vjp flattening passing param leaves
    through UNCOPIED — an implementation detail, not API. If a future
    JAX re-wraps them, they stop matching and would silently ride the
    stash as one weight copy per slot per leaf. Every casted chunk-param
    leaf is consumed by chunk_fn, so each must reappear as a passthrough
    member of the closure — fail loudly at trace time otherwise. Not
    exact-count: a few SMALL leaves legitimately fail the id() match
    (norm scales are consumed through their fp32-stat conversion, so an
    h-sized converted copy rides the stash). What must never happen is
    the h²-sized weights failing it — so gate on bytes, not presence."""
    closure_ids = {id(l) for l in closure_leaves}
    missing_b = sum(l.size * l.dtype.itemsize
                    for l in jax.tree.leaves(chunk_params_v)
                    if id(l) not in closure_ids)
    total_b = sum(l.size * l.dtype.itemsize
                  for l in jax.tree.leaves(chunk_params_v))
    assert missing_b <= 0.05 * total_b, (
        f"store-activations dedup regressed{label}: {missing_b} of "
        f"{total_b} chunk param bytes are no longer identity-passthrough "
        "in the vjp closure (a jax.vjp flattening change?); refusing to "
        "stash weight copies — use recompute mode")


def pipeline_train_1f1b(
    params,            # {"transformer": stacked [L, ...], **shared}
    streams,           # pytree of [n_micro, ...] arrays (replicated on 'pp')
    cfg: ModelConfig,
    mesh,
    *,
    intake_fn,         # (shared, mb_slice, rng_mb) -> [b, s, h]
    chunk_fn,          # (chunk_params, h, mb_slice, layer_offset, rng_mb)
                       #   -> h or (h, moe_aux)
    head_loss_fn,      # (shared, h, mb_slice, rng_mb) -> scalar per-mb loss
    batch_shape,       # (b, s) of one microbatch's activations
    rng=None,
    cotangent_seed: float = 1.0,
    store_activations: bool = False,
    vpp: int = 1,
):
    """One-forward-one-backward pipeline schedule with hand-written backward
    (ref: megatron/schedules.py:606-722 forward_backward_pipelining_without_
    interleaving). Returns (mean_microbatch_loss, grads).

    `vpp>1` dispatches to the interleaved variant (its own function, the way
    the reference splits forward_backward_pipelining_with_interleaving out,
    schedules.py:253-502) — virtual stages under the SAME 1F1B memory bound:
    live bytes flat in n_micro (see _pipeline_train_1f1b_interleaved).

    `store_activations=False` (default): the stash holds chunk INPUTS and
    the backward slot recomputes its chunk forward inside a same-tick vjp
    — the reference's --recompute-granularity=full under 1F1B.
    `store_activations=True`: the forward slot's vjp RESIDUALS are carried
    instead (the reference's no-recompute default): each tick's vjp
    closure is flattened to leaves, leaves that are identity-passthrough
    params are dropped (they are loop-invariant — stashing them would
    materialize 2pp-1 copies of the stage weights), the rest ride a
    per-leaf circular stash, and the backward slot rebuilds the closure
    with the live params. Removes the per-tick chunk recompute (~1/3 of
    pipeline compute) at the cost of holding each in-flight microbatch's
    chunk residuals; pair it with recompute_granularity="selective"/"none"
    (with "full", the per-layer rematerialization happens inside the vjp
    anyway and storing residuals buys nothing). The head is
    jax.checkpoint-ed in this mode so logits-sized CE residuals never
    enter the stash.

    Why not jax.grad of the lockstep schedule: reverse-mode differentiates
    the whole T-tick scan, so every microbatch's stage-boundary activation
    stays live until the backward sweep — memory grows with n_micro
    (VERDICT r2 item 2). Here each tick runs ONE forward micro-step and ONE
    backward micro-step per stage:

    - tick t, stage s forwards microbatch  t - s
    - tick t, stage s backwards microbatch t - 2(pp-1) + s
      (the cotangent for mb j reaches stage s exactly then: fwd arrives at
      the last stage at tick pp-1+j, turns around same-tick, and rides the
      reverse ring one stage per tick)
    - the ONLY cross-tick activation state is a circular stash of depth
      D = 2pp-1 (the widest in-flight window, at stage 0) — live bytes
      are flat in n_micro at fixed pp, the 1F1B memory bound. What the
      stash HOLDS depends on `store_activations` (below): chunk inputs
      (default) or the forward vjp residuals.
    - default mode: the backward micro-step recomputes its chunk forward
      from the stashed input inside a same-tick jax.vjp (the reference's
      --recompute-granularity=full under 1F1B); residuals never cross
      ticks. Store mode: no recompute — residuals cross ticks in the
      stash instead.
    - total ticks T = n_micro + 2(pp-1) with one fwd + one bwd slot each,
      vs the derived lockstep's (n_micro + pp - 1) fwd ticks + as many
      derived bwd ticks — same steady-state compute, pp-bounded memory.

    The embedding intake runs inside stage 0's tick, the head/loss inside
    the last stage's tick (ref: the last rank's forward_step computing loss
    in schedules.py:606-722); shared-parameter grads (embedding both tied
    ends, final norm, heads) are psum'd over 'pp' at the end.
    """
    if vpp > 1:
        return _pipeline_train_1f1b_interleaved(
            params, streams, cfg, mesh, intake_fn=intake_fn,
            chunk_fn=chunk_fn, head_loss_fn=head_loss_fn,
            batch_shape=batch_shape, rng=rng,
            cotangent_seed=cotangent_seed,
            store_activations=store_activations, vpp=vpp)
    pp = mesh.shape["pp"]
    n_micro = jax.tree.leaves(streams)[0].shape[0]
    L = jax.tree.leaves(params["transformer"])[0].shape[0]
    assert L % pp == 0, f"num_layers {L} not divisible by pp {pp}"
    Lc = L // pp
    n_b, n_s = batch_shape
    T = n_micro + 2 * (pp - 1)
    D = 2 * pp - 1  # stash depth: widest in-flight window (stage 0)

    from megatron_tpu.config import as_dtype
    compute_dtype = as_dtype(cfg.compute_dtype)
    # same CPU-partitioner workaround as the lockstep schedule (bf16 psum
    # inside partial-manual regions CHECK-fails on the XLA CPU backend)
    boundary_dtype = (jnp.float32 if jax.default_backend() == "cpu"
                      else compute_dtype)

    staged = stage_params_reshape(params["transformer"], pp)  # [pp, Lc, ...]
    shared = {k: v for k, v in params.items() if k != "transformer"}

    def per_stage(chunk_shard, shared_p, streams_all):
        chunk_p = jax.tree.map(lambda p: p[0], chunk_shard)  # [Lc, ...]
        stage = jax.lax.axis_index("pp")
        is_first = stage == 0
        is_last = stage == pp - 1
        offset = stage * Lc
        ring_fwd = [(i, (i + 1) % pp) for i in range(pp)]
        ring_bwd = [(i, (i - 1) % pp) for i in range(pp)]

        def mb_rng(i):
            return jax.random.fold_in(rng, i) if rng is not None else None

        def combined_f(sl, rng_m):
            """(chunk -> checkpointed head) as one vjp target returning
            (boundary h_out, per-mb loss, chunk moe aux). Seeding aux's
            cotangent on EVERY stage (unlike the last-stage-only loss
            seed) is what lets each stage's router aux reach its own
            params AND send d(aux)/d(h_in) up the reverse ring."""
            def f(cp, sp, h):
                h_out, aux = _chunk_ret(chunk_fn(
                    cp, h.astype(compute_dtype), sl, offset, rng_m))
                loss = jax.checkpoint(
                    lambda sp_, ho: head_loss_fn(sp_, ho, sl, rng_m),
                    prevent_cse=False)(sp, h_out)
                return h_out.astype(boundary_dtype), loss, aux
            return f

        param_like = [chunk_p, shared_p]  # +chunk_p_v in store mode below

        def split_vjp_leaves(vjp_fn):
            """Flatten a vjp closure, separating identity-passthrough
            param leaves (loop-invariant — never stashed) from true
            residuals."""
            leaves, treedef = jax.tree.flatten(vjp_fn)
            param_ids = {id(l) for l in jax.tree.leaves(param_like)}
            is_param = [id(l) in param_ids for l in leaves]
            resid = [l for l, p in zip(leaves, is_param) if not p]
            return leaves, treedef, is_param, resid

        if store_activations:
            # Pre-cast the chunk params to compute dtype ONCE, outside the
            # scan: every in-model `w.astype(compute_dtype)` then hits the
            # dtype-equal fast path (convert_element_type returns its
            # operand unchanged), so the casted weights stay
            # identity-passthrough leaves and the id() dedup excludes them
            # from the stash. Without this, bf16 compute would stash
            # 2pp-1 bf16 COPIES of every stage weight (the cast creates a
            # new value the dedup cannot recognize). Numerics are
            # unchanged — chunk weights are always consumed at compute
            # dtype — and the grad-through-cast is the same f32
            # conversion the accumulator applies. The head keeps the
            # ORIGINAL shared params (it is checkpointed, so its weight
            # casts are recomputed at bwd, and precision-sensitive
            # f32-param uses stay exact).
            chunk_p_v = jax.tree.map(
                lambda p: p.astype(compute_dtype)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, chunk_p)
            param_like.append(chunk_p_v)
            # trace-time prototype: residual leaf shapes for the stash
            # buffers (outputs unused -> the duplicate forward is DCE'd)
            h0 = jnp.zeros((n_b, n_s, cfg.hidden_size), boundary_dtype)
            _, vjp_proto = jax.vjp(
                combined_f(_dyn(streams_all, jnp.int32(0)),
                           mb_rng(jnp.int32(0))),
                chunk_p_v, shared_p, h0)
            proto_leaves, _, proto_is_param, proto_resid = \
                split_vjp_leaves(vjp_proto)
            resid_shapes = [(l.shape, l.dtype) for l in proto_resid]
            _assert_dedup_passthrough(proto_leaves, chunk_p_v)

        def tick(carry, t):
            (fwd_msg, bwd_msg, stash, g_chunk, g_shared, loss_acc,
             aux_acc) = carry
            fwd_mb = t - stage
            bwd_mb = t - 2 * (pp - 1) + stage
            fwd_valid = (fwd_mb >= 0) & (fwd_mb < n_micro)
            bwd_valid = (bwd_mb >= 0) & (bwd_mb < n_micro)
            fmb = jnp.clip(fwd_mb, 0, n_micro - 1)
            bmb = jnp.clip(bwd_mb, 0, n_micro - 1)
            fsl = _dyn(streams_all, fmb)
            bsl = _dyn(streams_all, bmb)

            # --- forward slot: intake (stage 0) or ring message
            x0 = intake_fn(shared_p, fsl, mb_rng(fmb)).astype(boundary_dtype)
            h_in = jnp.where(is_first, x0, fwd_msg)
            slot_f = jnp.mod(fmb, D)
            slot_b = jnp.mod(bmb, D)
            ct_l_seed = jnp.asarray(cotangent_seed / n_micro, jnp.float32)
            # every stage's chunk aux contributes to the loss with the
            # same 1/n_micro weight (see combined_f)
            ct_aux = ct_l_seed * cfg.moe_aux_loss_coeff

            # Both modes keep every stage on the IDENTICAL op sequence —
            # branch-free because GSPMD inserts tp/sp collectives inside
            # this region and devices in different lax.cond branches would
            # execute divergent collective sequences, deadlocking the
            # runtime. Stage roles are expressed through the vjp COTANGENT
            # instead: mid stages seed the chunk output with the ring
            # cotangent and the loss with 0; the last stage seeds the loss
            # with loss_scale/n_micro and the chunk output with 0. The head
            # forward+backward thus runs (masked) on every stage — a
            # ~2·h·V/(layers/pp · 12·h²) FLOP overhead (≈5% at 7B/pp8)
            # traded for a deadlock-free single program. Slot reuse is safe
            # because the in-flight window 2(pp-1-s) is < D; writes happen
            # before the same-tick read (on the last stage fmb == bmb).
            if store_activations:
                # ONE fwd (this tick's microbatch) whose vjp residuals ride
                # the stash; the bwd slot rebuilds the closure — no
                # recompute anywhere outside the checkpointed head.
                (h_pair, loss_f, aux_f), vjp_f = jax.vjp(
                    combined_f(fsl, mb_rng(fmb)), chunk_p_v, shared_p,
                    h_in)
                leaves, treedef, is_param, resid = split_vjp_leaves(vjp_f)
                assert is_param == proto_is_param, "vjp structure drifted"
                assert [(r.shape, r.dtype) for r in resid] == resid_shapes
                stash = [s.at[slot_f].set(jnp.where(fwd_valid, r,
                                                    s[slot_f]))
                         for s, r in zip(stash, resid)]
                resid_b = [jax.lax.dynamic_index_in_dim(s, slot_b, 0,
                                                        False)
                           for s in stash]
                rb = iter(resid_b)
                rebuilt = [l if p else next(rb)
                           for l, p in zip(leaves, is_param)]
                vjp_b = jax.tree.unflatten(treedef, rebuilt)
                ct_h = jnp.where(is_last, jnp.zeros_like(bwd_msg), bwd_msg)
                ct_l = jnp.where(is_last, ct_l_seed,
                                 jnp.zeros((), jnp.float32))
                dcp, dsp, dh = vjp_b((ct_h, ct_l, ct_aux))
                h_out = jnp.where(is_last, jnp.zeros_like(h_pair), h_pair)
                # loss/aux are known at the FWD slot in this mode
                loss_contrib = jnp.where(
                    fwd_valid & is_last, loss_f, 0.0)
                aux_contrib = jnp.where(fwd_valid, aux_f, 0.0)
            else:
                # recompute mode: stash chunk INPUTS; the bwd slot reruns
                # the chunk forward inside a same-tick vjp
                stash = stash.at[slot_f].set(
                    jnp.where(fwd_valid, h_in, stash[slot_f]))
                h_saved = jax.lax.dynamic_index_in_dim(stash, slot_b, 0,
                                                       False)
                h_out_f, _ = _chunk_ret(chunk_fn(
                    chunk_p, h_in.astype(compute_dtype), fsl, offset,
                    mb_rng(fmb)))
                h_out_f = h_out_f.astype(boundary_dtype)

                def f(cp, sp, h):
                    h_out, aux = _chunk_ret(chunk_fn(
                        cp, h.astype(compute_dtype), bsl, offset,
                        mb_rng(bmb)))
                    loss = head_loss_fn(sp, h_out, bsl, mb_rng(bmb))
                    return h_out.astype(boundary_dtype), loss, aux

                ((_, loss_mb, aux_mb), vjp) = jax.vjp(f, chunk_p, shared_p,
                                                      h_saved)
                ct_h = jnp.where(is_last, jnp.zeros_like(bwd_msg), bwd_msg)
                ct_l = jnp.where(is_last, ct_l_seed,
                                 jnp.zeros((), jnp.float32))
                dcp, dsp, dh = vjp((ct_h, ct_l, ct_aux))
                h_out = jnp.where(is_last, jnp.zeros_like(h_out_f),
                                  h_out_f)
                loss_contrib = jnp.where(
                    bwd_valid & is_last, loss_mb, 0.0)
                aux_contrib = jnp.where(bwd_valid, aux_mb, 0.0)

            # --- embedding intake backward (uniform; only stage 0's
            # cotangent is nonzero, so other stages accumulate zeros)
            _, vjp_in = jax.vjp(
                lambda sp: intake_fn(sp, bsl, mb_rng(bmb)).astype(
                    boundary_dtype), shared_p)
            (d_intake,) = vjp_in(
                jnp.where(is_first, dh, jnp.zeros_like(dh)))

            # --- masked fp32 accumulation
            def acc(g, *ds):
                upd = sum(d.astype(jnp.float32) for d in ds)
                return g + jnp.where(bwd_valid, upd, 0.0)

            g_chunk = jax.tree.map(acc, g_chunk, dcp)
            g_shared = jax.tree.map(acc, g_shared, dsp, d_intake)
            loss_acc = loss_acc + loss_contrib
            aux_acc = aux_acc + aux_contrib

            # --- ring rotation: activations down, cotangents up
            if pp > 1:
                fwd_nxt = jax.lax.ppermute(h_out, "pp", ring_fwd)
                bwd_nxt = jax.lax.ppermute(dh, "pp", ring_bwd)
            else:
                fwd_nxt, bwd_nxt = h_out, dh
            return (fwd_nxt, bwd_nxt, stash, g_chunk, g_shared,
                    loss_acc, aux_acc), None

        msg0 = jnp.zeros((n_b, n_s, cfg.hidden_size), boundary_dtype)
        if store_activations:
            stash0 = [jnp.zeros((D,) + tuple(shape), dtype)
                      for shape, dtype in resid_shapes]
        else:
            stash0 = jnp.zeros((D, n_b, n_s, cfg.hidden_size),
                               boundary_dtype)
        gc0 = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), chunk_p)
        gs0 = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                           shared_p)
        (_, _, _, g_chunk, g_shared, loss_acc, aux_acc), _ = jax.lax.scan(
            tick, (msg0, msg0, stash0, gc0, gs0,
                   jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
            jnp.arange(T))

        # shared-param grads meet across stages (tied embedding: intake on
        # stage 0 + head on the last stage — ref: optimizer.py:203-229
        # embedding-group all-reduce); loss lives on the last stage only,
        # router aux on every stage (each owns its own layers' routers)
        g_shared = jax.lax.psum(g_shared, "pp")
        loss = (jax.lax.psum(loss_acc, "pp")
                + cfg.moe_aux_loss_coeff * jax.lax.psum(aux_acc, "pp")
                ) / n_micro
        return loss, jax.tree.map(lambda g: g[None], g_chunk), g_shared

    shmap = jax.shard_map(
        per_stage,
        in_specs=(P("pp"), P(), P()),
        out_specs=(P(), P("pp"), P()),
        check_vma=False,
        axis_names={"pp"},
    )
    loss, g_chunk, g_shared = shmap(staged, shared, streams)
    grads = dict(g_shared)
    grads["transformer"] = stage_params_flatten(g_chunk)
    return loss, grads


def _pipeline_train_1f1b_interleaved(
    params, streams, cfg: ModelConfig, mesh, *,
    intake_fn, chunk_fn, head_loss_fn, batch_shape,
    rng=None, cotangent_seed: float = 1.0,
    store_activations: bool = False, vpp: int = 2,
):
    """Interleaved virtual stages under the 1F1B memory bound
    (ref: megatron/schedules.py:253-502 forward_backward_pipelining_with_
    interleaving; interleaved layer->stage offsets ref:
    transformer.py:1014-1044).

    Each stage owns vpp layer chunks (chunk c covers layers starting at
    (c*pp + stage)*Lc); a microbatch makes P = pp*vpp forward hops —
    position pos(s,c) = c*pp + s — so the fwd/bwd timetable is the
    single-chunk 1F1B with pp replaced by P:

    - tick t, stage s, chunk c forwards mb  t - pos(s,c)
    - tick t, stage s, chunk c backwards mb t - 2(P-1) + pos(s,c)
    - T = n_micro + 2(P-1) ticks; stash depth D = 2P-1 per chunk

    The vpp boundary buffers ride ONE ppermute per direction per tick; the
    wraparound edge (stage pp-1 -> 0 forward, 0 -> pp-1 backward) rolls the
    chunk axis so chunk c's output becomes chunk c+1's input (exactly the
    lockstep pipeline_apply trick, but for cotangents too). The head is
    pulled OUT of the per-chunk vjp and run once per tick on chunk vpp-1's
    fresh output — a microbatch's last fwd hop and its head+turnaround
    land on the same tick (pos = P-1 gives fwd_mb == bwd_mb there), so the
    head's input-cotangent feeds chunk vpp-1's SAME-TICK backward slot and
    no head state ever crosses ticks. Every stage still executes the
    identical branch-free op sequence (the GSPMD-collective deadlock
    argument in pipeline_train_1f1b); stage roles ride the cotangent
    seeds.

    MEMORY: per-stage live bytes are flat in n_micro — the vpp gate the
    gpipe fallback failed (VERDICT r3 missing #2). The stash holds
    vpp*(2P-1) chunk inputs (recompute mode) or vpp*(2P-1) chunk-residual
    sets (store mode) — a factor ~vpp² more boundary buffers than vpp=1
    (the in-flight window grows with P), but INDEPENDENT of n_micro, so
    gbs-1000-style runs still operate at n_micro >> P. BUBBLE: T grows to
    n_micro + 2(P-1) — the module docstring's structural argument that
    lockstep interleaving cannot shrink the bubble applies here too (it
    GROWS with vpp). vpp under 1F1B is therefore for the reference's
    interleaved layer->stage ASSIGNMENT (checkpoint-layout parity, layer
    balance) at bounded memory, not a throughput lever; the bubble lever
    remains n_micro.
    """
    pp = mesh.shape["pp"]
    n_micro = jax.tree.leaves(streams)[0].shape[0]
    L = jax.tree.leaves(params["transformer"])[0].shape[0]
    npos = pp * vpp  # P in the docstring: total forward hops
    assert L % npos == 0, (
        f"num_layers {L} not divisible by pp*vpp {pp}x{vpp}")
    Lc = L // npos
    n_b, n_s = batch_shape
    T = n_micro + 2 * (npos - 1)
    D = 2 * npos - 1  # per-chunk stash depth: widest in-flight window

    from megatron_tpu.config import as_dtype
    compute_dtype = as_dtype(cfg.compute_dtype)
    boundary_dtype = (jnp.float32 if jax.default_backend() == "cpu"
                      else compute_dtype)

    chunked = stage_params_chunked(params["transformer"], pp, vpp)
    shared = {k: v for k, v in params.items() if k != "transformer"}

    def per_stage(chunk_shard, shared_p, streams_all):
        # chunk_shard [1, vpp, Lc, ...]; the chunk loop is PYTHON-unrolled
        # (vpp is small and static): each chunk's param slices are
        # loop-invariant outer values, so the store-mode id() dedup works
        # per chunk exactly as in the single-chunk schedule (a lax.scan
        # over chunks would re-slice params into fresh per-iteration
        # tracers and defeat it).
        chunk_ps = [jax.tree.map(lambda p: p[0, c], chunk_shard)
                    for c in range(vpp)]
        stage = jax.lax.axis_index("pp")
        is_first = stage == 0
        is_last = stage == pp - 1
        ring_fwd = [(i, (i + 1) % pp) for i in range(pp)]
        ring_bwd = [(i, (i - 1) % pp) for i in range(pp)]

        def mb_rng(i):
            return jax.random.fold_in(rng, i) if rng is not None else None

        def chunk_f(c, sl, rng_m):
            """Chunk c's forward (no head) as a vjp target returning
            (h, moe_aux) — aux's cotangent is seeded on every stage/chunk
            (each owns its own routers; d(aux)/d(h_in) rides the reverse
            ring like any other cotangent)."""
            offset = (c * pp + stage) * Lc

            def f(cp, h):
                h_out, aux = _chunk_ret(chunk_fn(
                    cp, h.astype(compute_dtype), sl, offset, rng_m))
                return h_out.astype(boundary_dtype), aux
            return f

        if store_activations:
            # per-chunk pre-cast so casted weights stay identity-
            # passthrough (rationale in pipeline_train_1f1b's store-mode
            # comments; the shared byte guard below keeps this path
            # equally loud on a dedup regression)
            chunk_ps_v = [jax.tree.map(
                lambda p: p.astype(compute_dtype)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, cp)
                for cp in chunk_ps]
            param_ids = [
                {id(l) for l in jax.tree.leaves([chunk_ps[c],
                                                 chunk_ps_v[c]])}
                for c in range(vpp)]

            def split_leaves(vjp_fn, c):
                leaves, treedef = jax.tree.flatten(vjp_fn)
                is_param = [id(l) in param_ids[c] for l in leaves]
                resid = [l for l, p in zip(leaves, is_param) if not p]
                return leaves, treedef, is_param, resid

            h0 = jnp.zeros((n_b, n_s, cfg.hidden_size), boundary_dtype)
            protos = []
            for c in range(vpp):
                _, vjp_proto = jax.vjp(
                    chunk_f(c, _dyn(streams_all, jnp.int32(0)),
                            mb_rng(jnp.int32(0))), chunk_ps_v[c], h0)
                protos.append(split_leaves(vjp_proto, c))
                _assert_dedup_passthrough(protos[c][0], chunk_ps_v[c],
                                          label=f" (chunk {c})")
            resid_shapes = [(l.shape, l.dtype) for l in protos[0][3]]
            for c in range(1, vpp):
                assert [(l.shape, l.dtype) for l in protos[c][3]] == \
                    resid_shapes, "residual structure differs across chunks"

        def tick(carry, t):
            (fwd_msgs, bwd_msgs, stash, g_chunks, g_shared, loss_acc,
             aux_acc) = carry
            ct_l_seed = jnp.asarray(cotangent_seed / n_micro, jnp.float32)
            ct_aux = ct_l_seed * cfg.moe_aux_loss_coeff

            # ---- forward slots: all vpp chunks, one hop each
            h_outs, fwd_closures = [], []
            for c in range(vpp):
                fwd_mb = t - stage - c * pp
                fwd_valid = (fwd_mb >= 0) & (fwd_mb < n_micro)
                fmb = jnp.clip(fwd_mb, 0, n_micro - 1)
                fsl = _dyn(streams_all, fmb)
                h_in = fwd_msgs[c]
                if c == 0:
                    x0 = intake_fn(shared_p, fsl,
                                   mb_rng(fmb)).astype(boundary_dtype)
                    h_in = jnp.where(is_first, x0, h_in)
                slot_f = jnp.mod(fmb, D)
                if store_activations:
                    (h_out, aux_f), vjp_f = jax.vjp(
                        chunk_f(c, fsl, mb_rng(fmb)), chunk_ps_v[c], h_in)
                    leaves, treedef, is_param, resid = \
                        split_leaves(vjp_f, c)
                    assert is_param == protos[c][2], "vjp structure drifted"
                    assert [(r.shape, r.dtype) for r in resid] == \
                        resid_shapes
                    stash = [s.at[c, slot_f].set(
                        jnp.where(fwd_valid, r, s[c, slot_f]))
                        for s, r in zip(stash, resid)]
                    fwd_closures.append((leaves, treedef, is_param))
                else:
                    stash = stash.at[c, slot_f].set(
                        jnp.where(fwd_valid, h_in, stash[c, slot_f]))
                    h_out, aux_f = chunk_f(c, fsl, mb_rng(fmb))(
                        chunk_ps[c], h_in)
                # aux VALUE from the fwd slot (each real microbatch passes
                # each chunk's fwd slot exactly once)
                aux_acc = aux_acc + jnp.where(fwd_valid, aux_f, 0.0)
                h_outs.append(h_out)

            # ---- head: once per tick, on chunk vpp-1's fresh output (its
            # last-stage fwd and the same microbatch's turnaround backward
            # share this tick)
            head_mb = t - stage - (vpp - 1) * pp  # == t-(P-1) on is_last
            head_valid = (head_mb >= 0) & (head_mb < n_micro)
            hmb = jnp.clip(head_mb, 0, n_micro - 1)
            hsl = _dyn(streams_all, hmb)
            # one combined head vjp over (shared, h): grads and the
            # input-cotangent come from a single pullback
            loss_head, vjp_head = jax.vjp(
                lambda sp, h: head_loss_fn(sp, h.astype(compute_dtype),
                                           hsl, mb_rng(hmb)),
                shared_p, h_outs[vpp - 1])
            ct_l = jnp.where(is_last & head_valid, ct_l_seed,
                             jnp.zeros((), jnp.float32))
            d_sp_head, d_h_head = vjp_head(ct_l)
            loss_contrib = jnp.where(head_valid & is_last, loss_head, 0.0)

            # ---- backward slots: all vpp chunks
            dhs = []
            for c in range(vpp):
                bwd_mb = t - 2 * (npos - 1) + c * pp + stage
                bwd_valid = (bwd_mb >= 0) & (bwd_mb < n_micro)
                bmb = jnp.clip(bwd_mb, 0, n_micro - 1)
                bsl = _dyn(streams_all, bmb)
                slot_b = jnp.mod(bmb, D)
                ct_in = bwd_msgs[c]
                if c == vpp - 1:
                    ct_in = jnp.where(is_last, d_h_head.astype(ct_in.dtype),
                                      ct_in)
                if store_activations:
                    leaves, treedef, is_param = fwd_closures[c]
                    resid_b = [jax.lax.dynamic_index_in_dim(s[c], slot_b, 0,
                                                            False)
                               for s in stash]
                    rb = iter(resid_b)
                    rebuilt = [l if p else next(rb)
                               for l, p in zip(leaves, is_param)]
                    vjp_b = jax.tree.unflatten(treedef, rebuilt)
                    dcp, dh = vjp_b((ct_in, ct_aux))
                else:
                    h_saved = jax.lax.dynamic_index_in_dim(
                        stash[c], slot_b, 0, False)
                    _, vjp_b = jax.vjp(chunk_f(c, bsl, mb_rng(bmb)),
                                       chunk_ps[c], h_saved)
                    dcp, dh = vjp_b((ct_in, ct_aux))
                g_chunks[c] = jax.tree.map(
                    lambda g, d: g + jnp.where(bwd_valid,
                                               d.astype(jnp.float32), 0.0),
                    g_chunks[c], dcp)
                dhs.append(dh)
                if c == 0:
                    # intake backward consumes chunk 0's input-cotangent on
                    # stage 0 (uniform: other stages accumulate zeros)
                    _, vjp_in = jax.vjp(
                        lambda sp: intake_fn(sp, bsl, mb_rng(bmb)).astype(
                            boundary_dtype), shared_p)
                    (d_intake,) = vjp_in(
                        jnp.where(is_first, dh, jnp.zeros_like(dh)))
                    bwd_valid_0 = bwd_valid

            g_shared = jax.tree.map(
                lambda g, a, b: g
                + jnp.where(head_valid, a.astype(jnp.float32), 0.0)
                + jnp.where(bwd_valid_0, b.astype(jnp.float32), 0.0),
                g_shared, d_sp_head, d_intake)
            loss_acc = loss_acc + loss_contrib

            # ---- ring rotation with the chunk-promoting wraparound roll
            outs = jnp.stack(h_outs)          # [vpp, b, s, h]
            dstk = jnp.stack(dhs)             # [vpp, b, s, h]
            if pp > 1:
                rot_f = jax.lax.ppermute(outs, "pp", ring_fwd)
                rot_b = jax.lax.ppermute(dstk, "pp", ring_bwd)
            else:
                rot_f, rot_b = outs, dstk
            fwd_nxt = jnp.where(is_first, jnp.roll(rot_f, 1, axis=0), rot_f)
            bwd_nxt = jnp.where(is_last, jnp.roll(rot_b, -1, axis=0), rot_b)
            return (fwd_nxt, bwd_nxt, stash, g_chunks, g_shared,
                    loss_acc, aux_acc), None

        msg0 = jnp.zeros((vpp, n_b, n_s, cfg.hidden_size), boundary_dtype)
        if store_activations:
            stash0 = [jnp.zeros((vpp, D) + tuple(shape), dtype)
                      for shape, dtype in resid_shapes]
        else:
            stash0 = jnp.zeros((vpp, D, n_b, n_s, cfg.hidden_size),
                               boundary_dtype)
        gc0 = [jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), cp)
               for cp in chunk_ps]
        gs0 = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                           shared_p)
        (_, _, _, g_chunks, g_shared, loss_acc, aux_acc), _ = jax.lax.scan(
            tick, (msg0, msg0, stash0, gc0, gs0,
                   jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
            jnp.arange(T))

        g_shared = jax.lax.psum(g_shared, "pp")
        loss = (jax.lax.psum(loss_acc, "pp")
                + cfg.moe_aux_loss_coeff * jax.lax.psum(aux_acc, "pp")
                ) / n_micro
        g_stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *g_chunks)
        return loss, jax.tree.map(lambda g: g[None], g_stacked), g_shared

    shmap = jax.shard_map(
        per_stage,
        in_specs=(P("pp"), P(), P()),
        out_specs=(P(), P("pp"), P()),
        check_vma=False,
        axis_names={"pp"},
    )
    loss, g_chunked, g_shared = shmap(chunked, shared, streams)
    grads = dict(g_shared)
    grads["transformer"] = stage_params_unchunk(g_chunked)
    return loss, grads


def gpt_1f1b_fns(cfg: ModelConfig, rope=None, deterministic: bool = True,
                 cp_pre_zigzag: bool = False):
    """(intake_fn, chunk_fn, head_loss_fn) reproducing the GPT lockstep
    semantics (embed intake -> causal stack -> final norm + tied/untied
    head + per-microbatch masked-mean CE).

    `cp_pre_zigzag`: the streams were pre-permuted into ring-cp zigzag
    order (gpt_1f1b_streams zigzag_cp>0), so ring attention skips its 4
    runtime permute-gathers per call — the pp>1 + cp composition no longer
    pays them (VERDICT r3 weak #4). The per-microbatch masked-mean CE is
    permutation-invariant because labels/mask ride the same permutation."""
    from megatron_tpu.config import as_dtype
    from megatron_tpu.models import language_model as lm
    from megatron_tpu.models.norms import apply_norm
    from megatron_tpu.ops.cross_entropy import cross_entropy_loss
    from megatron_tpu.parallel.sharding import constrain

    if rope is None:
        rope = lm.make_rope(cfg)
    compute_dtype = as_dtype(cfg.compute_dtype)

    def intake(shared_p, sl, rng_mb):
        x = _embed(shared_p["embedding"], sl["inputs"], cfg, compute_dtype,
                   sl["position_ids"])
        if rng_mb is not None and not deterministic and \
                cfg.hidden_dropout > 0.0:
            from megatron_tpu.ops.dropout import dropout as _drop
            x = _drop(jax.random.fold_in(rng_mb, 0), x, cfg.hidden_dropout)
        return x

    def chunk(cp, h, sl, offset, rng_mb):
        layer_rng = (jax.random.fold_in(rng_mb, 1)
                     if rng_mb is not None and not deterministic else None)
        x, _, aux = tfm.stack_apply(
            cp, h, cfg,
            rope_cos=rope.cos if rope else None,
            rope_sin=rope.sin if rope else None,
            position_ids=sl["position_ids"], segment_ids=sl["segment_ids"],
            rng=layer_rng, deterministic=deterministic,
            layer_offset=offset, cp_pre_zigzag=cp_pre_zigzag)
        return x, aux

    def head_loss(shared_p, h, sl, rng_mb):
        logits = lm.head_logits(shared_p, h, cfg)
        losses = cross_entropy_loss(logits, sl["labels"],
                                    vocab_size=cfg.vocab_size)
        mask = sl["loss_mask"].astype(losses.dtype)
        return (jnp.sum(losses * mask)
                / jnp.maximum(jnp.sum(mask), 1.0))

    return intake, chunk, head_loss


def gpt_1f1b_streams(tokens, cfg: ModelConfig, loss_mask=None,
                     position_ids=None, segment_ids=None, zigzag_cp: int = 0):
    """GPT stream pytree for pipeline_train_1f1b from [n_micro, b, s+1]
    token blocks.

    `zigzag_cp > 0`: permute every per-token stream into ring-cp zigzag
    order ONCE here (ints + mask — cheap, data-level), so the ring inside
    each pipeline chunk runs permute-free (layout="pre_zigzag"); pair with
    gpt_1f1b_fns(cp_pre_zigzag=True). Positions are materialized first so
    RoPE sees the ORIGINAL positions through the permutation."""
    n_micro, n_b, _ = tokens.shape
    inputs = tokens[..., :-1]
    labels = tokens[..., 1:]
    n_s = inputs.shape[-1]
    if loss_mask is None:
        loss_mask = jnp.ones(labels.shape, jnp.float32)
    if position_ids is None:
        position_ids = jnp.broadcast_to(
            jnp.arange(n_s, dtype=jnp.int32), (n_micro, n_b, n_s))
    if zigzag_cp > 0:
        from megatron_tpu.parallel.ring_attention import zigzag_permutation
        perm, _ = zigzag_permutation(n_s, zigzag_cp)
        inputs = inputs[..., perm]
        labels = labels[..., perm]
        loss_mask = loss_mask[..., perm]
        position_ids = position_ids[..., perm]
        if segment_ids is not None:  # zigzag requires no segments, but
            segment_ids = segment_ids[..., perm]  # keep the math honest
    # segment_ids stay None when absent — materializing zeros would push
    # every chunk's attention off the flash/ring branches, which require
    # segment_ids is None (models/attention.py ring_branch)
    return {"inputs": inputs, "labels": labels, "loss_mask": loss_mask,
            "position_ids": position_ids, "segment_ids": segment_ids}


def pipeline_loss_fn(
    params,
    tokens,  # [n_micro, b, s+1]
    cfg: ModelConfig,
    mesh,
    *,
    vpp: int = 1,
    loss_mask=None,  # [n_micro, b, s]
    rope=None,
    rng=None,
    deterministic: bool = True,
    position_ids=None,  # [n_micro, b, s]
    segment_ids=None,   # [n_micro, b, s]
):
    """Full-model loss with the transformer stack pipelined over 'pp'.

    Final-norm / LM-head / CE run OUTSIDE the shard_map with the microbatch
    dim resharded over 'pp' (logits computed once, work spread over stages —
    see module docstring). Loss is the mean over microbatches of each
    microbatch's masked mean, matching the sequential train_step and the
    reference's per-microbatch loss averaging (ref: schedules.py:176-186) —
    so pp=1 and pp>1 train identically even with non-uniform loss masks.
    """
    from megatron_tpu.config import as_dtype
    from megatron_tpu.models import language_model as lm
    from megatron_tpu.models.norms import apply_norm
    from megatron_tpu.ops.cross_entropy import cross_entropy_loss
    from megatron_tpu.parallel.sharding import constrain

    if rope is None:
        rope = lm.make_rope(cfg)
    compute_dtype = as_dtype(cfg.compute_dtype)
    inputs = tokens[..., :-1]
    labels = tokens[..., 1:]
    if loss_mask is None:
        loss_mask = jnp.ones(labels.shape, jnp.float32)

    # data-level ring-cp zigzag, as in lm.loss_fn: permute every per-token
    # stream once so the ring inside each stage runs permute-free
    from megatron_tpu.parallel.ring_attention import (data_zigzag_cp,
                                                      zigzag_permutation)
    n_s = inputs.shape[-1]
    zz_cp = data_zigzag_cp(cfg, n_s, segment_ids=segment_ids)
    pre_zigzag = zz_cp > 0
    if pre_zigzag:
        perm, _ = zigzag_permutation(n_s, zz_cp)
        if position_ids is None:
            position_ids = jnp.broadcast_to(
                jnp.arange(n_s, dtype=jnp.int32), inputs.shape)
        inputs = inputs[..., perm]
        labels = labels[..., perm]
        loss_mask = loss_mask[..., perm]
        position_ids = position_ids[..., perm]

    x, moe_aux = pipeline_transformer(
        params, inputs, cfg, mesh, vpp=vpp,
        rope_cos=rope.cos if rope else None,
        rope_sin=rope.sin if rope else None,
        rng=rng, deterministic=deterministic,
        position_ids=position_ids, segment_ids=segment_ids,
        cp_pre_zigzag=pre_zigzag)

    # head work spread over the idle-in-the-bubble stages: microbatch dim
    # resharded onto 'pp' (mb_axis); same head implementation as the 1F1B
    # per-microbatch tail
    logits = lm.head_logits(params, x, cfg, mb_axis=True)
    losses = cross_entropy_loss(logits, labels, vocab_size=cfg.vocab_size)
    loss_mask = loss_mask.astype(losses.dtype)
    # per-microbatch masked mean, then mean over microbatches (== train_step)
    per_mb = (jnp.sum(losses * loss_mask, axis=(1, 2))
              / jnp.maximum(jnp.sum(loss_mask, axis=(1, 2)), 1.0))
    n_micro = inputs.shape[0]
    # aux matches lm.loss_fn's mean-over-microbatches normalization
    aux_term = (cfg.moe_aux_loss_coeff * moe_aux / n_micro
                if cfg.num_experts > 1 else 0.0)
    return jnp.mean(per_mb) + aux_term
