"""Ring attention: context parallelism over the 'cp' mesh axis.

The reference has NO context parallelism — no ring attention, no Ulysses, no
blockwise sequence sharding (SURVEY.md §2.8: explicitly absent; long context
is reached only via FlashAttention-2 + Megatron-SP + RoPE scaling, §5). This
module is the idiomatic TPU upgrade called for by SURVEY.md §7 stage 9:
sequences shard along 'cp', and K/V blocks rotate around the ring
(`lax.ppermute` over ICI) while each device's Q stays resident — attention
memory per chip drops by 1/cp and the KV transfers overlap with the
per-block attention compute (RingAttention, Liu et al. 2023; the public
"How to Scale Your Model" recipe).

Formulation: one partial-manual shard_map (manual over 'cp' only, dp/tp stay
GSPMD-automatic), cp hops of blockwise attention merged online via
(out, logsumexp) pairs — out_total = Σ_i out_i · exp(lse_i − lse_total).
The inner block is the Pallas flash kernel (scores never materialize in
HBM; `pallas_flash_attention_with_lse` exposes a differentiable lse whose
cotangent feeds back through the merge weights); the XLA einsum block
remains as the fallback for odd shapes / non-TPU backends. Causality per
hop: the block from rank r itself is the causal diagonal, blocks from
earlier ranks attend fully, later ranks are excluded via a −inf lse (their
compute is the standard causal-ring waste; zigzag balancing is a possible
future refinement).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_tpu.ops.flash_attention import flash_attention

NEG_INF = -1e30


def _local_block_attention(q, k, v, q_off, kv_off, *, scale, causal):
    """XLA fallback: blockwise attention of local q [b,s,nq,d] against one
    rotating kv block [b,c,nkv,d]; returns (out [b,s,nq,d] f32 normalized,
    lse [b,s,nq] f32) for online merging."""
    b, s, nq, d = q.shape
    c, nkv = k.shape[1], k.shape[2]
    g = nq // nkv
    qg = (q.astype(jnp.float32) * scale).reshape(b, s, nkv, g, d)
    scores = jnp.einsum("bsngd,btnd->bsngt", qg, k.astype(jnp.float32))
    if causal:
        q_pos = q_off + jnp.arange(s)
        kv_pos = kv_off + jnp.arange(c)
        mask = q_pos[:, None] >= kv_pos[None, :]  # [s, c]
        scores = jnp.where(mask[None, :, None, None, :], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)  # [b,s,nkv,g]
    p = jnp.exp(scores - m[..., None])
    p = jnp.where(m[..., None] <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bsngt,btnd->bsngd", p, v.astype(jnp.float32))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)
    return out.reshape(b, s, nq, d), lse.reshape(b, s, nq)


def _flash_ok(s_loc: int) -> bool:
    from megatron_tpu.ops.flash_attention_pallas import _pick_block
    try:
        _pick_block(s_loc, 512)
        return True
    except ValueError:
        return False


def ring_attention(q, k, v, mesh, *, causal: bool = True,
                   scale: float | None = None, axis: str = "cp",
                   impl: str = "auto"):
    """q/k/v [b, S, n, d] with S the GLOBAL sequence length, sharded over
    `axis` on dim 1. Returns [b, S, nq, d] with the same sharding.

    impl: "flash" forces the Pallas inner block (interpret mode off-TPU),
    "xla" forces the einsum fallback, "auto" picks flash on TPU when the
    local shard length tiles. Must run under jit with the ambient mesh set
    (same contract as the pipeline shard_map)."""
    cp = mesh.shape[axis]
    if cp == 1:
        return flash_attention(q, k, v, causal=causal, scale=scale)
    d = q.shape[-1]
    s_loc = q.shape[1] // cp
    if scale is None:
        scale = d ** -0.5
    out_dtype = q.dtype
    on_tpu = jax.default_backend() == "tpu"
    if impl == "auto":
        use_flash = on_tpu and _flash_ok(s_loc)
    else:
        use_flash = impl == "flash"
    interpret = not on_tpu
    # the CPU SPMD partitioner CHECK-fails on bf16 collectives in
    # partial-manual regions; ring K/V in compute dtype on TPU only
    ring_dtype = q.dtype if on_tpu else jnp.float32

    def per_rank(q, k, v):
        # local shards: q [b, s_loc, nq, d], k/v [b, s_loc, nkv, d]
        r = jax.lax.axis_index(axis)
        b, s_loc, nq, _ = q.shape
        perm = [(i, (i + 1) % cp) for i in range(cp)]

        def inner_flash(k_cur, v_cur, src):
            from megatron_tpu.ops.flash_attention_pallas import (
                pallas_flash_attention_with_lse as fl)
            kd, vd = k_cur.astype(q.dtype), v_cur.astype(q.dtype)
            if not causal:
                return fl(q, kd, vd, False, scale, 512, 512, interpret)
            # diagonal hop -> causal kernel; others -> full kernel (later
            # ranks are zero-weighted at merge)
            return jax.lax.cond(
                src == r,
                lambda a, bb, c: fl(a, bb, c, True, scale, 512, 512,
                                    interpret),
                lambda a, bb, c: fl(a, bb, c, False, scale, 512, 512,
                                    interpret),
                q, kd, vd)

        def hop(carry, step):
            out_tot, lse_tot, k_cur, v_cur = carry
            # after `step` rotations this rank holds the block that
            # originated at rank (r - step) mod cp
            src = (r - step) % cp
            if use_flash:
                out_i, lse_i = inner_flash(k_cur, v_cur, src)
                out_i = out_i.astype(jnp.float32)
                if causal:
                    # exclude blocks from later ranks
                    lse_i = jnp.where(src <= r, lse_i, NEG_INF)
            else:
                out_i, lse_i = _local_block_attention(
                    q, k_cur, v_cur, r * s_loc, src * s_loc,
                    scale=scale, causal=causal)
            new_tot = jnp.logaddexp(lse_tot, lse_i)
            safe = jnp.where(new_tot <= NEG_INF / 2, 0.0, new_tot)
            alpha = jnp.where(lse_tot <= NEG_INF / 2, 0.0,
                              jnp.exp(lse_tot - safe))
            beta = jnp.where(lse_i <= NEG_INF / 2, 0.0,
                             jnp.exp(lse_i - safe))
            out_tot = (out_tot * alpha[..., None]
                       + out_i * beta[..., None])
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return (out_tot, new_tot, k_nxt, v_nxt), None

        out0 = jnp.zeros(q.shape, jnp.float32)
        lse0 = jnp.full((b, s_loc, nq), NEG_INF, jnp.float32)
        (out, _, _, _), _ = jax.lax.scan(
            hop, (out0, lse0, k.astype(ring_dtype), v.astype(ring_dtype)),
            jnp.arange(cp))
        return out.astype(out_dtype)

    shmap = jax.shard_map(
        per_rank,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
        check_vma=False,
        axis_names={axis},
    )
    return shmap(q, k, v)
