"""Ring attention: context parallelism over the 'cp' mesh axis.

The reference has NO context parallelism — no ring attention, no Ulysses, no
blockwise sequence sharding (SURVEY.md §2.8: explicitly absent; long context
is reached only via FlashAttention-2 + Megatron-SP + RoPE scaling, §5). This
module is the idiomatic TPU upgrade called for by SURVEY.md §7 stage 9:
sequences shard along 'cp', and K/V blocks rotate around the ring
(`lax.ppermute` over ICI) while each device's Q stays resident — attention
memory per chip drops by 1/cp and the KV transfers overlap with the
per-block attention compute (RingAttention, Liu et al. 2023; the public
"How to Scale Your Model" recipe).

Formulation: one partial-manual shard_map (manual over 'cp' only, dp/tp stay
GSPMD-automatic), cp steps of blockwise attention with online-softmax
merging — the same merge the flash kernel does across kv blocks, here across
ring hops. Causality uses global positions derived from the ring rank, so
rotating blocks never breaks the causal mask.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_tpu.ops.flash_attention import flash_attention

NEG_INF = -1e30


def _local_block_attention(q, k, v, q_off, kv_off, *, scale, causal):
    """Blockwise attention of local q [b,s,nq,d] against one rotating kv
    block [b,c,nkv,d]; returns (unnormalized acc [b,s,nq,d] f32,
    m [b,s,nq] f32, l [b,s,nq] f32) for online merging."""
    b, s, nq, d = q.shape
    c, nkv = k.shape[1], k.shape[2]
    g = nq // nkv
    qg = (q.astype(jnp.float32) * scale).reshape(b, s, nkv, g, d)
    scores = jnp.einsum("bsngd,btnd->bsngt", qg, k.astype(jnp.float32))
    if causal:
        q_pos = q_off + jnp.arange(s)
        kv_pos = kv_off + jnp.arange(c)
        mask = q_pos[:, None] >= kv_pos[None, :]  # [s, c]
        scores = jnp.where(mask[None, :, None, None, :], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)  # [b,s,nkv,g]
    p = jnp.exp(scores - m[..., None])
    p = jnp.where(m[..., None] <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bsngt,btnd->bsngd", p, v.astype(jnp.float32))
    return (acc.reshape(b, s, nq, d), m.reshape(b, s, nq),
            l.reshape(b, s, nq))


def ring_attention(q, k, v, mesh, *, causal: bool = True,
                   scale: float | None = None, axis: str = "cp"):
    """q/k/v [b, S, n, d] with S the GLOBAL sequence length, sharded over
    `axis` on dim 1. Returns [b, S, nq, d] with the same sharding.

    Must run under jit with the ambient mesh set (same contract as the
    pipeline shard_map)."""
    cp = mesh.shape[axis]
    if cp == 1:
        return flash_attention(q, k, v, causal=causal, scale=scale)
    d = q.shape[-1]
    if scale is None:
        scale = d ** -0.5
    out_dtype = q.dtype

    def per_rank(q, k, v):
        # local shards: q [b, s_loc, nq, d], k/v [b, s_loc, nkv, d]
        r = jax.lax.axis_index(axis)
        s_loc = q.shape[1]
        b, _, nq, _ = q.shape
        perm = [(i, (i + 1) % cp) for i in range(cp)]

        def hop(carry, step):
            acc, m, l, k_cur, v_cur = carry
            # after `step` rotations this rank holds the block that
            # originated at rank (r - step) mod cp
            src = (r - step) % cp
            a_new, m_new, l_new = _local_block_attention(
                q, k_cur, v_cur, r * s_loc, src * s_loc,
                scale=scale, causal=causal)
            m_tot = jnp.maximum(m, m_new)
            m_safe = jnp.where(m_tot <= NEG_INF / 2, 0.0, m_tot)
            c1 = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_safe))
            c2 = jnp.where(m_new <= NEG_INF / 2, 0.0,
                           jnp.exp(m_new - m_safe))
            acc = acc * c1[..., None] + a_new * c2[..., None]
            l = l * c1 + l_new * c2
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return (acc, m_tot, l, k_nxt, v_nxt), None

        acc0 = jnp.zeros(q.shape, jnp.float32)
        m0 = jnp.full((b, s_loc, nq), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, s_loc, nq), jnp.float32)
        (acc, m, l, _, _), _ = jax.lax.scan(
            hop, (acc0, m0, l0,
                  k.astype(jnp.float32), v.astype(jnp.float32)),
            jnp.arange(cp))
        out = acc / jnp.maximum(l[..., None], 1e-30)
        return out.astype(out_dtype)

    shmap = jax.shard_map(
        per_rank,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
        check_vma=False,
        axis_names={axis},
    )
    return shmap(q, k, v)
