"""Ring attention: context parallelism over the 'cp' mesh axis.

The reference has NO context parallelism — no ring attention, no Ulysses, no
blockwise sequence sharding (SURVEY.md §2.8: explicitly absent; long context
is reached only via FlashAttention-2 + Megatron-SP + RoPE scaling, §5). This
module is the idiomatic TPU upgrade called for by SURVEY.md §7 stage 9:
sequences shard along 'cp', and K/V blocks rotate around the ring
(`lax.ppermute` over ICI) while each device's Q stays resident — attention
memory per chip drops by 1/cp and the KV transfers overlap with the
per-block attention compute (RingAttention, Liu et al. 2023; the public
"How to Scale Your Model" recipe).

Formulation: one partial-manual shard_map (manual over 'cp' only, dp/tp stay
GSPMD-automatic), cp hops of blockwise attention merged online via
(out, logsumexp) pairs — out_total = Σ_i out_i · exp(lse_i − lse_total).
The inner block is the Pallas flash kernel (scores never materialize in
HBM; `pallas_flash_attention_with_lse` exposes a differentiable lse whose
cotangent feeds back through the merge weights); the XLA einsum block
remains as the fallback for odd shapes / non-TPU backends.

Causal load balance — ZIGZAG layout (default for causal): a contiguous
sequence split makes rank r's useful causal work proportional to r+1 (the
last rank attends to everything, the first to almost nothing) — at cp=8
nearly half the ring's attention FLOPs are masked away. Instead the
sequence is split into 2·cp chunks and rank r owns chunks {r, 2cp-1-r}
(one early + one late chunk, the Megatron-LM cp / llama3 zigzag): every
rank's useful pair count becomes exactly 2cp+1 chunk-pairs (r+1 for the
head chunk + 2cp-r for the tail chunk), equal by construction —
`zigzag_pair_counts` asserts this and the flash path's per-pair
lax.switch SKIPS fully-masked pairs so balanced schedule = balanced
compute. Two zigzag modes: layout="zigzag" permutes q/k/v in and the
output back out OUTSIDE the shard_map (GSPMD lowers it to a pairwise
exchange per call); layout="pre_zigzag" declares the batch ALREADY
permuted — lm.loss_fn does that once per batch via `data_zigzag_cp` +
`zigzag_permutation` (tokens/labels/mask/positions ride the same
permutation; the masked-mean loss is permutation-invariant), making the
ring's data movement zero. The pipelined (pp>1) paths pre-permute too
(round 4): gpt_1f1b_streams permutes the microbatch streams once
(zigzag_cp) and pipeline_loss_fn mirrors lm.loss_fn, so pp>1 + cp no
longer pays the 4 runtime permute-gathers per attention call.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_tpu.ops.flash_attention import flash_attention

NEG_INF = -1e30


def _local_block_attention(q, k, v, q_pos, kv_pos, *, scale, causal):
    """XLA fallback: blockwise attention of local q [b,s,nq,d] against one
    rotating kv block [b,c,nkv,d]; returns (out [b,s,nq,d] f32 normalized,
    lse [b,s,nq] f32) for online merging. `q_pos`/`kv_pos` are the GLOBAL
    position vectors of the local rows — offsets for a contiguous layout,
    arbitrary permutations for zigzag."""
    b, s, nq, d = q.shape
    c, nkv = k.shape[1], k.shape[2]
    g = nq // nkv
    qg = (q.astype(jnp.float32) * scale).reshape(b, s, nkv, g, d)
    scores = jnp.einsum("bsngd,btnd->bsngt", qg, k.astype(jnp.float32))
    if causal:
        mask = q_pos[:, None] >= kv_pos[None, :]  # [s, c]
        scores = jnp.where(mask[None, :, None, None, :], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)  # [b,s,nkv,g]
    p = jnp.exp(scores - m[..., None])
    p = jnp.where(m[..., None] <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bsngt,btnd->bsngd", p, v.astype(jnp.float32))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), NEG_INF)
    return out.reshape(b, s, nq, d), lse.reshape(b, s, nq)


def zigzag_permutation(S: int, cp: int):
    """Row permutation putting a length-S sequence into zigzag order:
    rank r's shard = [chunk r ; chunk 2cp-1-r] of the 2cp equal chunks.
    Returns (perm, inv) index vectors; x_zig = x[perm], x = x_zig[inv]."""
    import numpy as np
    c = S // (2 * cp)
    parts = []
    for r in range(cp):
        parts.append(np.arange(r * c, (r + 1) * c))
        parts.append(np.arange((2 * cp - 1 - r) * c, (2 * cp - r) * c))
    perm = np.concatenate(parts)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(S)
    return perm, inv


def data_zigzag_cp(cfg, seq_len: int, *, causal: bool = True,
                   segment_ids=None) -> int:
    """cp when DATA-LEVEL zigzag applies (loss permutes tokens/labels/mask
    once; ring attention then skips its 4 runtime permute-gathers per
    call), else 0. Conditions: ring attention will actually run (ambient
    mesh has cp>1), causal, no segment path, and 2*cp divides the
    sequence. The loss is permutation-invariant as long as labels and
    mask ride the same permutation, and RoPE stays correct because the
    permuted position_ids carry the ORIGINAL positions."""
    if getattr(cfg, "attention_impl", None) != "ring" or not causal \
            or segment_ids is not None:
        return 0
    if getattr(cfg, "attention_dropout", 0.0) > 0.0:
        # active attention dropout routes attention to the dot path
        # (models/attention.py dropout_active), where a pre-permuted batch
        # would get causal masks on the wrong rows; conservatively keep
        # the runtime-permute mode for such configs (eval traces too)
        return 0
    if getattr(cfg, "sliding_window", None) is not None:
        # the ring path has no banded-mask plumbing: attention falls back
        # to the dot path (models/attention.py ring_branch gating), so a
        # pre-permuted batch would be masked on the wrong rows — same
        # reasoning as the dropout exclusion above
        return 0
    try:
        mesh = jax.sharding.get_abstract_mesh()
    except Exception:
        return 0
    if mesh.empty or "cp" not in mesh.axis_names:
        return 0
    cp = mesh.shape["cp"]
    if cp <= 1 or seq_len % (2 * cp) != 0:
        return 0
    return cp


def zigzag_pair_counts(cp: int):
    """Useful (non-fully-masked) chunk-pairs per rank under the zigzag
    schedule — equal across ranks by construction (the balance assert)."""
    counts = []
    for r in range(cp):
        head, tail = r, 2 * cp - 1 - r
        # a q chunk with global index i usefully attends to kv chunks
        # 0..i: i full pairs + 1 causal diagonal
        counts.append((head + 1) + (tail + 1))
    return counts


def _flash_ok(s_loc: int) -> bool:
    from megatron_tpu.ops.flash_attention_pallas import _pick_block
    try:
        _pick_block(s_loc, 512)
        return True
    except ValueError:
        return False


def ring_attention(q, k, v, mesh, *, causal: bool = True,
                   scale: float | None = None, axis: str = "cp",
                   impl: str = "auto", layout: str = "auto"):
    """q/k/v [b, S, n, d] with S the GLOBAL sequence length, sharded over
    `axis` on dim 1. Returns [b, S, nq, d] with the same sharding.

    impl: "flash" forces the Pallas inner block (interpret mode off-TPU),
    "xla" forces the einsum fallback, "auto" picks flash on TPU when the
    local shard length tiles. layout: "zigzag" balances causal work across
    ranks (module docstring), "contiguous" is the plain split,
    "pre_zigzag" declares the data ALREADY in zigzag order (loss-level
    pre-permutation via data_zigzag_cp — no runtime permutes), "auto"
    picks zigzag for causal when S divides 2·cp. Must run under jit with
    the ambient mesh set (same contract as the pipeline shard_map)."""
    cp = mesh.shape[axis]
    if cp == 1:
        return flash_attention(q, k, v, causal=causal, scale=scale)
    d = q.shape[-1]
    S = q.shape[1]
    s_loc = S // cp
    if scale is None:
        scale = d ** -0.5
    out_dtype = q.dtype
    on_tpu = jax.default_backend() == "tpu"
    interpret = not on_tpu
    # the CPU SPMD partitioner CHECK-fails on bf16 collectives in
    # partial-manual regions; ring K/V in compute dtype on TPU only
    ring_dtype = q.dtype if on_tpu else jnp.float32

    flash_zigzag_ok = (impl == "flash"
                       or (impl == "auto" and on_tpu
                           and _flash_ok(s_loc // 2)))
    if layout == "auto":
        # zigzag only pays off when the flash path SKIPS masked pairs; the
        # XLA fallback masks inside full-score blocks (already balanced),
        # so the in/out permutation gathers would be pure overhead there
        layout = ("zigzag" if causal and S % (2 * cp) == 0
                  and flash_zigzag_ok else "contiguous")
    zigzag = layout in ("zigzag", "pre_zigzag") and causal
    if zigzag:
        assert S % (2 * cp) == 0, (
            f"zigzag layout needs seq {S} divisible by 2*cp={2 * cp} "
            "(zigzag_permutation would silently truncate); use "
            "layout='contiguous'")
    c = s_loc // 2  # zigzag chunk length
    if impl == "auto":
        use_flash = on_tpu and _flash_ok(c if zigzag else s_loc)
    else:
        use_flash = impl == "flash"

    runtime_permute = zigzag and layout != "pre_zigzag"
    if runtime_permute:
        perm, inv = zigzag_permutation(S, cp)
        q, k, v = q[:, perm], k[:, perm], v[:, perm]

    from megatron_tpu.ops.flash_attention_pallas import (
        pallas_flash_attention_with_lse as fl)

    def _merge(out_a, lse_a, out_b, lse_b):
        """Online (out, lse) merge of two partial attention results."""
        tot = jnp.logaddexp(lse_a, lse_b)
        safe = jnp.where(tot <= NEG_INF / 2, 0.0, tot)
        alpha = jnp.where(lse_a <= NEG_INF / 2, 0.0, jnp.exp(lse_a - safe))
        beta = jnp.where(lse_b <= NEG_INF / 2, 0.0, jnp.exp(lse_b - safe))
        return out_a * alpha[..., None] + out_b * beta[..., None], tot

    def per_rank(q, k, v):
        # local shards: q [b, s_loc, nq, d], k/v [b, s_loc, nkv, d]
        r = jax.lax.axis_index(axis)
        b, s_loc, nq, _ = q.shape
        perm_ring = [(i, (i + 1) % cp) for i in range(cp)]

        def local_positions(rank):
            """Global positions of the local rows under the layout."""
            if zigzag:
                head = rank * c + jnp.arange(c)
                tail = (2 * cp - 1 - rank) * c + jnp.arange(c)
                return jnp.concatenate([head, tail])
            return rank * s_loc + jnp.arange(s_loc)

        def flash_block(q_blk, k_blk, v_blk, sel):
            """One (q chunk, kv chunk) pair via lax.switch on the pair
            class: 0 = fully masked (skip — this is what balances the
            schedule's COMPUTE, in aggregate across the ring sweep; within
            a single hop ranks can take different branch mixes, so the
            synchronized ppermute waits on that hop's slowest rank),
            1 = causal diagonal, 2 = fully allowed. No collectives inside
            the branches."""
            bq = q_blk.shape[1]

            def skip(a, bb, cc):
                return (jnp.zeros(a.shape, jnp.float32),
                        jnp.full((b, bq, nq), NEG_INF, jnp.float32))

            def diag(a, bb, cc):
                o, l = fl(a, bb, cc, True, scale, 512, 512, interpret)
                return o.astype(jnp.float32), l

            def full(a, bb, cc):
                o, l = fl(a, bb, cc, False, scale, 512, 512, interpret)
                return o.astype(jnp.float32), l

            return jax.lax.switch(sel, (skip, diag, full),
                                  q_blk, k_blk.astype(q.dtype),
                                  v_blk.astype(q.dtype))

        def inner_flash(k_cur, v_cur, src):
            if not causal:
                o, l = fl(q, k_cur.astype(q.dtype), v_cur.astype(q.dtype),
                          False, scale, 512, 512, interpret)
                return o.astype(jnp.float32), l
            if not zigzag:
                # contiguous causal: diagonal hop -> causal kernel; earlier
                # ranks full; later ranks skipped entirely
                sel = jnp.clip(jnp.sign(r - src) + 1, 0, 2)
                return flash_block(q, k_cur, v_cur, sel)
            # zigzag: 2x2 chunk pairs, each full/diag/skip by global
            # chunk index comparison
            q_idx = (r, 2 * cp - 1 - r)
            kv_idx = (src, 2 * cp - 1 - src)
            outs, lses = [], []
            for i in range(2):
                q_blk = q[:, i * c:(i + 1) * c]
                o_acc = jnp.zeros(q_blk.shape, jnp.float32)
                l_acc = jnp.full((b, c, nq), NEG_INF, jnp.float32)
                for j in range(2):
                    sel = jnp.clip(jnp.sign(q_idx[i] - kv_idx[j]) + 1,
                                   0, 2)
                    o_ij, l_ij = flash_block(
                        q_blk, k_cur[:, j * c:(j + 1) * c],
                        v_cur[:, j * c:(j + 1) * c], sel)
                    o_acc, l_acc = _merge(o_acc, l_acc, o_ij, l_ij)
                outs.append(o_acc)
                lses.append(l_acc)
            return (jnp.concatenate(outs, axis=1),
                    jnp.concatenate(lses, axis=1))

        def hop(carry, step):
            out_tot, lse_tot, k_cur, v_cur = carry
            # after `step` rotations this rank holds the block that
            # originated at rank (r - step) mod cp
            src = (r - step) % cp
            if use_flash:
                out_i, lse_i = inner_flash(k_cur, v_cur, src)
            else:
                out_i, lse_i = _local_block_attention(
                    q, k_cur, v_cur, local_positions(r),
                    local_positions(src), scale=scale, causal=causal)
            out_tot, lse_tot = _merge(out_tot, lse_tot, out_i, lse_i)
            k_nxt = jax.lax.ppermute(k_cur, axis, perm_ring)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm_ring)
            return (out_tot, lse_tot, k_nxt, v_nxt), None

        out0 = jnp.zeros(q.shape, jnp.float32)
        lse0 = jnp.full((b, s_loc, nq), NEG_INF, jnp.float32)
        (out, _, _, _), _ = jax.lax.scan(
            hop, (out0, lse0, k.astype(ring_dtype), v.astype(ring_dtype)),
            jnp.arange(cp))
        return out.astype(out_dtype)

    shmap = jax.shard_map(
        per_rank,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
        check_vma=False,
        axis_names={axis},
    )
    out = shmap(q, k, v)
    if runtime_permute:
        out = out[:, inv]
    return out
