"""Logical-axis sharding rules.

TPU-native replacement for the reference's hand-written tensor-parallel layer
classes (ref: megatron/core/tensor_parallel/layers.py — ColumnParallelLinear
:410, RowParallelLinear :566, VocabParallelEmbedding :128) and autograd-wrapped
collectives (ref: megatron/core/tensor_parallel/mappings.py:127-278).

Under GSPMD the same placement is expressed declaratively: every parameter and
activation carries logical axis names, and a rules table maps logical names to
mesh axes. XLA then inserts exactly the collectives the reference hand-codes:

  Column-parallel (out-dim on 'tp')  -> matmul keeps activations replicated,
                                        no comm fwd (ref: layers.py:463-474)
  Row-parallel (in-dim on 'tp')      -> XLA inserts psum (== the forward
                                        all-reduce at layers.py:690-694)
  Vocab-parallel embedding           -> vocab-dim shard + psum gather
                                        (ref: layers.py:187-210)
  Sequence parallel                  -> activations sharded ('sp' -> tp) along
                                        seq outside attention/MLP; the
                                        all-gather/reduce-scatter pair the
                                        reference codes at layers.py:225-296
                                        falls out of the sharding switch.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from megatron_tpu.parallel.mesh import (
    CONTEXT_AXIS, DATA_AXIS, PIPELINE_AXIS, TENSOR_AXIS)

# ---------------------------------------------------------------------------
# Logical axis vocabulary.
# Parameters:
#   "embed"      hidden dim (replicated over tp unless fsdp)
#   "heads"      attention-head output dim of QKV proj   -> tp
#   "mlp"        ffn hidden dim                          -> tp
#   "vocab"      vocabulary dim                          -> tp
#   "layers"     stacked-layer dim (scan over layers)    -> pp (when pipelined)
# Activations:
#   "batch"      global batch                            -> dp
#   "seq"        sequence dim inside attention           -> cp (ring attention)
#   "seq_sp"     sequence dim outside attn/mlp (SP)      -> tp
#   "act_embed"  activation hidden dim (replicated)
# ---------------------------------------------------------------------------

# rules as (logical_name, mesh_axis-or-None) pairs; first match wins.
def make_logical_rules(sequence_parallel: bool = False,
                       expert_axis: str = "tp"):
    assert expert_axis in ("tp", "dp"), expert_axis
    return (
        ("batch", DATA_AXIS),
        ("layers", PIPELINE_AXIS),
        ("stage", PIPELINE_AXIS),
        # microbatch stream dim: resharded over 'pp' for the post-pipeline
        # LM-head/CE so the head's FLOPs spread across stages
        ("microbatch", PIPELINE_AXIS),
        ("heads", TENSOR_AXIS),
        ("kv_heads", TENSOR_AXIS),
        ("mlp", TENSOR_AXIS),
        # MoE expert bank: each device holds whole experts; the mesh axis
        # is selectable (ParallelConfig.expert_axis) — 'tp' (default) or
        # 'dp' (GShard-style EP over the data axis; models/moe.py)
        ("experts", DATA_AXIS if expert_axis == "dp" else TENSOR_AXIS),
        ("vocab", TENSOR_AXIS),
        ("seq", CONTEXT_AXIS),
        # Megatron-SP: the residual-stream sequence dim is sharded over 'tp'
        # outside attention/MLP (ref: core/tensor_parallel/layers.py:225-296,
        # mappings.py:191-246). With context parallelism the same dim is
        # additionally split over 'cp' (ring attention), so the full rule is
        # ('cp','tp') when SP is on and 'cp' alone when it is off.
        ("seq_sp", (CONTEXT_AXIS, TENSOR_AXIS) if sequence_parallel
         else CONTEXT_AXIS),
        ("embed", None),
        ("act_embed", None),
        ("head_dim", None),
        ("qkv", None),
    )


def logical_to_spec(logical_axes: tuple, rules) -> P:
    """Map a tuple of logical axis names to a PartitionSpec via rules."""
    table = dict(rules)
    out = []
    for name in logical_axes:
        if name is None:
            out.append(None)
        else:
            out.append(table.get(name))
    # trim trailing Nones for cleanliness
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def logical_sharding(mesh: Mesh, logical_axes: tuple, rules) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(logical_axes, rules))


def tree_logical_to_sharding(mesh: Mesh, logical_tree, rules):
    """Map a pytree of logical-axis tuples to NamedShardings.

    `type(x) is tuple` (not isinstance): axes LEAVES are plain tuples,
    while NamedTuple pytree nodes in the tree (e.g. the W8 int8-weight
    containers from ops/quantized.quantize_axes) must be recursed INTO —
    isinstance would swallow a W8 whole and emit a replicated
    PartitionSpec() for its int8 payload."""
    return jax.tree.map(
        lambda ax: logical_sharding(mesh, ax, rules),
        logical_tree,
        is_leaf=lambda x: type(x) is tuple,
    )


def with_sharding(x, mesh: Mesh, logical_axes: tuple, rules):
    """Constrain an intermediate activation's sharding (GSPMD hint).

    This is the declarative analogue of the reference's explicit
    scatter/gather mapping functions (ref: mappings.py:253-278).

    When an ambient abstract mesh is active (jax.set_mesh — the pipelined
    paths run under one), pass the raw PartitionSpec so jax resolves it
    against the CONTEXT mesh: inside a partial-manual shard_map region the
    context mesh marks 'pp' Manual, and a NamedSharding built on the
    concrete (all-Auto) mesh would poison the value's aval — the next
    dot_general consuming it unchanged (e.g. post-LN models feed a layer
    output straight into the next QKV matmul) raises a mesh-mismatch."""
    spec = logical_to_spec(logical_axes, rules)
    try:
        cur = jax.sharding.get_abstract_mesh()
    except AttributeError:  # older jax: no ambient-mesh API
        cur = None
    if cur is not None and not cur.empty:
        return jax.lax.with_sharding_constraint(x, spec)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


# ---------------------------------------------------------------------------
# Activation-sharding context: lets pure model code place
# with_sharding_constraint hints without threading a mesh through every call.
#
# make_train_step enters the context around tracing; model code calls
# `constrain(x, logical_axes)`, a no-op outside the context (single-device
# runs, inference decode). This is how sequence parallelism becomes REAL: the
# residual stream is pinned to [b, s/(cp*tp), h] between TP blocks, and GSPMD
# inserts the all-gather on entry to QKV/MLP-in and the reduce-scatter on
# exit of the row-parallel projections — exactly the collective placement the
# reference hand-codes (ref: layers.py:225-296, mappings.py:191-246).
# ---------------------------------------------------------------------------

_ACT_CTX = threading.local()


@contextlib.contextmanager
def activation_shardings(mesh: Mesh, rules):
    prev = getattr(_ACT_CTX, "cur", None)
    _ACT_CTX.cur = (mesh, rules)
    try:
        yield
    finally:
        _ACT_CTX.cur = prev


def constrain(x, logical_axes: tuple):
    """Pin activation `x` to the sharding its logical axes imply, if an
    activation-sharding context is active; identity otherwise."""
    cur = getattr(_ACT_CTX, "cur", None)
    if cur is None:
        return x
    mesh, rules = cur
    if all(a is None for a in logical_to_spec(logical_axes, rules)):
        return x
    return with_sharding(x, mesh, logical_axes, rules)


def active_tp_mesh():
    """The activation-sharding context's mesh when it actually shards
    the tensor axis (tp > 1), else None. Model code that must wrap a
    hand-written kernel in an explicit shard_map (XLA cannot partition
    a custom call — e.g. the serving block-attention Pallas kernel,
    models/attention.py) reads the mesh from here at TRACE time, the
    same context `constrain` uses — so the wrap appears exactly when
    the enclosing jit runs the mesh treatment and never on
    single-device traces."""
    cur = getattr(_ACT_CTX, "cur", None)
    if cur is None:
        return None
    mesh = cur[0]
    if TENSOR_AXIS in mesh.shape and mesh.shape[TENSOR_AXIS] > 1:
        return mesh
    return None


def distributed_opt_sharding(mesh: Mesh, logical_axes: tuple, rules,
                             shape: tuple,
                             pipelined: bool = False) -> NamedSharding:
    """ZeRO-1 optimizer-state sharding (ref: megatron/optimizer/
    distrib_optimizer.py:32-610 DistributedOptimizer).

    The reference shards Adam state across DP ranks over the *flattened* grad
    buffer (ranges ignore parameter boundaries) and hand-codes grad
    reduce-scatter + param all-gather. The GSPMD formulation: give each
    optimizer-state leaf its parameter's spec PLUS 'dp' on the first
    dimension that is unsharded and dp-divisible. XLA then reduce-scatters
    the grads feeding the update and all-gathers the updated params — the
    same collectives, derived from the placement (SURVEY.md §7).

    `pipelined`: with pp>1 the non-stacked params (embedding / final norm /
    lm_head) enter the pipeline shard_map pp-replicated and their grads exit
    as pp-psums; dp-sharding THEIR moments trips a CHECK in XLA's SPMD
    partitioner (spmd_partitioner_util.cc partition-group mismatch), so
    ZeRO sharding is applied to the 'layers'-stacked params only — which at
    scale is >98% of the state."""
    if pipelined and "layers" not in logical_axes:
        return logical_sharding(mesh, logical_axes, rules)
    spec = list(logical_to_spec(logical_axes, rules))
    spec += [None] * (len(shape) - len(spec))
    dp = mesh.shape[DATA_AXIS]
    # expert_axis='dp' already places 'dp' on the bank's experts dim —
    # adding it to a second dim would be a DuplicateSpecError; those
    # moments are dp-sharded (by the expert dim) either way
    if dp > 1 and DATA_AXIS not in spec:
        for i, (ax, dim) in enumerate(zip(spec, shape)):
            if ax is None and dim % dp == 0:
                spec[i] = DATA_AXIS
                break
    while spec and spec[-1] is None:
        spec.pop()
    return NamedSharding(mesh, P(*spec))


def tree_distributed_opt_sharding(mesh: Mesh, logical_tree, rules,
                                  shape_tree, pipelined: bool = False):
    return jax.tree.map(
        lambda ax, sh: distributed_opt_sharding(mesh, ax, rules,
                                                tuple(sh.shape),
                                                pipelined=pipelined),
        logical_tree, shape_tree,
        is_leaf=lambda x: type(x) is tuple,  # see tree_logical_to_sharding
    )
