"""Ulysses-style all-to-all sequence parallelism over the 'cp' mesh axis.

Head-parallel attention (DeepSpeed-Ulysses, arXiv:2309.14509 — absent in
the reference, SURVEY.md §2.8 "DeepSpeed-Ulysses: ❌"): activations live
seq-sharded [b, S/cp, n, d]; two all-to-alls re-shard to head-sharded
[b, S, n/cp, d] around the attention core, so every device runs FULL-
sequence attention for its slice of heads. Communication is O(S·h/cp) per
device per all-to-all — cheaper than ring's cp K/V rotations when heads
divide evenly — at the cost of requiring n_heads % cp == 0.

Complements `parallel/ring_attention.py` (which has no head-count
constraint and overlaps compute with the K/V rotation); select with
`--context_parallel_algo {ring,ulysses}`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from megatron_tpu.ops.flash_attention import flash_attention


def ulysses_attention(q, k, v, mesh, *, causal: bool = True,
                      scale: float | None = None, axis: str = "cp"):
    """q [b, S, nq, d], k/v [b, S, nkv, d], S GLOBAL and sharded over
    `axis` on dim 1. Returns [b, S, nq, d], same sharding. Must run under
    jit with the ambient mesh set (same contract as ring_attention)."""
    cp = mesh.shape[axis]
    if cp == 1:
        return flash_attention(q, k, v, causal=causal, scale=scale)
    nq, nkv = q.shape[2], k.shape[2]
    if nq % cp or nkv % cp:
        raise ValueError(
            f"ulysses needs query AND kv head counts divisible by cp={cp} "
            f"(got nq={nq}, nkv={nkv}); use --context_parallel_algo ring")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    on_tpu = jax.default_backend() == "tpu"
    # CPU SPMD partitioner rejects bf16 collectives in partial-manual
    # regions; keep compute dtype on TPU only (mirrors ring_attention)
    comm_dtype = q.dtype if on_tpu else jnp.float32
    out_dtype = q.dtype

    def per_rank(q, k, v):
        # seq-shard -> head-shard: [b, s_loc, n, d] -> [b, S, n/cp, d]
        def fwd(x):
            return jax.lax.all_to_all(x.astype(comm_dtype), axis,
                                      split_axis=2, concat_axis=1,
                                      tiled=True)

        qh, kh, vh = fwd(q), fwd(k), fwd(v)
        # full-sequence attention on this device's head slice; the
        # dispatcher picks the Pallas kernel on TPU (XLA blockwise
        # otherwise / on non-tiling shapes) — O(S) memory either way
        out = flash_attention(qh.astype(q.dtype), kh.astype(q.dtype),
                              vh.astype(q.dtype), causal=causal,
                              scale=scale)
        # head-shard -> seq-shard
        out = jax.lax.all_to_all(out.astype(comm_dtype), axis,
                                 split_axis=1, concat_axis=2, tiled=True)
        return out.astype(out_dtype)

    shmap = jax.shard_map(
        per_rank,
        in_specs=(P(None, axis), P(None, axis), P(None, axis)),
        out_specs=P(None, axis),
        check_vma=False,
        axis_names={axis},
    )
    return shmap(q, k, v)
