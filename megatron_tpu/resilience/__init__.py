"""Fault-tolerance subsystem: survive the failure modes that dominate
week-long preemptible training runs (see docs/resilience.md).

- `integrity`: checkpoint manifests + verification, newest-valid
  fallback, retention that never strands the run;
- `retry`: exponential-backoff + jitter wrapper for flaky storage I/O;
- `guard`: divergence policy (skip / rollback / abort);
- `watchdog`: hung-step monitor with a distinct exit code;
- `faults`: the injection harness that proves all of the above
  end-to-end (tests/test_resilience.py, tools/chaos_train.py).
"""
from megatron_tpu.resilience.faults import (  # noqa: F401
    FaultInjector, InjectedFault, activate, deactivate, fault_point,
    get_fault_injector, use_fault_injector)
from megatron_tpu.resilience.guard import (  # noqa: F401
    DivergenceGuard, GuardAction, TrainingDivergedError)
from megatron_tpu.resilience.integrity import (  # noqa: F401
    MANIFEST, apply_retention, find_latest_valid, list_iter_checkpoints,
    verify_checkpoint, write_manifest)
from megatron_tpu.resilience.retry import (  # noqa: F401
    RetryPolicy, policy_from, retry)
from megatron_tpu.resilience.watchdog import StepWatchdog  # noqa: F401
