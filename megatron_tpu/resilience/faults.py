"""Fault-injection harness: make every failure path testable on demand.

The resilience subsystem's claims (retry survives transient write
errors, the loop rolls back a NaN streak, the watchdog catches a hung
step, integrity catches a corrupt checkpoint) are only claims until a
fault actually fires. `FaultInjector` is the single switchboard that
fires them deterministically:

- **transient write errors**: named *fault points* inside the
  checkpoint I/O path (`fault_point("checkpoint_write")`, ...) consult
  the active injector and raise `OSError` on configured call counts —
  the retry layer then has a real exception to absorb;
- **NaN injection**: `corrupt_batch` poisons a batch's loss_mask with
  +inf so the loss AND gradients genuinely go non-finite through the
  real compiled train step (no metric faking);
- **step delays**: `maybe_delay` stalls the host between steps, the
  observable shape of a hung infeed/host callback, to trip the
  watchdog;
- **checkpoint corruption**: `corrupt_file`/`corrupt_checkpoint` flip
  bytes on disk so integrity verification has something to catch;
- **dataset corruption**: `corrupt_dataset(prefix, mode)` injects the
  three dominant on-disk corpus failures (truncated `.bin`, garbage
  `.idx` header, out-of-range pointer) so the open-time validation in
  `data/indexed_dataset.py` is provable end-to-end;
- **serving faults** (`serve_delay`/`serve_crash`/`serve_nan`): stall,
  crash, or NaN-poison one slot of the serving engine's step loop, so
  the engine supervisor (watchdog restart, crash-loop circuit breaker,
  per-slot non-finite guard — serving/engine.py) is provable through a
  REAL engine — tools/chaos_serve.py composes them with overload;
- **serving state corruption** (`serve_host_corrupt`/
  `serve_adapter_corrupt`): flip bytes in a demoted host-RAM KV-tier
  entry / a demoted host adapter copy at a scheduled engine step, so
  the CRC gates (serving/host_tier.py, serving/adapters.py) are
  provable under randomized schedules — a corrupt demotion must
  degrade to a checksum MISS (recompute / reload), never to wrong
  tokens or weights. tools/chaos_mesh.py draws these (with the kinds
  above) from a single seed; see docs/resilience.md "Chaos
  conformance" for the complete env-spec grammar.

Activation is process-global (`activate`/`deactivate` or the
`with use_fault_injector(...)` context) and OFF by default — production
code paths pay one `is None` check. `FaultInjector.from_env` parses the
`MEGATRON_TPU_FAULTS` spec used by tools/chaos_train.py, e.g.
``write_error@2,write_error@3,nan@5,nan@6,delay@4:1.5`` meaning: fail
the 2nd and 3rd checkpoint writes, poison the 5th and 6th train-step
calls, sleep 1.5s before the 4th.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Optional, Set

import numpy as np

# ---------------------------------------------------------------------------
# the active injector (process-global switchboard)
# ---------------------------------------------------------------------------

_ACTIVE: Optional["FaultInjector"] = None
_LOCK = threading.Lock()


def get_fault_injector() -> Optional["FaultInjector"]:
    return _ACTIVE


def activate(injector: "FaultInjector") -> "FaultInjector":
    global _ACTIVE
    with _LOCK:
        _ACTIVE = injector
    return injector


def deactivate() -> None:
    global _ACTIVE
    with _LOCK:
        _ACTIVE = None


@contextlib.contextmanager
def use_fault_injector(injector: "FaultInjector"):
    activate(injector)
    try:
        yield injector
    finally:
        deactivate()


def fault_point(name: str) -> None:
    """Named hook inside production I/O paths. No-op (one attribute
    read) unless an injector is active and armed for `name`."""
    inj = _ACTIVE
    if inj is not None:
        inj.check(name)


class InjectedFault(OSError):
    """Transient-looking failure raised at a fault point. Subclasses
    OSError so the retry layer treats it exactly like a real
    filesystem flake."""


class FaultInjector:
    """Deterministic fault schedule, keyed by per-name call counts.

    `transient_errors`: fault-point name -> set of 1-based call counts
    that raise `InjectedFault` (each fires once).
    `nan_step_calls`: 1-based train-step CALL counts (monotonic across
    rollbacks — a replayed iteration is a new call) whose batch gets
    poisoned.
    `delay_step_calls`: step call count -> seconds to sleep before it.

    Serving faults (keyed by the ENGINE-step call counter — the serving
    engine advances it once per `_step`, independently of the train
    counter):
    `serve_delay_calls`: engine-step call -> seconds to stall the loop
    (the observable shape of a wedged decode dispatch — trips the
    engine watchdog).
    `serve_crash_calls`: engine-step calls that raise `InjectedFault`
    inside the loop (the supervisor must restart, not hang).
    `serve_nan_calls`: engine-step call -> active-slot ordinal whose
    carried logits are poisoned with NaN before the dispatch, so the
    non-finite guard has a REAL poisoned slot to catch (the fault rides
    the actual sampling + forward, no metric faking).
    `serve_host_corrupt_calls`: engine-step calls at which one demoted
    host-RAM KV-tier entry's bytes are flipped (the tier's CRC gate
    must turn it into a miss — serving/host_tier.py).
    `serve_adapter_corrupt_calls`: engine-step calls at which one
    demoted host adapter copy's bytes are flipped (the bank's CRC gate
    must reload from disk — serving/adapters.py).
    """

    def __init__(self,
                 transient_errors: Optional[Dict[str, Set[int]]] = None,
                 nan_step_calls: Optional[Set[int]] = None,
                 delay_step_calls: Optional[Dict[int, float]] = None,
                 serve_delay_calls: Optional[Dict[int, float]] = None,
                 serve_crash_calls: Optional[Set[int]] = None,
                 serve_nan_calls: Optional[Dict[int, int]] = None,
                 serve_host_corrupt_calls: Optional[Set[int]] = None,
                 serve_adapter_corrupt_calls: Optional[Set[int]] = None):
        self.transient_errors = {
            k: set(v) for k, v in (transient_errors or {}).items()}
        self.nan_step_calls = set(nan_step_calls or ())
        self.delay_step_calls = dict(delay_step_calls or {})
        self.serve_delay_calls = dict(serve_delay_calls or {})
        self.serve_crash_calls = set(serve_crash_calls or ())
        self.serve_nan_calls = dict(serve_nan_calls or {})
        self.serve_host_corrupt_calls = set(
            serve_host_corrupt_calls or ())
        self.serve_adapter_corrupt_calls = set(
            serve_adapter_corrupt_calls or ())
        self._counts: Dict[str, int] = {}
        self._step_calls = 0
        self._serve_steps = 0
        self._lock = threading.Lock()
        # audit trail: (kind, detail) of every fault actually fired
        self.fired: list = []

    # ---- fault points (I/O) ------------------------------------------
    def check(self, name: str) -> None:
        with self._lock:
            n = self._counts.get(name, 0) + 1
            self._counts[name] = n
            armed = n in self.transient_errors.get(name, ())
            if armed:
                self.fired.append(("transient_error", f"{name}@{n}"))
        if armed:
            raise InjectedFault(
                f"injected transient failure at {name} (call {n})")

    # ---- train-step hooks --------------------------------------------
    def next_step_call(self) -> int:
        """Advance the step-call counter; the loop calls this once per
        executed train step (replays after rollback keep counting)."""
        with self._lock:
            self._step_calls += 1
            return self._step_calls

    def maybe_delay(self, step_call: int,
                    sleep=time.sleep) -> float:
        d = self.delay_step_calls.get(step_call, 0.0)
        if d > 0.0:
            with self._lock:
                self.fired.append(("delay", f"step@{step_call}:{d}"))
            sleep(d)
        return d

    def corrupt_batch(self, batch: dict, step_call: int) -> dict:
        """Poison the loss_mask with +inf so the REAL compiled step
        produces a non-finite loss and non-finite gradients — the
        honest end-to-end shape of a divergence, not a faked metric."""
        if step_call not in self.nan_step_calls:
            return batch
        with self._lock:
            self.fired.append(("nan", f"step@{step_call}"))
        batch = dict(batch)
        mask = np.asarray(batch.get("loss_mask"), dtype=np.float32).copy()
        mask[...] = np.inf
        batch["loss_mask"] = mask
        return batch

    # ---- serving-engine hooks ----------------------------------------
    def next_serve_step(self) -> int:
        """Advance the engine-step counter; the serving loop calls this
        once per `_step` (restarted loops keep counting — a restart is
        not a reset, so a crash-loop schedule keeps firing)."""
        with self._lock:
            self._serve_steps += 1
            return self._serve_steps

    def maybe_serve_delay(self, step_call: int, sleep=time.sleep) -> float:
        d = self.serve_delay_calls.get(step_call, 0.0)
        if d > 0.0:
            with self._lock:
                self.fired.append(("serve_delay",
                                   f"step@{step_call}:{d}"))
            sleep(d)
        return d

    def check_serve_crash(self, step_call: int) -> None:
        if step_call in self.serve_crash_calls:
            with self._lock:
                self.fired.append(("serve_crash", f"step@{step_call}"))
            raise InjectedFault(
                f"injected engine-step crash (step {step_call})")

    def serve_host_corrupt(self, step_call: int) -> bool:
        """True when this engine step is scheduled to corrupt a demoted
        host-tier KV entry (the engine then calls
        `corrupt_host_tier_entry`, which records the firing only if it
        actually flipped bytes — an empty tier is a no-op)."""
        return step_call in self.serve_host_corrupt_calls

    def serve_adapter_corrupt(self, step_call: int) -> bool:
        """True when this engine step is scheduled to corrupt a demoted
        host adapter copy (see `corrupt_adapter_host_entry`)."""
        return step_call in self.serve_adapter_corrupt_calls

    def corrupt_host_tier_entry(self, tier) -> bool:
        """Flip one byte in the LARGEST demoted host-tier entry's
        arrays (serving/host_tier.py HostKVTier). Returns True (and
        records the firing) when an entry existed to corrupt; the
        tier's CRC verify must then turn the next restore of that
        entry into a checksum MISS."""
        entries = getattr(tier, "_entries", None)
        if not entries:
            return False
        ent = max(entries.values(), key=lambda e: e.nbytes)
        name = sorted(ent.arrays)[0]
        ent.arrays[name].view(np.uint8).flat[0] ^= 0xFF
        with self._lock:
            self.fired.append(("serve_host_corrupt",
                               f"entry@{ent.key!r}"))
        return True

    def corrupt_adapter_host_entry(self, bank) -> bool:
        """Flip one byte in one demoted host adapter copy
        (serving/adapters.py AdapterBank._host). Returns True (and
        records the firing) when a demoted copy existed; the bank's
        CRC verify must then reload that adapter from its source
        instead of serving the corrupt copy."""
        host = getattr(bank, "_host", None)
        if not host:
            return False
        aid, ent = next(iter(host.items()))
        name = sorted(ent.arrays)[0]
        ent.arrays[name].view(np.uint8).flat[0] ^= 0xFF
        with self._lock:
            self.fired.append(("serve_adapter_corrupt",
                               f"adapter@{aid!r}"))
        return True

    def serve_nan_slot(self, step_call: int) -> Optional[int]:
        """Active-slot ordinal to poison with NaN logits at this engine
        step, or None. The engine maps the ordinal onto its active-slot
        list (mod), so the schedule never depends on slot layout."""
        slot = self.serve_nan_calls.get(step_call)
        if slot is not None:
            with self._lock:
                self.fired.append(("serve_nan",
                                   f"step@{step_call}:slot{slot}"))
        return slot

    # ---- on-disk corruption (static helpers) -------------------------
    @staticmethod
    def corrupt_file(path: str, offset: int = 0, nbytes: int = 8) -> None:
        """Flip `nbytes` bytes in place — simulated bit rot / torn
        write."""
        size = os.path.getsize(path)
        if size == 0:
            with open(path, "wb") as f:
                f.write(b"\xff" * nbytes)
            return
        offset = min(offset, size - 1)
        with open(path, "r+b") as f:
            f.seek(offset)
            chunk = f.read(min(nbytes, size - offset))
            f.seek(offset)
            f.write(bytes(b ^ 0xFF for b in chunk))

    @staticmethod
    def truncate_file(path: str, drop_bytes: int = 8,
                      keep_bytes: Optional[int] = None) -> int:
        """Chop the tail off a file (simulated torn copy / partial
        upload); returns the new size."""
        size = os.path.getsize(path)
        new = (keep_bytes if keep_bytes is not None
               else max(size - drop_bytes, 0))
        with open(path, "r+b") as f:
            f.truncate(new)
        return new

    DATASET_FAULTS = ("truncate_bin", "garbage_idx", "oob_pointer")

    @staticmethod
    def corrupt_dataset(prefix: str, mode: str = "truncate_bin") -> str:
        """Inject on-disk dataset corruption into a `.idx`/`.bin` pair;
        returns the path touched. The open-time validation in
        MMapIndexedDataset must catch every mode with a typed
        DatasetCorruptionError (tests/test_resilience.py,
        tools/chaos_train.py, tools/validate_dataset.py --smoke):

        - ``truncate_bin``: chop the tail off `.bin` so index pointers
          run past EOF (torn copy / disk-full write);
        - ``garbage_idx``: overwrite the `.idx` header (bad magic —
          classic wrong-file / bit-rot shape);
        - ``oob_pointer``: rewrite the LAST pointer in `.idx` to far
          beyond the `.bin` size (single flipped high byte shape).
        """
        from megatron_tpu.data import indexed_dataset as idx_mod
        bin_path = idx_mod.data_file_path(prefix)
        idx_path = idx_mod.index_file_path(prefix)
        if mode == "truncate_bin":
            size = os.path.getsize(bin_path)
            FaultInjector.truncate_file(
                bin_path, drop_bytes=max(size // 2, 1))
            return bin_path
        if mode == "garbage_idx":
            with open(idx_path, "r+b") as f:
                f.write(b"\xff" * 16)
            return idx_path
        if mode == "oob_pointer":
            import struct
            with open(idx_path, "rb") as f:
                header = f.read(34)
            (n,) = struct.unpack("<Q", header[18:26])
            if n == 0:
                raise ValueError(f"{prefix}: empty index has no "
                                 "pointers to corrupt")
            last_ptr_off = 34 + 4 * n + 8 * (n - 1)
            huge = os.path.getsize(bin_path) * 2 + 4096
            with open(idx_path, "r+b") as f:
                f.seek(last_ptr_off)
                f.write(struct.pack("<q", huge))
            return idx_path
        raise ValueError(f"unknown dataset fault {mode!r} "
                         f"(valid: {FaultInjector.DATASET_FAULTS})")

    @staticmethod
    def dataset_corruption_drill(workdir: str) -> Dict[str, bool]:
        """Build → prime handle cache → corrupt → reopen, once per
        DATASET_FAULTS mode; maps mode → "reopen raised the typed
        DatasetCorruptionError". Priming the cache before corrupting
        also proves `make_dataset` re-validates on mtime/size change
        instead of serving the stale pre-corruption mmap. Shared by
        tools/chaos_train.py and tools/validate_dataset.py --smoke so
        their records cannot silently diverge."""
        from megatron_tpu.data.indexed_dataset import (
            DatasetCorruptionError, IndexedDatasetBuilder, make_dataset)
        detected = {}
        for mode in FaultInjector.DATASET_FAULTS:
            prefix = os.path.join(workdir, f"drill_{mode}")
            b = IndexedDatasetBuilder(prefix, dtype="int32")
            for i in range(8):
                b.add_item(list(range(i, i + 12)))
                b.end_document()
            b.finalize()
            make_dataset(prefix)
            FaultInjector.corrupt_dataset(prefix, mode)
            try:
                make_dataset(prefix)
                detected[mode] = False
            except DatasetCorruptionError:
                detected[mode] = True
        return detected

    @staticmethod
    def corrupt_checkpoint(ckpt_dir: str, nbytes: int = 8) -> str:
        """Corrupt the largest payload file under an iteration dir
        (skipping the manifest itself) and return its path."""
        from megatron_tpu.resilience.integrity import MANIFEST
        victim, vsize = None, -1
        for root, _, files in os.walk(ckpt_dir):
            for fn in files:
                if fn == MANIFEST:
                    continue
                p = os.path.join(root, fn)
                s = os.path.getsize(p)
                if s > vsize:
                    victim, vsize = p, s
        if victim is None:
            raise FileNotFoundError(f"no files to corrupt in {ckpt_dir}")
        FaultInjector.corrupt_file(victim, offset=max(vsize // 2, 0),
                                   nbytes=nbytes)
        return victim

    # ---- env-driven construction -------------------------------------
    ENV_VAR = "MEGATRON_TPU_FAULTS"

    @classmethod
    def from_env(cls, spec: Optional[str] = None
                 ) -> Optional["FaultInjector"]:
        """Parse a comma-separated spec (see module docstring). Returns
        None when the spec is empty/absent. Unknown kinds raise — a
        typo'd chaos schedule must not silently test nothing."""
        spec = spec if spec is not None else os.environ.get(cls.ENV_VAR, "")
        spec = spec.strip()
        if not spec:
            return None
        transient: Dict[str, Set[int]] = {}
        nans: Set[int] = set()
        delays: Dict[int, float] = {}
        serve_delays: Dict[int, float] = {}
        serve_crashes: Set[int] = set()
        serve_nans: Dict[int, int] = {}
        serve_host_corrupts: Set[int] = set()
        serve_adapter_corrupts: Set[int] = set()
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            kind, _, arg = item.partition("@")
            if kind == "write_error":
                transient.setdefault("checkpoint_write", set()).add(
                    int(arg))
            elif kind == "tracker_error":
                transient.setdefault("tracker_read", set()).add(int(arg))
            elif kind == "nan":
                nans.add(int(arg))
            elif kind == "delay":
                n, _, secs = arg.partition(":")
                delays[int(n)] = float(secs or 1.0)
            elif kind == "serve_delay":
                n, _, secs = arg.partition(":")
                serve_delays[int(n)] = float(secs or 1.0)
            elif kind == "serve_crash":
                serve_crashes.add(int(arg))
            elif kind == "serve_nan":
                n, _, slot = arg.partition(":")
                serve_nans[int(n)] = int(slot or 0)
            elif kind == "serve_host_corrupt":
                serve_host_corrupts.add(int(arg))
            elif kind == "serve_adapter_corrupt":
                serve_adapter_corrupts.add(int(arg))
            else:
                raise ValueError(
                    f"unknown fault kind {kind!r} in {cls.ENV_VAR} "
                    f"(valid: write_error, tracker_error, nan, delay, "
                    f"serve_delay, serve_crash, serve_nan, "
                    f"serve_host_corrupt, serve_adapter_corrupt — "
                    "docs/resilience.md 'Chaos conformance' has the "
                    "full grammar)")
        return cls(transient_errors=transient, nan_step_calls=nans,
                   delay_step_calls=delays,
                   serve_delay_calls=serve_delays,
                   serve_crash_calls=serve_crashes,
                   serve_nan_calls=serve_nans,
                   serve_host_corrupt_calls=serve_host_corrupts,
                   serve_adapter_corrupt_calls=serve_adapter_corrupts)
