"""Divergence guard: skip, roll back, or abort on pathological loss.

The reference's loop merely *counts* NaN iterations
(ref: megatron/training.py:700-706 `got_nan` accounting) — a run that
diverges at 3am keeps burning cluster-weeks skipping every update.
Here the loop consults a policy after every step:

- one non-finite loss / found_inf step → **SKIP** (the optimizer
  already dropped the update via its skip-as-select path; the guard
  just tracks the streak);
- `max_consecutive_nonfinite` bad steps in a row, or a finite loss
  exceeding `loss_spike_factor ×` the rolling-window mean → **ROLLBACK**
  to the last checkpoint; the loop then replays the EXACT data order
  from the checkpoint's saved iterator state and deterministically
  skips the quarantined step window — the poison batches are dodged by
  construction, never by a re-seeded order (the loop owns the
  restore/quarantine; the guard owns the decision);
- more than `max_rollbacks` rollbacks → **ABORT** with
  `TrainingDivergedError` so the supervisor sees a clean, distinct
  failure instead of an infinite crash-loop.

Pure host-side bookkeeping: no device sync beyond the loss float the
loop already pulls for its dashboard.
"""
from __future__ import annotations

import collections
import enum
import math


class TrainingDivergedError(RuntimeError):
    """Raised for a clean abort when divergence survives the rollback
    budget (or no checkpoint exists to roll back to)."""


class GuardAction(enum.Enum):
    OK = "ok"
    SKIP = "skip"          # bad step, already dropped; keep going
    ROLLBACK = "rollback"  # restore last checkpoint, quarantine window


class DivergenceGuard:
    """Per-step divergence policy. `observe()` after every step;
    `note_rollback()` when the loop actually restored (returns True
    when the rollback budget is exhausted → caller aborts)."""

    def __init__(self, max_consecutive_nonfinite: int = 3,
                 loss_spike_factor: float = None,
                 loss_spike_window: int = 32,
                 max_rollbacks: int = 2,
                 min_spike_history: int = 5):
        assert max_consecutive_nonfinite >= 0
        assert loss_spike_factor is None or loss_spike_factor > 1.0, (
            f"loss_spike_factor={loss_spike_factor} must exceed 1.0")
        assert max_rollbacks >= 0
        self.max_consecutive_nonfinite = max_consecutive_nonfinite
        self.loss_spike_factor = loss_spike_factor
        self.max_rollbacks = max_rollbacks
        self.min_spike_history = min_spike_history
        self._history = collections.deque(maxlen=max(loss_spike_window, 1))
        self.nonfinite_streak = 0
        self.rollbacks = 0

    @property
    def enabled(self) -> bool:
        return (self.max_consecutive_nonfinite > 0
                or self.loss_spike_factor is not None)

    def observe(self, loss: float, found_inf: bool) -> GuardAction:
        bad = found_inf or not math.isfinite(loss)
        if bad:
            self.nonfinite_streak += 1
            if (self.max_consecutive_nonfinite > 0
                    and self.nonfinite_streak
                    >= self.max_consecutive_nonfinite):
                return GuardAction.ROLLBACK
            return GuardAction.SKIP
        self.nonfinite_streak = 0
        if (self.loss_spike_factor is not None
                and len(self._history) >= self.min_spike_history):
            mean = sum(self._history) / len(self._history)
            if mean > 0 and loss > self.loss_spike_factor * mean:
                # spike breach: do NOT admit the spiked loss into the
                # history — after rollback the baseline must reflect
                # the healthy run, not the excursion
                return GuardAction.ROLLBACK
        self._history.append(loss)
        return GuardAction.OK

    def note_rollback(self) -> bool:
        """Record a performed rollback and reset streak/history (the
        restored run restarts the statistics). Returns True when the
        budget is now exhausted and the caller must abort."""
        self.rollbacks += 1
        self.nonfinite_streak = 0
        self._history.clear()
        return self.rollbacks > self.max_rollbacks
