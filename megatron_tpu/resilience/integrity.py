"""Checkpoint integrity: content manifests, verification, fallback,
retention.

A week-long run's only durable asset is its checkpoint chain, and the
reference trusts it blindly: `latest_checkpointed_iteration.txt` names
a directory and `torch.load` discovers corruption (torn write, bit
rot, a half-deleted dir) only by crashing at restore time
(ref: megatron/checkpointing.py:170-174, :476-677) — on a preemptible
cluster that turns one bad checkpoint into a dead run. Here every save
writes a `manifest.json` of per-file sizes + SHA-256 digests as the
LAST step before the tracker is published, so:

- a checkpoint without a complete, matching manifest is detectably
  torn/corrupt *before* any tensor is read;
- `load_checkpoint` verifies the tracker-named dir and falls back to
  the newest checkpoint that passes (training/checkpointing.py);
- retention (`keep_last_k`) prunes old `iter_*` dirs but NEVER deletes
  the newest verified-valid checkpoint — a corrupt tip must not leave
  the run with nothing to roll back to.

Checkpoints predating this subsystem carry no manifest; they verify as
valid-with-warning (`unverified`) so legacy dirs keep loading.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
from typing import List, Optional, Tuple

MANIFEST = "manifest.json"
_ITER_RE = re.compile(r"^iter_(\d{7,})$")
_CHUNK = 1 << 20  # 1 MiB digest read chunks


def _digest_file(path: str) -> Tuple[str, int]:
    h = hashlib.sha256()
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            h.update(chunk)
            size += len(chunk)
    return h.hexdigest(), size


def _walk_files(ckpt_dir: str) -> List[str]:
    """All file paths under `ckpt_dir` relative to it, manifest
    excluded, sorted for a deterministic manifest."""
    out = []
    for root, _, files in os.walk(ckpt_dir):
        for fn in files:
            rel = os.path.relpath(os.path.join(root, fn), ckpt_dir)
            if rel == MANIFEST:
                continue
            out.append(rel)
    return sorted(out)


def write_manifest(ckpt_dir: str) -> str:
    """Digest every file under the checkpoint dir and write
    `manifest.json` atomically (tmp + rename: a crash mid-manifest
    leaves no half-manifest to misverify). Must be called only after
    all payload writes are durable — the save path orders it after the
    backend write and before the tracker publish."""
    entries = {}
    for rel in _walk_files(ckpt_dir):
        digest, size = _digest_file(os.path.join(ckpt_dir, rel))
        entries[rel] = {"sha256": digest, "size": size}
    doc = {"version": 1, "algorithm": "sha256", "files": entries}
    path = os.path.join(ckpt_dir, MANIFEST)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def verify_checkpoint(ckpt_dir: str, *, deep: bool = True
                      ) -> Tuple[bool, str]:
    """Return (valid, reason).

    Invalid when: the dir or its `metadata.json` is missing/unreadable
    (torn), a manifest entry's file is missing or its size differs, or
    (`deep=True`, the default) its SHA-256 digest differs (bit rot).
    A dir with metadata but no manifest is valid-with-warning
    (`'unverified (no manifest)'`) for pre-manifest checkpoints."""
    if not os.path.isdir(ckpt_dir):
        return False, "not a directory"
    meta_path = os.path.join(ckpt_dir, "metadata.json")
    try:
        with open(meta_path) as f:
            json.load(f)
    except (OSError, ValueError) as e:
        return False, f"metadata.json unreadable ({e})"
    man_path = os.path.join(ckpt_dir, MANIFEST)
    if not os.path.exists(man_path):
        return True, "unverified (no manifest)"
    try:
        with open(man_path) as f:
            doc = json.load(f)
        files = doc["files"]
    except (OSError, ValueError, KeyError) as e:
        return False, f"manifest unreadable ({e})"
    for rel, want in files.items():
        p = os.path.join(ckpt_dir, rel)
        if not os.path.exists(p):
            return False, f"missing file {rel}"
        size = os.path.getsize(p)
        if size != want["size"]:
            return False, (f"size mismatch for {rel}: "
                           f"{size} != {want['size']}")
        if deep:
            digest, _ = _digest_file(p)
            if digest != want["sha256"]:
                return False, f"checksum mismatch for {rel}"
    return True, "ok"


def list_iter_checkpoints(root: str) -> List[Tuple[int, str]]:
    """(iteration, dir) for every `iter_*` dir under root, newest
    first. Unparseable names are ignored."""
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return []
    for name in names:
        m = _ITER_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(root, name)))
    out.sort(reverse=True)
    return out


def find_latest_valid(root: str, *, exclude: Tuple[str, ...] = (),
                      deep: bool = True) -> Optional[Tuple[int, str]]:
    """Newest `iter_*` checkpoint that verifies, skipping `exclude`
    dirs (typically the one that just failed)."""
    excl = {os.path.abspath(e) for e in exclude}
    for it, d in list_iter_checkpoints(root):
        if os.path.abspath(d) in excl:
            continue
        ok, _ = verify_checkpoint(d, deep=deep)
        if ok:
            return it, d
    return None


def apply_retention(root: str, keep_last_k: Optional[int]) -> List[str]:
    """Delete `iter_*` dirs beyond the newest `keep_last_k`, returning
    the deleted paths. Never touches `release`; never deletes the
    newest checkpoint that actually VERIFIES — if every kept dir is
    corrupt, the newest valid one survives regardless of age (deleting
    it would leave divergence rollback with nothing to restore)."""
    if not keep_last_k or keep_last_k < 1:
        return []
    ckpts = list_iter_checkpoints(root)
    if len(ckpts) <= keep_last_k:
        return []
    keep = {d for _, d in ckpts[:keep_last_k]}
    if not any(verify_checkpoint(d, deep=False)[0] for d in keep):
        newest_valid = find_latest_valid(root, deep=False)
        if newest_valid is not None:
            keep.add(newest_valid[1])
    deleted = []
    from megatron_tpu.utils.logging import print_rank_0
    for _, d in ckpts[keep_last_k:]:
        if d in keep:
            continue
        shutil.rmtree(d, ignore_errors=True)
        deleted.append(d)
    if deleted:
        print_rank_0(f"retention: pruned {len(deleted)} checkpoint(s) "
                     f"beyond keep_last_k={keep_last_k}")
    return deleted
