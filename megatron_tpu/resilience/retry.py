"""Retrying I/O: exponential backoff + jitter for flaky storage.

Long preemptible runs checkpoint to GCS/NFS-class filesystems whose
transient failures (connection resets, stale handles, throttling) are
routine at week-long timescales; the reference has no retry layer at
all — one flaky `torch.save` kills the run (ref: megatron/
checkpointing.py:304-337 writes with no error handling). Here every
checkpoint/tracker I/O path goes through `retry(fn, policy)`:
full-jitter exponential backoff, a bounded attempt budget, and loud
logging of every retried failure so storage flakes are auditable
rather than silent.

Only exceptions in `policy.retry_on` (default: OSError — covering
IOError/FileNotFoundError-on-NFS-lag/TimeoutError) are retried;
anything else is a programming error and propagates immediately.
"""
from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Tuple, Type, TypeVar

T = TypeVar("T")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule: attempt n (1-based) sleeps
    `min(base * 2**(n-1), max) * (1 ± jitter)` before retrying.
    `max_attempts=1` disables retrying (one try, no sleep)."""

    max_attempts: int = 4
    base_delay_s: float = 0.5
    max_delay_s: float = 30.0
    jitter: float = 0.25  # fraction of the delay randomized both ways
    retry_on: Tuple[Type[BaseException], ...] = (OSError,)

    def validate(self) -> "RetryPolicy":
        assert self.max_attempts >= 1, self.max_attempts
        assert self.base_delay_s >= 0.0, self.base_delay_s
        assert self.max_delay_s >= self.base_delay_s, (
            self.base_delay_s, self.max_delay_s)
        assert 0.0 <= self.jitter <= 1.0, self.jitter
        return self

    def delay_for(self, attempt: int, rng: random.Random) -> float:
        """Sleep before retry number `attempt` (1-based count of
        FAILED attempts so far)."""
        d = min(self.base_delay_s * (2.0 ** (attempt - 1)),
                self.max_delay_s)
        if self.jitter:
            d *= 1.0 + rng.uniform(-self.jitter, self.jitter)
        return max(d, 0.0)


def policy_from(resilience) -> RetryPolicy:
    """Build the I/O RetryPolicy from a ResilienceConfig (kept here so
    config.py stays import-free of this package)."""
    return RetryPolicy(
        max_attempts=resilience.io_retries,
        base_delay_s=resilience.io_backoff_s,
        max_delay_s=resilience.io_backoff_max_s,
        jitter=resilience.io_jitter,
    ).validate()


def retry(fn: Callable[[], T], policy: RetryPolicy = RetryPolicy(), *,
          label: str = "io", sleep: Callable[[float], None] = time.sleep,
          rng: random.Random = None) -> T:
    """Call `fn()` until it succeeds or the attempt budget runs out.

    Retries only `policy.retry_on` exceptions; the final failure
    re-raises the LAST exception unchanged so callers see the real
    error. `sleep`/`rng` are injectable for tests."""
    from megatron_tpu.utils.logging import print_rank_0
    rng = rng if rng is not None else random.Random()
    last: BaseException = None
    for attempt in range(1, policy.max_attempts + 1):
        try:
            return fn()
        except policy.retry_on as e:  # noqa: PERF203 — cold path
            last = e
            if attempt >= policy.max_attempts:
                break
            d = policy.delay_for(attempt, rng)
            print_rank_0(
                f"retry[{label}]: attempt {attempt}/{policy.max_attempts} "
                f"failed ({type(e).__name__}: {e}); retrying in {d:.2f}s")
            sleep(d)
    print_rank_0(f"retry[{label}]: giving up after {policy.max_attempts} "
                 f"attempts ({type(last).__name__}: {last})")
    raise last
