"""Hung-step watchdog: a stuck train step must kill the process, loudly.

On TPU pods the classic wedge modes — a hung collective, a stalled
infeed, a host callback that never returns — leave the process ALIVE
but making no progress, which no exit-code supervisor can see; the
reference has nothing for this (its dist_signal_handler only covers
SIGTERM, ref: megatron/dist_signal_handler.py:50-81). `StepWatchdog`
is a monitor thread armed by a per-step `heartbeat()`: when no
heartbeat lands within `timeout_s` it

1. dumps every thread's stack via `faulthandler` (the post-mortem for
   "where was it stuck"),
2. runs the `on_timeout` callback (the loop passes a best-effort
   final-checkpoint attempt),
3. exits the process with a DISTINCT code (default 43) so a
   supervisor/restart policy can tell "hung" from "crashed" from
   "clean exit".

The loop arms it only after the first step completes — the first step
includes the jit compile, whose duration is unrelated to the steady
state the deadline protects.
"""
from __future__ import annotations

import faulthandler
import os
import sys
import threading
import time
from typing import Callable, Optional

# module-level exit hook: tests monkeypatch this to observe a firing
# without losing the process
_exit = os._exit

DEFAULT_EXIT_CODE = 43


class StepWatchdog:
    """Deadline monitor. `start()` arms it; `heartbeat()` resets the
    deadline; `stop()` disarms (idempotent, called from the loop's
    finally)."""

    def __init__(self, timeout_s: float,
                 on_timeout: Optional[Callable[[], None]] = None,
                 exit_code: int = DEFAULT_EXIT_CODE,
                 poll_s: Optional[float] = None,
                 dump_stacks: bool = True,
                 on_timeout_budget_s: float = 60.0,
                 exit_process: bool = True):
        assert timeout_s > 0.0, timeout_s
        self.timeout_s = float(timeout_s)
        self.on_timeout = on_timeout
        self.exit_code = int(exit_code)
        # exit_process=False: DETECTION-ONLY mode (the serving engine
        # supervisor) — on deadline run `on_timeout` and latch `fired`
        # instead of killing the process; the supervisor restarts the
        # wedged loop and `rearm()`s. Training keeps the default True:
        # a hung train step has no supervisor above it in-process.
        self.exit_process = bool(exit_process)
        self.poll_s = poll_s if poll_s is not None else min(
            self.timeout_s / 4.0, 1.0)
        self.dump_stacks = dump_stacks
        # hard bound on the final-checkpoint callback: when the hang IS
        # the storage, an unbounded save attempt would wedge the
        # watchdog itself and the exit would never happen
        self.on_timeout_budget_s = float(on_timeout_budget_s)
        self.fired = False
        self._last = time.monotonic()
        self._suspended = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def started(self) -> bool:
        return self._thread is not None

    def start(self) -> "StepWatchdog":
        if self._thread is not None:
            return self
        self._last = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="step-watchdog")
        self._thread.start()
        return self

    def heartbeat(self) -> None:
        self._last = time.monotonic()

    def rearm(self) -> None:
        """Detection-only mode: clear a latched firing and restart the
        deadline clock (called by the serving supervisor after it
        restarted the wedged loop)."""
        self.fired = False
        self._last = time.monotonic()

    def suspend(self) -> "StepWatchdog":
        """Pause deadline checking across a phase whose duration is
        unrelated to step health (eval sweep, checkpoint save):

            with watchdog.suspend(): evaluate(...)

        The deadline clock restarts at resume."""
        self._suspended = True
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._suspended = False
        self._last = time.monotonic()
        return False

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)

    def _run(self) -> None:
        from megatron_tpu.utils.logging import print_rank_0
        while not self._stop.wait(self.poll_s):
            if self._suspended:
                self._last = time.monotonic()
                continue
            if self.fired and not self.exit_process:
                continue  # latched until rearm()
            stalled = time.monotonic() - self._last
            if stalled <= self.timeout_s:
                continue
            self.fired = True
            print_rank_0(
                f"watchdog: no step progress for {stalled:.1f}s "
                f"(deadline {self.timeout_s:.1f}s); "
                + (f"dumping stacks and exiting with code "
                   f"{self.exit_code}" if self.exit_process
                   else "running the timeout callback (detection-only "
                        "mode; the supervisor restarts the loop)"))
            if self.dump_stacks:
                try:
                    faulthandler.dump_traceback(file=sys.stderr,
                                                all_threads=True)
                except Exception:  # noqa: BLE001 — never block the exit
                    pass
            if self.on_timeout is not None:
                # bounded: run the final-checkpoint attempt in a daemon
                # thread so a wedged storage stack cannot block the exit
                def _cb():
                    try:
                        self.on_timeout()
                    except Exception as e:  # noqa: BLE001
                        print_rank_0(f"watchdog: on_timeout callback "
                                     f"failed: {e!r}")
                t = threading.Thread(target=_cb, daemon=True,
                                     name="watchdog-final-checkpoint")
                t.start()
                t.join(self.on_timeout_budget_s)
                if t.is_alive():
                    print_rank_0("watchdog: final checkpoint attempt "
                                 f"exceeded {self.on_timeout_budget_s}s; "
                                 "exiting without it")
            if not self.exit_process:
                continue  # stay armed-but-latched; rearm() resets
            _exit(self.exit_code)
            return  # only reached when _exit is monkeypatched in tests
