"""Continuous-batching serving subsystem (new layer between the
generator and the HTTP front end — see docs/serving.md)."""
from megatron_tpu.serving.adapters import (  # noqa: F401
    AdapterBank, AdapterBankFullError, UnknownAdapterError,
    adapter_bank_nbytes, load_adapter_npz)
from megatron_tpu.serving.degrade import (  # noqa: F401
    DEFAULT_RAISE_AT, DegradeController)
from megatron_tpu.serving.engine import (  # noqa: F401
    EngineHungError, ServingEngine)
from megatron_tpu.serving.host_tier import HostKVTier  # noqa: F401
from megatron_tpu.serving.invariants import (  # noqa: F401
    InvariantViolation, check_all, check_degrade_revert,
    check_goodput_floor, check_grammar_validity, check_kv_accounting,
    check_metrics_conservation, check_schema, check_shed_monotone,
    check_slo_bounds, check_token_exact, resolve_terminals)
from megatron_tpu.serving.router import (  # noqa: F401
    EngineRouter, NoReplicaAvailableError, RollingUpgradeError,
    RouterRequest)
from megatron_tpu.serving.weights import (  # noqa: F401
    CheckpointWatcher, StagedWeights, WeightSwapError, WeightVersion,
    host_params, load_staged)
from megatron_tpu.serving.kv_pool import (  # noqa: F401
    BlockKV, RetainedPrefix, SlotKVPool, clone_prefix, insert_blocks,
    insert_prefill, resolve_view, scatter_view, slice_blocks, slice_slot)
from megatron_tpu.serving.metrics import ServingMetrics  # noqa: F401
from megatron_tpu.serving.prefix_index import PrefixIndex  # noqa: F401
from megatron_tpu.serving.remote import (  # noqa: F401
    RemoteConnectionRefusedError, RemoteConnectionResetError,
    RemoteProtocolError, RemoteReplica, RemoteRequest,
    RemoteTimeoutError, RemoteTransportError, digest_peek)
from megatron_tpu.serving.request import (  # noqa: F401
    DeadlineExceededError, FanoutRequest, GenRequest, GrammarDeadEndError,
    RequestFailedError, RequestState, SamplingOptions,
    ServiceUnavailableError)
from megatron_tpu.serving.structured import (  # noqa: F401
    CharDFA, GrammarCompileError, TokenFSM, compile_regex,
    compile_response_format, schema_to_regex, validate_response_format)
from megatron_tpu.serving.scheduler import (  # noqa: F401
    AdmissionError, AdmissionScheduler, EngineUnhealthyError,
    FIFOScheduler, OverloadShedError, QueueFullError)
from megatron_tpu.serving.spec_decode import (  # noqa: F401
    Drafter, NGramDrafter)
from megatron_tpu.serving.topology import (  # noqa: F401
    ServingTopology, build_topology, devices_per_engine,
    resolve_phase_tp)
from megatron_tpu.serving.placement import (  # noqa: F401
    PlacementError, PlacementPlan, feasible_splits, plan_placement,
    signals_from_snapshot)
