"""Device-resident LoRA adapter bank for multi-tenant serving.

The north star ("millions of users") never looks like one model — it
looks like thousands of cheap fine-tuned variants of one base model on
one grid. S-LoRA (Sheng et al., 2023) showed that batching
heterogeneous low-rank adapters inside a single base-model forward is
the unlock; Punica (Chen et al., 2023) showed the mechanism — a batched
gather-grouped matmul keyed by a per-row adapter index. That is exactly
the shape of this engine's per-slot, device-resident, dispatch-resolved
index tables (the PR 9 KV block map), so the serving side is one more
int32 per slot:

- the BANK is a stacked `LoraAdapter` pytree (models/attention.py):
  per-layer A/B factors for the q/k/v/o projections, `[L, n, h, r]` /
  `[L, n, r, out]`, with ROW 0 the reserved IDENTITY (all-zero)
  adapter so base-model requests ride the same trace with a zero
  delta;
- the per-slot `adapter_idx int32 [S]` rides next to the KV block map
  as plain DATA — decode, speculative verify, and prefill keep ONE
  compile each with adapters on, and `adapter_slots=0` compiles to
  today's graph bit-identically (attention_apply's adapters=None
  path adds no ops);
- scaling (alpha / rank) is folded into the B factors at load time, and
  adapters exported at a smaller rank zero-pad up to the bank's rank
  (a zero-padded factor pair is numerically the same delta).

Capacity management mirrors the prefix cache's retained-LRU plus the
`HostKVTier` demote/restore/CRC discipline: loading adapter N+1 into a
full bank DEMOTES the least-recently-used unpinned adapter (its device
rows are gathered to host RAM with a checksum) rather than failing;
restoring verifies the checksum, and a corrupt demotion degrades to a
recompute-from-disk reload of the adapter's `.npz` — a miss, never
wrong weights. Adapters pinned by running slots are never evicted;
when every row is pinned `acquire` raises `AdapterBankFullError` and
the engine simply requeues the request until a slot (and its pin)
frees.

Thread contract: `known`/`peek` may be called from HTTP threads (dict
reads under the bank lock — the router's adapter-locality signal);
`acquire`/`release`/`register`/`reset_pins` run on the engine thread.

The `.npz` adapter format (written by training/lora.py
`export_adapter`) is versioned: raw (unscaled, unpadded) factors
`aq/bq/ak/bk/av/bv/ao/bo` each with a leading layers dim, plus
`format_version`, `rank`, `alpha`, and an optional JSON `meta` blob.
"""
from __future__ import annotations

import collections
import itertools
import json
import threading
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from megatron_tpu.config import ModelConfig
from megatron_tpu.models.attention import LoraAdapter
from megatron_tpu.serving.host_tier import _checksum
from megatron_tpu.serving.scheduler import AdmissionError
from megatron_tpu.utils.logging import print_rank_0

ADAPTER_FORMAT_VERSION = 1

FACTOR_NAMES = LoraAdapter._fields  # ("aq","bq","ak","bk","av","bv","ao","bo")


class UnknownAdapterError(AdmissionError):
    """Request named an adapter_id nothing registered — the HTTP layer
    maps this to 400 (a typo'd adapter can never be served)."""


class AdapterBankFullError(RuntimeError):
    """Every non-identity bank row is pinned by a running slot: nothing
    is evictable right now. The engine REQUEUES the request (a pin
    frees when a slot finishes) instead of failing it."""


def adapter_factor_shapes(cfg: ModelConfig, rank: int) -> Dict[str, tuple]:
    """Per-adapter factor shapes (leading layers dim, no bank dim) —
    the `.npz` export layout and the unit the bank zero-pads/folds."""
    L = cfg.num_layers
    h = cfg.hidden_size
    dq = cfg.num_attention_heads * cfg.kv_channels
    dkv = cfg.num_kv_heads * cfg.kv_channels
    r = rank
    return {
        "aq": (L, h, r), "bq": (L, r, dq),
        "ak": (L, h, r), "bk": (L, r, dkv),
        "av": (L, h, r), "bv": (L, r, dkv),
        "ao": (L, dq, r), "bo": (L, r, h),
    }


def adapter_bank_nbytes(cfg: ModelConfig, slots: int, rank: int,
                        itemsize: int = 4) -> int:
    """Device bytes the bank's stacked arrays will occupy (slots + the
    identity row) — ServingConfig.validate sizes the budget check from
    the same formula the bank allocates with."""
    per = sum(int(np.prod(s))
              for s in adapter_factor_shapes(cfg, rank).values())
    return per * (slots + 1) * itemsize


def load_adapter_npz(path: str):
    """Read a versioned adapter export. Returns (factors dict of
    float32 [L, ...] arrays, rank, alpha, meta dict)."""
    with np.load(path, allow_pickle=False) as z:
        version = int(z["format_version"])
        if version > ADAPTER_FORMAT_VERSION:
            raise ValueError(
                f"adapter {path}: format_version={version} is newer "
                f"than this build supports ({ADAPTER_FORMAT_VERSION})")
        missing = [n for n in FACTOR_NAMES if n not in z]
        if missing:
            raise ValueError(f"adapter {path}: missing factors {missing}")
        factors = {n: np.asarray(z[n], np.float32) for n in FACTOR_NAMES}
        rank = int(z["rank"])
        alpha = float(z["alpha"])
        meta = json.loads(str(z["meta"])) if "meta" in z else {}
    return factors, rank, alpha, meta


def fold_factors(factors: Dict[str, np.ndarray], rank: int, alpha: float,
                 cfg: ModelConfig, bank_rank: int) -> Dict[str, np.ndarray]:
    """Validate raw factors against the model geometry, fold the
    alpha/rank scale into the B factors, and zero-pad rank up to the
    bank's (a padded pair is the same delta: the extra A columns meet
    zero B rows). Raises ValueError on any mismatch — a wrong-shape
    adapter must 400 at registration, never load garbage."""
    if rank < 1:
        raise ValueError(f"adapter rank {rank} must be >= 1")
    if rank > bank_rank:
        raise ValueError(
            f"adapter rank {rank} exceeds the bank's adapter_rank="
            f"{bank_rank}; rebuild the engine with a larger rank")
    want = adapter_factor_shapes(cfg, rank)
    scale = float(alpha) / float(rank)
    out = {}
    for name in FACTOR_NAMES:
        a = np.asarray(factors[name], np.float32)
        if a.shape != want[name]:
            raise ValueError(
                f"adapter factor {name}: shape {a.shape} != expected "
                f"{want[name]} (model geometry or rank mismatch)")
        if name.startswith("b"):
            a = a * scale
        else:
            # ALWAYS copy: an aliased caller buffer stored as the
            # bank's reload source would let later in-place mutation
            # (e.g. continued training on the same numpy arrays)
            # silently change the weights a post-eviction reload
            # serves — no checksum would trip
            a = np.array(a)
        if rank < bank_rank:
            pad = bank_rank - rank
            # A factors pad the trailing rank dim, B factors the
            # leading-after-layers rank dim
            widths = ([(0, 0), (0, 0), (0, pad)] if name.startswith("a")
                      else [(0, 0), (0, pad), (0, 0)])
            a = np.pad(a, widths)
        out[name] = np.ascontiguousarray(a)
    return out


def random_adapter_factors(cfg: ModelConfig, rank: int, seed: int,
                           scale: float = 0.05) -> Dict[str, np.ndarray]:
    """Random NONZERO raw factors — the shared builder for benches,
    chaos drills, and tests (one copy so the scale that makes deltas
    flip greedy tokens cannot drift between harnesses). Real adapters
    come from training/lora.py, whose B factors start at zero."""
    import jax.random as jrandom
    shapes = adapter_factor_shapes(cfg, rank)
    key = jrandom.PRNGKey(seed)
    out = {}
    for name, shape in sorted(shapes.items()):
        key, k = jrandom.split(key)
        out[name] = (np.asarray(jrandom.normal(k, shape))
                     * scale).astype(np.float32)
    return out


class _HostAdapter:
    """A demoted adapter's folded factors in host RAM, checksummed like
    a HostKVTier entry — a corrupt demotion is a reload-from-disk miss,
    never wrong weights."""

    __slots__ = ("arrays", "crc", "nbytes")

    def __init__(self, arrays: Dict[str, np.ndarray]):
        self.arrays = arrays
        self.crc = _checksum(arrays)
        self.nbytes = int(sum(a.nbytes for a in arrays.values()))


class AdapterBank:
    """Up to `slots` LoRA adapters resident on device (plus the
    identity row 0), LRU-managed with checksummed host-RAM overflow.

    `stacked` is the live LoraAdapter pytree the engine passes into its
    compiled programs every dispatch — replaced functionally on load,
    so in-flight dispatches keep reading the buffer they captured."""

    def __init__(self, cfg: ModelConfig, slots: int, rank: int,
                 host_bytes: int = 0, metrics=None,
                 dtype=jnp.float32, shardings: Optional[LoraAdapter]
                 = None, prefill_shardings: Optional[LoraAdapter]
                 = None):
        assert slots >= 1, slots
        assert rank >= 1, (
            f"adapter_rank={rank} must be >= 1 (a rank-0 bank holds "
            "no delta at all)")
        self.cfg = cfg
        self.capacity = slots + 1  # + the identity row
        self.rank = int(rank)
        self.dtype = dtype
        self.metrics = metrics
        self.host_budget = int(host_bytes)
        shapes = adapter_factor_shapes(cfg, self.rank)
        self._stacked = LoraAdapter(**{
            # [L, n, ...]: the leading layers dim is what stack_apply
            # scans; the bank dim is gathered per row at apply time
            n: jnp.zeros((s[0], self.capacity) + s[1:], dtype)
            for n, s in shapes.items()})
        if shardings is not None:
            # TP-sharded serving (serving/topology.py): the bank's
            # B factors shard their projection out-dims over 'tp' like
            # the base weights. Placement commits ONCE here — the
            # functional row writes in _write update committed arrays,
            # so the layout survives every load
            self._stacked = jax.device_put(self._stacked, shardings)
        # disaggregated serving: the prefill chip group's programs
        # cannot consume a decode-group-committed bank, so a MIRROR
        # copy lives on the prefill mesh and _write updates both —
        # loads are rare control-plane events, and the bank is tiny
        # next to the KV arena
        self._stacked_pre = (
            jax.device_put(self._stacked, prefill_shardings)
            if prefill_shardings is not None else None)
        self._ids: list = [("identity",)] + [None] * slots
        self._by_id: Dict[object, int] = {}
        self._pins = np.zeros(self.capacity, np.int64)
        self._lru: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()  # resident idx, oldest first
        # id -> ("path", str) | ("arrays", folded dict): the reload
        # source of truth (arrays-registered adapters keep their folded
        # host copy forever, so they never demote — it would duplicate)
        self._sources: Dict[object, tuple] = {}
        self._host: "collections.OrderedDict[object, _HostAdapter]" = \
            collections.OrderedDict()
        self._host_used = 0
        # one-shot warm cache: register(path=) must eager-validate the
        # .npz anyway, so the folded result is kept for the FIRST
        # acquire instead of re-reading the file (popped on use)
        self._warm: Dict[object, Dict[str, np.ndarray]] = {}
        # registration GENERATION per id: (id, generation) is the
        # engine's prefix-cache namespace, so KV decoded under a
        # previous registration of the SAME id can never prefix-hit a
        # request running the re-registered weights
        self._gen_counter = itertools.count(1)
        self._gen: Dict[object, int] = {}
        self._lock = threading.Lock()

    def reshard(self, shardings: Optional[LoraAdapter] = None,
                prefill_shardings: Optional[LoraAdapter] = None):
        """Re-commit the bank under new per-group shardings — the
        placement re-mesh path (ServingEngine._apply_placement, only
        ever at the quiesced upgrade barrier). Value-preserving:
        `device_put` re-lays the SAME factor values out, so every
        registered row survives and the registry / LRU / pin / source
        state is untouched. `shardings=None` commits an unsharded copy
        (topology dropped to one device); `prefill_shardings=None`
        drops the mirror (the new topology is not disaggregated)."""
        with self._lock:
            self._stacked = (jax.device_put(self._stacked, shardings)
                             if shardings is not None
                             else jax.device_put(self._stacked))
            self._stacked_pre = (
                jax.device_put(self._stacked, prefill_shardings)
                if prefill_shardings is not None else None)

    # ---- registry (HTTP-thread readable) -----------------------------
    def known(self, adapter_id) -> bool:
        with self._lock:
            return adapter_id in self._sources

    def peek(self, adapter_id) -> int:
        """Locality signal for the router: 2 = device-resident,
        1 = registered (host/disk), 0 = unknown."""
        with self._lock:
            if adapter_id in self._by_id:
                return 2
            return 1 if adapter_id in self._sources else 0

    def ids(self) -> list:
        with self._lock:
            return list(self._sources)

    def active_count(self) -> int:
        """Device-resident non-identity adapters (the active_adapters
        gauge)."""
        with self._lock:
            return sum(1 for i in range(1, self.capacity)
                       if self._ids[i] is not None)

    def register(self, adapter_id, path: Optional[str] = None,
                 factors: Optional[Dict[str, np.ndarray]] = None,
                 rank: Optional[int] = None, alpha: float = 1.0):
        """Make `adapter_id` servable. Exactly one of `path` (a
        versioned `.npz` from training/lora.py export_adapter — its
        rank/alpha ride in the file) or `factors` (+ `rank`/`alpha`)
        must be given. Validation is EAGER — a wrong-shape or corrupt
        adapter fails here, not at some later request's admission."""
        if adapter_id is None:
            raise ValueError("adapter_id must not be None")
        if (path is None) == (factors is None):
            raise ValueError("register: pass exactly one of path/factors")
        warm = None
        if path is not None:
            f, r, a, _ = load_adapter_npz(path)
            warm = fold_factors(f, r, a, self.cfg, self.rank)  # validate
            src = ("path", str(path))
        else:
            if rank is None:
                raise ValueError("register(factors=...) needs rank=")
            folded = fold_factors(factors, int(rank), float(alpha),
                                  self.cfg, self.rank)
            src = ("arrays", folded)
        with self._lock:
            self._sources[adapter_id] = src
            if warm is not None:
                self._warm[adapter_id] = warm
            self._gen[adapter_id] = next(self._gen_counter)
            # a PREVIOUS registration's device row must never serve
            # this id again: unmap it now (pinned rows keep their
            # content for the slots still decoding under the old
            # weights — they become anonymous and evictable once
            # unpinned), and drop the old-weights host copy
            self._invalidate_resident(adapter_id)
            self._host_drop(adapter_id)

    def deregister(self, adapter_id):
        """Forget an adapter: future requests 400; a pinned
        device-resident copy stays until its slots finish (their pins
        keep the row's content valid), but is unmapped immediately."""
        with self._lock:
            self._sources.pop(adapter_id, None)
            self._warm.pop(adapter_id, None)
            self._gen.pop(adapter_id, None)
            self._invalidate_resident(adapter_id)
            self._host_drop(adapter_id)

    def _invalidate_resident(self, adapter_id):
        """(lock held) Unmap `adapter_id`'s device row. Unpinned rows
        free immediately; pinned rows are renamed to an anonymous
        stale marker — running slots keep reading the row content they
        admitted with, and the row recycles once the pins drain."""
        idx = self._by_id.pop(adapter_id, None)
        if idx is None:
            return
        if self._pins[idx] == 0:
            self._ids[idx] = None
            self._lru.pop(idx, None)
        else:
            self._ids[idx] = ("stale", adapter_id,
                              next(self._gen_counter))

    def bump_generations(self) -> int:
        """Weight hot-swap compatibility sweep (serving/engine.py
        `_apply_swap`): every registered adapter was trained against
        the OLD base weights, so (a) its registration generation bumps
        — the engine's prefix-cache namespaces change, and a preempted
        or requeued stream pinned to the old (id, generation) fails
        TYPED at re-acquire instead of silently resuming an N-era
        adapter against N+1 base weights — and (b) its device row is
        unmapped and its host-RAM overflow copy dropped, exactly like a
        re-registration. Sources stay registered: the NEXT acquire
        reloads from source under the new generation, so serving the
        adapter against the new base is an explicit fresh start (and an
        operator re-registration with retrained factors re-admits the
        same way). Returns the number of adapters bumped."""
        with self._lock:
            ids = list(self._sources)
            for adapter_id in ids:
                self._gen[adapter_id] = next(self._gen_counter)
                self._invalidate_resident(adapter_id)
                self._host_drop(adapter_id)
            return len(ids)

    def namespace(self, adapter_id):
        """The prefix-cache namespace for `adapter_id`'s CURRENT
        registration — (id, generation), or None when unregistered.
        Generations make cross-REGISTRATION prefix hits structurally
        impossible, the same way the id itself isolates tenants."""
        with self._lock:
            g = self._gen.get(adapter_id)
            return None if g is None else (adapter_id, g)

    # ---- device residency (engine thread) ----------------------------
    @property
    def stacked(self) -> LoraAdapter:
        return self._stacked

    @property
    def stacked_prefill(self) -> LoraAdapter:
        """The prefill chip group's bank copy (disaggregated engines;
        == `stacked` on single-group topologies)."""
        return (self._stacked_pre if self._stacked_pre is not None
                else self._stacked)

    def nbytes(self) -> int:
        return sum(getattr(self._stacked, n).nbytes for n in FACTOR_NAMES)

    def acquire(self, adapter_id) -> int:
        """Resolve `adapter_id` to its bank row, loading it (host
        restore, else source reload) if absent — demoting the LRU
        unpinned resident under pressure — and PIN it for the lifetime
        of the slot admission. Raises UnknownAdapterError (→ 400) for
        unregistered ids and AdapterBankFullError when every row is
        pinned (the engine requeues and retries).

        Engine thread only for the load itself — which is what makes
        the lock DROP across the slow middle section safe: no second
        allocator exists, the lock only shields the registry/pin/LRU
        dicts from the HTTP-thread readers (`known`/`peek`/
        `active_count` back every submit and health probe), and
        holding it across a multi-MB .npz read + CRC + the device
        write would stall health() past the router's heartbeat
        deadline and eject a healthy replica mid-load. register() MAY
        run concurrently from an HTTP thread, so the publish
        re-checks the registration GENERATION captured up front: a
        re-register that raced the unlocked load discards the
        now-stale row and retries with the fresh source — old weights
        can never publish under a new registration."""
        for _ in range(8):  # re-register storms bound the retry
            with self._lock:
                gen0 = self._gen.get(adapter_id)
                if adapter_id not in self._sources or gen0 is None:
                    raise UnknownAdapterError(
                        f"unknown adapter_id {adapter_id!r}: register "
                        "it before submitting requests against it")
                idx = self._by_id.get(adapter_id)
                if idx is not None:
                    self._pins[idx] += 1
                    self._lru[idx] = None
                    self._lru.move_to_end(idx)
                    return idx
                # pick (and unmap) the target row under the lock; the
                # row is invisible to readers until published below
                idx, evicted_id = self._alloc_index()
            try:
                # the victim's host demotion, the host-restore CRC,
                # the disk load, and the device write all run with the
                # lock dropped (each takes it briefly for bookkeeping)
                self._maybe_host_demote(idx, evicted_id)
                arrays = self._fetch_host(adapter_id)
                if arrays is None:
                    arrays = self._load_source(adapter_id)  # disk I/O
                self._write(idx, arrays)  # device writes
            except Exception:
                with self._lock:
                    self._ids[idx] = None  # return the row unpublished
                raise
            with self._lock:
                if self._gen.get(adapter_id) != gen0:
                    # re-registered while the lock was dropped: the
                    # arrays just written are the OLD registration's —
                    # discard the row and retry against the new source
                    self._ids[idx] = None
                    continue
                self._ids[idx] = adapter_id
                self._by_id[adapter_id] = idx
                self._count("adapter_loads")
                self._pins[idx] += 1
                self._lru[idx] = None
                self._lru.move_to_end(idx)
                return idx
        raise RuntimeError(
            f"adapter {adapter_id!r} was re-registered faster than it "
            "could load, 8 times in a row; retry the request")

    def release(self, idx: int):
        """Unpin a row (slot finished / preempted / dropped). Row 0
        (identity) is never pinned."""
        if idx <= 0:
            return
        with self._lock:
            self._pins[idx] = max(self._pins[idx] - 1, 0)

    def reset_pins(self):
        """Engine restart: every slotted request failed, so no pin
        survives (device bank content does — it is not donated)."""
        with self._lock:
            self._pins[:] = 0

    # ---- internals (lock held) ---------------------------------------
    def _count(self, name: str, n: int = 1):
        if self.metrics is not None:
            self.metrics.count(name, n)

    def _alloc_index(self):
        """(lock held) Pick a row for a load: a free one, else EVICT
        the LRU unpinned resident — unmapping it immediately; the host
        demotion of its still-intact content happens OUTSIDE the lock
        (`_maybe_host_demote`). Returns (idx, evicted_id or None)."""
        for i in range(1, self.capacity):
            if self._ids[i] is None:
                return i, None
        for i in list(self._lru):
            if i == 0 or self._pins[i] > 0 or self._ids[i] is None:
                continue
            old_id = self._ids[i]
            self._ids[i] = None
            self._by_id.pop(old_id, None)
            self._lru.pop(i, None)
            self._count("adapter_evictions")
            return i, old_id
        raise AdapterBankFullError(
            f"all {self.capacity - 1} adapter rows are pinned by "
            "running slots; retried when a slot frees")

    def _maybe_host_demote(self, idx: int, evicted_id):
        """Gather an evicted adapter's device rows to a checksummed
        host entry (path-sourced, still-registered adapters only — an
        arrays-sourced adapter's folded host copy already exists, and
        a stale/deregistered row's weights must not resurrect). Runs
        with the lock DROPPED: the row content is untouched until the
        caller's `_write`, and only `_host_put` re-takes the lock."""
        if evicted_id is None or self.host_budget <= 0:
            return
        kind, _ = self._sources.get(evicted_id, ("gone", None))
        if kind != "path":
            return
        arrays = {n: np.array(jax.device_get(
            getattr(self._stacked, n)[:, idx]))
            for n in FACTOR_NAMES}
        ent = _HostAdapter(arrays)
        with self._lock:
            self._host_put(evicted_id, ent)

    def _host_put(self, adapter_id, ent: _HostAdapter):
        if ent.nbytes > self.host_budget:
            return
        self._host_drop(adapter_id)
        while self._host_used + ent.nbytes > self.host_budget \
                and self._host:
            old, _ = next(iter(self._host.items()))
            self._host_drop(old)
        self._host[adapter_id] = ent
        self._host_used += ent.nbytes

    def _host_drop(self, adapter_id):
        ent = self._host.pop(adapter_id, None)
        if ent is not None:
            self._host_used -= ent.nbytes

    def _fetch_host(self, adapter_id) -> Optional[Dict[str, np.ndarray]]:
        """Checksum-verified host-tier read. Called with the lock
        DROPPED (acquire): the multi-MB CRC runs unlocked — a
        concurrent drop/re-register just orphans the entry object
        (still-valid memory), and acquire's generation re-check at
        publish rejects anything that went stale meanwhile."""
        with self._lock:
            ent = self._host.get(adapter_id)
        if ent is None:
            return None
        ok = _checksum(ent.arrays) == ent.crc
        with self._lock:
            if not ok:
                # corrupt demotion: a MISS — drop it and reload from
                # the source of truth; wrong weights are structurally
                # impossible
                if self._host.get(adapter_id) is ent:
                    self._host_drop(adapter_id)
                self._count("adapter_host_checksum_misses")
            else:
                if self._host.get(adapter_id) is ent:
                    self._host.move_to_end(adapter_id)
                self._count("adapter_host_hits")
        if not ok:
            print_rank_0(f"adapter bank: host copy of {adapter_id!r} "
                         "failed its checksum; reloading from source")
            return None
        return ent.arrays

    def _load_source(self, adapter_id) -> Dict[str, np.ndarray]:
        """Runs OUTSIDE the lock (disk I/O — see acquire): GIL-atomic
        dict reads, and a deregister racing in from an HTTP thread
        surfaces as the typed unknown-adapter error."""
        warm = self._warm.pop(adapter_id, None)
        if warm is not None:
            return warm
        entry = self._sources.get(adapter_id)
        if entry is None:
            raise UnknownAdapterError(
                f"adapter_id {adapter_id!r} was deregistered while "
                "loading")
        kind, src = entry
        if kind == "arrays":
            return src
        factors, rank, alpha, _ = load_adapter_npz(src)
        return fold_factors(factors, rank, alpha, self.cfg, self.rank)

    def _write(self, idx: int, arrays: Dict[str, np.ndarray]):
        """Functional row update — DELIBERATELY a full-buffer copy per
        factor: the engine's chained decode dispatches may still hold
        the previous stacked buffers as operands, so an in-place
        (donated) row write could corrupt a program in flight. Loads
        are rare control-plane events; the copy is the price of the
        never-mutate-in-flight-buffers discipline the whole engine
        rests on. Runs outside the bank lock (see acquire)."""
        self._stacked = LoraAdapter(**{
            n: getattr(self._stacked, n).at[:, idx].set(
                jnp.asarray(arrays[n], self.dtype))
            for n in FACTOR_NAMES})
        if self._stacked_pre is not None:
            # keep the prefill-group mirror in lockstep (same
            # functional-update discipline)
            self._stacked_pre = LoraAdapter(**{
                n: getattr(self._stacked_pre, n).at[:, idx].set(
                    jnp.asarray(arrays[n], self.dtype))
                for n in FACTOR_NAMES})
