"""Graceful degradation: a brownout ladder instead of a shed cliff.

PR 15's overload story is binary — the EWMA admission gate either
admits a request at full service or 429s it. This module inserts the
rungs in between: a controller that watches the SAME host-side
pressure signals the scheduler and placement optimizer already export
(queue depth, slot occupancy, `service_time_ewma`) and walks a
configurable ladder of service reductions under SUSTAINED overload,
one level at a time:

- **level 1** — disable speculative decoding for new decode windows.
  The draft+verify rounds reclaim their compute; the plain `_decode`
  path is already pinned bit-identical to a non-speculative engine,
  so streams switch mid-flight without a token changing.
- **level 2** — cap fan-out and length for NEW admissions: `best_of`
  clamps to `n` (the exploration samples beyond what the caller gets
  back are the first work to go) and `max_new_tokens` clamps to
  `degrade_max_new_tokens`. The clamped values become the request's
  EFFECTIVE config — its serial oracle keys off the request's own
  fields, so token-exactness holds by construction.
- **level 3** — shed only the lowest priority class (priority 0) at
  admission; higher classes still get level-2 service. A single-class
  config (priority_levels == 1) has no "lowest" class to distinguish,
  so level 3 adds nothing there and the ladder goes straight from
  2's clamps to 4's full shed.
- **level 4** — today's full shed: every new admission 429s with a
  Retry-After; queued and running work keeps draining.

Levels strictly nest: each rung keeps every restriction below it.
Degradation changes *which* work is admitted and *how it is decoded*
— never the tokens a given request's effective config produces.

The pressure signal is dimensionless backlog per slot, gated on
occupancy so a draining queue with free slots never trips it:

    pressure = (queue_depth / num_slots) * (active / num_slots)

Hysteresis on BOTH edges keeps one burst from thrashing levels: a
raise needs `dwell_up` consecutive evaluations above the level's
threshold, a lower needs `dwell_down` consecutive evaluations below
`hysteresis * threshold`, and the level moves ONE rung per decision.
The engine evaluates once per supervisor-loop iteration; the current
level rides `health()` and the `degrade_level` gauge (router
aggregate: max — the fleet reports its most-degraded replica), and
every transition counts `degrade_transitions`.

The controller is HOST state, like the scheduler queue: an engine
supervisor restart (`_restart_session`) rebuilds device state only,
so the level deliberately SURVIVES a restart — a replica that wedged
under overload would otherwise come back at level 0 and re-admit the
very flood that wedged it (tests pin this choice).

`degrade_ladder = 0` (the default) builds no controller at all: the
engine is behaviorally bit-identical to the pre-ladder code — same
tokens, same shed decisions — and only the fixed metrics schema
carries the new keys at 0.
"""
from __future__ import annotations

from typing import Optional, Sequence

# the ladder's rungs, by effect — level numbers are the public
# contract (docs/serving.md "Overload, degradation & SLO conformance")
LEVEL_FULL_SERVICE = 0
LEVEL_NO_SPEC = 1
LEVEL_CAP_WORK = 2
LEVEL_SHED_LOW_PRIORITY = 3
LEVEL_SHED_ALL = 4
MAX_LEVEL = LEVEL_SHED_ALL

# default raise thresholds (pressure = backlog/slot * occupancy) for
# levels 1..4: half a queued request per busy slot already means the
# next window cannot absorb the backlog on a tiny grid, and each rung
# doubles. Deliberately low-scaled so the ladder engages on the small
# slot grids the chaos tools drive; production configs override via
# `degrade_raise_at`.
DEFAULT_RAISE_AT = (0.5, 1.0, 2.0, 4.0)
# lower edge = hysteresis * raise edge; dwell counts are consecutive
# supervisor-loop evaluations (each one decode window apart), so a
# single bursty window can neither raise nor lower a level by itself
DEFAULT_HYSTERESIS = 0.5
DEFAULT_DWELL_UP = 2
DEFAULT_DWELL_DOWN = 4


class DegradeController:
    """Walks the brownout ladder from host-side pressure signals.

    Single-writer: `observe()` runs on the engine supervisor thread
    only. `level` is a plain int attribute so HTTP submit threads can
    read it without a lock (GIL-atomic read of an int)."""

    def __init__(self, max_level: int,
                 raise_at: Optional[Sequence[float]] = None,
                 hysteresis: float = DEFAULT_HYSTERESIS,
                 dwell_up: int = DEFAULT_DWELL_UP,
                 dwell_down: int = DEFAULT_DWELL_DOWN):
        assert 1 <= max_level <= MAX_LEVEL, (
            f"degrade ladder max_level must be in 1..{MAX_LEVEL}, got "
            f"{max_level} (0 disables the ladder — build no controller)")
        raise_at = tuple(raise_at) if raise_at is not None \
            else DEFAULT_RAISE_AT[:max_level]
        assert len(raise_at) == max_level, (
            f"degrade ladder needs one raise threshold per level: "
            f"max_level={max_level} but raise_at has {len(raise_at)}")
        assert all(b > a for a, b in zip(raise_at, raise_at[1:])), (
            f"degrade raise thresholds must be strictly increasing "
            f"(monotone ladder), got {raise_at}")
        assert raise_at[0] > 0.0, "degrade thresholds must be positive"
        assert 0.0 < hysteresis < 1.0, (
            f"degrade hysteresis must be a ratio in (0, 1) — the lower "
            f"edge is hysteresis * raise edge — got {hysteresis}")
        assert dwell_up >= 1 and dwell_down >= 1, "dwell counts >= 1"
        self.max_level = max_level
        self.raise_at = raise_at
        self.hysteresis = hysteresis
        self.dwell_up = dwell_up
        self.dwell_down = dwell_down
        self.level = LEVEL_FULL_SERVICE
        self.transitions = 0
        self._above = 0   # consecutive evals above the next rung's edge
        self._below = 0   # consecutive evals below the current rung's
        #                   lower edge
        self._last_pressure = 0.0

    @staticmethod
    def pressure(queue_depth: int, active_slots: int,
                 num_slots: int) -> float:
        """Dimensionless backlog-per-slot, occupancy-gated: free slots
        mean the queue drains on the next admission pass, so pressure
        only registers as the grid fills."""
        slots = max(int(num_slots), 1)
        occupancy = max(0.0, min(float(active_slots) / slots, 1.0))
        return (float(queue_depth) / slots) * occupancy

    def observe(self, queue_depth: int, active_slots: int,
                num_slots: int) -> int:
        """One evaluation (one supervisor-loop iteration). Returns the
        (possibly new) level; the caller pushes metrics on change."""
        p = self.pressure(queue_depth, active_slots, num_slots)
        self._last_pressure = p
        # raise edge: pressure above the NEXT rung's threshold
        if self.level < self.max_level and p >= self.raise_at[self.level]:
            self._above += 1
        else:
            self._above = 0
        # lower edge: pressure below the CURRENT rung's lower edge
        if (self.level > LEVEL_FULL_SERVICE
                and p <= self.raise_at[self.level - 1] * self.hysteresis):
            self._below += 1
        else:
            self._below = 0
        if self._above >= self.dwell_up:
            self.level += 1
            self.transitions += 1
            self._above = 0
            self._below = 0
        elif self._below >= self.dwell_down:
            self.level -= 1
            self.transitions += 1
            self._above = 0
            self._below = 0
        return self.level

    # -- per-level effect predicates (the submit/_step seams ask these
    #    instead of comparing level numbers inline) -------------------
    def spec_disabled(self) -> bool:
        return self.level >= LEVEL_NO_SPEC

    def cap_work(self) -> bool:
        return self.level >= LEVEL_CAP_WORK

    def shed_priority(self, priority: int, priority_levels: int) -> bool:
        """Should an admission at `priority` shed at the current level?
        Level 4 sheds everything; level 3 sheds only the lowest class,
        and only when more than one class exists to distinguish."""
        if self.level >= LEVEL_SHED_ALL:
            return True
        if self.level >= LEVEL_SHED_LOW_PRIORITY:
            return priority_levels > 1 and priority == 0
        return False

    def describe(self) -> dict:
        """The shape `health()["degrade"]` exports (the bare level also
        rides top-level `health()["degrade_level"]` for the router)."""
        return {
            "level": self.level,
            "max_level": self.max_level,
            "pressure": self._last_pressure,
            "transitions": self.transitions,
        }

    @classmethod
    def from_config(cls, serving) -> Optional["DegradeController"]:
        """Build from a `ServingConfig`, or None when the ladder is
        disabled — the None path is the bit-identical pre-ladder
        engine."""
        if not getattr(serving, "degrade_ladder", 0):
            return None
        return cls(
            max_level=serving.degrade_ladder,
            raise_at=serving.degrade_raise_at,
            hysteresis=serving.degrade_hysteresis,
            dwell_up=serving.degrade_dwell_up,
            dwell_down=serving.degrade_dwell_down,
        )
