"""Continuous-batching serving engine.

The reference (and the serial port in inference/server.py) generates one
whole batch at a time behind a lock: a 128-prompt request's entire
prefill + decode blocks every other caller. This engine implements
Orca-style iteration-level scheduling over a vLLM-style pooled KV cache,
TPU-native:

- ONE persistent jitted decode step over a fixed grid of `num_slots`
  batch slots — static shapes, compiled exactly once, no per-request
  retrace. Per-slot sequence positions ride the vector KV-cache offsets
  (models/attention.py), per-slot sampling knobs ride
  `sample_batched` (inference/sampling.py), per-request seeds ride a
  [slots, 2] PRNG-key grid.
- Each slot owns a region of a pre-allocated KV pool
  (serving/kv_pool.py, built by init_kv_caches — int8 and
  sliding-window ROLLING layouts included). Admission prefills a
  request at batch=1 and inserts its KV into the slot region via
  `lax.dynamic_update_slice`; eviction on EOS/max-tokens frees the slot
  with no copying.
- A bounded FIFO (serving/scheduler.py) provides backpressure; the
  engine loop drains it into free slots between decode steps, so
  new requests join the running batch at token granularity.

Seeded determinism: a request with seed s reproduces the serial
`Generator.generate([prompt], ..., seed=s)` output token-for-token —
the engine burns the same number of PRNG splits the serial path spends
on its bucketed in-prompt steps, and `sample_batched` is row-for-row
bit-identical to `sample`.
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from megatron_tpu.inference.generation import Generator
from megatron_tpu.inference.sampling import sample_batched
from megatron_tpu.models import language_model as lm
from megatron_tpu.serving.kv_pool import SlotKVPool, insert_prefill
from megatron_tpu.serving.metrics import ServingMetrics
from megatron_tpu.serving.request import (GenRequest, RequestState,
                                          SamplingOptions)
from megatron_tpu.serving.scheduler import FIFOScheduler
from megatron_tpu.utils.logging import print_rank_0

from megatron_tpu.config import SERVING_KV_DTYPES as _KV_DTYPES


class ServingEngine:
    """Drives generation for many concurrent requests through one
    compiled decode step. Construct from a `Generator` (whose params /
    config / mesh treatment / rope tables are reused as-is)."""

    def __init__(self, generator: Generator, serving=None,
                 metrics: Optional[ServingMetrics] = None,
                 writer=None, report_interval: int = 100,
                 start: bool = True):
        from megatron_tpu.config import ServingConfig
        self.gen = generator
        cfg = generator.cfg
        self.cfg = cfg
        self.serving = serving if serving is not None else ServingConfig()
        self.max_len = self.serving.max_len or cfg.max_position_embeddings
        assert self.max_len <= cfg.max_position_embeddings, (
            f"ServingConfig.max_len={self.max_len} exceeds "
            f"max_position_embeddings={cfg.max_position_embeddings}")
        self.num_slots = self.serving.num_slots
        kv_dtype = (generator.kv_cache_dtype
                    if self.serving.kv_dtype is None
                    else _KV_DTYPES[self.serving.kv_dtype])
        self.pool = SlotKVPool(cfg, self.num_slots, self.max_len,
                               dtype=kv_dtype)
        self.scheduler = FIFOScheduler(self.serving.max_queue,
                                       max_total_len=self.max_len)
        self.scheduler.notify = self._wake
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self._writer = writer
        self._report_interval = max(report_interval, 1)

        S, Vp = self.num_slots, cfg.padded_vocab_size
        # per-slot device state (functionally replaced every step)
        self._last_logits = jnp.zeros((S, Vp), jnp.float32)
        self._rngs = jnp.zeros((S, 2), jnp.uint32)
        # per-slot host state (engine thread only)
        self._lengths = np.zeros(S, np.int32)
        self._active = np.zeros(S, bool)
        self._temps = np.ones(S, np.float32)
        self._top_ks = np.zeros(S, np.int32)
        self._top_ps = np.zeros(S, np.float32)
        self._slot_req: List[Optional[GenRequest]] = [None] * S

        self._decode_traces = 0  # trace count — MUST stay 1 in steady state
        self._decode = self.gen._jit(self._decode_fn, n_array_args=7,
                                     donate_argnums=(1, 2, 3))
        # one jit; jax retraces per padded prompt length (bucketed by
        # _prefill_bucket so the cache hits across request sizes)
        self._prefill = self.gen._jit(self._prefill_fn, n_array_args=7,
                                      donate_argnums=(1, 2, 3))
        self._steps = 0
        self._cond = threading.Condition()
        self._stop = False
        self._draining = False
        self._deadline_s = self.serving.request_deadline_s
        self._broken: Optional[str] = None
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serving-engine")
        if start:
            self._thread.start()

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 64,
               sampling: SamplingOptions = SamplingOptions(),
               seed: int = 0) -> GenRequest:
        """Non-blocking: enqueue and return the request handle. Raises
        QueueFullError (→ 429) when the bounded queue is full and
        AdmissionError (→ 400) when the request can never fit."""
        if self._broken:
            raise RuntimeError(f"engine failed: {self._broken}")
        if self._draining:
            from megatron_tpu.serving.scheduler import QueueFullError
            raise QueueFullError(
                "engine draining (shutdown in progress); retry against "
                "another replica")
        req = GenRequest(list(prompt), max_new_tokens, sampling, seed)
        self.metrics.count("requests_received")
        try:
            if max_new_tokens == 0:
                # nothing to decode: the serial path returns the prompt
                # row unchanged — short-circuit without occupying a
                # slot, but through the SAME admission check (an
                # oversize prompt must 400 on both routes)
                self.scheduler.check_admissible(req)
                req.mark_admitted()
                req.finish()
                self.metrics.record_admitted(0.0)
                self.metrics.record_completed(0.0, 0)
                return req
            self.scheduler.submit(req)
        except Exception:
            self.metrics.count("requests_rejected")
            raise
        return req

    def cancel(self, req: GenRequest):
        """Best-effort cancellation: a QUEUED request is dropped and
        failed immediately; a RUNNING one is flagged and evicted at the
        next decode step (frees its slot without decoding to
        completion). Used by the HTTP layer to avoid orphaned work when
        a multi-prompt payload fails partway through submission."""
        req.cancel()
        if not req.done():
            self.scheduler.cancel(req)
        self._wake()

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 64,
                 sampling: SamplingOptions = SamplingOptions(),
                 seed: int = 0, timeout: Optional[float] = None):
        """Blocking convenience: submit + wait. Returns (tokens,
        logprobs) with tokens = prompt + generated."""
        return self.submit(prompt, max_new_tokens, sampling,
                           seed).result(timeout)

    def close(self):
        """Stop the loop; fail queued and in-flight requests. Safe on a
        never-started (start=False) engine."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread.ident is not None:  # was started
            self._thread.join(timeout=30)
        for req in self.scheduler.close():
            req.fail("engine shut down")
        for req in self._slot_req:
            if req is not None and req.state is RequestState.RUNNING:
                req.fail("engine shut down")

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: stop admitting (queued-but-unstarted
        requests fail immediately with a retry-later error; new submits
        are rejected the same way), let every IN-FLIGHT slot decode to
        completion, then stop the loop. Returns True when all in-flight
        work finished within `timeout` (None = wait indefinitely);
        False leaves the stragglers to `close()`'s hard failure. The
        SIGTERM handler in inference/server.py calls this so a rolling
        restart never truncates a response mid-stream."""
        self._draining = True
        backlog = self.scheduler.close()
        for req in backlog:
            req.fail("engine draining (shutdown in progress); retry "
                     "against another replica", kind="unavailable")
        if backlog:
            self.metrics.count("requests_rejected", len(backlog))
        self._wake()
        if self._thread.ident is not None:
            self._thread.join(timeout)
        drained = not self._thread.is_alive()
        if drained:
            print_rank_0("serving engine drained: all in-flight "
                         "requests completed")
        return drained

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------
    # device programs
    # ------------------------------------------------------------------
    def _decode_fn(self, params, pool, last_logits, rngs, lengths,
                   temps, top_ks, top_ps):
        """ONE interleaved decode step for the whole slot grid: sample
        each slot's next token from its carried logits, then forward all
        slots' tokens (s=1) through the model with per-slot positions.
        Inactive slots ride along at length 0 (static shapes); their
        writes land at position 0 and are fully overwritten by the next
        prefill insert."""
        self._decode_traces += 1
        cfg = self.cfg
        split = jax.vmap(jax.random.split)(rngs)  # [S, 2, 2]
        new_rngs, step_keys = split[:, 0], split[:, 1]
        toks = sample_batched(step_keys, last_logits,
                              temperature=temps, top_k=top_ks,
                              top_p=top_ps, vocab_size=cfg.vocab_size)
        # logprob of the chosen token under the RAW carried logits —
        # the serial path's convention (generation.py _decode_fn)
        lp = jax.nn.log_softmax(last_logits, axis=-1)
        tok_lp = jnp.take_along_axis(lp, toks[:, None], axis=-1)[:, 0]
        # the engine's host `lengths` are the source of truth for every
        # row's position; broadcast them over layers into the pool
        L = pool.offset.shape[0]
        pool = pool._replace(offset=jnp.broadcast_to(
            lengths[None, :], (L, lengths.shape[0])).astype(jnp.int32))
        logits, pool = lm.model_forward(
            params, toks[:, None], cfg, kv_caches=pool,
            position_ids=lengths[:, None], rope=self.gen.rope,
            logits_dtype=jnp.float32)
        return pool, logits[:, 0], new_rngs, toks, tok_lp

    def _prefill_fn(self, params, pool, last_logits, rngs, tokens,
                    plen, slot, rng0):
        caches = self.pool.make_prefill_caches(1)
        logits, caches = lm.model_forward(
            params, tokens, self.cfg, kv_caches=caches,
            rope=self.gen.rope, logits_dtype=jnp.float32)
        pool = insert_prefill(pool, caches, slot, plen)
        # logits at the LAST REAL prompt position (bucket pads sit
        # after it and are causally invisible to it)
        last = jax.lax.dynamic_slice_in_dim(
            logits, plen - 1, 1, axis=1)[0, 0]
        last_logits = last_logits.at[slot].set(last)
        rngs = rngs.at[slot].set(rng0)
        return pool, last_logits, rngs

    def _prefill_bucket(self, plen: int) -> int:
        """Pad prompts up to a bucket so the prefill jit cache hits
        across request sizes. ROLLING pools prefill at the exact length:
        pad positions fed through the ring would evict real tokens from
        the W-slot buffer."""
        if self.pool.rolling:
            return plen
        b = max(self.serving.prefill_bucket, 1)
        return min(-(-plen // b) * b, self.max_len)

    @staticmethod
    def _initial_rng(seed: int, plen: int):
        """Per-request key, advanced past the splits the SERIAL path
        spends on its bucketed in-prompt steps (Generator.generate
        rounds the prefill down to a PREFILL_BUCKET multiple and
        consumes the remaining prompt tokens through decode steps,
        splitting once per step) — so a seeded engine request reproduces
        the serial output bit-for-bit from the first generated token."""
        from megatron_tpu.inference.generation import PREFILL_BUCKET
        key = jax.random.PRNGKey(seed)
        burn = plen - max((plen // PREFILL_BUCKET) * PREFILL_BUCKET, 1)
        for _ in range(burn):
            key = jax.random.split(key)[0]
        return key

    # ------------------------------------------------------------------
    # engine loop (single thread)
    # ------------------------------------------------------------------
    def _wake(self):
        with self._cond:
            self._cond.notify_all()

    def _loop(self):
        print_rank_0(
            f"serving engine: {self.num_slots} slots x cap "
            f"{self.pool.cap} ({self.pool.dtype}"
            f"{', rolling' if self.pool.rolling else ''}), "
            f"pool {self.pool.nbytes() / 2**20:.1f} MiB, "
            f"queue bound {self.serving.max_queue}")
        while True:
            with self._cond:
                while (not self._stop and not self._draining
                       and self.scheduler.depth() == 0
                       and not self._active.any()):
                    self._cond.wait(timeout=0.5)
                if self._stop:
                    return
                if self._draining and not self._active.any():
                    return  # drained: queue closed, slots empty
            try:
                self._reap_cancelled()
                self._reap_expired()
                self._admit()
                if self._active.any():
                    self._step()
            except Exception as e:  # noqa: BLE001 — fail loudly, not hang
                self._broken = repr(e)
                print_rank_0(f"serving engine loop failed: {e!r}")
                for req in self._slot_req:
                    if req is not None:
                        req.fail(self._broken)
                for req in self.scheduler.close():
                    req.fail(self._broken)
                return

    def _admit(self):
        popped = self.scheduler.pop_ready(self.pool.free_count())
        for i, req in enumerate(popped):
            try:
                self._prefill_into_slot(req)
            except Exception as e:
                # the failing request AND the rest of this pop are in
                # neither _slot_req nor the scheduler — fail them here
                # or their callers would hang to the request timeout
                for r in popped[i:]:
                    r.fail(repr(e))
                raise

    def _prefill_into_slot(self, req: GenRequest):
        slot = self.pool.alloc()
        plen = len(req.prompt)
        padded = self._prefill_bucket(plen)
        toks = np.full((1, padded), self.gen.pad_id, np.int32)
        toks[0, :plen] = req.prompt
        self.pool.caches, self._last_logits, self._rngs = self._prefill(
            self.gen.params, self.pool.caches, self._last_logits,
            self._rngs, jnp.asarray(toks), np.int32(plen), np.int32(slot),
            self._initial_rng(req.seed, plen))
        self._lengths[slot] = plen
        self._active[slot] = True
        self._temps[slot] = req.sampling.temperature
        self._top_ks[slot] = req.sampling.top_k
        self._top_ps[slot] = req.sampling.top_p
        self._slot_req[slot] = req
        req.mark_admitted()
        self.metrics.record_admitted(req.admit_time - req.submit_time)

    def _reap_cancelled(self):
        for slot in np.nonzero(self._active)[0]:
            req = self._slot_req[slot]
            if req is not None and req.cancelled:
                self._evict(slot, failed="cancelled")

    def _reap_expired(self):
        """Per-request deadline (ServingConfig.request_deadline_s):
        evict running slots and drop queued requests whose wall clock
        ran out — their callers have already timed out; decoding for
        them starves live traffic."""
        if self._deadline_s is None:
            return
        import time
        now = time.monotonic()
        for slot in np.nonzero(self._active)[0]:
            req = self._slot_req[slot]
            if req is not None and \
                    now - req.submit_time > self._deadline_s:
                self._evict(
                    slot,
                    failed=(f"deadline exceeded after "
                            f"{now - req.submit_time:.1f}s "
                            f"(deadline {self._deadline_s:.1f}s, "
                            f"{len(req.generated)} tokens generated)"),
                    kind="deadline")
        expired = self.scheduler.drop_expired(self._deadline_s, now)
        if expired:
            self.metrics.count("requests_expired", len(expired))

    def _evict(self, slot: int, failed: Optional[str] = None,
               kind: str = "error"):
        req = self._slot_req[slot]
        self._slot_req[slot] = None
        self._active[slot] = False
        self._lengths[slot] = 0  # inactive rows park at position 0
        self.pool.release(slot)
        if failed is not None:
            req.fail(failed, kind=kind)
            self.metrics.count("requests_expired" if kind == "deadline"
                               else "requests_cancelled")
            return
        req.finish()
        self.metrics.record_completed(
            req.finish_time - req.submit_time, len(req.generated))

    def _step(self):
        """One interleaved decode step + host bookkeeping."""
        out = self._decode(
            self.gen.params, self.pool.caches, self._last_logits,
            self._rngs, jnp.asarray(self._lengths),
            jnp.asarray(self._temps), jnp.asarray(self._top_ks),
            jnp.asarray(self._top_ps))
        self.pool.caches, self._last_logits, self._rngs = out[:3]
        toks = np.asarray(out[3])
        tok_lp = np.asarray(out[4])
        n_active = 0
        for slot in np.nonzero(self._active)[0]:
            req = self._slot_req[slot]
            first = not req.generated
            req.append_token(int(toks[slot]), float(tok_lp[slot]))
            if first:
                self.metrics.record_first_token(req.ttft)
            self._lengths[slot] += 1
            n_active += 1
            if (int(toks[slot]) == self.gen.eos_id
                    or len(req.generated) >= req.max_new_tokens):
                self._evict(slot)
        self._steps += 1
        self.metrics.record_step(n_active, self.num_slots, n_active,
                                 self.scheduler.depth())
        if self._writer is not None and \
                self._steps % self._report_interval == 0:
            self.metrics.report(self._writer, self._steps)
