"""Continuous-batching serving engine.

The reference (and the serial port in inference/server.py) generates one
whole batch at a time behind a lock: a 128-prompt request's entire
prefill + decode blocks every other caller. This engine implements
Orca-style iteration-level scheduling over a vLLM-style pooled KV cache,
TPU-native:

- ONE persistent jitted decode step over a fixed grid of `num_slots`
  batch slots — static shapes, compiled exactly once, no per-request
  retrace. Per-slot sequence positions ride the vector KV-cache offsets
  (models/attention.py), per-slot sampling knobs ride
  `sample_batched` (inference/sampling.py), per-request seeds ride a
  [slots, 2] PRNG-key grid.
- Each slot owns a region of a pre-allocated KV pool
  (serving/kv_pool.py, built by init_kv_caches — int8 and
  sliding-window ROLLING layouts included). Admission prefills a
  request at batch=1 and inserts its KV into the slot region via
  `lax.dynamic_update_slice`; eviction on EOS/max-tokens frees the slot
  with no copying.
- A bounded FIFO (serving/scheduler.py) provides backpressure; the
  engine loop drains it into free slots between decode steps, so
  new requests join the running batch at token granularity.
- Host/device overlap: `decode_sync_interval=K` chains K decode
  dispatches on device-resident state (lengths ride the device and
  self-increment) and fetches all K sampled tokens in ONE transfer —
  syncs/token = 1/K, at the cost of up to K-1 wasted slot-steps per
  finished request and K-1 extra steps of admission latency (EOS /
  eviction / admission decide at sync boundaries). Sampling knobs and
  lengths keep cached device copies re-uploaded only on slot churn,
  and queued same-length-bucket admissions coalesce into one batched
  prefill call (`prefill_max_batch`).

Seeded determinism: a request with seed s reproduces the serial
`Generator.generate([prompt], ..., seed=s)` output token-for-token —
the engine burns the same number of PRNG splits the serial path spends
on its bucketed in-prompt steps, and `sample_batched` is row-for-row
bit-identical to `sample`.
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from megatron_tpu.inference.generation import Generator
from megatron_tpu.inference.sampling import sample_batched
from megatron_tpu.models import language_model as lm
from megatron_tpu.serving.kv_pool import SlotKVPool, insert_prefill
from megatron_tpu.serving.metrics import ServingMetrics
from megatron_tpu.serving.request import (GenRequest, RequestState,
                                          SamplingOptions)
from megatron_tpu.serving.scheduler import FIFOScheduler
from megatron_tpu.utils.logging import print_rank_0

from megatron_tpu.config import SERVING_KV_DTYPES as _KV_DTYPES


class ServingEngine:
    """Drives generation for many concurrent requests through one
    compiled decode step. Construct from a `Generator` (whose params /
    config / mesh treatment / rope tables are reused as-is)."""

    def __init__(self, generator: Generator, serving=None,
                 metrics: Optional[ServingMetrics] = None,
                 writer=None, report_interval: int = 100,
                 start: bool = True):
        from megatron_tpu.config import ServingConfig
        self.gen = generator
        cfg = generator.cfg
        self.cfg = cfg
        self.serving = serving if serving is not None else ServingConfig()
        self.max_len = self.serving.max_len or cfg.max_position_embeddings
        assert self.max_len <= cfg.max_position_embeddings, (
            f"ServingConfig.max_len={self.max_len} exceeds "
            f"max_position_embeddings={cfg.max_position_embeddings}")
        self.num_slots = self.serving.num_slots
        kv_dtype = (generator.kv_cache_dtype
                    if self.serving.kv_dtype is None
                    else _KV_DTYPES[self.serving.kv_dtype])
        self.pool = SlotKVPool(cfg, self.num_slots, self.max_len,
                               dtype=kv_dtype)
        self.scheduler = FIFOScheduler(self.serving.max_queue,
                                       max_total_len=self.max_len)
        self.scheduler.notify = self._wake
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self._writer = writer
        self._report_interval = max(report_interval, 1)

        S, Vp = self.num_slots, cfg.padded_vocab_size
        # per-slot device state (functionally replaced every step)
        self._last_logits = jnp.zeros((S, Vp), jnp.float32)
        self._rngs = jnp.zeros((S, 2), jnp.uint32)
        # per-slot host state (engine thread only)
        self._lengths = np.zeros(S, np.int32)
        self._active = np.zeros(S, bool)
        self._temps = np.ones(S, np.float32)
        self._top_ks = np.zeros(S, np.int32)
        self._top_ps = np.zeros(S, np.float32)
        self._slot_req: List[Optional[GenRequest]] = [None] * S
        # cached DEVICE copies of the per-slot state: sampling knobs and
        # lengths only change on slot churn (admit/evict), so they are
        # re-uploaded only when the dirty flags say so instead of
        # jnp.asarray'ing 4 host arrays every decode step. Between
        # churns the lengths chain device-side through the decode calls.
        self._d_lengths = jnp.asarray(self._lengths)
        self._d_temps = jnp.asarray(self._temps)
        self._d_top_ks = jnp.asarray(self._top_ks)
        self._d_top_ps = jnp.asarray(self._top_ps)
        self._sampling_dirty = True
        self._lengths_dirty = True
        self._sync_interval = max(self.serving.decode_sync_interval, 1)
        self._prefill_max_batch = max(
            min(self.serving.prefill_max_batch, self.num_slots), 1)

        self._decode_traces = 0  # trace count — MUST stay 1 in steady state
        # lengths (arg 4) chains device-side but is NOT donated: it is
        # [S] int32 (nothing to save), and donating a buffer that the
        # next chained call consumes while the previous one is still in
        # flight hits the CPU jax 0.4.x donation-aliasing bug the
        # rollback path in training/loop.py documents (observed here as
        # rare wrong tokens on the 8-virtual-device CPU mesh)
        self._decode = self.gen._jit(self._decode_fn, n_array_args=7,
                                     donate_argnums=(1, 2, 3))
        # one jit; jax retraces per (batch-bucket, padded prompt length)
        # combo (both bucketed — _prefill_bucket / _batch_bucket — so
        # the cache hits across request sizes and arrival bursts)
        self._prefill = self.gen._jit(self._prefill_fn, n_array_args=7,
                                      donate_argnums=(1, 2, 3))
        self._steps = 0
        self._cond = threading.Condition()
        self._stop = False
        self._draining = False
        self._deadline_s = self.serving.request_deadline_s
        self._broken: Optional[str] = None
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serving-engine")
        if start:
            self._thread.start()

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 64,
               sampling: SamplingOptions = SamplingOptions(),
               seed: int = 0) -> GenRequest:
        """Non-blocking: enqueue and return the request handle. Raises
        QueueFullError (→ 429) when the bounded queue is full and
        AdmissionError (→ 400) when the request can never fit."""
        if self._broken:
            raise RuntimeError(f"engine failed: {self._broken}")
        if self._draining:
            from megatron_tpu.serving.scheduler import QueueFullError
            raise QueueFullError(
                "engine draining (shutdown in progress); retry against "
                "another replica")
        req = GenRequest(list(prompt), max_new_tokens, sampling, seed)
        self.metrics.count("requests_received")
        try:
            if max_new_tokens == 0:
                # nothing to decode: the serial path returns the prompt
                # row unchanged — short-circuit without occupying a
                # slot, but through the SAME admission check (an
                # oversize prompt must 400 on both routes)
                self.scheduler.check_admissible(req)
                req.mark_admitted()
                req.finish()
                self.metrics.record_admitted(0.0)
                self.metrics.record_completed(0.0, 0)
                return req
            self.scheduler.submit(req)
        except Exception:
            self.metrics.count("requests_rejected")
            raise
        return req

    def cancel(self, req: GenRequest):
        """Best-effort cancellation: a QUEUED request is dropped and
        failed immediately; a RUNNING one is flagged and evicted at the
        next decode step (frees its slot without decoding to
        completion). Used by the HTTP layer to avoid orphaned work when
        a multi-prompt payload fails partway through submission."""
        req.cancel()
        if not req.done():
            self.scheduler.cancel(req)
        self._wake()

    def generate(self, prompt: Sequence[int], max_new_tokens: int = 64,
                 sampling: SamplingOptions = SamplingOptions(),
                 seed: int = 0, timeout: Optional[float] = None):
        """Blocking convenience: submit + wait. Returns (tokens,
        logprobs) with tokens = prompt + generated."""
        return self.submit(prompt, max_new_tokens, sampling,
                           seed).result(timeout)

    def close(self):
        """Stop the loop; fail queued and in-flight requests. Safe on a
        never-started (start=False) engine."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread.ident is not None:  # was started
            self._thread.join(timeout=30)
        for req in self.scheduler.close():
            req.fail("engine shut down")
        for req in self._slot_req:
            if req is not None and req.state is RequestState.RUNNING:
                req.fail("engine shut down")

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: stop admitting (queued-but-unstarted
        requests fail immediately with a retry-later error; new submits
        are rejected the same way), let every IN-FLIGHT slot decode to
        completion, then stop the loop. Returns True when all in-flight
        work finished within `timeout` (None = wait indefinitely);
        False leaves the stragglers to `close()`'s hard failure. The
        SIGTERM handler in inference/server.py calls this so a rolling
        restart never truncates a response mid-stream."""
        self._draining = True
        backlog = self.scheduler.close()
        for req in backlog:
            req.fail("engine draining (shutdown in progress); retry "
                     "against another replica", kind="unavailable")
        if backlog:
            self.metrics.count("requests_rejected", len(backlog))
        self._wake()
        if self._thread.ident is not None:
            self._thread.join(timeout)
        drained = not self._thread.is_alive()
        if drained:
            print_rank_0("serving engine drained: all in-flight "
                         "requests completed")
        return drained

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------
    # device programs
    # ------------------------------------------------------------------
    def _decode_fn(self, params, pool, last_logits, rngs, lengths,
                   temps, top_ks, top_ps):
        """ONE interleaved decode step for the whole slot grid: sample
        each slot's next token from its carried logits, then forward all
        slots' tokens (s=1) through the model with per-slot positions.
        Inactive slots ride along at length 0 (static shapes); their
        writes land at position 0 and are fully overwritten by the next
        prefill insert.

        `lengths` is the DEVICE copy of the per-slot positions and is
        returned incremented, so K chained calls advance positions
        without a host round-trip (decode_sync_interval). The clamp at
        max_len-1 only ever binds for rows idling past their eviction
        inside a window — admission guarantees a live row never needs a
        position past max_len-1 — and keeps their rope/cache indices in
        bounds until the boundary re-upload re-parks them."""
        self._decode_traces += 1
        cfg = self.cfg
        split = jax.vmap(jax.random.split)(rngs)  # [S, 2, 2]
        new_rngs, step_keys = split[:, 0], split[:, 1]
        toks = sample_batched(step_keys, last_logits,
                              temperature=temps, top_k=top_ks,
                              top_p=top_ps, vocab_size=cfg.vocab_size)
        # logprob of the chosen token under the RAW carried logits —
        # the serial path's convention (generation.py _decode_fn)
        lp = jax.nn.log_softmax(last_logits, axis=-1)
        tok_lp = jnp.take_along_axis(lp, toks[:, None], axis=-1)[:, 0]
        # `lengths` is the source of truth for every row's position;
        # broadcast them over layers into the pool
        L = pool.offset.shape[0]
        pool = pool._replace(offset=jnp.broadcast_to(
            lengths[None, :], (L, lengths.shape[0])).astype(jnp.int32))
        logits, pool = lm.model_forward(
            params, toks[:, None], cfg, kv_caches=pool,
            position_ids=lengths[:, None], rope=self.gen.rope,
            logits_dtype=jnp.float32)
        new_lengths = jnp.minimum(lengths + 1,
                                  jnp.int32(self.max_len - 1))
        return pool, logits[:, 0], new_rngs, toks, tok_lp, new_lengths

    def _prefill_fn(self, params, pool, last_logits, rngs, tokens,
                    plens, slots, rng0s):
        """Batched prefill: B prompts (same padded bucket) forward in
        ONE call — the weight stream is paid once per batch instead of
        once per request — then each row's KV inserts into its slot.
        Row results are independent (per-row causal attention), so a
        B>1 prefill is the B=1 prefill done B times. Duplicate rows
        (the batch-bucket pads replicate row 0) rewrite the same slot
        with identical values — idempotent by construction."""
        B = tokens.shape[0]
        caches = self.pool.make_prefill_caches(B)
        logits, caches = lm.model_forward(
            params, tokens, self.cfg, kv_caches=caches,
            rope=self.gen.rope, logits_dtype=jnp.float32)
        for i in range(B):  # static unroll: B is a trace-time shape
            def row(x):
                return jax.lax.dynamic_slice_in_dim(x, i, 1, axis=1)
            sub = caches._replace(
                k=row(caches.k), v=row(caches.v),
                k_scale=(None if caches.k_scale is None
                         else row(caches.k_scale)),
                v_scale=(None if caches.v_scale is None
                         else row(caches.v_scale)))
            pool = insert_prefill(pool, sub, slots[i], plens[i])
            # logits at the LAST REAL prompt position (bucket pads sit
            # after it and are causally invisible to it)
            last = jax.lax.dynamic_slice_in_dim(
                logits[i], plens[i] - 1, 1, axis=0)[0]
            last_logits = last_logits.at[slots[i]].set(last)
            rngs = rngs.at[slots[i]].set(rng0s[i])
        return pool, last_logits, rngs

    def _prefill_bucket(self, plen: int) -> int:
        """Pad prompts up to a bucket so the prefill jit cache hits
        across request sizes. ROLLING pools prefill at the exact length:
        pad positions fed through the ring would evict real tokens from
        the W-slot buffer."""
        if self.pool.rolling:
            return plen
        b = max(self.serving.prefill_bucket, 1)
        return min(-(-plen // b) * b, self.max_len)

    @staticmethod
    def _batch_bucket(n: int) -> int:
        """Round a prefill batch up to a power of two so the jit cache
        holds O(log slots) entries per length bucket, not one per
        arrival-burst size."""
        b = 1
        while b < n:
            b *= 2
        return b

    @staticmethod
    def _initial_rng(seed: int, plen: int):
        """Per-request key, advanced past the splits the SERIAL path
        spends on its bucketed in-prompt steps (Generator.generate
        rounds the prefill down to a PREFILL_BUCKET multiple and
        consumes the remaining prompt tokens through decode steps,
        splitting once per step) — so a seeded engine request reproduces
        the serial output bit-for-bit from the first generated token."""
        from megatron_tpu.inference.generation import PREFILL_BUCKET
        key = jax.random.PRNGKey(seed)
        burn = plen - max((plen // PREFILL_BUCKET) * PREFILL_BUCKET, 1)
        for _ in range(burn):
            key = jax.random.split(key)[0]
        return key

    # ------------------------------------------------------------------
    # engine loop (single thread)
    # ------------------------------------------------------------------
    def _wake(self):
        with self._cond:
            self._cond.notify_all()

    def _loop(self):
        print_rank_0(
            f"serving engine: {self.num_slots} slots x cap "
            f"{self.pool.cap} ({self.pool.dtype}"
            f"{', rolling' if self.pool.rolling else ''}), "
            f"pool {self.pool.nbytes() / 2**20:.1f} MiB, "
            f"queue bound {self.serving.max_queue}")
        while True:
            with self._cond:
                while (not self._stop and not self._draining
                       and self.scheduler.depth() == 0
                       and not self._active.any()):
                    self._cond.wait(timeout=0.5)
                if self._stop:
                    return
                if self._draining and not self._active.any():
                    return  # drained: queue closed, slots empty
            try:
                self._reap_cancelled()
                self._reap_expired()
                self._admit()
                if self._active.any():
                    self._step()
            except Exception as e:  # noqa: BLE001 — fail loudly, not hang
                self._broken = repr(e)
                print_rank_0(f"serving engine loop failed: {e!r}")
                for req in self._slot_req:
                    if req is not None:
                        req.fail(self._broken)
                for req in self.scheduler.close():
                    req.fail(self._broken)
                return

    def _admit(self):
        groups = self.scheduler.pop_ready_grouped(
            self.pool.free_count(),
            lambda r: self._prefill_bucket(len(r.prompt)),
            self._prefill_max_batch)
        pending = [r for _, reqs in groups for r in reqs]
        for padded, reqs in groups:
            try:
                self._prefill_group(reqs, padded)
                for r in reqs:
                    pending.remove(r)
            except Exception as e:
                # the failing group AND the rest of this pop are in
                # neither _slot_req nor the scheduler — fail them here
                # or their callers would hang to the request timeout
                for r in pending:
                    r.fail(repr(e))
                raise

    def _prefill_group(self, reqs: List[GenRequest], padded: int):
        """One batched prefill for same-bucket admissions. The batch
        dim rounds up to a power of two; pad rows replicate row 0
        (identical re-write of the same slot — harmless)."""
        B_real = len(reqs)
        B = self._batch_bucket(B_real)
        slots = [self.pool.alloc() for _ in reqs]
        plens = [len(r.prompt) for r in reqs]
        toks = np.full((B, padded), self.gen.pad_id, np.int32)
        for i, r in enumerate(reqs):
            toks[i, :plens[i]] = r.prompt
        toks[B_real:] = toks[0]
        plens_a = np.asarray(plens + [plens[0]] * (B - B_real), np.int32)
        slots_a = np.asarray(slots + [slots[0]] * (B - B_real), np.int32)
        rng0s = jnp.stack(
            [self._initial_rng(r.seed, p)
             for r, p in zip(reqs, plens)]
            + [self._initial_rng(reqs[0].seed, plens[0])] * (B - B_real))
        self.pool.caches, self._last_logits, self._rngs = self._prefill(
            self.gen.params, self.pool.caches, self._last_logits,
            self._rngs, jnp.asarray(toks), jnp.asarray(plens_a),
            jnp.asarray(slots_a), rng0s)
        for slot, plen, req in zip(slots, plens, reqs):
            self._lengths[slot] = plen
            self._active[slot] = True
            self._temps[slot] = req.sampling.temperature
            self._top_ks[slot] = req.sampling.top_k
            self._top_ps[slot] = req.sampling.top_p
            self._slot_req[slot] = req
            req.mark_admitted()
            self.metrics.record_admitted(req.admit_time - req.submit_time)
        self._sampling_dirty = True
        self._lengths_dirty = True
        self.metrics.count("prefill_calls")
        self.metrics.count("prefill_prompts", B_real)

    def _reap_cancelled(self):
        for slot in np.nonzero(self._active)[0]:
            req = self._slot_req[slot]
            if req is not None and req.cancelled:
                self._evict(slot, failed="cancelled")

    def _reap_expired(self):
        """Per-request deadline (ServingConfig.request_deadline_s):
        evict running slots and drop queued requests whose wall clock
        ran out — their callers have already timed out; decoding for
        them starves live traffic."""
        if self._deadline_s is None:
            return
        import time
        now = time.monotonic()
        for slot in np.nonzero(self._active)[0]:
            req = self._slot_req[slot]
            if req is not None and \
                    now - req.submit_time > self._deadline_s:
                self._evict(
                    slot,
                    failed=(f"deadline exceeded after "
                            f"{now - req.submit_time:.1f}s "
                            f"(deadline {self._deadline_s:.1f}s, "
                            f"{len(req.generated)} tokens generated)"),
                    kind="deadline")
        expired = self.scheduler.drop_expired(self._deadline_s, now)
        if expired:
            self.metrics.count("requests_expired", len(expired))

    def _evict(self, slot: int, failed: Optional[str] = None,
               kind: str = "error"):
        req = self._slot_req[slot]
        self._slot_req[slot] = None
        self._active[slot] = False
        self._lengths[slot] = 0  # inactive rows park at position 0
        self._lengths_dirty = True  # device copy re-parks at next step
        self._sampling_dirty = True
        self.pool.release(slot)
        if failed is not None:
            req.fail(failed, kind=kind)
            self.metrics.count("requests_expired" if kind == "deadline"
                               else "requests_cancelled")
            return
        req.finish()
        self.metrics.record_completed(
            req.finish_time - req.submit_time, len(req.generated))

    @staticmethod
    def _fetch(tree):
        """ONE device→host transfer for the window's sampled tokens —
        the engine's sync seam (counted as `host_syncs`; wrapped by the
        cadence tests and tools/bench_sync.py)."""
        return jax.device_get(tree)

    def _step(self):
        """K chained decode dispatches + ONE host sync + bookkeeping.

        With decode_sync_interval=1 this is the classic per-token sync.
        With K>1 the host enqueues K decode calls back-to-back — each
        consumes the previous call's device outputs, so XLA runs them
        gap-free — and fetches all K token grids in one transfer. The
        host then consumes each slot's K tokens in order; a request
        hitting EOS/max at inner step k discards the trailing K-1-k
        tokens (its slot burned them as `wasted_decode_steps` — the
        documented cost of the batched sync) and evicts at the
        boundary. Per-request streams are token-exact vs K=1: slot
        rng/logits/KV chains never cross slots or sync boundaries."""
        K = self._sync_interval
        if self._sampling_dirty:
            self._d_temps = jnp.asarray(self._temps)
            self._d_top_ks = jnp.asarray(self._top_ks)
            self._d_top_ps = jnp.asarray(self._top_ps)
            self._sampling_dirty = False
            self.metrics.count("sampling_uploads")
        if self._lengths_dirty or not self._active.all():
            # churn re-syncs positions from the host truth; partially
            # active grids also re-park idle rows at 0 each window so
            # their device-side drift stays bounded by K
            self._d_lengths = jnp.asarray(self._lengths)
            self._lengths_dirty = False
        tok_steps, lp_steps = [], []
        for _ in range(K):
            out = self._decode(
                self.gen.params, self.pool.caches, self._last_logits,
                self._rngs, self._d_lengths, self._d_temps,
                self._d_top_ks, self._d_top_ps)
            (self.pool.caches, self._last_logits, self._rngs) = out[:3]
            self._d_lengths = out[5]
            tok_steps.append(out[3])
            lp_steps.append(out[4])
        fetched = self._fetch((tok_steps, lp_steps))
        self.metrics.count("host_syncs")
        toks = [np.asarray(t) for t in fetched[0]]   # K x [S]
        tok_lp = [np.asarray(l) for l in fetched[1]]
        active_slots = np.nonzero(self._active)[0]
        n_active = len(active_slots)
        consumed = np.zeros(K, np.int64)  # tokens delivered per step
        for slot in active_slots:
            req = self._slot_req[slot]
            for k in range(K):
                first = not req.generated
                tok = int(toks[k][slot])
                req.append_token(tok, float(tok_lp[k][slot]))
                if first:
                    self.metrics.record_first_token(req.ttft)
                self._lengths[slot] += 1
                consumed[k] += 1
                if (tok == self.gen.eos_id
                        or len(req.generated) >= req.max_new_tokens):
                    if K - 1 - k:
                        self.metrics.count("wasted_decode_steps",
                                           K - 1 - k)
                    self._evict(slot)
                    break
        self._steps += K
        depth = self.scheduler.depth()
        for k in range(K):
            self.metrics.record_step(n_active, self.num_slots,
                                     int(consumed[k]), depth)
        if self._writer is not None and \
                self._steps % self._report_interval < K:
            self.metrics.report(self._writer, self._steps)
